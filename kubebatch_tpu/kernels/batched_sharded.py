"""Multi-chip batched allocate — the round solver's node axis over a mesh.

The production throughput engine (kernels/batched.py) is already pure
tensor ops with a node axis everywhere the data is big: the [T, N] fit
and score matrices, the [N, R] capacity carry, the sig-indexed [S, N]
predicate rows. This module runs THE SAME round loop partitioned over a
``jax.sharding.Mesh`` axis ``"nodes"`` via GSPMD: node-axis arrays are
placed with ``NamedSharding(P(..., "nodes"))``, task/job/queue arrays are
replicated, and XLA's SPMD partitioner inserts the collectives (psum for
the per-task any-eligible and acceptance reductions, all-gathers for the
global waterfall order) — the scaling-book recipe: pick a mesh, annotate
shardings, let the compiler place the communication on ICI.

The inter-pod affinity / host-port vocabulary (kernels/affinity.py)
rides the same recipe: the [T,P] x [P,N] affinity matmuls get their
node dimension from the sharded ``node_dom`` / ``port_base`` columns,
while the [P,D] domain-count carry stays REPLICATED — D indexes
topology-label values, not nodes, and a replicated carry is what makes
the per-(pair, domain) serialization deterministic on every device
(docs/SCALING.md "Sharded affinity"). Predicate-rich cycles therefore
run first-class on the mesh; there is no sharded->batched demotion.

Numerics: identical operations to the single-chip engine; the only
tolerated divergence is floating-point reduction order inside segment
sums, which sits far below the resource epsilons. The equivalence test
(tests/test_sharded_batched.py) pins decisions, not carry bits.

Reached from the action layer via KUBEBATCH_SOLVER=sharded (or
AllocateAction(mode="sharded")) when more than one device is visible;
single-device processes fall back to the plain batched engine.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compilesvc import instrument as _instrument
from ..compilesvc import register_provider as _register_provider
from .batched import RoundState, CycleArrays, _IMAX, batched_allocate
from .fused import SKIP
from .narrow import narrow_enabled
from .telemetry import ENGINE_SHARDED, decision_frame

AXIS = "nodes"
HOST_AXIS = "hosts"


def node_mesh(n_devices: Optional[int] = None,
              n_hosts: int = 1) -> Mesh:
    """A mesh over the local devices with the node axis partitioned.

    ``n_hosts > 1`` builds the hierarchical 2-D mesh of the multi-host
    recipe (docs/SCALING.md "Multi-host (DCN)" step 4): axis ``"hosts"``
    over host groups (DCN) x ``"nodes"`` within a host (ICI); the node
    dimension of every sharded array is then split over BOTH axes, so
    the waterfall's all-gather becomes hierarchical — XLA inserts the
    ICI-then-DCN pattern from the same annotations."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if n_hosts > 1:
        if len(devs) % n_hosts:
            raise ValueError(f"{len(devs)} devices do not split over "
                             f"{n_hosts} hosts")
        return Mesh(np.array(devs).reshape(n_hosts, -1), (HOST_AXIS, AXIS))
    return Mesh(np.array(devs), (AXIS,))


def _specs_for(mesh: Mesh, affinity: bool = False, ports: bool = False,
               ip: bool = False):
    """(array_specs, state_specs) for the mesh: the node dimension is
    split over every mesh axis — ``("nodes",)`` on a 1-D mesh,
    ``("hosts", "nodes")`` hierarchically on the 2-D multi-host mesh.

    Affinity placement mirrors the resource terms: the node axis is the
    ONLY partitioned axis. ``node_dom`` [P,N] and ``port_base`` /
    ``port_claim`` [N,PT] shard on their node dimension like the sig
    matrices / capacity carry; the [T,P] term matrices and — crucially —
    the [P,D] domain-count CARRY stay replicated. The carry is the state
    the per-(pair, domain) serialization adjudicates against, and with
    it replicated every device computes the identical keep/reject
    verdict from the identical all-gathered proposal set (see the
    replicated-carry argument in docs/SCALING.md); the domain axis D is
    NOT the node axis (it indexes distinct topology-label values), so
    partitioning it would buy nothing and cost the serialization its
    locality."""
    na = (tuple(mesh.axis_names) if len(mesh.axis_names) > 1
          else AXIS)
    array_specs = dict(
        backfilled=P(na, None), allocatable_cm=P(na, None),
        max_task_num=P(na), node_ok=P(na),
        resreq=P(), init_resreq=P(), task_nz=P(), task_job=P(),
        task_rank=P(), task_sig=P(), task_pair=P(), task_valid=P(),
        sig_scores=P(None, na), sig_pred=P(None, na),
        pair_sig=P(), pair_nz=P(),
        order_min_available=P(), job_queue=P(), job_priority=P(),
        job_create_rank=P(), job_valid=P(),
        q_deserved=P(), q_create_rank=P(), cluster_total=P(),
        dyn_weights=P())
    state_specs = dict(
        idle=P(na, None), releasing=P(na, None), n_tasks=P(na),
        nz_req=P(na, None), q_allocated=P(), j_allocated=P(),
        alloc_cnt=P(), job_alive=P(), task_state=P(), task_node=P(),
        task_seq=P())
    if affinity:
        array_specs.update(
            node_dom=P(None, na), task_grp=P(), task_req_aff=P(),
            task_req_anti=P(), task_self_ok=P(), task_carry_w=P(),
            task_pref_w=P())
        state_specs.update(aff_grp_cnt=P(), aff_anti_cnt=P(),
                           aff_pref_w=P(), aff_grp_total=P())
        if ports:
            array_specs.update(task_ports=P(), port_base=P(na, None))
            state_specs.update(port_claim=P(na, None))
        if ip:
            array_specs.update(ip_weight=P())
    return array_specs, state_specs




@partial(jax.jit, static_argnames=("job_keys", "queue_keys", "prop_overused",
                                   "dyn_enabled", "pipe_enabled",
                                   "max_rounds", "narrow", "narrow_gate"))
def _sharded_entry(state: RoundState, arrays: CycleArrays, job_keys,
                   queue_keys, prop_overused, dyn_enabled, pipe_enabled,
                   max_rounds, narrow=False, narrow_gate=False):
    final, rounds, retries, stranded = batched_allocate(
        state, arrays, job_keys=job_keys, queue_keys=queue_keys,
        prop_overused=prop_overused, dyn_enabled=dyn_enabled,
        pipe_enabled=pipe_enabled, max_rounds=max_rounds,
        compact_bucket=0,   # compaction gathers are counterproductive SPMD
        narrow=narrow)
    frame = decision_frame(
        ENGINE_SHARDED, final.task_state, final.task_seq,
        arrays.task_valid, waves=rounds,
        stride=arrays.task_valid.shape[0], narrow=narrow,
        narrow_gate=narrow_gate, retries=retries, stranded=stranded)
    return final, jnp.concatenate(
        [final.task_state, final.task_node, final.task_seq,
         rounds.astype(jnp.int32)[None], frame])


# accounted trace boundary (compilesvc): the GSPMD mesh entry
_sharded_entry = _instrument("sharded", "_sharded_entry", _sharded_entry)


def _pad_nodes(a: np.ndarray, n_pad: int) -> np.ndarray:
    if a.shape[0] == n_pad:
        return a
    pad = [(0, n_pad - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad)


def _pad_node_cols(a: np.ndarray, n_pad: int, fill) -> np.ndarray:
    """Pad axis 1 (the node columns of [P,N] arrays) to the shard
    bucket. ``fill`` = -1 for node_dom: a padding node belongs to NO
    domain, so it can never satisfy, reject or count toward any pair."""
    if a.shape[1] == n_pad:
        return a
    out = np.full((a.shape[0], n_pad), fill, a.dtype)
    out[:, :a.shape[1]] = a
    return out


def shard_bucket(n: int, n_devices: int, minimum: int = 8) -> int:
    """Node bucket: tensorize.pad_to_bucket (pow2, re-grained above
    LARGE_BUCKET), then rounded up to the next multiple of the mesh size
    so every shard gets equal rows (a 6- or 12-device mesh is not a
    power of two)."""
    from .tensorize import pad_to_bucket

    b = pad_to_bucket(max(n, minimum), minimum)
    if b % n_devices:
        b = -(-b // n_devices) * n_devices
    return b


def solve_batched_sharded(mesh: Mesh, device, inputs,
                          max_rounds: int = 0) -> Tuple[np.ndarray, ...]:
    """Sharded twin of kernels/batched.solve_batched: same CycleInputs in,
    same (task_state, task_node, task_seq, rounds) out, with the node axis
    of every big array partitioned over ``mesh``.

    ``device`` is the session's DeviceSession — its committed numpy-backed
    state provides the capacity carry; the updated carry is written back
    so later actions observe the same node accounting as the single-chip
    path.
    """
    from ..faults import check as _fault_check
    from ..metrics import count_blocking_readback
    from ..obs import span as _span

    # injection seam: before any carry is consumed, so a faulted sharded
    # dispatch leaves the DeviceSession state untouched
    _fault_check("device.dispatch")

    n_pad = device.n_padded
    t_pad = inputs.task_valid.shape[0]
    placed_state, placed_arrays, statics = prepare_sharded(
        mesh, device, inputs, max_rounds)
    with _span("batched_allocate_sharded", cat="kernel") as sp:
        final, packed = _sharded_entry(placed_state, placed_arrays,
                                       **statics)
        count_blocking_readback()
        with _span("readback", cat="readback"):
            out = np.asarray(packed)
        task_state = out[:t_pad]
        task_node = out[t_pad:2 * t_pad]
        task_seq = out[2 * t_pad:3 * t_pad]
        rounds = out[3 * t_pad]
        from ..obs import telemetry as _obs_telemetry
        _obs_telemetry.record(out[3 * t_pad + 1:], span=sp)

        # commit the carry back to the session's device state (trimmed to
        # the single-chip bucket) so later actions see the updated
        # accounting
        count_blocking_readback(4)
        with _span("readback_carry", cat="readback", n=4):
            device.idle = jnp.asarray(np.asarray(final.idle)[:n_pad])
            device.releasing = jnp.asarray(
                np.asarray(final.releasing)[:n_pad])
            device.n_tasks = jnp.asarray(np.asarray(final.n_tasks)[:n_pad])
            device.nz_req = jnp.asarray(np.asarray(final.nz_req)[:n_pad])
    return task_state, task_node, task_seq, int(rounds)


def prepare_sharded(mesh: Mesh, device, inputs, max_rounds: int = 0):
    """Pad, annotate, and place the round solver's inputs on ``mesh`` —
    the exact (placed RoundState, placed CycleArrays, statics) the mesh
    entry dispatches, shared by the live path above and the compilesvc
    signature provider."""
    n_dev = mesh.devices.size
    n_pad = device.n_padded
    n_sh = shard_bucket(n_pad, n_dev)
    t_pad = inputs.task_valid.shape[0]
    if max_rounds <= 0:
        max_rounds = int(t_pad) + 8

    task_pair, pair_sig, pair_nz, _ = inputs.pair_terms()

    def nodes_np(x):
        return _pad_nodes(np.asarray(x), n_sh)

    # inter-pod affinity / host ports join the mesh run with the node
    # dimension of node_dom / port_base / port_claim padded to the shard
    # bucket (padding nodes carry no domain and no ports); everything
    # else ships as-is and the specs in _specs_for place it
    aff = getattr(inputs, "affinity", None)
    aff_arrays: dict = {}
    aff_state: dict = {}
    has_ports = False
    if aff is not None:
        has_ports = bool(np.any(aff.task_ports))
        aff_arrays = dict(
            node_dom=_pad_node_cols(aff.node_dom, n_sh, -1),
            task_grp=aff.task_grp, task_req_aff=aff.task_req_aff,
            task_req_anti=aff.task_req_anti,
            task_self_ok=aff.task_self_ok,
            task_carry_w=aff.task_carry_w, task_pref_w=aff.task_pref_w)
        if has_ports:
            aff_arrays.update(task_ports=aff.task_ports,
                              port_base=_pad_nodes(aff.port_base, n_sh))
        if aff.ip_enabled:
            aff_arrays["ip_weight"] = np.float32(aff.ip_weight)
        aff_state = dict(
            aff_grp_cnt=aff.grp_cnt0, aff_anti_cnt=aff.anti_cnt0,
            aff_pref_w=aff.pref_w0, aff_grp_total=aff.grp_total0)
        if has_ports:
            aff_state["port_claim"] = np.zeros(
                (n_sh, aff.task_ports.shape[1]), bool)

    arrays = CycleArrays(
        backfilled=nodes_np(device.backfilled),
        allocatable_cm=nodes_np(device.allocatable_cm),
        max_task_num=nodes_np(device.max_task_num),
        node_ok=nodes_np(device.node_ok),
        resreq=inputs.resreq, init_resreq=inputs.init_resreq,
        task_nz=inputs.task_nz, task_job=inputs.task_job,
        task_rank=inputs.task_rank, task_sig=inputs.task_sig,
        task_pair=task_pair, task_valid=inputs.task_valid,
        sig_scores=_pad_nodes(inputs.sig_scores.T, n_sh).T,
        sig_pred=_pad_nodes(inputs.sig_pred.T, n_sh).T,
        pair_sig=pair_sig, pair_nz=pair_nz,
        order_min_available=inputs.order_min_available,
        job_queue=inputs.job_queue, job_priority=inputs.job_priority,
        job_create_rank=inputs.job_create_rank, job_valid=inputs.job_valid,
        q_deserved=inputs.q_deserved, q_create_rank=inputs.q_create_rank,
        cluster_total=inputs.cluster_total, dyn_weights=inputs.dyn_weights,
        **aff_arrays)
    state = RoundState(
        idle=nodes_np(device.idle), releasing=nodes_np(device.releasing),
        n_tasks=nodes_np(device.n_tasks), nz_req=nodes_np(device.nz_req),
        q_allocated=inputs.q_alloc0, j_allocated=inputs.j_alloc0,
        alloc_cnt=inputs.init_allocated, job_alive=inputs.job_valid,
        task_state=np.full(t_pad, SKIP, np.int32),
        task_node=np.full(t_pad, -1, np.int32),
        task_seq=np.full(t_pad, _IMAX, np.int32),
        **aff_state)

    def put(tree, specs):
        return type(tree)(**{
            k: jax.device_put(getattr(tree, k), NamedSharding(mesh, s))
            for k, s in specs.items()})

    array_specs, state_specs = _specs_for(
        mesh, affinity=aff is not None, ports=has_ports,
        ip=aff is not None and aff.ip_enabled)
    # PER-SHARD narrow policy: each device materializes
    # [T, N/shards]; AUTO additionally requires bf16-exact scores
    narrow = narrow_enabled(
        max(1, n_sh // n_dev), t_pad,
        static_scores=inputs.sig_scores,
        dyn_weights=(inputs.dyn_weights if inputs.dyn_enabled
                     else None),
        ip_weight=(aff.ip_weight
                   if aff is not None and aff.ip_enabled else 0.0))
    statics = dict(
        job_keys=inputs.job_keys, queue_keys=inputs.queue_keys,
        prop_overused=inputs.prop_overused,
        dyn_enabled=inputs.dyn_enabled,
        pipe_enabled=inputs.pipe_enabled,
        max_rounds=min(max_rounds, 4096),
        narrow=narrow,
        narrow_gate=(not narrow
                     and narrow_enabled(max(1, n_sh // n_dev), t_pad)))
    return put(state, state_specs), put(arrays, array_specs), statics


# ---------------------------------------------------------------------
# compilesvc signature provider — the mesh twin registers whenever more
# than one device is visible and the node axis clears the auto-sharded
# threshold (the shipped default then partitions the round engine)
# ---------------------------------------------------------------------

@_register_provider("kernels.batched_sharded")
def compile_signatures(materials):
    from ..actions.allocate import (AUTO_BATCHED_MIN, AUTO_HIER_MIN_NODES,
                                    AUTO_SHARDED_MIN_NODES)
    from ..compilesvc.registry import Signature, signature_key

    if len(jax.devices()) <= 1:
        return []
    out = []
    for regime, inputs in (("cold", materials.cold_inputs),
                           ("steady", materials.steady_inputs)):
        if inputs is None or isinstance(inputs, str):
            continue
        if len(inputs.tasks) < AUTO_BATCHED_MIN \
                or len(inputs.device.state.names) < AUTO_SHARDED_MIN_NODES:
            continue
        if len(inputs.device.state.names) >= AUTO_HIER_MIN_NODES \
                and getattr(inputs, "affinity", None) is None:
            continue    # the two-level engine owns this regime
        mesh = node_mesh()
        placed_state, placed_arrays, base = prepare_sharded(
            mesh, inputs.device, inputs)
        args = (placed_state, placed_arrays)
        # pipe_enabled is a static: like the batched twin, reclaim/
        # preempt configs can open a sharded cycle with releasing
        # capacity on the nodes — both variants are registered surface
        pipes = ((False, True)
                 if ("reclaim" in materials.actions
                     or "preempt" in materials.actions)
                 else (base["pipe_enabled"],))
        for pipe in pipes:
            statics = dict(base, pipe_enabled=pipe)
            out.append(Signature(
                engine="sharded", entry="_sharded_entry",
                key=signature_key("_sharded_entry", args, statics),
                lower=lambda a=args, s=statics: _sharded_entry.lower(
                    *a, **s),
                run=lambda a=args, s=statics: _sharded_entry(*a, **s),
                note=(f"{regime} T={inputs.task_valid.shape[0]} "
                      f"mesh={mesh.devices.size} pipe={pipe}")))
    return out
