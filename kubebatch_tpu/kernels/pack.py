"""Host->device input packing.

Through a high-latency link (the axon tunnel charges ~70 ms per
transfer), per-cycle upload cost is dominated by TRANSFER COUNT, not
bytes: ~20 individual device_puts cost more than one concatenated
buffer. Solvers pack their per-cycle inputs into one flat buffer per
dtype class plus a static layout tuple; the jitted entry slices the
buffers back into arrays at trace time (free for XLA — static offsets).
"""
from __future__ import annotations

import numpy as np

__all__ = ["pack", "unpack"]


def pack(values, dtype):
    """Concatenate (name, array) pairs into one flat buffer + a static
    (hashable) layout tuple of (name, offset, shape)."""
    layout = []
    flats = []
    off = 0
    for name, arr in values:
        arr = np.asarray(arr)
        layout.append((name, off, tuple(arr.shape)))
        flats.append(arr.ravel().astype(dtype, copy=False))
        off += arr.size
    buf = np.concatenate(flats) if flats else np.zeros(0, dtype)
    return buf, tuple(layout)


def unpack(buf, layout):
    """Slice a packed buffer back into named arrays (inside jit; offsets
    and shapes are static)."""
    out = {}
    for name, off, shape in layout:
        size = int(np.prod(shape)) if shape else 1
        arr = buf[off:off + size]
        out[name] = arr.reshape(shape) if shape else arr[0]
    return out
