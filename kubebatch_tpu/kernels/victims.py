"""Victim-selection kernel — preempt/reclaim node visits as tensor ops.

The reference's preempt hot loop evaluates, per preemptor task, a
predicate+score pass over ALL nodes and then a per-node victim scan
calling every evictability plugin per (victim) pair
(ref: actions/preempt/preempt.go:266-334, reclaim/reclaim.go:128-173).
This module evaluates ONE ENTIRE NODE VISIT — all nodes' predicate mask,
scores, tiered-intersection victim masks, resource-sufficiency validation
and the cumulative eviction stop-scan — as one jitted dispatch over dense
[V] (cluster-wide running tasks) and [N] (nodes) arrays.

Semantics preserved exactly (vs framework/session.py + plugins):
- tier dispatch: per tier, victims = INTERSECTION of enabled plugin
  verdicts; the first tier with a non-empty set per node wins
  (session.py:_evictable); the conformance veto then re-applies.
- gang: victim's job stays >= MinAvailable after losing ONE task, or the
  MinAvailable==1 fork quirk (plugins/gang.py preemptable_fn). The check
  reads the job's CURRENT ready count — victims of one call don't see
  each other (the reference computes the list wholesale, then evicts).
- drf: preemptor's post-share vs victim-job's post-eviction share within
  1e-6, with the reference's CUMULATIVE per-job allocation decrements in
  candidate-list order within one call (plugins/drf.py:58-78).
- proportion (reclaim): victim's queue stays >= deserved after the
  cumulative eviction, with the allocated.less(resreq) skip guard; the
  guard is sequential-by-nature, so the kernel detects any guard trip per
  node and the action falls back to an exact host scan for that node
  (plugins/proportion.py:105-124) — exactness over speed on that path.
- validation: victims' total NOT strictly-less than the request in every
  dimension (preempt.go:355-370 — note: Less, not LessEqual).
- eviction order and the cumulative early-stop rule
  (`resreq.less_equal(victim.resreq)`, preempt.go:317-334) replay ON THE
  HOST in float64, through the real Statement/session mutators — the
  kernel picks the first validating node and hands back its victim mask;
  the host walks it in candidate order, stopping exactly where the
  reference would (and handling reclaim's per-evict failure `continue`).
  Evictions on a validating-but-not-covering node PERSIST and the walk
  continues (preempt.go:340-350) — the action re-dispatches with a
  `visited` mask, since the partial evictions changed the very state the
  victim masks derive from.

Wave dispatch (default; KUBEBATCH_VICTIM_WAVE=0 for per-visit): the
analysis — NOT the node choice — runs vmapped over a whole chunk of
pending preemptors in ONE dispatch, returning per-lane (pickable-node
mask, guard mask, victims over ALL nodes). The host then chooses nodes
in fresh score order per visit, consuming cached lanes directly;
mutation events (replayed evictions/pipelines) are folded into per-node
shrink/grow dirty sets, and only a visit whose best candidate node is
dirty pays a single-lane re-dispatch. The monotonicity that makes this
exact: evictions/pipelines only shrink a node's analysis unless the
touched job/queue has running tasks there (the grow sets), and node
scores change only on pipelined nodes (downward for least-requested;
the chooser recomputes fresh scores host-side with the same float32
math either way). Dispatches therefore scale with replay CONFLICTS, not
preemptor or visit count — preempt at many pending preemptors runs in a
handful of kernel calls, which is what lets the analysis ride a
high-latency accelerator link (reclaim's proportion math moves
queue-wide state per eviction, so its waves degrade gracefully to
per-visit counts).

Device placement: KUBEBATCH_VICTIM_DEVICE selects where the kernels
run: "auto" (default — the platform-default device when an accelerator
is attached and its MEASURED dispatch+readback round trip is under
KUBEBATCH_VICTIM_RTT_MAX_MS [4 ms]; the host-process XLA CPU backend
otherwise), "cpu", or "default" (force the platform default). With
wave dispatch the accelerator pays per-WAVE round trips, not per-visit
ones, and wave size auto-tunes to the pending set
(KUBEBATCH_VICTIM_WAVE_SIZE overrides).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import TaskInfo, TaskStatus, ready_statuses
from ..util import env_on
from ..metrics import count_blocking_readback
from ..obs import span as _span
from ..api.resource import RESOURCE_DIM
from .solver import dynamic_node_score
from .telemetry import ENGINE_VICTIM_VISIT, ENGINE_VICTIM_WAVE, host_frame
from .tensorize import (VEC_EPS, _intern_paths, accumulate_nz, load_kb_pack,
                        nz_request_vec, pad_to_bucket)
from ..api.resource import VEC_SCALE

_IMAX = jnp.iinfo(jnp.int32).max
_READY = None

#: extraction paths for the native packer (VictimState's node-task walk)
_RES_PATHS = _intern_paths(
    ("resreq", "milli_cpu"), ("resreq", "memory"), ("resreq", "milli_gpu"))


_CRIT_CONSTS = None


def _pod_critical(pod) -> bool:
    """conformance's never-evict rule, memoized on the pod (spec fields
    are immutable for the pod's lifetime; runs per victim row per
    action)."""
    global _CRIT_CONSTS
    crit = getattr(pod, "_kb_crit", None)
    if crit is None:
        if _CRIT_CONSTS is None:
            from ..plugins.conformance import (NAMESPACE_SYSTEM,
                                               SYSTEM_CLUSTER_CRITICAL,
                                               SYSTEM_NODE_CRITICAL)
            _CRIT_CONSTS = ((SYSTEM_CLUSTER_CRITICAL, SYSTEM_NODE_CRITICAL),
                            NAMESPACE_SYSTEM)
        classes, ns_system = _CRIT_CONSTS
        crit = (pod.priority_class_name in classes
                or pod.namespace == ns_system)
        pod._kb_crit = crit
    return crit


def _ready_statuses():
    global _READY
    if _READY is None:
        _READY = tuple(ready_statuses())
    return _READY


#: memoized device->host round-trip time of the default backend (s)
_LINK_RTT: Optional[float] = None

#: above this RTT the accelerator loses to host XLA for victim analysis:
#: an action runs ~4-15 wave dispatches with blocking readbacks, so at
#: 4 ms+ the link alone exceeds the whole host-side analysis (~30-50 ms);
#: co-located hardware measures sub-ms and rides the accelerator
_LINK_RTT_MAX = float(os.environ.get("KUBEBATCH_VICTIM_RTT_MAX_MS",
                                     "4.0")) * 1e-3


def _link_rtt() -> float:
    """One-time probe of the default device's dispatch+readback latency
    (measured, not assumed: a tunneled chip can sit ~75 ms away while a
    co-located one answers in microseconds)."""
    global _LINK_RTT
    if _LINK_RTT is None:
        dev = jax.devices()[0]
        x = jax.device_put(np.zeros(8, np.float32), dev)
        np.asarray(x)                      # warm the path
        with _span("link_rtt_probe", cat="probe") as sp:
            for _ in range(3):
                np.asarray(jax.device_put(np.zeros(8, np.float32), dev))
        _LINK_RTT = sp.dur / 3
    return _LINK_RTT


def _device():
    """Where the visit kernels run (see module docstring).

    "auto" (default): the platform-default device when an accelerator is
    attached AND its measured round trip is fast enough for per-wave
    readbacks (wave dispatch amortizes round trips per WAVE, but a
    high-latency link still loses to host XLA); the host-process XLA CPU
    backend otherwise. "cpu"/"default" force either side."""
    mode = os.environ.get("KUBEBATCH_VICTIM_DEVICE", "auto")
    if mode == "default":
        return None
    if (mode == "auto" and jax.default_backend() != "cpu"
            and _link_rtt() < _LINK_RTT_MAX):
        return None
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # pragma: no cover — cpu backend always exists
        return None


# ---------------------------------------------------------------------
# in-kernel helpers
# ---------------------------------------------------------------------

def _le_eps(a, b, eps):
    """Resource.less_equal elementwise: (a < b) | (|b - a| < eps)."""
    return (a < b) | (jnp.abs(b - a) < eps)


def _share3(vec, total):
    """share() per dimension: x/0 -> 1, 0/0 -> 0; returns max over dims."""
    s = jnp.where(total == 0.0,
                  jnp.where(vec == 0.0, 0.0, 1.0),
                  vec / jnp.where(total == 0.0, 1.0, total))
    return jnp.max(s, axis=-1)


def _seg_excl_cumsum(values, head):
    """Exclusive cumulative sum within segments. ``head[i]`` flags the
    first row of row i's segment; rows of one segment are contiguous."""
    flag = head
    if values.ndim == 2:
        flag = head[:, None]

    def comb(a, b):
        sa, fa = a
        sb, fb = b
        return jnp.where(fb, sb, sa + sb), fa | fb

    sums, _ = jax.lax.associative_scan(comb, (values, flag))
    return sums - values


def _seg_any(mask, seg, num):
    return jax.ops.segment_max(mask.astype(jnp.int32), seg,
                               num_segments=num) > 0


# ---------------------------------------------------------------------
# the visit kernel
# ---------------------------------------------------------------------

def _analysis_core(
        # preemptor
        p_res, p_resreq, p_nz, p_score, p_pred, p_job, p_queue,
        # node state
        node_ok, n_tasks, max_task_num, nz_req, allocatable_cm, host_rank,
        # victim arrays (rows sorted by (node, candidate order))
        v_node, v_job, v_res, v_critical, v_live,
        perm_nj, nj_head, perm_nq, nq_head,
        # job / queue state
        ready_cnt, min_av, j_alloc, job_queue, q_alloc, q_deserved,
        q_prop_ok, cluster_total, dyn_weights,
        # static config
        tiers: Tuple[Tuple[str, ...], ...], veto_critical: bool,
        filter_kind: str, dyn_enabled: bool, score_nodes: bool,
        room_check: bool):
    """The node-visit ANALYSIS for one preemptor/reclaimer task, without
    the node choice: (pick0[N], guard_n[N], victims[V]) — pick0 flags
    nodes where the tiered victim set validates (or the proportion guard
    tripped), before the caller's visited mask; victims holds the chosen
    victim rows for EVERY node at once (rows are node-segmented)."""
    eps = jnp.asarray(VEC_EPS)
    n_pad = node_ok.shape[0]
    v_pad = v_node.shape[0]
    known_job = v_job >= 0

    # ---- candidate filter (host task_filter semantics) ----------------
    if filter_kind == "inter_queue":       # preempt phase 1
        cand = (v_live & known_job
                & (job_queue[jnp.maximum(v_job, 0)] == p_queue)
                & (v_job != p_job))
    elif filter_kind == "intra_job":       # preempt phase 2
        cand = v_live & known_job & (v_job == p_job)
    else:                                  # reclaim: other queues only
        cand = (v_live & known_job
                & (job_queue[jnp.maximum(v_job, 0)] != p_queue))

    # ---- plugin verdict masks -----------------------------------------
    vj = jnp.maximum(v_job, 0)
    gang_ok = ((ready_cnt[vj] - 1 >= min_av[vj]) | (min_av[vj] == 1)) \
        & known_job
    conf_ok = ~v_critical

    drf_ok = jnp.zeros(v_pad, bool)
    if any("drf" in t for t in tiers):
        # cumulative per (node, job) in candidate order: drf decrements its
        # working allocation for EVERY candidate of the job, accepted or not
        vals = jnp.where(cand[:, None], v_res, 0.0)[perm_nj]
        excl = _seg_excl_cumsum(vals, nj_head)
        cum_incl = jnp.zeros_like(vals).at[perm_nj].set(
            excl + jnp.where(cand[:, None], v_res, 0.0)[perm_nj])
        rs = _share3(j_alloc[vj] - cum_incl, cluster_total[None, :])
        ls = _share3((j_alloc[jnp.maximum(p_job, 0)] + p_resreq)[None, :],
                     cluster_total[None, :])[0]
        drf_ok = ((ls < rs) | (jnp.abs(ls - rs) <= 1e-6)) & known_job

    prop_ok = jnp.zeros(v_pad, bool)
    prop_guard_v = jnp.zeros(v_pad, bool)
    if any("proportion" in t for t in tiers):
        vq = job_queue[vj]
        p_elig = cand & q_prop_ok[jnp.maximum(vq, 0)] & (vq >= 0)
        vals = jnp.where(p_elig[:, None], v_res, 0.0)[perm_nq]
        excl_s = _seg_excl_cumsum(vals, nq_head)
        excl = jnp.zeros_like(vals).at[perm_nq].set(excl_s)
        before = q_alloc[jnp.maximum(vq, 0)] - excl
        after = before - v_res
        prop_ok = p_elig & jnp.all(_le_eps(q_deserved[jnp.maximum(vq, 0)],
                                           after, eps), axis=-1)
        # the reference SKIPS (without decrementing) a candidate whose
        # queue allocation is strictly below its request in every dim —
        # sequential semantics the cumsum can't express; flag per node
        prop_guard_v = p_elig & jnp.all(before < v_res, axis=-1)

    masks = {"gang": gang_ok, "conformance": conf_ok, "drf": drf_ok,
             "proportion": prop_ok}

    # ---- tier selection: first tier with a non-empty set per node -----
    chosen = jnp.zeros(v_pad, bool)
    taken_n = jnp.zeros(n_pad, bool)
    for tier in tiers:
        tier_mask = cand
        for name in tier:
            tier_mask = tier_mask & masks[name]
        any_n = _seg_any(tier_mask, v_node, n_pad)
        use_n = any_n & ~taken_n
        chosen = chosen | (tier_mask & use_n[v_node])
        taken_n = taken_n | any_n
    victims = chosen & conf_ok if veto_critical else chosen

    # ---- validation: total not strictly-less in every dim -------------
    vic_res = jnp.where(victims[:, None], v_res, 0.0)
    tot_n = jax.ops.segment_sum(vic_res, v_node, num_segments=n_pad)
    any_v_n = _seg_any(victims, v_node, n_pad)
    valid_n = any_v_n & ~jnp.all(tot_n < p_res[None, :], axis=-1)

    # ---- node pickability ---------------------------------------------
    base0 = node_ok & p_pred
    if room_check:
        base0 = base0 & (n_tasks < max_task_num)
    # a node where the proportion skip-guard tripped has an UNKNOWN victim
    # set (the guard is sequential); it must be offered to the host for
    # exact evaluation, never silently skipped
    guard_n = _seg_any(prop_guard_v, v_node, n_pad)
    pick0 = base0 & (valid_n | guard_n)
    return pick0, guard_n, victims


def _visit_core(p_res, p_resreq, p_nz, p_sig, sig_scores, sig_pred,
                p_job, p_queue,
                visited,
                node_ok, n_tasks, max_task_num, nz_req, allocatable_cm,
                host_rank, v_node, v_job, v_res, v_critical, v_live,
                perm_nj, nj_head, perm_nq, nq_head,
                ready_cnt, min_av, j_alloc, job_queue, q_alloc, q_deserved,
                q_prop_ok, cluster_total, dyn_weights,
                tiers: Tuple[Tuple[str, ...], ...], veto_critical: bool,
                filter_kind: str, dyn_enabled: bool, score_nodes: bool,
                room_check: bool):
    """Analysis + in-kernel node choice (the per-visit dispatch mode).

    ``sig_scores``/``sig_pred`` are the whole [S, N] static-term
    matrices (device-resident across the action); the visit's rows are
    gathered in-kernel from ``p_sig`` — shipping an index per dispatch
    instead of two [N] rows was worth ~1 ms/visit of host->device
    conversion on the steady path.

    Returns ONE packed int32 buffer [4+V]:
    [found, node_idx, victims_count, prop_guard, victims_mask[V]...] —
    a single blocking readback per visit (each device->host transfer
    pays the full tunnel RTT).
    """
    # the [S, N] score matrix may be stored narrow (kernels/narrow.py,
    # engaged by host_sig_arrays at big node counts); every consumer —
    # the dyn add and the choice lexsort — runs f32 (the accumulation
    # seam), and the upcast is exact for the integer-valued plugin
    # scores, so choices are identical to the f32 store. No-op on f32.
    p_score = sig_scores[p_sig].astype(jnp.float32)
    p_pred = sig_pred[p_sig]
    pick0, guard_n, victims = _analysis_core(
        p_res, p_resreq, p_nz, p_score, p_pred, p_job, p_queue,
        node_ok, n_tasks, max_task_num, nz_req, allocatable_cm, host_rank,
        v_node, v_job, v_res, v_critical, v_live,
        perm_nj, nj_head, perm_nq, nq_head,
        ready_cnt, min_av, j_alloc, job_queue, q_alloc, q_deserved,
        q_prop_ok, cluster_total, dyn_weights,
        tiers=tiers, veto_critical=veto_critical, filter_kind=filter_kind,
        dyn_enabled=dyn_enabled, score_nodes=score_nodes,
        room_check=room_check)
    pick_n = pick0 & ~visited
    if score_nodes:
        score = p_score
        if dyn_enabled:
            score = score + dynamic_node_score(nz_req, p_nz,
                                               allocatable_cm, dyn_weights)
        perm = jnp.lexsort([host_rank, -score])
    else:
        perm = jnp.lexsort([host_rank])
    m = pick_n[perm]
    found = jnp.any(m)
    node = perm[jnp.argmax(m)].astype(jnp.int32)

    # ONE packed int32 result buffer — every blocking device->host read
    # pays the full tunnel RTT, so the five logical outputs ship as one
    # transfer (same discipline as batched._pack_result):
    # [found, node, count, guard, mask[V]...]
    mask = victims & (v_node == node)
    head = jnp.stack([found.astype(jnp.int32), node,
                      jnp.sum(mask).astype(jnp.int32),
                      guard_n[node].astype(jnp.int32)])
    return jnp.concatenate([head, mask.astype(jnp.int32)])


from ..compilesvc import instrument as _cs_instrument
from ..compilesvc import register_provider as _cs_register_provider

_visit_kernel = _cs_instrument("victims", "_visit_kernel", partial(
    jax.jit, static_argnames=(
        "tiers", "veto_critical", "filter_kind", "dyn_enabled",
        "score_nodes", "room_check"))(_visit_core))


@partial(jax.jit, static_argnames=("tiers", "veto_critical", "filter_kind",
                                   "dyn_enabled", "score_nodes",
                                   "room_check"))
def _wave_kernel(p_res, p_resreq, p_nz, p_sig, sig_scores, sig_pred,
                 p_job, p_queue,
                 *shared,
                 tiers: Tuple[Tuple[str, ...], ...], veto_critical: bool,
                 filter_kind: str, dyn_enabled: bool, score_nodes: bool,
                 room_check: bool):
    """A WAVE of node-visit ANALYSES — _analysis_core vmapped over the
    preemptor axis, one dispatch (and one readback) for a whole chunk of
    pending preemptors. Node CHOICE happens host-side per consumption
    (VictimSolver._choose), so consuming a node, growing the visited
    mask, or another preemptor touching an unrelated node costs no
    re-dispatch. Lanes carry sig INDICES; the [S, N] matrices stay
    device-resident (see _visit_core)."""

    def one(a, b, c, sig, f, g):
        # f32 seam for a possibly-narrow score store (see _visit_core)
        return _analysis_core(a, b, c,
                              sig_scores[sig].astype(jnp.float32),
                              sig_pred[sig], f, g, *shared,
                              tiers=tiers, veto_critical=veto_critical,
                              filter_kind=filter_kind,
                              dyn_enabled=dyn_enabled,
                              score_nodes=score_nodes,
                              room_check=room_check)

    pick, guard, victims = jax.vmap(one)(p_res, p_resreq, p_nz, p_sig,
                                         p_job, p_queue)
    # one packed bool buffer per wave (columns [pick | guard | victims]);
    # the host slices it — one readback instead of three
    return jnp.concatenate([pick, guard, victims], axis=1)


_wave_kernel = _cs_instrument("victims", "_wave_kernel", _wave_kernel)


def _shared_args(static, mut):
    """The interleaved shared-arg tail of both kernels — the ONE place
    the order is written down, shared by the local dispatches, the rpc
    sidecar's server-side execution (rpc/victims_wire.py), and the
    compilesvc signature provider."""
    return (static[0], mut[0], static[1], mut[1],
            static[2], static[3],
            static[4], static[5], static[6], static[7],
            mut[2],
            static[8], static[9], static[10], static[11],
            mut[3], static[12], mut[4], static[13],
            mut[5], static[14], static[15], static[16], static[17])


def wave_kernel_args(static, mut, sig, p_res, p_resreq, p_nz, p_sig,
                     p_job, p_queue):
    """The wave kernel's full positional tuple."""
    sig_scores, sig_pred = sig
    return (p_res, p_resreq, p_nz, p_sig, sig_scores, sig_pred,
            p_job, p_queue) + _shared_args(static, mut)


def visit_kernel_args(static, mut, sig, p_res, p_resreq, p_nz, p_sig,
                      p_job, p_queue, visited):
    """The single-lane visit kernel's full positional tuple."""
    sig_scores, sig_pred = sig
    return (p_res, p_resreq, p_nz, p_sig, sig_scores, sig_pred,
            p_job, p_queue, visited) + _shared_args(static, mut)


def run_wave_kernel(static, mut, sig, p_res, p_resreq, p_nz, p_sig,
                    p_job, p_queue, *, tiers, veto_critical, filter_kind,
                    dyn_enabled, score_nodes, room_check):
    """Invoke the wave kernel from the (static, mutable, sig) tuples of
    VictimSolver._upload."""
    return _wave_kernel(
        *wave_kernel_args(static, mut, sig, p_res, p_resreq, p_nz, p_sig,
                          p_job, p_queue),
        tiers=tiers, veto_critical=veto_critical,
        filter_kind=filter_kind, dyn_enabled=dyn_enabled,
        score_nodes=score_nodes, room_check=room_check)


def run_visit_kernel(static, mut, sig, p_res, p_resreq, p_nz, p_sig,
                     p_job, p_queue, visited, *, tiers, veto_critical,
                     filter_kind, dyn_enabled, score_nodes, room_check):
    """Single-lane twin of run_wave_kernel (kernels' _visit_kernel)."""
    return _visit_kernel(
        *visit_kernel_args(static, mut, sig, p_res, p_resreq, p_nz, p_sig,
                           p_job, p_queue, visited),
        tiers=tiers, veto_critical=veto_critical,
        filter_kind=filter_kind, dyn_enabled=dyn_enabled,
        score_nodes=score_nodes, room_check=room_check)


# ---------------------------------------------------------------------
# host-side state
# ---------------------------------------------------------------------

@dataclass
class _Victim:
    task: TaskInfo          # the node's copy (clone at evict time)
    node_idx: int
    job_idx: int


class _NodeSegment:
    """Per-node victim-row material persisted across cycles: the RUNNING
    task subset (insertion order) with its packed resources/criticality,
    plus the whole-node nonzero-request sum and task count."""
    __slots__ = ("run_tasks", "run_res", "run_crit", "nz", "n_tasks")

    def __init__(self, node):
        running = TaskStatus.RUNNING
        tasks = list(node.tasks.values())
        run = [t for t in tasks if t.status == running]
        self.run_tasks = run
        k = len(run)
        res = np.empty((k, RESOURCE_DIM), np.float64)
        if k:
            pack = load_kb_pack()
            if pack is not None:
                pack.extract_f64(run, _RES_PATHS, res)
            else:
                for i, t in enumerate(run):
                    rr = t.resreq
                    res[i] = (rr.milli_cpu, rr.memory, rr.milli_gpu)
        self.run_res = (res * VEC_SCALE).astype(np.float32)
        # backfill tenants are lent capacity: never criticality-shielded
        # from eviction (backfill-over-reserved reclaim depends on it)
        self.run_crit = np.fromiter(
            (_pod_critical(t.pod) and not t.is_backfill for t in run),
            bool, count=k)
        self.nz = accumulate_nz(tasks, [0] * len(tasks), 1)[0]
        self.n_tasks = len(tasks)


def _build_segments(pairs) -> Dict[str, _NodeSegment]:
    """Bulk _NodeSegment construction for a large refresh set (cold
    builds, node-set changes): ONE native extract + ONE nonzero
    accumulation over every task of the given nodes — the old full-build
    fast path — sliced back into per-node segments."""
    running = TaskStatus.RUNNING
    flat: List[TaskInfo] = []
    rows: List[int] = []
    per_node: List[List[TaskInfo]] = []
    for j, (_, node) in enumerate(pairs):
        ts = list(node.tasks.values())
        per_node.append(ts)
        flat.extend(ts)
        rows.extend([j] * len(ts))
    nz = accumulate_nz(flat, rows, max(1, len(pairs)))
    n_flat = len(flat)
    res_flat = np.empty((n_flat, RESOURCE_DIM), np.float64)
    if flat:
        pack = load_kb_pack()
        if pack is not None:
            pack.extract_f64(flat, _RES_PATHS, res_flat)
        else:
            for i, t in enumerate(flat):
                rr = t.resreq
                res_flat[i] = (rr.milli_cpu, rr.memory, rr.milli_gpu)
    res32 = (res_flat * VEC_SCALE).astype(np.float32)
    # single flat passes + per-node array splits instead of per-node
    # comprehensions (this runs for ~500 dirty nodes per steady-skew
    # cycle; the per-node Python overhead WAS the segrefresh phase)
    run_mask = np.fromiter((t.status == running for t in flat), bool,
                           count=n_flat)
    run_pos = np.flatnonzero(run_mask)
    run_tasks_flat = [flat[x] for x in run_pos]
    # same backfill exemption as _NodeSegment.__init__: lent capacity
    # is always evictable
    crit_flat = np.fromiter(
        (_pod_critical(t.pod) and not t.is_backfill
         for t in run_tasks_flat), bool,
        count=len(run_tasks_flat))
    res_run = res32[run_pos]
    run_counts = np.bincount(np.asarray(rows, np.int64)[run_pos],
                             minlength=len(pairs))
    bounds = np.cumsum(run_counts)[:-1]
    res_split = np.split(res_run, bounds)
    crit_split = np.split(crit_flat, bounds)
    segs: Dict[str, _NodeSegment] = {}
    base = 0
    for j, (name, _) in enumerate(pairs):
        seg = _NodeSegment.__new__(_NodeSegment)
        k = int(run_counts[j])
        seg.run_tasks = run_tasks_flat[base:base + k]
        seg.run_res = res_split[j]
        seg.run_crit = crit_split[j]
        seg.nz = nz[j]
        seg.n_tasks = len(per_node[j])
        segs[name] = seg
        base += k
    return segs


class SegmentStore:
    """Cache-owned cross-cycle store of victim-row material, keyed by
    node name; the cache migrates dirty marks into _vic_refresh /
    _vicjob_refresh at snapshot time and folds session-touched entities
    in at adoption, exactly like the DeviceSession discipline (cache.py).

    Beyond the per-node ``_NodeSegment``s (``nz_mat``/``cnt`` mirror
    their whole-node aggregates in node-column order), the store
    persists the ASSEMBLED index spaces so a steady-state VictimState
    build is O(churn) instead of O(cluster):

    - **row space**: big parallel victim arrays (v_node/v_job/v_res/
      v_crit/v_live + the aligned ``row_tasks`` list) where each node
      owns a fixed slot ``[off, off+cap)`` holding its RUNNING tasks in
      insertion order (dead tail rows have live=False, so within-node
      eviction order matches a fresh build exactly). Refreshing a node
      rewrites only its slot; a slot that outgrows its capacity
      relocates to the tail, and the space compacts when dead capacity
      dominates. Row position across nodes is NOT semantic: the kernels
      order by (node, job) lexsort and consume masks per node.
    - **job space**: a grow-only uid -> row assignment with parallel
      ready_cnt/min_av/j_alloc/job_queue arrays refreshed only for
      dirty jobs. Rows of jobs absent from the current session keep
      their assignment (presence is the ``j_present`` mask, folded into
      the session's effective v_live) so validate-dropped jobs can
      return; the space compacts — rows densely reassigned and v_job
      remapped — when the assignment outgrows the live set. Dirty
      marks for absent jobs are carried in ``job_marks_pending`` until
      the job is seen again.
    """
    __slots__ = ("segs", "col_names", "nz_mat", "cnt",
                 "slot_of", "row_tasks", "v_node", "v_job", "v_res",
                 "v_crit", "v_live", "rows_used", "dead_cap",
                 "job_rows", "j_present", "ready_cnt",
                 "min_av", "j_alloc", "job_queue", "q_ids",
                 "present_uids", "job_marks_pending", "orphan_uids",
                 "host_rank", "host_rank_epoch")

    def __init__(self):
        self.segs: Dict[str, _NodeSegment] = {}
        self.col_names: Optional[List[str]] = None
        self.nz_mat: Optional[np.ndarray] = None
        self.cnt: Optional[np.ndarray] = None
        # row space
        self.slot_of: Dict[str, tuple] = {}
        self.row_tasks: List[Optional[TaskInfo]] = []
        self.v_node = np.zeros(0, np.int32)
        self.v_job = np.zeros(0, np.int32)
        self.v_res = np.zeros((0, RESOURCE_DIM), np.float32)
        self.v_crit = np.zeros(0, bool)
        self.v_live = np.zeros(0, bool)
        self.rows_used = 0
        self.dead_cap = 0
        # job space
        self.job_rows: Dict[str, int] = {}
        self.host_rank: Optional[np.ndarray] = None
        self.host_rank_epoch = None
        self.j_present: Optional[np.ndarray] = None
        self.ready_cnt: Optional[np.ndarray] = None
        self.min_av: Optional[np.ndarray] = None
        self.j_alloc: Optional[np.ndarray] = None
        self.job_queue: Optional[np.ndarray] = None
        self.q_ids: Optional[List[str]] = None
        self.present_uids: set = set()
        self.job_marks_pending: set = set()
        #: job uids some stored row references as v_job=-1 (no assignment
        #: existed at slot-write time). When such a uid finally gets a
        #: row, its tasks' nodes are forced into the refresh set so the
        #: stale -1 references repair — a job's return to the session
        #: dirties no node by itself.
        self.orphan_uids: set = set()

    def _ensure_row_cap(self, need: int) -> None:
        cap = len(self.v_node)
        if need <= cap:
            return
        new = pad_to_bucket(max(need, cap + (cap >> 1)), 64)
        grow = new - cap
        self.v_node = np.concatenate([self.v_node,
                                      np.zeros(grow, np.int32)])
        self.v_job = np.concatenate([self.v_job,
                                     np.full(grow, -1, np.int32)])
        self.v_res = np.concatenate(
            [self.v_res, np.zeros((grow, RESOURCE_DIM), np.float32)])
        self.v_crit = np.concatenate([self.v_crit, np.zeros(grow, bool)])
        self.v_live = np.concatenate([self.v_live, np.zeros(grow, bool)])
        self.row_tasks.extend([None] * grow)

    def _clear_rows(self) -> None:
        self.slot_of = {}
        self.rows_used = 0
        self.dead_cap = 0
        self.v_live[:] = False
        tasks = self.row_tasks
        for i in range(len(tasks)):
            tasks[i] = None

    def _ensure_job_cap(self, need: int) -> None:
        if self.ready_cnt is None:
            cap = pad_to_bucket(max(1, need), 4)
            self.ready_cnt = np.zeros(cap, np.int32)
            self.min_av = np.zeros(cap, np.int32)
            self.j_alloc = np.zeros((cap, RESOURCE_DIM), np.float32)
            self.job_queue = np.full(cap, -1, np.int32)
            self.j_present = np.zeros(cap, bool)
            return
        cap = len(self.ready_cnt)
        if need <= cap:
            return
        new = pad_to_bucket(max(need, cap * 2), 4)
        grow = new - cap
        self.ready_cnt = np.concatenate([self.ready_cnt,
                                         np.zeros(grow, np.int32)])
        self.min_av = np.concatenate([self.min_av,
                                      np.zeros(grow, np.int32)])
        self.j_alloc = np.concatenate(
            [self.j_alloc, np.zeros((grow, RESOURCE_DIM), np.float32)])
        self.job_queue = np.concatenate([self.job_queue,
                                         np.full(grow, -1, np.int32)])
        self.j_present = np.concatenate([self.j_present,
                                         np.zeros(grow, bool)])


def _segment_store(ssn):
    """(SegmentStore, node-refresh, job-refresh) for this build.
    Incremental caches persist the store with the same consume-at-
    handout / re-adopt-under-epoch-check discipline as the
    DeviceSession: the first build of a session takes the store OFF the
    cache (a mid-session cluster-wide invalidation or a refused
    adoption must not leave a stale store behind), later builds in the
    same session reuse it via the session (refresh = the grown touched
    sets), and cache.adopt_snapshot puts it back if the session's epoch
    still matches. Fake/non-incremental caches get a throwaway store,
    i.e. a plain fresh build."""
    store = getattr(ssn, "_victim_store", None)
    if store is not None:
        return store, set(ssn.touched_nodes), set(ssn.touched_jobs)
    cache = getattr(ssn, "cache", None)
    if cache is None or not getattr(cache, "_incremental", False) \
            or not hasattr(cache, "victim_segments"):
        return SegmentStore(), set(), set()
    with cache._lock:
        store = cache.victim_segments
        cache.victim_segments = None      # consumed; re-adopted at close
        refresh = set(cache._vic_refresh)
        cache._vic_refresh.clear()
        job_refresh = set(cache._vicjob_refresh)
        cache._vicjob_refresh.clear()
    if store is None:
        store = SegmentStore()
    ssn._victim_store = store
    return (store, refresh | ssn.touched_nodes,
            job_refresh | ssn.touched_jobs)


class _VictimRows:
    """Lazy row view over the VictimState's parallel victim arrays —
    indexing materializes a _Victim for just that row. ``tasks`` is the
    store's slot-aligned list (dead slots hold None); ``live`` is the
    session's live-row count, which drives truthiness (the SKIP_ACTION
    check: no live victim row means no victim can exist)."""
    __slots__ = ("_state", "tasks", "live")

    def __init__(self, state, tasks, live: int):
        self._state = state
        self.tasks = tasks
        self.live = live

    def __len__(self):
        return self.live

    def __bool__(self):
        return self.live > 0

    def __getitem__(self, row: int) -> _Victim:
        # v_node/v_job are PADDED arrays — plain indexing would pair a
        # real task with pad-row data on negative indices; dead slots
        # hold no task
        st = self._state
        if not 0 <= row < len(st.v_node):
            raise IndexError(row)
        task = self.tasks[row]
        if task is None:
            raise IndexError(row)
        return _Victim(task, int(st.v_node[row]), int(st.v_job[row]))


class VictimState:
    """Host mirror of the mutable state the visit kernel reads, plus the
    static victim/job/queue index spaces for one preempt/reclaim action.

    The action applies every session mutation (stmt.evict / stmt.pipeline
    / direct ssn.evict+pipeline) through apply_* so the mirrors track the
    host truth; Statement.discard is mirrored by the inverse methods.
    """

    def __init__(self, ssn, node_index: Dict[str, int], n_pad: int,
                 node_ok: np.ndarray, max_task_num: np.ndarray,
                 allocatable_cm: np.ndarray):
        self.node_index = node_index
        self.n_pad = n_pad
        from ..obs import now as _obs_now
        _t = _obs_now if os.environ.get("KB_VICTIM_TIMING") else None
        _m = [] if _t else None
        if _t:
            _m.append(("start", _t()))
        # mutable node mirrors + victim-row material, assembled from the
        # cache's persistent SegmentStore: only nodes/jobs the cache
        # dirtied or the session touched recompute from HOST truth, and
        # the assembled row/job index spaces persist too — the full
        # 10k-row re-assembly this build used to pay every
        # preempt/reclaim action now costs O(churn) in the steady
        # regime.
        store, refresh, job_refresh = _segment_store(ssn)
        segs = store.segs
        nodes_map = ssn.nodes
        if (store.col_names is not None
                and len(store.col_names) == len(nodes_map)
                and all(n in nodes_map for n in store.col_names)):
            # node set unchanged: the store's column order IS the index
            # order — skip the per-build sort of 5k (name, node) pairs
            names = store.col_names
        else:
            ordered = sorted(nodes_map.items(),
                             key=lambda kv: node_index.get(kv[0], 0))
            names = [name for name, _ in ordered if name in node_index]
        rows_reset = False
        if (store.col_names != names or store.nz_mat is None
                or store.nz_mat.shape[0] != n_pad
                or len(segs) < len(names)):
            # node set / order / padding changed: aggregates restart
            store.col_names = names
            store.nz_mat = np.zeros((n_pad, 2), np.float32)
            store.cnt = np.zeros(n_pad, np.int32)
            refresh = set(names)
            rows_reset = True
            # pin the invariant the fast path above relies on: column
            # order == node_index order (NodeState.from_nodes sorts by
            # name; if that ever changes, this catches it at reset time
            # instead of silently misplacing cached aggregate rows).
            # A real raise, not assert — it must survive python -O.
            if any(node_index.get(nm) != i
                   for i, nm in enumerate(names)):
                raise RuntimeError(
                    "segment column order diverged from the node index")
        nz_mat, cnt = store.nz_mat, store.cnt

        if _t:
            _m.append(("jobspace", _t()))
        # ---- job index space (persistent, grow-only) ------------------
        self.queue_ids = sorted(ssn.queues)
        self.q_index = {q: i for i, q in enumerate(self.queue_ids)}
        jobs_map = ssn.jobs
        job_refresh |= store.job_marks_pending
        update_all = False
        if (store.ready_cnt is None or store.q_ids != self.queue_ids
                or len(store.job_rows) > 2 * len(jobs_map) + 64):
            # fresh store / queue-set change / assignment outgrew the
            # live set: rebuild the job space densely and remap the row
            # arrays' job references (job-row NUMBERS are not semantic —
            # kernels only group by them)
            old_rows = store.job_rows
            old_cap = (len(store.ready_cnt)
                       if store.ready_cnt is not None else 0)
            store.job_rows = {uid: i for i, uid in enumerate(jobs_map)}
            store.ready_cnt = None
            store._ensure_job_cap(len(jobs_map))
            store.q_ids = list(self.queue_ids)
            store.present_uids = set()
            store.job_marks_pending = set()
            if old_cap and len(store.v_job):
                remap = np.full(old_cap + 1, -1, np.int32)
                for uid, r in old_rows.items():
                    nr = store.job_rows.get(uid)
                    if nr is not None:
                        remap[r] = nr
                vj = store.v_job
                safe = np.where((vj >= 0) & (vj < old_cap), vj, old_cap)
                store.v_job = remap[safe]
            # exact orphan recompute: live rows whose job reference is
            # now unknown (dropped assignments) need repair if the job
            # ever returns — this also prunes uids that never will
            vj = store.v_job
            orphan_rows = np.flatnonzero(store.v_live[:len(vj)]
                                         & (vj < 0))
            store.orphan_uids = {
                store.row_tasks[i].job for i in orphan_rows
                if store.row_tasks[i] is not None}
            update_all = True
        job_rows = store.job_rows
        ready = _ready_statuses()
        drf = ssn.plugins.get("drf")
        q_get = self.q_index.get

        repair_nodes: set = set()

        def _update_job(uid, job):
            r = job_rows[uid]
            store.ready_cnt[r] = job.count(*ready)
            store.min_av[r] = job.min_available
            store.job_queue[r] = q_get(job.queue, -1)
            attr = drf.job_opts.get(uid) if drf is not None else None
            if attr is not None:
                store.j_alloc[r] = attr.allocated.to_vec()
            else:
                store.j_alloc[r] = 0.0
            if uid in store.orphan_uids:
                # stored rows reference this job as v_job=-1; refresh its
                # tasks' nodes so the slots repair with the new row
                store.orphan_uids.discard(uid)
                for t in job.tasks.values():
                    if t.node_name:
                        repair_nodes.add(t.node_name)

        cur = set(jobs_map)
        if update_all:
            for uid, job in jobs_map.items():
                store.j_present[job_rows[uid]] = True
                _update_job(uid, job)
        else:
            for uid in store.present_uids - cur:
                store.j_present[job_rows[uid]] = False
            updated = set()
            for uid in cur - store.present_uids:
                # new or returning job; values of a returning row are
                # still valid unless a dirty mark is pending (handled
                # by the job_refresh pass below)
                r = job_rows.get(uid)
                if r is None:
                    r = len(job_rows)
                    store._ensure_job_cap(r + 1)
                    job_rows[uid] = r
                    _update_job(uid, jobs_map[uid])
                    updated.add(uid)
                store.j_present[r] = True
            for uid in job_refresh:
                job = jobs_map.get(uid)
                if job is not None and uid not in updated:
                    if uid not in job_rows:
                        r = len(job_rows)
                        store._ensure_job_cap(r + 1)
                        job_rows[uid] = r
                        store.j_present[r] = True
                    _update_job(uid, job)
                    updated.add(uid)
            # carry marks of stored-but-absent jobs until they return
            store.job_marks_pending = {
                u for u in job_refresh - updated if u in job_rows}
        store.present_uids = cur
        self.j_index = job_rows
        self.cluster_total = (drf.total_resource.to_vec() if drf is not None
                              else np.ones(RESOURCE_DIM, np.float32))

        if _t:
            _m.append(("segrefresh", _t()))
        # ---- segment refresh ------------------------------------------
        refresh |= repair_nodes
        if rows_reset:
            stale_names = names           # already in node-index order
        else:
            stale_names = sorted(
                (n for n in refresh if n in node_index and n in nodes_map),
                key=node_index.get)
        stale = [(n, nodes_map[n]) for n in stale_names]
        if len(stale) > 64:
            # large refresh (cold build / node-set change): one batched
            # extract instead of thousands of per-node ones
            segs.update(_build_segments(stale))
        else:
            for name, node in stale:
                segs[name] = _NodeSegment(node)
        for name, _ in stale:
            seg = segs[name]
            ni = node_index[name]
            nz_mat[ni] = seg.nz
            cnt[ni] = seg.n_tasks
        if len(segs) > len(names):
            live_names = set(names)
            for name in list(segs):
                if name not in live_names:
                    del segs[name]

        if _t:
            _m.append(("rowspace", _t()))
        # ---- row space: per-node slots, refreshed slots rewritten -----
        if rows_reset or store.dead_cap > max(64, store.rows_used // 3):
            store._clear_rows()
            row_stale = names
        else:
            row_stale = stale_names
        jr_get = job_rows.get
        tasks_l = store.row_tasks
        for name in row_stale:
            seg = segs[name]
            run = seg.run_tasks
            k = len(run)
            slot = store.slot_of.get(name)
            if slot is None or k > slot[1]:
                if slot is not None:
                    off0, cap0 = slot
                    store.v_live[off0:off0 + cap0] = False
                    for i in range(off0, off0 + cap0):
                        tasks_l[i] = None
                    store.dead_cap += cap0
                # +12.5% slack (min 1): every idle slot row is dead
                # weight EVERY kernel dispatch scans — at cfg5 shapes the
                # old 25%+2 slack pushed ~10k live rows to a 32k pow2
                # pad, 3.4x the wave kernel's row axis for nothing. A
                # node outgrowing the tighter cap just re-slots (dead_cap
                # accounting below bounds the leak)
                cap = k + max(1, k >> 3)
                off = store.rows_used
                store._ensure_row_cap(off + cap)
                tasks_l = store.row_tasks
                store.rows_used = off + cap
                store.slot_of[name] = (off, cap)
            else:
                off, cap = slot
            ni = node_index[name]
            store.v_node[off:off + cap] = ni
            store.v_live[off:off + cap] = False
            if k:
                store.v_res[off:off + k] = seg.run_res
                store.v_crit[off:off + k] = seg.run_crit
                vjs = []
                for t in run:
                    jr = jr_get(t.job, -1)
                    if jr < 0:
                        store.orphan_uids.add(t.job)
                    vjs.append(jr)
                store.v_job[off:off + k] = vjs
                store.v_live[off:off + k] = True
                for i, t in enumerate(run):
                    tasks_l[off + i] = t
            for i in range(off + k, off + cap):
                tasks_l[i] = None

        if _t:
            _m.append(("mirrors", _t()))
        # ---- node mirrors ---------------------------------------------
        self.nz_req = nz_mat.copy()
        self.n_tasks = cnt.copy()
        self.node_ok = node_ok
        self.max_task_num = max_task_num
        self.allocatable_cm = allocatable_cm
        # host visit order (ssn.nodes dict order) — stable while the node
        # set is; persist on the store instead of walking 5k nodes per
        # action build
        cached_rank = getattr(store, "host_rank", None)
        order_epoch = getattr(ssn, "node_order_epoch", None)
        if rows_reset or cached_rank is None \
                or len(cached_rank) != n_pad \
                or order_epoch is None \
                or store.host_rank_epoch != order_epoch:
            host_rank = np.full(n_pad, np.iinfo(np.int32).max, np.int32)
            for pos, name in enumerate(ssn.nodes):
                idx = node_index.get(name)
                if idx is not None:
                    host_rank[idx] = pos
            store.host_rank = host_rank
            store.host_rank_epoch = order_epoch
        self.host_rank = store.host_rank

        if _t:
            _m.append(("queues", _t()))
        # ---- queue arrays (small; rebuilt per build) ------------------
        q_pad = pad_to_bucket(max(1, len(self.queue_ids)), 4)
        self.q_alloc = np.zeros((q_pad, RESOURCE_DIM), np.float32)
        self.q_deserved = np.zeros((q_pad, RESOURCE_DIM), np.float32)
        self.q_prop_ok = np.zeros(q_pad, bool)
        prop = ssn.plugins.get("proportion")
        if prop is not None:
            for q, attr in prop.queue_opts.items():
                qi = self.q_index.get(q)
                if qi is not None:
                    self.q_alloc[qi] = attr.allocated.to_vec()
                    self.q_deserved[qi] = attr.deserved.to_vec()
                    self.q_prop_ok[qi] = True

        # ---- session views over the persistent spaces -----------------
        # Rows: read-only aliases of the store's arrays (apply_* only
        # mutates the per-session copies below); within-node insertion
        # order is preserved by the slot discipline, so eviction order
        # matches a fresh build. Effective liveness folds job presence:
        # rows of session-absent jobs are dead this cycle.
        used = store.rows_used
        # pow2 padding doubles the kernel's row axis right past each
        # boundary (20k used -> 32k pad); above 4096 pad to the next
        # 4096 multiple instead — still a handful of compile shapes per
        # store lifetime (rows_used is slot-stable between clears), at
        # <= 1/8th the padding waste
        if used <= 4096:
            v_pad = pad_to_bucket(max(1, used), 8)
        else:
            v_pad = -(-used // 4096) * 4096
        store._ensure_row_cap(v_pad)
        self.v_node = store.v_node[:v_pad]
        self.v_job = store.v_job[:v_pad]
        self.v_res = store.v_res[:v_pad]
        self.v_critical = store.v_crit[:v_pad]
        vj = self.v_job
        live = store.v_live[:v_pad] & (vj >= 0)
        np.logical_and(live, store.j_present[np.maximum(vj, 0)], out=live)
        self.v_live = live
        self.victims = _VictimRows(self, store.row_tasks,
                                   int(live.sum()))
        # per-session copies of the arrays apply_* mutates
        self.ready_cnt = store.ready_cnt.copy()
        self.min_av = store.min_av
        self.j_alloc = store.j_alloc.copy()
        self.job_queue = store.job_queue

        # orderings + segment heads (dead rows keep stale keys — they
        # contribute nothing: every kernel term masks on v_live/cand).
        # One combined int64 key + stable argsort per ordering instead of
        # a 3-key lexsort + 2-column stack: same order (stable argsort's
        # index tiebreak IS the arange key), ~half the build cost at 10k+
        # rows
        nj_key = (self.v_node.astype(np.int64) << 32) \
            + self.v_job.astype(np.int64) + (1 << 31)
        self.perm_nj = np.argsort(nj_key, kind="stable").astype(np.int32)
        njs = nj_key[self.perm_nj]
        self.nj_head = np.ones(v_pad, bool)
        self.nj_head[1:] = njs[1:] != njs[:-1]
        vq = np.where(self.v_job >= 0,
                      self.job_queue[np.maximum(self.v_job, 0)], -1)
        nq_key = (self.v_node.astype(np.int64) << 32) \
            + vq.astype(np.int64) + (1 << 31)
        self.perm_nq = np.argsort(nq_key, kind="stable").astype(np.int32)
        nqs = nq_key[self.perm_nq]
        self.nq_head = np.ones(v_pad, bool)
        self.nq_head[1:] = nqs[1:] != nqs[:-1]

        self._row_of: Optional[Dict[str, int]] = None
        if _t:
            _m.append(("end", _t()))
            import sys as _sys
            spans = " ".join(
                f"{lbl}={1e3 * (t1 - t0):.2f}ms"
                for (lbl, t0), (_, t1) in zip(_m, _m[1:]))
            print(f"victimstate: {spans}", file=_sys.stderr)

        #: mutation event log for the wave cache's fine-grained
        #: invalidation (VictimSolver.visit): ("evict", row, node, job),
        #: ("pipeline", node, job, queue), ("rollback",)
        self.events: List[tuple] = []
        self._job_nodes_memo: Dict[int, frozenset] = {}
        self._queue_nodes_memo: Dict[int, frozenset] = {}

    @property
    def row_of(self) -> Dict[str, int]:
        """task.uid -> victim row (host replay bookkeeping), built on
        first use — most actions never consult it."""
        if self._row_of is None:
            self._row_of = {t.uid: i
                            for i, t in enumerate(self.victims.tasks)
                            if t is not None}
        return self._row_of

    def job_nodes(self, ji: int) -> frozenset:
        """Node columns hosting running tasks of job row ji (victim rows
        are static for the action, so memoized)."""
        got = self._job_nodes_memo.get(ji)
        if got is None:
            got = self._job_nodes_memo[ji] = frozenset(
                int(n) for n in self.v_node[self.v_job == ji])
        return got

    def queue_nodes(self, qi: int) -> frozenset:
        got = self._queue_nodes_memo.get(qi)
        if got is None:
            jq = self.job_queue[np.maximum(self.v_job, 0)]
            sel = (self.v_job >= 0) & (jq == qi)
            got = self._queue_nodes_memo[qi] = frozenset(
                int(n) for n in self.v_node[sel])
        return got

    # ---- mutation mirrors (called alongside session mutations) --------
    #: bumped by every apply_*; VictimSolver re-uploads mutable arrays only
    #: when it changed (most visits mutate nothing). Set in __init__ via
    #: the class default.
    version = 0

    def _job_row(self, job_uid: str) -> Optional[int]:
        return self.j_index.get(job_uid)

    def _queue_row(self, job_uid: str) -> Optional[int]:
        ji = self.j_index.get(job_uid)
        if ji is None:
            return None
        qi = int(self.job_queue[ji])
        return qi if qi >= 0 else None

    def apply_evict(self, row: int) -> None:
        self.version += 1
        self.v_live[row] = False
        res = self.v_res[row]
        ji = int(self.v_job[row])
        if ji >= 0:
            self.ready_cnt[ji] -= 1
            self.j_alloc[ji] -= res
            qi = int(self.job_queue[ji])
            if qi >= 0:
                self.q_alloc[qi] -= res
        # releasing grows; nz/n_tasks unchanged (the task stays on-node)
        self.events.append(("evict", row, int(self.v_node[row]), ji))

    def apply_unevict(self, row: int) -> None:
        self.version += 1
        self.v_live[row] = True
        res = self.v_res[row]
        ji = int(self.v_job[row])
        if ji >= 0:
            self.ready_cnt[ji] += 1
            self.j_alloc[ji] += res
            qi = int(self.job_queue[ji])
            if qi >= 0:
                self.q_alloc[qi] += res
        # rollback resurrects a row — every cached wave lane is suspect
        self.events.append(("rollback",))

    def apply_pipeline(self, task: TaskInfo, node_idx: int) -> None:
        self.version += 1
        res = task.resreq.to_vec()
        nz = nz_request_vec(task.resreq.to_vec())
        self.n_tasks[node_idx] += 1
        self.nz_req[node_idx] += nz
        ji = self._job_row(task.job)
        qi = -1
        if ji is not None:
            self.ready_cnt[ji] += 1
            self.j_alloc[ji] += res
            qi = int(self.job_queue[ji])
            if qi >= 0:
                self.q_alloc[qi] += res
        self.events.append(("pipeline", node_idx,
                            ji if ji is not None else -1, qi))

    def apply_unpipeline(self, task: TaskInfo, node_idx: int) -> None:
        self.version += 1
        res = task.resreq.to_vec()
        nz = nz_request_vec(task.resreq.to_vec())
        self.n_tasks[node_idx] -= 1
        self.nz_req[node_idx] -= nz
        ji = self._job_row(task.job)
        if ji is not None:
            self.ready_cnt[ji] -= 1
            self.j_alloc[ji] -= res
            qi = int(self.job_queue[ji])
            if qi >= 0:
                self.q_alloc[qi] -= res
        self.events.append(("rollback",))


@dataclass
class VisitResult:
    found: bool
    node_idx: int
    node_name: str
    victim_rows: List[int]          # victim rows in candidate order
    victims_count: int
    prop_guard: bool                # proportion skip-guard tripped on node


class VictimSolver:
    """Drives the visit kernels for a sequence of preemptor/reclaimer
    visits. Built per action execution from the session + the sig-term
    encoder (kernels/terms.solver_terms over the action's pending tasks).

    Two dispatch strategies:
    - wave (default): ONE _wave_kernel dispatch analyses a whole chunk of
      pending preemptors; the host consumes lanes in the actions' rank
      order, invalidating cached lanes whose inputs later replays touched
      (see _advance_entry/_choose — the rules are conservative, so wave
      results equal per-visit results exactly). Dispatches scale with the
      number of REPLAY CONFLICTS, not with the preemptor count — the
      property that lets preempt/reclaim ride a high-latency accelerator
      link.
    - per-visit (KUBEBATCH_VICTIM_WAVE=0): one dispatch per node visit,
      the round-2 behavior.
    """

    def __init__(self, state: VictimState, terms, names: List[str],
                 tiers: Tuple[Tuple[str, ...], ...], veto_critical: bool,
                 score_nodes: bool, room_check: bool,
                 pending: Sequence[TaskInfo] = ()):
        self.state = state
        self.terms = terms
        self.names = names              # node column -> name
        self.tiers = tiers
        self.veto_critical = veto_critical
        self.score_nodes = score_nodes
        self.room_check = room_check
        self.dyn = terms.dynamic if terms is not None else None
        self._dev = _device()
        self._static_dev = None
        self._sig_dev = None
        self._mut_dev = None
        self._mut_version = -1
        #: rpc sidecar backend (rpc/victims_wire.RemoteVictimBackend) —
        #: attached by build_action_solver under KUBEBATCH_SOLVER=rpc;
        #: None = local kernels. Remote calls fall back to local per
        #: dispatch (the analysis is pure)
        self.remote = None
        #: exact affinity/port node masks for snapshots carrying those
        #: features (kernels/affinity.SessionAffinityMasks) — folded
        #: into the visited mask per visit, so the kernels stay
        #: affinity-blind while the node CHOICE honors the predicate
        self.aff_masks = None
        self._aff_device = None
        #: wave state
        self.pending = list(pending)
        self._pos = {t.uid: i for i, t in enumerate(self.pending)}
        self._wave_on = env_on("KUBEBATCH_VICTIM_WAVE")
        env_wave = os.environ.get("KUBEBATCH_VICTIM_WAVE_SIZE")
        if env_wave is not None:
            self._wave_size = max(1, int(env_wave))
        elif self._dev is None:
            # accelerator: each wave pays a link round trip — size waves
            # to cover the pending set (bucketed) up to a lane budget so
            # typical actions resolve in ONE dispatch
            self._wave_size = min(512, max(
                64, pad_to_bucket(max(1, len(self.pending)), 64)))
        else:
            # host XLA: latency ~free; moderate waves keep compile shapes
            # small and the lazy-escalation path cheap
            self._wave_size = 128
        self._wave_cache: Dict[tuple, dict] = {}
        self._prop = any("proportion" in t for t in tiers)
        #: dispatch counter (tests assert the wave property)
        self.dispatches = 0
        #: lazy escalation: a wave lane costs real compute, so on the
        #: host-process CPU backend (self._dev set, latency ~free) the
        #: solver starts with cheap per-visit dispatches and only
        #: escalates to wave caching once the visit count shows a wave
        #: will amortize; on the platform-default device (accelerator —
        #: dispatch LATENCY dominates) waves start immediately
        self._wave_after = 4 if self._dev is not None else 0

    def host_static_arrays(self):
        """The 18 immutable state arrays in _upload/run_*_kernel order,
        as host numpy (shared with the rpc backend's one-time upload)."""
        st = self.state
        dyn_enabled = bool(self.dyn is not None and self.dyn.enabled)
        dyn_w = np.asarray(
            [self.dyn.least_requested, self.dyn.balanced_resource]
            if dyn_enabled else [0.0, 0.0], np.float32)
        return (st.node_ok, st.max_task_num, st.allocatable_cm,
                st.host_rank, st.v_node, st.v_job, st.v_res, st.v_critical,
                st.perm_nj, st.nj_head, st.perm_nq, st.nq_head, st.min_av,
                st.job_queue, st.q_deserved, st.q_prop_ok,
                st.cluster_total, dyn_w)

    def host_sig_arrays(self):
        """The bucket-padded [S, N] static-term matrices (score, pred).

        At big node counts the score matrix ships and resides NARROW
        (kernels/narrow.py policy; the pred matrix is already bool) —
        the kernels upcast gathered rows to f32 before any arithmetic,
        and the host chooser's fresh-score recompute keeps reading the
        f32 ``terms.static.score``, so choices are bit-identical either
        way (scores are integer-valued; parity pinned in
        tests/test_zscale.py)."""
        from .narrow import narrow_enabled, score_dtype

        score = self.terms.static.score
        pred = self.terms.static.pred
        s_pad = pad_to_bucket(score.shape[0], 4)
        if s_pad != score.shape[0]:
            pad = s_pad - score.shape[0]
            score = np.pad(score, ((0, pad), (0, 0)))
            pred = np.pad(pred, ((0, pad), (0, 0)))
        dyn_w = None
        if self.dyn is not None and self.dyn.enabled:
            dyn_w = (self.dyn.least_requested, self.dyn.balanced_resource)
        narrow = narrow_enabled(score.shape[1], s_pad,
                                static_scores=score, dyn_weights=dyn_w)
        if narrow:
            score = score.astype(score_dtype(True))
        return score, pred

    def host_mutable_arrays(self):
        """The 6 mutable mirrors in _upload order (numpy views)."""
        st = self.state
        return (st.n_tasks, st.nz_req, st.v_live, st.ready_cnt,
                st.j_alloc, st.q_alloc)

    def _upload(self):
        """Device copies of the state arrays: the immutable set once per
        action, the mutable mirrors only when a mutation bumped the state
        version — most visits change nothing, and ~30 per-visit host->
        device conversions dominated the visit otherwise."""
        st = self.state
        put = jax.device_put
        if self._static_dev is None:
            # ONE batched transfer for the whole immutable set — 18
            # per-array device_put calls paid ~0.5 ms of dispatch
            # overhead each on the steady path
            self._static_dev = put(self.host_static_arrays())
            # the [S, N] static-term matrices ride along once per action;
            # visits/waves then ship sig indices, not rows. S is padded
            # to a bucket so a cycle introducing a new unique signature
            # shape doesn't recompile the kernels (same discipline as
            # cycle_inputs' sig arrays)
            self._sig_dev = put(self.host_sig_arrays())
        if self._mut_version != st.version:
            self._mut_dev = put(self.host_mutable_arrays())
            self._mut_version = st.version
        return self._static_dev, self._mut_dev

    # ------------------------------------------------------------------
    # wave dispatch: analyses for a chunk of preemptors in ONE kernel
    # call; node choice + staleness handling happen host-side per visit
    # ------------------------------------------------------------------
    def visit(self, task: TaskInfo, filter_kind: str,
              visited: np.ndarray) -> VisitResult:
        if self.aff_masks is not None:
            # fold the exact affinity/port node mask into the visited
            # set: the analysis kernels stay affinity-blind, the CHOICE
            # excludes predicate-failing nodes — same node the host
            # oracle's predicate_fn walk would reach
            mask = self.aff_masks.node_mask(task, self._aff_device)
            if mask is not None:
                visited = visited | ~mask
        key = (filter_kind, task.uid)
        # a prefetched lane answers regardless of the escalation gate —
        # it was dispatched precisely so this visit needn't pay a kernel
        if self._wave_on and key in self._wave_cache:
            return self._choose(key, task, filter_kind, visited)
        if not self._wave_on or task.uid not in self._pos \
                or self.dispatches < self._wave_after:
            self.dispatches += 1
            return self._visit_single(task, filter_kind, visited)
        self._dispatch_wave(filter_kind, task)
        return self._choose(key, task, filter_kind, visited)

    def prefetch(self, tasks: Sequence[TaskInfo], filter_kind: str) -> None:
        """One wave over an explicitly KNOWN upcoming visit set (the
        actions' first-iteration queue/job tops): a steady cycle's
        handful of visits then resolves from ONE kernel dispatch instead
        of N per-visit ones, without waiting for the lazy-escalation
        threshold. Lanes land in the same event-folded cache the block
        waves use, so staleness handling (and exactness vs per-visit
        dispatch) is unchanged."""
        if not self._wave_on:
            return
        chunk = [t for t in tasks
                 if t.uid in self._pos
                 and (filter_kind, t.uid) not in self._wave_cache]
        if chunk:
            self._dispatch_wave(filter_kind, chunk[0], chunk=chunk)

    def _dyn_scores(self, p_nz: np.ndarray) -> np.ndarray:
        """Fresh dynamic scores over ALL node columns against the CURRENT
        mirrors — the SAME dynamic_node_score the kernels run, with
        xp=np, so the host chooser orders nodes exactly as the in-kernel
        choice would."""
        st = self.state
        w = self.dyn
        weights = np.asarray([w.least_requested, w.balanced_resource],
                             np.float32)
        return np.asarray(dynamic_node_score(
            st.nz_req.astype(np.float32), p_nz.astype(np.float32),
            st.allocatable_cm.astype(np.float32), weights, xp=np))

    def _advance_entry(self, entry: dict) -> bool:
        """Fold the mutation events since the entry's wave into its
        per-node dirty sets. False = the entry as a whole is stale (its
        preemptor's own job was touched, or a rollback happened) and must
        be refreshed. Every rule is conservative; the monotonicity that
        makes caching productive: evictions/pipelines only SHRINK a
        node's analysis unless the touched job/queue has running tasks
        there (the grow sets)."""
        st = self.state
        events = st.events
        pos = entry["log_pos"]
        if pos == len(events):
            return True
        p_job = entry["p_job"]
        shrink: set = entry["shrink"]
        grow: set = entry["grow"]
        for e in events[pos:]:
            kind = e[0]
            if kind == "rollback":
                return False
            if kind == "evict":
                _, row, enode, ejob = e
                if ejob == p_job:
                    return False     # preemptor's own drf share moved
                shrink.add(enode)
                if ejob >= 0:
                    shrink |= st.job_nodes(ejob)
                    if self._prop:
                        # lowering q_alloc can newly TRIP the proportion
                        # skip-guard (before < v_res), which makes a node
                        # pickable — a GROW effect, not just shrink
                        q = int(st.job_queue[ejob])
                        if q >= 0:
                            grow |= st.queue_nodes(q)
            else:  # pipeline
                _, pnode, pjob, pqueue = e
                if pjob == p_job:
                    return False
                shrink.add(pnode)    # load/room changed (scores re-done
                                     # fresh by the chooser anyway)
                if pjob >= 0:
                    grow |= st.job_nodes(pjob)
                if self._prop and pqueue >= 0:
                    grow |= st.queue_nodes(pqueue)
        entry["log_pos"] = len(events)
        return True

    def _choose(self, key: tuple, task: TaskInfo, filter_kind: str,
                visited: np.ndarray) -> VisitResult:
        """Pick the entry's best usable node in FRESH score order: clean
        pickable nodes are consumed straight from the cached analysis;
        hitting a grow-dirty (possibly newly pickable) or a dirty
        pickable node first forces a single-lane refresh."""
        st = self.state
        for _ in range(2):
            entry = self._wave_cache[key]
            ok = self._advance_entry(entry)
            if ok:
                if self.score_nodes:
                    score = entry["static_score"].astype(np.float32)
                    if self.dyn is not None and self.dyn.enabled:
                        score = score + self._dyn_scores(entry["p_nz"])
                    if self.aff_masks is not None \
                            and self.aff_masks.with_scores:
                        ip = self.aff_masks.score_norm(task,
                                                       self._aff_device)
                        if ip is not None:
                            score = score + ip
                    order_rank = np.lexsort((st.host_rank, -score))
                else:
                    order_rank = np.lexsort((st.host_rank,))
                rank = np.empty(st.n_pad, np.int64)
                rank[order_rank] = np.arange(st.n_pad)
                live = ~visited
                pick = entry["pick"] & live
                shrink = entry["shrink"]
                grow = entry["grow"]
                inf = st.n_pad + 1

                def first(mask):
                    sel = rank[mask]
                    return int(sel.min()) if sel.size else inf

                dirty_mask = np.zeros(st.n_pad, bool)
                if shrink:
                    dirty_mask[list(shrink)] = True
                grow_mask = np.zeros(st.n_pad, bool)
                if grow:
                    grow_mask[list(grow)] = True
                f_clean = first(pick & ~dirty_mask & ~grow_mask)
                f_suspect = min(first(pick & dirty_mask),
                                first(grow_mask & live))
                if f_clean <= f_suspect:
                    if f_clean >= inf:
                        return VisitResult(False, 0, "", [], 0, False)
                    col = int(order_rank[f_clean])
                    vic = entry["victims"] & (st.v_node == col)
                    rows = np.nonzero(vic)[0].tolist()
                    return VisitResult(
                        found=True, node_idx=col,
                        node_name=self.names[col], victim_rows=rows,
                        victims_count=len(rows),
                        prop_guard=bool(entry["guard"][col]))
            # stale where it matters: refresh this lane alone
            self._dispatch_wave(filter_kind, task, single=True)
        raise AssertionError(
            "victim wave refresh did not converge")  # pragma: no cover

    def _dispatch_wave(self, filter_kind: str, anchor: TaskInfo,
                       single: bool = False, chunk=None) -> None:
        st = self.state
        if single:
            chunk = [anchor]
            p_bucket = 1
        elif chunk is None:
            # BLOCK-aligned chunks: consumption order (the actions'
            # fairness heaps) jumps around the pending list, so pos-based
            # slices would re-wave on nearly every visit; fixed blocks
            # keep any consumption order within ceil(len/W) waves
            block = self._pos[anchor.uid] // self._wave_size
            start = block * self._wave_size
            chunk = self.pending[start:start + self._wave_size]
            p_bucket = 8
        else:
            # explicit prefetch chunk: pad to the next pow2 of the REAL
            # lane count (1/2/4/...) — the steady-skew regime prefetches
            # a single queue's top task, and every padded lane is a full
            # [V]+[N] analysis the CPU backend computes for nothing
            p_bucket = 1
        p = len(chunk)
        p_pad = pad_to_bucket(p, p_bucket)
        p_res = np.zeros((p_pad, RESOURCE_DIM), np.float32)
        p_resreq = np.zeros((p_pad, RESOURCE_DIM), np.float32)
        p_nz = np.zeros((p_pad, 2), np.float32)
        p_sig = np.zeros(p_pad, np.int32)
        p_job = np.full(p_pad, -1, np.int32)
        p_queue = np.full(p_pad, -1, np.int32)
        sig_of = self.terms.static.sig_of
        for i, t in enumerate(chunk):
            p_res[i] = t.init_resreq.to_vec()
            p_resreq[i] = t.resreq.to_vec()
            p_nz[i] = nz_request_vec(t.resreq.to_vec())
            p_sig[i] = sig_of.get(t.uid, 0)
            ji = st.j_index.get(t.job, -1)
            p_job[i] = ji
            p_queue[i] = int(st.job_queue[ji]) if ji >= 0 else -1
        dyn_enabled = bool(self.dyn is not None and self.dyn.enabled)

        def run():
            static_dev, mut_dev = self._upload()
            return run_wave_kernel(
                static_dev, mut_dev, self._sig_dev,
                p_res, p_resreq, p_nz, p_sig, p_job, p_queue,
                tiers=self.tiers, veto_critical=self.veto_critical,
                filter_kind=filter_kind, dyn_enabled=dyn_enabled,
                score_nodes=self.score_nodes, room_check=self.room_check)

        self.dispatches += 1
        with _span("victim_wave", cat="kernel") as sp:
            packed = None
            if self.remote is not None:
                # sidecar analysis (KUBEBATCH_SOLVER=rpc): statics were
                # uploaded once; a failed call falls back to the local
                # kernels for THIS dispatch (analysis is pure — retrying
                # locally cannot double-apply anything)
                packed = self.remote.wave(
                    self, p_res, p_resreq, p_nz, p_sig, p_job, p_queue,
                    filter_kind=filter_kind, dyn_enabled=dyn_enabled)
            if packed is None:
                if self._dev is not None:
                    with jax.default_device(self._dev):
                        out = run()
                else:
                    out = run()
                count_blocking_readback()
                with _span("readback", cat="readback"):
                    packed = np.asarray(out)  # [W,N+N+V] — ONE blocking read
            n_pad = self.state.n_pad
            pick = packed[:, :n_pad]
            guard = packed[:, n_pad:2 * n_pad]
            victims = packed[:, 2 * n_pad:]
            # host-derived telemetry frame: the wave result is a bool
            # bitmap, so the frame comes from the SAME readback instead
            # of widening the transfer to int32 (kernels/telemetry.py)
            from ..obs import telemetry as _obs_telemetry
            _obs_telemetry.record(host_frame(
                ENGINE_VICTIM_WAVE, waves=1, pending=p,
                census=int(pick[:p].any(axis=1).sum()),
                bound=int(victims[:p].any(axis=1).sum())), span=sp)
        log_pos = len(st.events)
        for i, t in enumerate(chunk):
            self._wave_cache[(filter_kind, t.uid)] = {
                "pick": pick[i], "guard": guard[i], "victims": victims[i],
                "log_pos": log_pos,
                "p_job": int(p_job[i]), "p_queue": int(p_queue[i]),
                "p_nz": p_nz[i],
                "static_score": self.terms.static.score[p_sig[i]],
                "shrink": set(), "grow": set()}

    def _visit_single(self, task: TaskInfo, filter_kind: str,
                      visited: np.ndarray) -> VisitResult:
        st = self.state
        sig = self.terms.static.sig_of.get(task.uid, 0)
        dyn_enabled = bool(self.dyn is not None and self.dyn.enabled)
        p_job = st.j_index.get(task.job, -1)
        ji = p_job if p_job >= 0 else 0
        p_queue = int(st.job_queue[ji]) if p_job >= 0 else -1

        p_res = np.asarray(task.init_resreq.to_vec())
        p_resreq = np.asarray(task.resreq.to_vec())
        p_nz = nz_request_vec(task.resreq.to_vec())

        def run():
            static_dev, mut_dev = self._upload()
            return run_visit_kernel(
                static_dev, mut_dev, self._sig_dev,
                p_res, p_resreq, p_nz, np.int32(sig),
                np.int32(p_job), np.int32(p_queue), visited,
                tiers=self.tiers, veto_critical=self.veto_critical,
                filter_kind=filter_kind, dyn_enabled=dyn_enabled,
                score_nodes=self.score_nodes, room_check=self.room_check)

        with _span("victim_visit", cat="kernel") as sp:
            packed = None
            if self.remote is not None:
                packed = self.remote.visit(
                    self, p_res, p_resreq, p_nz, int(sig), int(p_job),
                    int(p_queue), visited, filter_kind=filter_kind,
                    dyn_enabled=dyn_enabled)
            if packed is None:
                if self._dev is not None:
                    with jax.default_device(self._dev):
                        out = run()
                else:
                    out = run()
                count_blocking_readback()
                with _span("readback", cat="readback"):
                    packed = np.asarray(out)   # [4+V] — ONE blocking read
            from ..obs import telemetry as _obs_telemetry
            _obs_telemetry.record(host_frame(
                ENGINE_VICTIM_VISIT, waves=1, pending=1,
                bound=int(bool(packed[0])),
                census=int(packed[2])), span=sp)
        found, node, vcount, guard = (bool(packed[0]), int(packed[1]),
                                      int(packed[2]), bool(packed[3]))
        rows = np.nonzero(packed[4:])[0].tolist() if found else []
        return VisitResult(
            found=found, node_idx=node,
            node_name=self.names[node] if found else "",
            victim_rows=rows,
            victims_count=vcount, prop_guard=guard)


#: build_action_solver sentinel: the action can observably do nothing
#: (no RUNNING task exists anywhere) — skip its loops entirely. ONE
#: decision point for both actions, host-oracle mode exempted.
SKIP_ACTION = object()


def build_action_solver(ssn, fns_attr: str, disabled_attr: str,
                        score_nodes: bool, pending=None):
    """The env-gated entry the preempt/reclaim actions share: collects the
    session's pending tasks and builds the kernel solver; returns None
    for the host path (KUBEBATCH_VICTIM_SOLVER=host, nothing pending, or
    an unsupported snapshot), or SKIP_ACTION when no victim can exist —
    with no RUNNING task in any job, every visit would scan to an empty
    set, so the action skips the solver build AND its loops (the
    task_status_index check is exact: empty buckets are deleted).
    ``pending``: the caller's precollected pending-task list (the action
    walks the job map anyway; passing it avoids a second 10k-job walk)."""
    if os.environ.get("KUBEBATCH_VICTIM_SOLVER", "device") == "host":
        return None
    if not any(TaskStatus.RUNNING in j.task_status_index
               for j in ssn.jobs.values()):
        return SKIP_ACTION
    if pending is None:
        pending = [t for job in ssn.jobs.values()
                   for t in job.task_status_index.get(TaskStatus.PENDING,
                                                      {}).values()]
    if not pending:
        return None
    solver = build_victim_solver(ssn, pending, fns_attr, disabled_attr,
                                 score_nodes)
    if solver is not None and not solver.state.victims:
        # running tasks exist but none materialized as victim rows
        # (e.g. all on placeholder nodes)
        return SKIP_ACTION
    return solver


def build_victim_solver(ssn, pending: Sequence[TaskInfo],
                        fns_attr: str, disabled_attr: str,
                        score_nodes: bool):
    """Construct (VictimSolver, VictimState) for an action, or None when
    the snapshot/plugin configuration falls outside the kernel vocabulary
    (the action then runs its reference-literal host path).

    ``fns_attr``: "preemptable_fns" or "reclaimable_fns"; ``disabled_attr``
    the matching per-plugin disable flag name.
    """
    from .solver import ensure_device_snapshot
    from .terms import device_supported, solver_terms

    KNOWN = {"gang", "conformance", "drf", "proportion"}
    fns = getattr(ssn, fns_attr)
    tiers: List[Tuple[str, ...]] = []
    for tier in ssn.tiers:
        members = tuple(
            opt.name for opt in tier.plugins
            if not getattr(opt, disabled_attr) and opt.name in fns)
        if members:
            if any(m not in KNOWN for m in members):
                return None
            tiers.append(members)
    if any(name not in KNOWN for name in ssn.victim_veto_fns):
        return None
    # affinity/host ports only gate the PREEMPTOR's node choice in the
    # victim actions (no tier fn reads them) — the device analysis stays
    # valid with an exact host-side node mask applied at choice time
    # (kernels/affinity.SessionAffinityMasks); other dynamic features
    # (a real volume binder, custom plugins) still take the host path
    if not device_supported(ssn, pending, allow_affinity=True):
        return None
    from .terms import _active
    pred_active = bool(_active(ssn, ssn.predicate_fns,
                               "predicate_disabled"))
    order_active = bool(_active(ssn, ssn.node_order_fns,
                                "node_order_disabled"))
    aff_masks = None
    aff_scored = False
    if pred_active or order_active:
        from .encode import dynamic_features
        if dynamic_features(ssn, pending) is not None:
            aff_scored = bool(score_nodes and order_active)
            if aff_scored and not env_on("KUBEBATCH_VICTIM_WAVE"):
                # the interpod score term (nodeorder.go:305-313) is
                # allocation-dependent; the exact reproduction lives in
                # the WAVE chooser's host-side node ordering — with
                # waves disabled the in-kernel choice would diverge
                # from the oracle's node_order_fn sum. Host path.
                return None
            if pred_active or aff_scored:
                from .affinity import SessionAffinityMasks
                # with_predicates gates the MASK half: a disabled
                # predicates plugin must not have affinity/ports
                # enforced at choice time (the host oracle would not
                # run that predicate either)
                aff_masks = SessionAffinityMasks(
                    ssn, pending, with_scores=aff_scored,
                    with_predicates=pred_active)
                if not aff_masks.supported:
                    return None
    device = ensure_device_snapshot(ssn)
    terms = solver_terms(ssn, device, pending, assume_supported=True)
    if terms is None:
        return None

    ns = device.state
    with _span("victim_state_build", cat="tensorize"):
        state = VictimState(
            ssn, node_index=ns.index, n_pad=ns.n_padded,
            node_ok=ns.schedulable & ns.valid,
            max_task_num=ns.max_task_num,
            allocatable_cm=ns.allocatable[:, :2])
    solver = VictimSolver(
        state, terms, names=ns.names, tiers=tuple(tiers),
        veto_critical="conformance" in ssn.victim_veto_fns,
        score_nodes=score_nodes, room_check=pred_active, pending=pending)
    if aff_masks is not None:
        solver.aff_masks = aff_masks
        solver._aff_device = device
        if aff_scored:
            # every node CHOICE must flow through the wave chooser's
            # host-side score ordering (where the interpod term is
            # reproduced exactly); per-visit in-kernel choice would
            # ignore it
            solver._wave_on = True
            solver._wave_after = 0
    if os.environ.get("KUBEBATCH_SOLVER", "") == "rpc":
        # route the victim analysis through the solver sidecar — the
        # full 4-action remote cycle (scheduler.go:88-105 runs every
        # action against its backend). Channel failure or any later RPC
        # error falls back to the local kernels per dispatch.
        from ..rpc.victims_wire import attach_remote
        attach_remote(solver, os.environ.get("KUBEBATCH_SOLVER_ADDR",
                                             "127.0.0.1:50061"))
    return solver


# ---------------------------------------------------------------------
# compilesvc signature provider — the preempt/reclaim analysis kernels
# at the steady regime's canonical lane buckets (victim rows only exist
# once the cluster carries RUNNING tasks, so these register from the
# profile's steady materials)
# ---------------------------------------------------------------------

def _wave_buckets(solver) -> List[int]:
    """The lane (p_pad) buckets the wave dispatcher produces: single-lane
    refresh, small prefetch pow2s, the full block, and the tail block's
    pow2 for the solver's pending count."""
    w = solver._wave_size
    tail = len(solver.pending) % w or w
    return sorted({1, 2, 4, w, pad_to_bucket(tail, 8)})


def _solver_signatures(solver, filter_kind: str) -> list:
    from ..compilesvc.registry import Signature, signature_key

    static = solver.host_static_arrays()
    mut = solver.host_mutable_arrays()
    sig = solver.host_sig_arrays()
    n_pad = solver.state.n_pad
    statics = dict(tiers=solver.tiers, veto_critical=solver.veto_critical,
                   filter_kind=filter_kind,
                   dyn_enabled=bool(solver.dyn is not None
                                    and solver.dyn.enabled),
                   score_nodes=solver.score_nodes,
                   room_check=solver.room_check)
    out = []
    for p_pad in _wave_buckets(solver):
        lanes = (np.zeros((p_pad, RESOURCE_DIM), np.float32),
                 np.zeros((p_pad, RESOURCE_DIM), np.float32),
                 np.zeros((p_pad, 2), np.float32),
                 np.zeros(p_pad, np.int32),
                 np.full(p_pad, -1, np.int32),
                 np.full(p_pad, -1, np.int32))
        args = wave_kernel_args(static, mut, sig, *lanes)
        out.append(Signature(
            engine="victims", entry="_wave_kernel",
            key=signature_key("_wave_kernel", args, statics),
            lower=lambda a=args, s=statics: _wave_kernel.lower(*a, **s),
            run=lambda a=args, s=statics: _wave_kernel(*a, **s),
            note=f"{filter_kind} wave W={p_pad} N={n_pad}"))
    vargs = visit_kernel_args(
        static, mut, sig,
        np.zeros(RESOURCE_DIM, np.float32),
        np.zeros(RESOURCE_DIM, np.float32),
        np.zeros(2, np.float32), np.int32(0), np.int32(0), np.int32(-1),
        np.zeros(n_pad, bool))
    out.append(Signature(
        engine="victims", entry="_visit_kernel",
        key=signature_key("_visit_kernel", vargs, statics),
        lower=lambda a=vargs, s=statics: _visit_kernel.lower(*a, **s),
        run=lambda a=vargs, s=statics: _visit_kernel(*a, **s),
        note=f"{filter_kind} visit N={n_pad}"))
    return out


@_cs_register_provider("kernels.victims")
def compile_signatures(materials):
    out = []
    for kind, solver in (("reclaim", materials.reclaim_solver),
                         ("preempt", materials.preempt_solver)):
        if solver is None:
            continue
        out.extend(_solver_signatures(solver, kind))
    return out
