"""Narrow-dtype policy for the [T, N]-scale solver intermediates.

docs/SCALING.md budgets the scale axis in [T, N] intermediates: at cfg5
(16384 x 8192) a float32 score matrix is 512 MB and the round keeps ~4
such arrays live; at cfg6/cfg7 (50-100k nodes) the f32 layout stops
fitting long before the FLOPs do.  The memory diet, applied where each
kernel materializes [T, N]-scale data:

- **eligibility / fit masks** are pred-typed ``bool`` (1 byte/cell under
  XLA — already the narrow layout; this module documents the invariant
  so a future refactor doesn't silently promote them to int32).
- **scores** ride ``bfloat16``.  Sound because every score the engines
  materialize at [T, N] scale is *integer-valued and small*: the static
  sig terms are host plugin scores (``floor(10 * x) * weight`` per
  nodeorder plugin), the dynamic least-requested / balanced-resource
  terms are threshold counts (kernels/solver.dynamic_node_score), and
  the inter-pod preferred term is ``floor(10 * x) * weight`` — all
  exactly representable in bf16's 8-bit mantissa up to 256.  The
  narrowed path is therefore DECISION-IDENTICAL to f32, which the
  parity tests in tests/test_zscale.py pin bit-for-bit on
  cfg2p/cfg5-shaped inputs.
- **resource arithmetic stays float32** — the f32 accumulation seam.
  Capacity carries, request prefixes, the waterfall mass ledger and
  every epsilon-compared fit quantity keep the exact dtype the
  documented resource epsilons (api/resource.VEC_EPS) were calibrated
  for; only the score gathers narrow.

The flag is a STATIC jit argument on every entry that honors it (part
of the trace signature and the compilesvc registry key), never ambient
state: flipping the env var between calls can therefore never reuse a
stale trace.

Selection: ``KUBEBATCH_NARROW=1/0`` forces; unset, the policy is
auto-by-size — narrow engages when the [T, N] product crosses
``NARROW_AUTO_CELLS`` (the cfg6+ regime), so every existing config keeps
its historical f32 graphs and signature keys.
"""
from __future__ import annotations

import os

import jax.numpy as jnp

__all__ = ["NARROW_AUTO_CELLS", "SCORE_WIDE_DTYPE", "SCORE_NARROW_DTYPE",
           "narrow_enabled", "score_dtype"]

#: auto threshold on the [T, N] cell count: cfg5 (16384 x 8192 = 1.3e8)
#: stays f32; cfg6 (53248 x 53248 = 2.8e9) narrows.  2**29 ~= 5.4e8
#: cells == 2 GiB of f32 score matrix — past it the f32 layout is the
#: thing that breaks, so narrowing is the default, not an opt-in.
NARROW_AUTO_CELLS = 2 ** 29

#: auto threshold on the node axis ALONE: past the hier/cfg6 regime
#: every node-dimensioned store ([S, N] victim sig matrices, small-T
#: affinity cycles) narrows regardless of its other axis, so one
#: cluster runs one dtype policy across its engines.
NARROW_AUTO_NODES = 16384

#: bf16 represents every integer exactly up to this magnitude; past it
#: integer neighbors collapse and argmax ties break differently than
#: f32 — the decision-identity contract's hard boundary
BF16_EXACT_MAX = 256.0

SCORE_WIDE_DTYPE = jnp.float32
SCORE_NARROW_DTYPE = jnp.bfloat16


def scores_bf16_exact(static_scores, dyn_weights=None,
                      ip_weight=0.0) -> bool:
    """True when every score the kernels materialize at [T, N] scale
    round-trips bf16 EXACTLY: the static matrix is integer-valued (the
    plugin floor-semantics guarantee — but NodeAffinity is a raw
    preferred-weight sum, so magnitude must be checked, not assumed)
    and the worst-case |static| + dynamic-term bound (<= 10 per unit
    weight) + interpod bound stays within bf16's exact-integer range.
    Host-side numpy on the [S, N] matrix — negligible at arg-build."""
    import numpy as np

    s = np.asarray(static_scores)
    if s.size and not np.array_equal(s, np.round(s)):
        return False
    bound = float(np.max(np.abs(s))) if s.size else 0.0
    if dyn_weights is not None:
        w = np.asarray(dyn_weights, np.float64)
        # fractional weights make the dynamic terms (integer counts x
        # weight) non-integral — not exactly representable, gate closed
        if not np.array_equal(w, np.round(w)):
            return False
        bound += 10.0 * float(np.sum(np.abs(w)))
    if ip_weight:
        if float(ip_weight) != round(float(ip_weight)):
            return False
        bound += 10.0 * abs(float(ip_weight))
    return bound <= BF16_EXACT_MAX


def narrow_enabled(n_pad: int, t_pad: int, static_scores=None,
                   dyn_weights=None, ip_weight=0.0) -> bool:
    """The policy decision for one (node bucket, other-axis bucket)
    pair — called at arg-build time (prepare_* / upload sites), result
    a static (or the store dtype itself).

    When ``static_scores`` is given, AUTO narrowing additionally
    requires :func:`scores_bf16_exact` — a cycle whose score scale
    exceeds bf16's exact-integer range keeps f32 rather than silently
    trading decisions for memory. The env override skips the gate (an
    explicit operator/A-B choice, e.g. tools/narrow_ab.py)."""
    env = os.environ.get("KUBEBATCH_NARROW", "").strip()
    if env:
        return env not in ("0", "false", "off")
    if not (int(n_pad) >= NARROW_AUTO_NODES
            or int(n_pad) * int(t_pad) >= NARROW_AUTO_CELLS):
        return False
    if static_scores is None:
        return True
    return scores_bf16_exact(static_scores, dyn_weights, ip_weight)


def score_dtype(narrow: bool):
    """The dtype score matrices materialize at [T, N] scale."""
    return SCORE_NARROW_DTYPE if narrow else SCORE_WIDE_DTYPE
