"""The allocate solver — a capacity-carrying assignment scan on TPU.

Replaces the reference's O(tasks x nodes x plugins) per-pair loops
(actions/allocate/allocate.go:128-186) with ONE jitted lax.scan per job
visit: for each task (in task-order) the scan computes the predicate mask
and score over ALL nodes at once, selects the best feasible node, and
updates the idle/releasing capacity carry before the next task — preserving
the reference's sequential-greedy semantics while amortizing device
dispatch over the whole job.

Decision codes (host applies them through Session.allocate/pipeline so all
plugin event handlers and the gang dispatch barrier still fire):

  0 SKIP      task not processed (job became ready first — reference
              re-pushes the job and handles the rest next visit)
  1 ALLOC     init_resreq fits node idle -> Allocated
  2 ALLOC_OB  fits idle+backfilled but not idle -> AllocatedOverBackfill
              (fork feature, allocate.go:157)
  3 PIPELINE  fits releasing -> Pipelined onto releasing resources
  4 FAIL      no feasible node -> job dropped this cycle (allocate.go:187)

Fit rules mirror allocate.go:153-184: a node is feasible if the launch
request fits accessible (idle+backfilled) OR releasing; the highest-scoring
feasible node wins (ties -> lowest node index; the reference's tie order is
Go map iteration, i.e. unspecified); the fit kind is then read off that
node. Readiness crossing counts only ALLOC decisions — AllocatedOverBackfill
and Pipelined don't advance gang readiness (api/types.go:82-84).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import NodeInfo
from ..compilesvc import instrument as _instrument
from ..compilesvc import register_provider as _register_provider
from ..metrics import count_blocking_readback
from ..obs import span as _span
from .telemetry import ENGINE_VISIT, TELEM_WIDTH, decision_frame
from .tensorize import VEC_EPS, NodeState, TaskBatch, pad_to_bucket

SKIP, ALLOC, ALLOC_OB, PIPELINE, FAIL = 0, 1, 2, 3, 4


class _Carry(NamedTuple):
    idle: jnp.ndarray        # [N,R]
    releasing: jnp.ndarray   # [N,R]
    n_tasks: jnp.ndarray     # [N]
    nz_req: jnp.ndarray      # [N,2] nonzero (cpu,mem) request sums
    allocated: jnp.ndarray   # scalar i32: ALLOC count so far (incl. initial)
    done: jnp.ndarray        # scalar bool


class _TaskIn(NamedTuple):
    resreq: jnp.ndarray       # [R]
    init_resreq: jnp.ndarray  # [R]
    nz: jnp.ndarray           # [2] nonzero (cpu,mem) request
    valid: jnp.ndarray        # scalar bool
    score: jnp.ndarray        # [N]
    pred: jnp.ndarray         # [N] per-task predicate mask


def dynamic_node_score(nz_req, t_nz, allocatable_cm, dyn_weights, xp=jnp):
    """nodeorder's allocation-dependent terms, from the capacity carry.

    Mirrors plugins/nodeorder.py least_requested_score /
    balanced_resource_score (upstream k8s-1.13 arithmetic) over all nodes
    at once. The Go integer division ``((cap - req) * 10) // cap`` is
    evaluated as a threshold count (how many d in 1..10 satisfy
    (cap-req)*10 >= d*cap) — division-free, so float32 rounding can only
    bite when a product pair is genuinely within f32 ulp of equal.
    dyn_weights: [least_requested_w, balanced_resource_w] float32.

    ``xp`` selects the array module: jnp inside the jitted kernels, np
    for the wave chooser's host-side fresh-score recompute
    (kernels/victims.py) — ONE implementation so the two can never
    drift; every scalar is pinned to float32 so numpy matches the
    kernel's weak-typed float32 arithmetic bit for bit.
    """
    f32 = xp.float32
    ten = f32(10.0)
    req = nz_req + t_nz[None, :]                      # [N,2]
    cap = allocatable_cm                              # [N,2]
    d = xp.arange(1.0, 11.0, dtype=f32)               # [10]
    ge = ((cap - req)[None] * ten >= d[:, None, None] * cap[None])
    dim = xp.where((cap > 0) & (req <= cap),
                   ge.sum(axis=0).astype(f32), f32(0.0))   # [N,2]
    least = xp.floor((dim[:, 0] + dim[:, 1]) / f32(2.0))

    frac = xp.where(cap > 0, req / xp.where(cap > 0, cap, f32(1.0)),
                    f32(1.0))
    diff = xp.abs(frac[:, 0] - frac[:, 1])
    balanced = xp.where((frac[:, 0] >= 1.0) | (frac[:, 1] >= 1.0),
                        f32(0.0), xp.trunc(ten - diff * ten))
    return least * dyn_weights[0] + balanced * dyn_weights[1]


@partial(jax.jit, static_argnames=("dyn_enabled",))
def _allocate_scan(idle, releasing, backfilled, allocatable_cm, nz_req,
                   max_task_num, n_tasks, node_ok, resreq, init_resreq,
                   task_nz, task_valid, scores, pred_mask, min_available,
                   init_allocated, dyn_weights, dyn_enabled: bool = False):
    """One job visit. Shapes: nodes [N,R]/[N,2]/[N]; tasks [T,R]/[T,2]/[T];
    scores and pred_mask [T,N]. Returns (packed[2T+1] int32 — decisions,
    node indices, became_ready flag, read back in ONE transfer — plus
    new_idle, new_releasing, new_n_tasks, new_nz_req)."""
    eps = jnp.asarray(VEC_EPS)

    def step(carry: _Carry, t: _TaskIn):
        accessible = carry.idle + backfilled
        room = carry.n_tasks < max_task_num
        pred = node_ok & room & t.pred
        fit_alloc = jnp.all(t.init_resreq <= accessible + eps, axis=-1)
        fit_idle = jnp.all(t.init_resreq <= carry.idle + eps, axis=-1)
        fit_pipe = jnp.all(t.init_resreq <= carry.releasing + eps, axis=-1)
        eligible = pred & (fit_alloc | fit_pipe)
        score = t.score
        if dyn_enabled:
            score = score + dynamic_node_score(carry.nz_req, t.nz,
                                               allocatable_cm, dyn_weights)
        masked_score = jnp.where(eligible, score, -jnp.inf)
        best = jnp.argmax(masked_score)
        feasible = eligible[best]

        is_alloc = fit_alloc[best]
        over_backfill = is_alloc & ~fit_idle[best]
        active = t.valid & ~carry.done
        do = active & feasible

        decision = jnp.where(
            ~active, SKIP,
            jnp.where(~feasible, FAIL,
                      jnp.where(~is_alloc, PIPELINE,
                                jnp.where(over_backfill, ALLOC_OB, ALLOC))))

        take = jnp.where(do, t.resreq, jnp.zeros_like(t.resreq))
        one_hot = (jnp.arange(carry.idle.shape[0]) == best)
        alloc_take = jnp.where(is_alloc, 1.0, 0.0) * take
        pipe_take = jnp.where(is_alloc, 0.0, 1.0) * take
        new_idle = carry.idle - one_hot[:, None] * alloc_take[None, :]
        new_rel = carry.releasing - one_hot[:, None] * pipe_take[None, :]
        new_ntasks = carry.n_tasks + (one_hot & do).astype(jnp.int32)
        # every assignment kind lands in node.tasks host-side, so each one
        # feeds the nonzero-request sums the dynamic scores read
        new_nz = carry.nz_req + jnp.where(
            do, one_hot[:, None] * t.nz[None, :], 0.0)

        # readiness counts plain Allocated AND Pipelined (gang's
        # pipelined-inclusive ready_task_num); only AllocatedOverBackfill
        # stays outside the quorum
        new_allocated = carry.allocated + jnp.where(do & ~over_backfill, 1, 0)
        ready_now = new_allocated >= min_available
        # stop after the assignment that crossed readiness, or on failure
        new_done = carry.done | (active & ~feasible) | (do & ready_now)

        out = (decision.astype(jnp.int32), best.astype(jnp.int32))
        return _Carry(new_idle, new_rel, new_ntasks, new_nz, new_allocated,
                      new_done), out

    init = _Carry(idle, releasing, n_tasks, nz_req,
                  jnp.asarray(init_allocated, jnp.int32),
                  jnp.asarray(False))
    tasks = _TaskIn(resreq, init_resreq, task_nz, task_valid, scores,
                    pred_mask)
    final, (decisions, node_idx) = jax.lax.scan(step, init, tasks)
    became_ready = final.allocated >= min_available
    # ONE packed int32 host result [2T+1+TELEM_WIDTH]: decisions, node
    # indices, the readiness flag, and the telemetry frame ship as a
    # single blocking transfer (each device->host read pays the full
    # tunnel RTT). A visit is one wave — every placement lands in wave
    # slot 0.
    frame = decision_frame(ENGINE_VISIT, decisions,
                           jnp.zeros_like(decisions), task_valid,
                           waves=1, stride=1)
    packed = jnp.concatenate([decisions, node_idx,
                              became_ready.astype(jnp.int32)[None], frame])
    return (packed, final.idle, final.releasing, final.n_tasks,
            final.nz_req)


# accounted trace boundary (compilesvc): per-visit allocate engine
_allocate_scan = _instrument("visit", "_allocate_scan", _allocate_scan)


class Decision(NamedTuple):
    kind: int
    node_name: str


def ensure_device_snapshot(ssn) -> "DeviceSession":
    """The session's shared DeviceSession, with every node row the
    CURRENT session has touched re-packed from host truth on each call.

    Actions run in sequence against one session; the first device
    consumer builds the snapshot (cache.device_session folds the dirty
    AND already-touched sets), but a LATER action must not consume rows
    an earlier action's host-side mutations made stale — reclaim's
    evictions land on host NodeInfo between the victim build and
    allocate's solve, and backfill's host-only placements can re-touch
    nodes a previous sync already covered. Re-packing the full touched
    set is idempotent (host truth is authoritative after each action's
    replay) and O(touched), so no delta bookkeeping can go stale.
    Caught by tests/test_rpc.py's remote-cycle fuzz: post-reclaim fused
    placements diverged from the host oracle while the wire path, which
    reads fresh host truth, matched it."""
    device = ssn.device_snapshot
    if device is None:
        mk = getattr(ssn.cache, "device_session", None)
        device = mk(ssn) if mk is not None else DeviceSession(ssn.nodes)
        ssn.device_snapshot = device
        return device
    touched = ssn.touched_nodes
    if touched and not device.update_rows(ssn.nodes, touched):
        device = DeviceSession(ssn.nodes)   # node set changed: rebuild
        ssn.device_snapshot = device
    return device


#: cap on the per-session dirty-row scatter high-water (see update_rows):
#: a single transient cluster-wide dirty set must not make every later
#: steady-cycle update pay its host-side pad construction; updates above
#: the cap fall back to plain pow2 buckets (rare, one compile each)
_SCATTER_HW_CAP = 4096


@partial(jax.jit, donate_argnums=tuple(range(8)))
def _scatter_rows(idle, releasing, backfilled, alloc_cm, nz_req, n_tasks,
                  max_task_num, node_ok, jidx, r_idle, r_rel, r_back, r_cm,
                  r_nz, r_nt, r_mt, r_ok):
    """All eight dirty-row scatters in ONE compiled dispatch (they were
    eight eager ops; per-op dispatch dominated the steady reclaim phase).
    Donation reuses the old buffers in place."""
    return (idle.at[jidx].set(r_idle),
            releasing.at[jidx].set(r_rel),
            backfilled.at[jidx].set(r_back),
            alloc_cm.at[jidx].set(r_cm),
            nz_req.at[jidx].set(r_nz),
            n_tasks.at[jidx].set(r_nt),
            max_task_num.at[jidx].set(r_mt),
            node_ok.at[jidx].set(r_ok))


# accounted trace boundary (compilesvc): steady dirty-row scatter
_scatter_rows = _instrument("scatter", "_scatter_rows", _scatter_rows)


class DeviceSession:
    """Per-session device state: node arrays uploaded once, carried across
    job visits, and kept in lock-step with the host Session's NodeInfo maps
    (the host applies exactly the decisions the kernel produced)."""

    def __init__(self, nodes: Dict[str, NodeInfo], min_bucket: int = 8):
        with _span("device_snapshot", cat="tensorize"):
            self.state = NodeState.from_nodes(nodes, min_bucket)
            self.idle = jnp.asarray(self.state.idle)
            self.releasing = jnp.asarray(self.state.releasing)
            self.backfilled = jnp.asarray(self.state.backfilled)
            self.allocatable_cm = jnp.asarray(self.state.allocatable[:, :2])
            self.nz_req = jnp.asarray(self.state.nz_requested)
            self.n_tasks = jnp.asarray(self.state.n_tasks)
            self.max_task_num = jnp.asarray(self.state.max_task_num)
            self.node_ok = jnp.asarray(self.state.schedulable
                                       & self.state.valid)
            #: grow-only high-water bucket for this session's dirty-row
            #: scatter shape: one shape per session lifetime -> one compile
            #: per shape, without a big session's mark leaking onto smaller
            #: sessions in the same process
            self._scatter_hw = 8

    @property
    def n_padded(self) -> int:
        return self.state.n_padded

    def node_name(self, idx: int) -> str:
        return self.state.names[idx]

    def node_index(self, name: str) -> Optional[int]:
        return self.state.index.get(name)

    def update_rows(self, nodes: Dict[str, NodeInfo], names) -> bool:
        """Re-pack the given nodes' rows from host truth (numpy mirror and
        device arrays both), reusing everything else from the previous
        cycle — the steady-state complement of the full per-cycle build.
        Returns False when the node set changed (caller rebuilds fresh).

        Soundness: rows NOT in ``names`` were neither event-mutated
        (cache dirty set) nor session-mutated (touched set folded in by
        the caller) since they were last packed, so both mirrors still
        hold their host-truth values."""
        state = self.state
        if len(nodes) != len(state.names) \
                or any(n not in state.index for n in nodes):
            return False
        rows = sorted(state.index[n] for n in names if n in state.index)
        if not rows:
            return True
        with _span("update_rows", cat="tensorize", rows=len(rows)):
            return self._update_rows_inner(nodes, rows, state)

    def _update_rows_inner(self, nodes, rows, state) -> bool:
        from ..api.resource import VEC_SCALE

        from .tensorize import accumulate_nz, pack_node_raw
        k = len(rows)
        dirty_nodes = [nodes[state.names[r]] for r in rows]
        raw = pack_node_raw(dirty_nodes)
        t_row: List[int] = []
        t_tasks: List = []
        for j, (r, ni) in enumerate(zip(rows, dirty_nodes)):
            t_tasks.extend(ni.tasks.values())
            t_row.extend([j] * len(ni.tasks))
            state.max_task_num[r] = ni.allocatable.max_task_num
            state.n_tasks[r] = len(ni.tasks)
            state.schedulable[r] = not (bool(ni.node.unschedulable)
                                        if ni.node else True)
        nz = accumulate_nz(t_tasks, t_row, k)
        raw *= VEC_SCALE
        raw32 = raw.astype(np.float32)
        idx = np.asarray(rows, np.int32)
        state.idle[idx] = raw32[:, 0]
        state.releasing[idx] = raw32[:, 1]
        state.backfilled[idx] = raw32[:, 2]
        state.allocatable[idx] = raw32[:, 3]
        state.nz_requested[idx] = nz
        # pad the scatter block to a pow2 bucket by REPEATING the first row
        # (identical values -> idempotent), so the jitted scatter shape is
        # stable across cycles instead of recompiling per dirty-row count.
        # The bucket is this session's grow-only high-water mark: a
        # scatter is equally trivial at any size, and a single shape means
        # a single compile — per-bucket first occurrences were the ~1 s
        # p95 tail cycles in the steady benches
        k_pad = pad_to_bucket(k, 8)
        if k_pad < self._scatter_hw:
            k_pad = self._scatter_hw
        elif k_pad <= _SCATTER_HW_CAP:
            self._scatter_hw = k_pad
        if k_pad != k:
            pad = np.full(k_pad - k, idx[0], np.int32)
            idx = np.concatenate([idx, pad])
            raw32 = np.concatenate(
                [raw32, np.repeat(raw32[:1], k_pad - k, axis=0)])
            nz = np.concatenate([nz, np.repeat(nz[:1], k_pad - k, axis=0)])
        (self.idle, self.releasing, self.backfilled, self.allocatable_cm,
         self.nz_req, self.n_tasks, self.max_task_num,
         self.node_ok) = _scatter_rows(
            self.idle, self.releasing, self.backfilled,
            self.allocatable_cm, self.nz_req, self.n_tasks,
            self.max_task_num, self.node_ok, idx,
            raw32[:, 0], raw32[:, 1], raw32[:, 2], raw32[:, 3, :2],
            nz, state.n_tasks[idx], state.max_task_num[idx],
            state.schedulable[idx] & state.valid[idx])
        return True

    def resync(self, nodes: Dict[str, NodeInfo]) -> None:
        """Rebuild device arrays from host truth (used if a host-side apply
        failed halfway, or after actions that mutated nodes host-side)."""
        fresh = DeviceSession(nodes, min_bucket=self.n_padded)
        self.state = fresh.state
        self.idle = fresh.idle
        self.releasing = fresh.releasing
        self.backfilled = fresh.backfilled
        self.allocatable_cm = fresh.allocatable_cm
        self.nz_req = fresh.nz_req
        self.n_tasks = fresh.n_tasks
        self.max_task_num = fresh.max_task_num
        self.node_ok = fresh.node_ok

    def solve_job(self, batch: TaskBatch, min_available: int,
                  init_allocated: int,
                  scores: Optional[np.ndarray] = None,
                  pred_mask: Optional[np.ndarray] = None,
                  dyn=None) -> Tuple[List[Decision], bool]:
        """Run the allocate scan for one job's pending tasks and commit the
        updated capacity carry to device state. Returns per-real-task
        decisions plus whether the job crossed readiness. ``dyn`` is a
        terms.DynamicScoreSpec enabling the in-kernel nodeorder terms."""
        t_pad, n_pad = batch.t_padded, self.n_padded
        if scores is None:
            scores = np.zeros((t_pad, n_pad), np.float32)
        if pred_mask is None:
            pred_mask = np.ones((t_pad, n_pad), bool)
        dyn_enabled = bool(dyn is not None and dyn.enabled)
        dyn_weights = np.asarray(
            [dyn.least_requested, dyn.balanced_resource] if dyn_enabled
            else [0.0, 0.0], np.float32)
        with _span("allocate_scan", cat="kernel") as sp:
            (packed, idle, releasing, n_tasks, nz_req) = _allocate_scan(
                self.idle, self.releasing, self.backfilled,
                self.allocatable_cm, self.nz_req, self.max_task_num,
                self.n_tasks, self.node_ok,
                jnp.asarray(batch.resreq), jnp.asarray(batch.init_resreq),
                jnp.asarray(batch.nz_req), jnp.asarray(batch.valid),
                jnp.asarray(scores), jnp.asarray(pred_mask),
                jnp.asarray(min_available, jnp.int32),
                jnp.asarray(init_allocated, jnp.int32),
                jnp.asarray(dyn_weights), dyn_enabled=dyn_enabled)
            count_blocking_readback()
            with _span("readback", cat="readback"):
                host = np.asarray(packed)  # ONE blocking read per job visit
            decisions = host[:t_pad]
            node_idx = host[t_pad:2 * t_pad]
            became_ready = bool(host[2 * t_pad])
            from ..obs import telemetry as _obs_telemetry
            _obs_telemetry.record(host[2 * t_pad + 1:], span=sp)
            self.idle, self.releasing, self.n_tasks = \
                idle, releasing, n_tasks
            self.nz_req = nz_req
        out: List[Decision] = []
        for i in range(len(batch.tasks)):
            kind = int(decisions[i])
            name = (self.state.names[int(node_idx[i])]
                    if kind in (ALLOC, ALLOC_OB, PIPELINE) else "")
            out.append(Decision(kind, name))
        return out, became_ready


# ---------------------------------------------------------------------
# compilesvc signature provider — the per-visit scan's (gang bucket x N)
# surface and the dirty-row scatter's grow-only bucket ladder
# ---------------------------------------------------------------------

def _scatter_buckets(n_pad: int):
    """Every k_pad the update_rows scatter can dispatch for an n_pad-row
    session: the pow2 ladder up to min(high-water cap, node axis) — the
    grow-only high-water walks it — plus the over-cap plain buckets up
    to the full node axis (rare transient cluster-wide dirty sets; the
    dirty-row count never exceeds the node count)."""
    top = min(_SCATTER_HW_CAP, pad_to_bucket(n_pad, 8))
    out = []
    b = 8
    while b <= top:
        out.append(b)
        b *= 2
    while b <= pad_to_bucket(n_pad, 8):   # over-cap plain buckets
        out.append(b)
        b *= 2
    return sorted(set(out))


@_register_provider("kernels.solver")
def compile_signatures(materials):
    from ..compilesvc.registry import Signature, signature_key

    inputs = materials.cold_inputs
    if inputs is None or isinstance(inputs, str):
        return []
    device = inputs.device
    n_pad = device.n_padded
    out = []

    # --- _allocate_scan: one signature per gang task-bucket -----------
    dyn_enabled = bool(inputs.dyn_enabled)
    for t_pad in materials.gang_buckets:
        args = (device.idle, device.releasing, device.backfilled,
                device.allocatable_cm, device.nz_req, device.max_task_num,
                device.n_tasks, device.node_ok,
                np.zeros((t_pad, 3), np.float32),
                np.zeros((t_pad, 3), np.float32),
                np.zeros((t_pad, 2), np.float32),
                np.zeros(t_pad, bool),
                np.zeros((t_pad, n_pad), np.float32),
                np.ones((t_pad, n_pad), bool),
                jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                np.zeros(2, np.float32))
        statics = {"dyn_enabled": dyn_enabled}
        out.append(Signature(
            engine="visit", entry="_allocate_scan",
            key=signature_key("_allocate_scan", args, statics),
            lower=lambda a=args, s=statics: _allocate_scan.lower(*a, **s),
            run=lambda a=args, s=statics: _allocate_scan(*a, **s),
            note=f"T={t_pad} N={n_pad} dyn={dyn_enabled}"))

    # --- _scatter_rows: the high-water bucket ladder ------------------
    st = device.state
    for k in _scatter_buckets(n_pad):
        def mk(k=k):
            """Fresh donated buffers per execution (donation consumes
            them); the numpy mirrors stay authoritative."""
            return (jnp.asarray(st.idle), jnp.asarray(st.releasing),
                    jnp.asarray(st.backfilled),
                    jnp.asarray(st.allocatable[:, :2]),
                    jnp.asarray(st.nz_requested), jnp.asarray(st.n_tasks),
                    jnp.asarray(st.max_task_num),
                    jnp.asarray(st.schedulable & st.valid),
                    np.zeros(k, np.int32),
                    np.zeros((k, 3), np.float32),
                    np.zeros((k, 3), np.float32),
                    np.zeros((k, 3), np.float32),
                    np.zeros((k, 2), np.float32),
                    np.zeros((k, 2), np.float32),
                    np.zeros(k, np.int32), np.zeros(k, np.int32),
                    np.zeros(k, bool))
        key_args = mk()
        out.append(Signature(
            engine="scatter", entry="_scatter_rows",
            key=signature_key("_scatter_rows", key_args, {}),
            lower=lambda mk=mk: _scatter_rows.lower(*mk()),
            run=lambda mk=mk: _scatter_rows(*mk()),
            note=f"k={k} N={n_pad}"))
    return out
