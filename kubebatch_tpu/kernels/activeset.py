"""Active-set allocate — the steady cycle solved as a packed sub-problem
at churn grain, with the full-width solve demoted to a periodic audit.

ROADMAP item 1 (ISSUE 15). The two-level hier engine (kernels/hier.py)
made cfg6/cfg7 *representable* — peak memory [T, pool] instead of
[T, N] — but its coarse pass still folds per-(task, pool) eligibility
at [T, pool] for EVERY pool on EVERY wave: an O(T x N x R) sweep per
wave that dominates the 904 ms cfg6 steady allocate (BENCH_DEVICE.jsonl
round 13) even though the steady task axis is already churn-sized.
This module is the round-12 snapshot -> audit-view demotion applied one
layer down, to the solve itself:

1. **Active set**: the steady cycle's pending tasks (the session built
   on the folded base — EventFold's dirty rows arrive through the
   consuming ``take_active_rows()`` API plus whatever the previous
   cycle left pending) are packed into the smallest registered task
   grain (``ACT_GRAINS``: 256 / 1024 / 4096 — fixed compilesvc shape
   buckets, so churn jitter never recompiles).
2. **Pair-level coarse pass**: tasks in one (sig, nonzero-request) pair
   are interchangeable to ``resource_eligibility`` when every member
   shares ``init_resreq`` bit-for-bit (a cheap host gate checks this
   per cycle; pairs must also be exact, not octave-bucketed). The
   per-wave pool oracle then folds eligibility over PAIRS instead of
   tasks — O(P x N x R) with P two orders of magnitude under T — and
   gathers back through ``task_pair``. Same ``resource_eligibility``,
   same any-fold, same majority-pair pool score: per-task results are
   bit-identical, so pool choice, wave order, quarantine evolution and
   therefore **decisions** are bit-identical to the hier engine's
   (task_seq differs only by the static round stride, compared as
   (seq // stride, seq % stride)).
3. **Scatter-back**: each wave's winning block folds into the
   persistent node carry by ``dynamic_update_slice`` exactly as hier's
   ``_merge_block`` — the device state the next cycle reads is updated
   in place; nothing is re-derived at full width. Still ONE dispatch
   and ONE blocking readback per cycle, with the telemetry frame
   extended to the active-set words (act_tasks / act_nodes /
   act_scatter / act_demoted).
4. **Audit rung**: every ``--solve-audit-every`` N-th engaged cycle
   dispatches the COMBINED entry — full-width hier solve and active-set
   solve from the same initial state inside one jit — compares
   decisions in-kernel, commits the full-width result, and returns the
   divergence count in the frame's ``act_demoted`` word (so audit
   cycles also cost exactly one readback). Any divergence — or a fired
   ``solve.activeset`` fault seam — calls :func:`demote`: the engine
   disables itself for the rest of the process and cycles fall back to
   the always-sound full-width solve, the same demote-not-raise rung
   as cache.fold (counted in ``activeset_demotions_total``,
   flight-dumped when armed, chaos-armed in sim/chaos.py).

Affinity / host-port cycles are not expressible here (same contract as
hier); the action layer gates them to the flat engines first.
"""
from __future__ import annotations

import logging
import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compilesvc import instrument as _instrument
from ..compilesvc import register_provider as _register_provider
from ..faults import armed as _faults_armed
from ..faults import should_fail as _should_fail
from ..metrics import (count_activeset_audit, count_activeset_cycle,
                       count_activeset_demotion, count_blocking_readback,
                       count_deferred_readback)
from ..obs import span as _span
from .batched import (CycleArrays, RoundState, _IMAX, _PACK_BOOL, _PACK_F32,
                      _PACK_I32, _pack_result, _rollback_stranded, _round,
                      _stranded_jobs, resource_eligibility)
from .fused import (ALLOC, ALLOC_OB, K_DRF_SHARE, K_GANG_READY, K_PRIORITY,
                    K_PROP_SHARE, PIPELINE, SKIP)
from .hier import (_block_arrays, _block_state, _merge_block, hier_allocate,
                   hier_pool_size, prepare_hier)
from .narrow import narrow_enabled
from .pack import pack_inputs
from .pack import unpack as _unpack
from .solver import dynamic_node_score
from .telemetry import ENGINE_ACTIVESET, F_ACT_DEMOTED, decision_frame
from .tensorize import VEC_EPS

log = logging.getLogger("kubebatch.activeset")

_BIG_NEG = jnp.float32(-3.0e38)

#: the registered task grains — the packed active set pads to the
#: smallest one that fits, so every steady dispatch lands on a shape
#: compilesvc already compiled regardless of per-cycle churn jitter
ACT_GRAINS = (256, 1024, 4096)

#: task-axis CycleInputs attributes re-sliced/padded to the grain
#: (everything else — job/queue/sig/pair/node axes — packs unchanged)
_TASK_AXIS = ("resreq", "init_resreq", "task_nz", "task_job", "task_rank",
              "task_sig", "task_valid")

#: the active-set float pack adds the per-pair init_resreq
#: representatives the pair-level coarse pass screens with
_ACT_PACK_F32 = _PACK_F32 + ("pair_init_resreq",)

AUDIT_EVERY_ENV = "KUBEBATCH_SOLVE_AUDIT_EVERY"
DEFAULT_AUDIT_EVERY = 16


def activeset_grain(n_real: int) -> int:
    """Smallest registered grain holding ``n_real`` active tasks; 0 when
    the active set outgrows the largest grain (the engine declines and
    the cycle runs full-width — cold starts land here by design)."""
    for g in ACT_GRAINS:
        if n_real <= g:
            return g
    return 0


# ---------------------------------------------------------------------
# engine state: audit cadence + the demotion rung (process-lifetime,
# like cache/eventfold.py's enabled flag — restart to re-enable)
# ---------------------------------------------------------------------

_audit_every: Optional[int] = None
_cycle_idx = 0
_demoted = False


def audit_every() -> int:
    global _audit_every
    if _audit_every is None:
        raw = os.environ.get(AUDIT_EVERY_ENV, "").strip()
        _audit_every = int(raw) if raw else DEFAULT_AUDIT_EVERY
    return _audit_every


def set_audit_every(n: int) -> None:
    """Audit cadence: every n-th engaged cycle runs the combined
    full-width comparison entry (0 disables audits — soak tests that
    audit out-of-band use this). ``--solve-audit-every`` lands here."""
    global _audit_every
    _audit_every = max(0, int(n))


def demoted() -> bool:
    return _demoted


def demote(reason: str) -> None:
    """The ladder rung back to the full-width solve: disable the
    active-set engine for the rest of the process. An audit divergence
    or a fired ``solve.activeset`` seam lands here — never an exception
    into the scheduling loop; a slower-but-sound cycle beats a wrong
    placement. Idempotent."""
    global _demoted
    if _demoted:
        return
    _demoted = True
    count_activeset_demotion(reason)
    log.error("active-set solve DEMOTED to full-width (reason=%s): "
              "steady cycles fall back to the hier engine; restart to "
              "re-enable", reason)
    try:
        from ..obs import flight as _flight
        _flight.dump(f"activeset_demotion-{reason}")
    except Exception:             # pragma: no cover — observer bug
        log.exception("activeset demotion flight dump failed")


def reset() -> None:
    """Test/bench hook: forget the demotion and restart the cadence."""
    global _cycle_idx, _demoted
    _cycle_idx = 0
    _demoted = False


# ---------------------------------------------------------------------
# the pair-level coarse pass
# ---------------------------------------------------------------------

def _pair_coarse(state: RoundState, a: CycleArrays, pair_init, pool: int,
                 pipe_enabled: bool, dyn_enabled: bool):
    """hier's pool oracle folded over PAIRS instead of tasks.

    ``resource_eligibility`` reads exactly two task-axis inputs —
    ``init_resreq`` and ``task_sig`` — so substituting the per-pair
    representatives (host-verified bit-identical to every member's row,
    see ``_pair_init_rows``) and gathering through ``task_pair`` yields
    the same [T, B] any-eligibility hier's ``_coarse_pass`` computes, at
    [P, pool] peak work instead of [T, pool]. The majority-pair pool
    score is hier's own, verbatim.

    Returns (task_pool_elig [T, B] bool, pool_best [B] f32)."""
    eps = jnp.asarray(VEC_EPS)
    n_pad = a.node_ok.shape[0]
    p_pad = a.pair_sig.shape[0]
    n_pools = n_pad // pool

    base = a.node_ok & (state.n_tasks < a.max_task_num)      # [N]

    def one_pool(p, acc_elig):
        off = p * pool
        bs = _block_state(state, off, pool)
        ba = _block_arrays(a, off, pool)
        pa = ba._replace(init_resreq=pair_init, task_sig=ba.pair_sig)
        elig = resource_eligibility(bs.idle, bs.releasing, bs.n_tasks,
                                    pa, pipe_enabled, eps)   # [P, pool]
        col = jnp.any(elig, axis=1)                          # [P]
        return jax.lax.dynamic_update_slice(acc_elig, col[:, None], (0, p))

    pair_pool_elig = jax.lax.fori_loop(
        0, n_pools, one_pool, jnp.zeros((p_pad, n_pools), bool))
    task_pool_elig = pair_pool_elig[jnp.maximum(a.task_pair, 0)]

    # demand-majority cohort — identical to hier._coarse_pass (the
    # per-task segment_sum is [T], not [T, N]; no need to pair-fold it)
    engaged = (a.task_valid & (state.task_state == SKIP)
               & state.job_alive[jnp.maximum(a.task_job, 0)]
               & a.job_valid[jnp.maximum(a.task_job, 0)])
    pair_demand = jax.ops.segment_sum(
        engaged.astype(jnp.int32), a.task_pair,
        num_segments=p_pad)
    maj = jnp.argmax(pair_demand)
    sc_maj = a.sig_scores[a.pair_sig[maj]].astype(jnp.float32)
    if dyn_enabled:
        sc_maj = sc_maj + dynamic_node_score(state.nz_req, a.pair_nz[maj],
                                             a.allocatable_cm,
                                             a.dyn_weights)
    pred_maj = a.sig_pred[a.pair_sig[maj]]
    pool_best = jnp.where(pred_maj & base, sc_maj, _BIG_NEG
                          ).reshape(n_pools, pool).max(axis=1)
    return task_pool_elig, pool_best


# ---------------------------------------------------------------------
# the wave loop — hier_allocate's exact structure with the pair-level
# oracle, plus a scatter counter for the telemetry frame
# ---------------------------------------------------------------------

def activeset_allocate(state: RoundState, a: CycleArrays, pair_init,
                       job_keys: Tuple[str, ...] = (K_PRIORITY,
                                                    K_GANG_READY,
                                                    K_DRF_SHARE),
                       queue_keys: Tuple[str, ...] = (K_PROP_SHARE,),
                       prop_overused: bool = True,
                       dyn_enabled: bool = False,
                       pipe_enabled: bool = True,
                       max_rounds: int = 64,
                       pool_size: int = 0,
                       max_waves: int = 0,
                       gang_enabled: bool = True,
                       narrow: bool = True):
    """The whole active-set cycle in ONE device dispatch: waves of
    (pair coarse pass -> within-bucket round loop) at grain task width.
    Returns hier_allocate's tuple plus ``blocks`` — the count of block
    solves folded back into the node carry (x pool_size = node rows
    scattered, the frame's act_scatter word)."""
    t_pad = a.task_valid.shape[0]
    n_pad = a.node_ok.shape[0]
    pool = pool_size if pool_size > 0 else hier_pool_size(n_pad)
    assert n_pad % pool == 0, (n_pad, pool)
    n_pools = n_pad // pool
    if max_waves <= 0:
        max_waves = (t_pad + 8) * (n_pools + 1)

    def block_rounds(st, barrays, rounds0, elig_elsewhere):
        def cond(carry):
            _, round_idx, progress = carry
            return progress & (round_idx < max_rounds)

        def body(carry):
            s, round_idx, _ = carry
            ns, progress = _round(s, barrays, round_idx, job_keys,
                                  queue_keys, prop_overused, dyn_enabled,
                                  pipe_enabled, seq_stride=t_pad,
                                  narrow=narrow,
                                  elig_elsewhere=elig_elsewhere,
                                  pair_init=pair_init)
            return ns, round_idx + 1, progress

        init = (st, rounds0, jnp.asarray(True))
        return jax.lax.while_loop(cond, body, init)

    def waves_loop(state, rounds0, blocks0):
        def cond(carry):
            _, _, wave, _, has_work, _, _, _ = carry
            return has_work & (wave < max_waves)

        def body(carry):
            st, rounds, wave, blocked, _, occ0, fill0, blocks = carry
            task_pool_elig, pool_best = _pair_coarse(st, a, pair_init,
                                                     pool, pipe_enabled,
                                                     dyn_enabled)
            pending = (a.task_valid & (st.task_state == SKIP)
                       & st.job_alive[jnp.maximum(a.task_job, 0)]
                       & a.job_valid[jnp.maximum(a.task_job, 0)])
            cand_cnt = (task_pool_elig
                        & pending[:, None]).sum(axis=0)      # [B]
            key = jnp.where((cand_cnt > 0) & ~blocked, pool_best, -jnp.inf)
            has_work = jnp.any(key > -jnp.inf)
            winner = jnp.argmax(key)
            first = wave == 0
            occ_n = jnp.where(first,
                              (cand_cnt > 0).sum().astype(jnp.int32), occ0)
            fill_n = jnp.where(first, cand_cnt[winner].astype(jnp.int32),
                               fill0)

            def run_block(args):
                st, rounds, blocked, blocks = args
                off = (winner * pool).astype(jnp.int32)
                elig_elsewhere = jnp.any(
                    task_pool_elig
                    & (jnp.arange(n_pools) != winner)[None, :], axis=1)
                bstate = _block_state(st, off, pool)
                barrays = _block_arrays(a, off, pool)
                bfinal, rounds_n, _ = block_rounds(bstate, barrays, rounds,
                                                   elig_elsewhere)
                merged = _merge_block(st, bfinal, off, pool)
                progressed = jnp.any(merged.task_state != st.task_state)
                blocked_n = jnp.where(
                    progressed, jnp.zeros_like(blocked),
                    blocked.at[winner].set(True))
                return merged, rounds_n, blocked_n, blocks + 1

            st_out, rounds_out, blocked_out, blocks_out = jax.lax.cond(
                has_work, run_block, lambda args: args,
                (st, rounds, blocked, blocks))
            return (st_out, rounds_out, wave + 1, blocked_out, has_work,
                    occ_n, fill_n, blocks_out)

        init = (state, rounds0, jnp.int32(0),
                jnp.zeros(n_pools, bool), jnp.asarray(True),
                jnp.int32(0), jnp.int32(0), blocks0)
        st, rounds, _, _, _, occ, fill, blocks = jax.lax.while_loop(
            cond, body, init)

        # terminal FAIL sweep — one block round on pool 0 with
        # elig_elsewhere = any-pool eligibility, exactly as hier's
        task_pool_elig, _ = _pair_coarse(st, a, pair_init, pool,
                                         pipe_enabled, dyn_enabled)
        elig_any = jnp.any(task_pool_elig, axis=1)
        off0 = jnp.int32(0)
        bfinal, rounds, _ = block_rounds(
            _block_state(st, off0, pool), _block_arrays(a, off0, pool),
            rounds, elig_any)
        return (_merge_block(st, bfinal, off0, pool), rounds, occ, fill,
                blocks + 1)

    final, rounds, pool_occ, bucket_fill, blocks = waves_loop(
        state, jnp.int32(0), jnp.int32(0))

    retries = jnp.int32(0)
    stranded = jnp.int32(0)
    if gang_enabled:
        def epi_cond(carry):
            s, _, k, _ = carry
            return (k < 3) & jnp.any(_stranded_jobs(s, a))

        def epi_body(carry):
            s, rounds, k, blocks = carry
            s, _ = _rollback_stranded(s, a, revive=True)
            s, rounds, _, _, blocks = waves_loop(s, rounds, blocks)
            return s, rounds, k + 1, blocks

        final, rounds, retries, blocks = jax.lax.while_loop(
            epi_cond, epi_body, (final, rounds, jnp.int32(0), blocks))
        final, stranded_mask = _rollback_stranded(final, a, revive=False)
        stranded = stranded_mask.sum().astype(jnp.int32)
    return final, rounds, retries, stranded, pool_occ, bucket_fill, blocks


# ---------------------------------------------------------------------
# packed jit entries
# ---------------------------------------------------------------------

def _state_arrays(f, i, b):
    """RoundState initial fields + CycleArrays from unpacked dicts —
    the construction _hier_packed inlines, shared here by the steady
    and the combined audit entry."""
    t_pad = i["task_job"].shape[0]

    def mk_state(idle, releasing, n_tasks, nz_req):
        return RoundState(
            idle=idle, releasing=releasing, n_tasks=n_tasks, nz_req=nz_req,
            q_allocated=f["q_alloc0"], j_allocated=f["j_alloc0"],
            alloc_cnt=i["init_allocated"], job_alive=b["job_valid"],
            task_state=jnp.full(t_pad, SKIP, jnp.int32),
            task_node=jnp.full(t_pad, -1, jnp.int32),
            task_seq=jnp.full(t_pad, _IMAX, jnp.int32))

    def mk_arrays(backfilled, allocatable_cm, max_task_num, node_ok):
        return CycleArrays(
            backfilled=backfilled, allocatable_cm=allocatable_cm,
            max_task_num=max_task_num, node_ok=node_ok,
            resreq=f["resreq"], init_resreq=f["init_resreq"],
            task_nz=f["task_nz"], task_job=i["task_job"],
            task_rank=i["task_rank"], task_sig=i["task_sig"],
            task_pair=i["task_pair"], task_valid=b["task_valid"],
            sig_scores=f["sig_scores"], sig_pred=b["sig_pred"],
            pair_sig=i["pair_sig"], pair_nz=f["pair_nz"],
            order_min_available=i["order_min_available"],
            job_queue=i["job_queue"], job_priority=f["job_priority"],
            job_create_rank=i["job_create_rank"], job_valid=b["job_valid"],
            q_deserved=f["q_deserved"], q_create_rank=i["q_create_rank"],
            cluster_total=f["cluster_total"], dyn_weights=f["dyn_weights"])

    return mk_state, mk_arrays


_ACT_STATICS = ("lay_f", "lay_i", "lay_b", "job_keys", "queue_keys",
                "prop_overused", "dyn_enabled", "pipe_enabled",
                "max_rounds", "pool_size", "max_waves", "gang_enabled",
                "narrow", "narrow_gate")

#: positional indices of the persistent device carry in the steady
#: entry's signature (idle / releasing / n_tasks / nz_req) — the
#: donate_argnums the pipelined twin hands back to XLA
_ACT_CARRY_ARGNUMS = (3, 4, 5, 6)


def _activeset_fn(buf_f, buf_i, buf_b, idle, releasing, n_tasks,
                  nz_req, backfilled, allocatable_cm, max_task_num,
                  node_ok, lay_f, lay_i, lay_b, job_keys, queue_keys,
                  prop_overused, dyn_enabled, pipe_enabled, max_rounds,
                  pool_size, max_waves=0, gang_enabled=True,
                  narrow=True, narrow_gate=False):
    f = _unpack(buf_f, lay_f)
    i = _unpack(buf_i, lay_i)
    b = _unpack(buf_b, lay_b)
    mk_state, mk_arrays = _state_arrays(f, i, b)
    state = mk_state(idle, releasing, n_tasks, nz_req)
    arrays = mk_arrays(backfilled, allocatable_cm, max_task_num, node_ok)
    grain = i["task_job"].shape[0]
    n_pad = node_ok.shape[0]
    pool = pool_size if pool_size > 0 else hier_pool_size(n_pad)
    final, rounds, retries, stranded, pool_occ, bucket_fill, blocks = \
        activeset_allocate(
            state, arrays, f["pair_init_resreq"], job_keys=job_keys,
            queue_keys=queue_keys, prop_overused=prop_overused,
            dyn_enabled=dyn_enabled, pipe_enabled=pipe_enabled,
            max_rounds=max_rounds, pool_size=pool, max_waves=max_waves,
            gang_enabled=gang_enabled, narrow=narrow)
    frame = decision_frame(
        ENGINE_ACTIVESET, final.task_state, final.task_seq,
        b["task_valid"], waves=rounds, stride=grain, narrow=narrow,
        narrow_gate=narrow_gate, retries=retries, stranded=stranded,
        pool_occ=pool_occ, bucket_fill=bucket_fill,
        act_tasks=b["task_valid"].sum().astype(jnp.int32),
        act_nodes=pool_occ * jnp.int32(pool),
        act_scatter=blocks * jnp.int32(pool), act_demoted=0)
    return _pack_result(final, rounds, frame)


_activeset_packed = _instrument(
    "activeset", "_activeset_packed",
    jax.jit(_activeset_fn, static_argnames=_ACT_STATICS))

#: the pipelined twin (ISSUE 16): same traced function, but the carry
#: slots are DONATED — XLA writes the next cycle's carry into the old
#: buffers instead of allocating. Only dispatched off-CPU (XLA-CPU
#: ignores donation with a warning per call); the executor keeps a
#: copy-shadow of the carry for conflict rollback, which doubles as the
#: second slot of the double-buffer pair.
_activeset_packed_donated = _instrument(
    "activeset", "_activeset_packed_donated",
    jax.jit(_activeset_fn, static_argnames=_ACT_STATICS,
            donate_argnums=_ACT_CARRY_ARGNUMS))

_donation: Optional[bool] = None


def _donation_enabled() -> bool:
    """Buffer donation on the carry slots — off on the CPU backend,
    where XLA ignores donate_argnums (it would warn every dispatch and
    donate nothing)."""
    global _donation
    if _donation is None:
        _donation = jax.default_backend() != "cpu"
    return _donation


def _divergence(afinal: RoundState, grain: int, ffinal: RoundState,
                t_full: int, valid):
    """In-kernel decision comparison over the rows both solves carry
    (``min(grain, t_full)`` — every REAL task lives below both widths;
    rows beyond are padding, constant SKIP/-1/IMAX on both sides).
    task_seq encodes round * stride + rank with each solve's own static
    stride, so equality is on the (round, rank) decomposition."""
    m = min(grain, t_full)
    va = valid[:m]
    sa, na, qa = (afinal.task_state[:m], afinal.task_node[:m],
                  afinal.task_seq[:m])
    sf, nf, qf = (ffinal.task_state[:m], ffinal.task_node[:m],
                  ffinal.task_seq[:m])
    div = sa != sf
    placed = (sf == ALLOC) | (sf == ALLOC_OB) | (sf == PIPELINE)
    both = placed & (sa == sf)
    div |= both & (na != nf)
    div |= both & ((qa // grain) != (qf // t_full))
    div |= both & ((qa % grain) != (qf % t_full))
    return (va & div).sum().astype(jnp.int32)


@partial(jax.jit, static_argnames=("alay_f", "alay_i", "alay_b", "flay_f",
                                   "flay_i", "flay_b", "job_keys",
                                   "queue_keys", "prop_overused",
                                   "dyn_enabled", "pipe_enabled",
                                   "amax_rounds", "fmax_rounds",
                                   "pool_size", "max_waves",
                                   "gang_enabled", "narrow",
                                   "narrow_gate"))
def _activeset_audit_packed(abuf_f, abuf_i, abuf_b, fbuf_f, fbuf_i, fbuf_b,
                            idle, releasing, n_tasks, nz_req, backfilled,
                            allocatable_cm, max_task_num, node_ok,
                            alay_f, alay_i, alay_b, flay_f, flay_i, flay_b,
                            job_keys, queue_keys, prop_overused,
                            dyn_enabled, pipe_enabled, amax_rounds,
                            fmax_rounds, pool_size, max_waves=0,
                            gang_enabled=True, narrow=True,
                            narrow_gate=False):
    """The audit cycle's ONE dispatch: full-width hier solve and
    active-set solve from the same initial device state, decisions
    compared in-kernel, the FULL-WIDTH result committed (the audit is
    also the repair pass), divergence returned in the frame's
    act_demoted word — so even audit cycles pay a single readback."""
    af = _unpack(abuf_f, alay_f)
    ai = _unpack(abuf_i, alay_i)
    ab = _unpack(abuf_b, alay_b)
    ff = _unpack(fbuf_f, flay_f)
    fi = _unpack(fbuf_i, flay_i)
    fb = _unpack(fbuf_b, flay_b)
    amk_state, amk_arrays = _state_arrays(af, ai, ab)
    fmk_state, fmk_arrays = _state_arrays(ff, fi, fb)
    grain = ai["task_job"].shape[0]
    t_full = fi["task_job"].shape[0]
    n_pad = node_ok.shape[0]
    pool = pool_size if pool_size > 0 else hier_pool_size(n_pad)

    afinal, _, _, _, aocc, _, ablocks = activeset_allocate(
        amk_state(idle, releasing, n_tasks, nz_req),
        amk_arrays(backfilled, allocatable_cm, max_task_num, node_ok),
        af["pair_init_resreq"], job_keys=job_keys, queue_keys=queue_keys,
        prop_overused=prop_overused, dyn_enabled=dyn_enabled,
        pipe_enabled=pipe_enabled, max_rounds=amax_rounds, pool_size=pool,
        max_waves=max_waves, gang_enabled=gang_enabled, narrow=narrow)
    ffinal, frounds, fretries, fstranded, focc, ffill = hier_allocate(
        fmk_state(idle, releasing, n_tasks, nz_req),
        fmk_arrays(backfilled, allocatable_cm, max_task_num, node_ok),
        job_keys=job_keys, queue_keys=queue_keys,
        prop_overused=prop_overused, dyn_enabled=dyn_enabled,
        pipe_enabled=pipe_enabled, max_rounds=fmax_rounds, pool_size=pool,
        max_waves=max_waves, gang_enabled=gang_enabled, narrow=narrow)

    div = _divergence(afinal, grain, ffinal, t_full, ab["task_valid"])
    frame = decision_frame(
        ENGINE_ACTIVESET, ffinal.task_state, ffinal.task_seq,
        fb["task_valid"], waves=frounds, stride=t_full, narrow=narrow,
        narrow_gate=narrow_gate, retries=fretries, stranded=fstranded,
        pool_occ=focc, bucket_fill=ffill,
        act_tasks=ab["task_valid"].sum().astype(jnp.int32),
        act_nodes=aocc * jnp.int32(pool),
        act_scatter=ablocks * jnp.int32(pool), act_demoted=div)
    return _pack_result(ffinal, frounds, frame)


_activeset_audit_packed = _instrument("activeset",
                                      "_activeset_audit_packed",
                                      _activeset_audit_packed)


# ---------------------------------------------------------------------
# host-side prepare — the (args, statics) the entries dispatch, shared
# by the live path and the compilesvc signature provider
# ---------------------------------------------------------------------

def _regrain(arr, grain: int):
    arr = np.asarray(arr)
    t = arr.shape[0]
    if t == grain:
        return arr
    if t > grain:
        # real tasks occupy rows [:n_real] (pair_terms and TaskBatch
        # both pin this); the slice only drops padding
        return arr[:grain]
    pad = [(0, grain - t)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def _pair_init_rows(inputs, task_pair, pair_sig) -> Optional[np.ndarray]:
    """Per-pair init_resreq representatives [P_pad, R] — or None when
    some pair's members differ bit-for-bit, the case where the
    pair-level screen would not equal the per-task one and the engine
    must decline the cycle. Padding pairs keep zero rows (no task
    gathers through them)."""
    n_real = inputs.n_tasks_real
    init = np.asarray(inputs.init_resreq)[:n_real]
    p_pad = int(np.asarray(pair_sig).shape[0])
    out = np.zeros((p_pad, init.shape[1] if init.ndim == 2 else 0),
                   init.dtype if init.size else np.float32)
    if n_real == 0:
        return out
    tp = np.asarray(task_pair)[:n_real]
    uniq, first = np.unique(tp, return_index=True)
    rep = init[first]
    if not np.array_equal(rep[np.searchsorted(uniq, tp)], init):
        return None
    out[uniq] = rep
    return out


def prepare_activeset(device, inputs, grain: int = 0, max_rounds: int = 0,
                      pool_size: int = 0):
    """The (args, statics, grain) the steady packed entry dispatches —
    or None when the engine declines: affinity cycle, active set over
    the largest grain, octave-bucketed (inexact) pairs, or a pair whose
    members' init_resreq rows differ. ``grain`` forces a specific
    registered bucket (the provider registers all three)."""
    if getattr(inputs, "affinity", None) is not None:
        return None
    n_real = inputs.n_tasks_real
    g = grain if grain > 0 else activeset_grain(n_real)
    if g <= 0 or n_real > g:
        return None
    task_pair, pair_sig, pair_nz, exact = inputs.pair_terms()
    if not exact:
        return None
    pair_init = _pair_init_rows(inputs, task_pair, pair_sig)
    if pair_init is None:
        return None

    override = {n: _regrain(getattr(inputs, n), g) for n in _TASK_AXIS}
    override["task_pair"] = _regrain(task_pair, g)
    override["pair_sig"] = pair_sig
    override["pair_nz"] = pair_nz
    override["pair_init_resreq"] = pair_init
    buf_f, lay_f, buf_i, lay_i, buf_b, lay_b = pack_inputs(
        lambda n: override[n] if n in override else getattr(inputs, n),
        _ACT_PACK_F32, _PACK_I32, _PACK_BOOL)

    t_full = inputs.task_valid.shape[0]
    n_pad = int(device.node_ok.shape[0])
    pool = pool_size if pool_size > 0 else hier_pool_size(n_pad)
    if max_rounds <= 0:
        max_rounds = g + 8
    # narrow by the FULL [T, N] problem so the dtype diet — and hence
    # the audit's bit-identity contract — matches the full-width twin
    narrow = narrow_enabled(
        n_pad, t_full, static_scores=inputs.sig_scores,
        dyn_weights=(inputs.dyn_weights if inputs.dyn_enabled
                     else None))
    args = (buf_f, buf_i, buf_b,
            device.idle, device.releasing, device.n_tasks, device.nz_req,
            device.backfilled, device.allocatable_cm, device.max_task_num,
            device.node_ok)
    statics = dict(
        lay_f=lay_f, lay_i=lay_i, lay_b=lay_b,
        job_keys=inputs.job_keys, queue_keys=inputs.queue_keys,
        prop_overused=inputs.prop_overused,
        pipe_enabled=inputs.pipe_enabled,
        dyn_enabled=inputs.dyn_enabled,
        max_rounds=min(max_rounds, 4096),
        pool_size=pool,
        gang_enabled=inputs.gang_enabled,
        narrow=narrow,
        narrow_gate=(not narrow and narrow_enabled(n_pad, t_full)))
    return args, statics, g


def prepare_activeset_audit(device, inputs, grain: int = 0,
                            max_rounds: int = 0, pool_size: int = 0):
    """(args, statics, grain) for the combined audit entry: the
    active-set plan joined with prepare_hier's full-width plan (device
    arrays passed once, shared by both halves). None whenever the
    steady plan is None."""
    plan = prepare_activeset(device, inputs, grain=grain,
                             max_rounds=max_rounds, pool_size=pool_size)
    if plan is None:
        return None
    aargs, astatics, g = plan
    fargs, fstatics = prepare_hier(device, inputs,
                                   pool_size=astatics["pool_size"])
    args = aargs[:3] + fargs[:3] + fargs[3:]
    statics = dict(
        alay_f=astatics["lay_f"], alay_i=astatics["lay_i"],
        alay_b=astatics["lay_b"],
        flay_f=fstatics["lay_f"], flay_i=fstatics["lay_i"],
        flay_b=fstatics["lay_b"],
        job_keys=fstatics["job_keys"], queue_keys=fstatics["queue_keys"],
        prop_overused=fstatics["prop_overused"],
        pipe_enabled=fstatics["pipe_enabled"],
        dyn_enabled=fstatics["dyn_enabled"],
        amax_rounds=astatics["max_rounds"],
        fmax_rounds=fstatics["max_rounds"],
        pool_size=fstatics["pool_size"],
        gang_enabled=fstatics["gang_enabled"],
        narrow=fstatics["narrow"],
        narrow_gate=fstatics["narrow_gate"])
    return args, statics, g


# ---------------------------------------------------------------------
# solve drivers — one dispatch, one blocking readback, carry committed
# ---------------------------------------------------------------------

def _read_result(packed, t: int, sp):
    count_blocking_readback()
    with _span("readback", cat="readback"):
        out = np.asarray(packed)
    task_state = out[:t]
    task_node = out[t:2 * t]
    task_seq = out[2 * t:3 * t]
    rounds = out[3 * t]
    frame = out[3 * t + 1:]
    from ..obs import telemetry as _obs_telemetry
    _obs_telemetry.record(frame, span=sp)
    return task_state, task_node, task_seq, int(rounds), frame


def _commit(device, final: RoundState) -> None:
    device.idle = final.idle
    device.releasing = final.releasing
    device.n_tasks = final.n_tasks
    device.nz_req = final.nz_req


def solve_activeset(device, inputs, plan=None):
    """The steady active-set cycle — CycleInputs in, (task_state,
    task_node, task_seq, rounds) numpy out at grain width (every real
    task row lives below the grain). None when the engine declines."""
    if plan is None:
        plan = prepare_activeset(device, inputs)
    if plan is None:
        return None
    args, statics, g = plan
    with _span("activeset_allocate", cat="kernel") as sp:
        final, packed = _activeset_packed(*args, **statics)
        task_state, task_node, task_seq, rounds, _ = _read_result(
            packed, g, sp)
        _commit(device, final)
    return task_state, task_node, task_seq, rounds


def solve_activeset_audit(device, inputs, plan=None):
    """The combined audit cycle: decisions are the FULL-WIDTH solve's
    (the audit doubles as the repair pass), divergence read from the
    frame's act_demoted word. Returns (task_state, task_node, task_seq,
    rounds, divergence) or None when the engine declines."""
    if plan is None:
        plan = prepare_activeset_audit(device, inputs)
    if plan is None:
        return None
    args, statics, _ = plan
    t_full = inputs.task_valid.shape[0]
    with _span("activeset_audit", cat="kernel") as sp:
        final, packed = _activeset_audit_packed(*args, **statics)
        task_state, task_node, task_seq, rounds, frame = _read_result(
            packed, t_full, sp)
        _commit(device, final)
    return task_state, task_node, task_seq, rounds, int(
        frame[F_ACT_DEMOTED])


def solve_cycle(device, inputs):
    """The action layer's one entry point: None when the engine declines
    (demoted, oversize active set, inexact pairs, affinity) — the caller
    falls back to the full-width solve — else the cycle's decisions,
    with the audit cadence, the fault seam, and the demotion rung
    handled here."""
    global _cycle_idx
    if _demoted:
        return None
    plan = prepare_activeset(device, inputs)
    if plan is None:
        return None
    if _faults_armed() and _should_fail("solve.activeset"):
        # demote-not-raise, the cache.fold discipline: the cycle that
        # crossed the fired seam still runs — on the sound full-width
        # engine — and every later cycle does too
        demote("fault")
        return None
    idx = _cycle_idx
    _cycle_idx += 1
    n = audit_every()
    audit = n > 0 and idx % n == 0
    count_activeset_cycle(audit)
    if not audit:
        return solve_activeset(device, inputs, plan=plan)
    res = solve_activeset_audit(device, inputs)
    if res is None:                       # pragma: no cover — plan raced
        return None
    task_state, task_node, task_seq, rounds, div = res
    count_activeset_audit(div == 0)
    if div:
        demote("audit")
    return task_state, task_node, task_seq, rounds


# ---------------------------------------------------------------------
# async dispatch (ISSUE 16; runtime/pipeline.py is the only consumer):
# the dispatch returns immediately with the result still on device —
# the readback happens at consume time, a cycle later, off the
# critical path
# ---------------------------------------------------------------------

def carry_shadow(device):
    """Snapshot the persistent carry BEFORE an async dispatch, for the
    conflict-invalidation rollback. With donation on, the dispatched
    buffers are dead the moment the call returns, so the shadow must be
    real device copies — the second slot of the double-buffer pair;
    without donation the old arrays stay alive and plain references
    suffice (zero cost)."""
    carry = (device.idle, device.releasing, device.n_tasks, device.nz_req)
    if _donation_enabled():
        return tuple(jnp.array(c, copy=True) for c in carry)
    return carry


class PendingSolve:
    """A dispatched-but-unread active-set solve. The carry was already
    committed forward at dispatch (the NEXT cycle's pack chains on the
    device-side futures without any host sync); ``consume()`` pays the
    one deferred readback and returns the decision arrays;
    ``restore_carry()`` rolls the device back to the pre-dispatch
    shadow when the consume-time conflict check invalidates the
    result."""

    __slots__ = ("packed", "t", "audit", "shadow", "device")

    def __init__(self, packed, t: int, audit: bool, shadow, device):
        self.packed = packed
        self.t = t
        self.audit = audit
        self.shadow = shadow
        self.device = device

    def consume(self, sp=None):
        """Block on the in-flight result (usually already landed — the
        host ran a whole cycle meanwhile and ``copy_to_host_async``
        started the transfer at dispatch) and decode it. Returns
        (task_state, task_node, task_seq, rounds) at ``self.t`` width.
        Audit pendings compare in-kernel like the sync path: the
        committed result is the full-width solve's (always sound), so
        the decisions replay regardless; a divergence demotes."""
        count_deferred_readback()
        out = np.asarray(self.packed)
        t = self.t
        task_state = out[:t]
        task_node = out[t:2 * t]
        task_seq = out[2 * t:3 * t]
        rounds = out[3 * t]
        frame = out[3 * t + 1:]
        from ..obs import telemetry as _obs_telemetry
        _obs_telemetry.record(frame, span=sp)
        if self.audit:
            div = int(frame[F_ACT_DEMOTED])
            count_activeset_audit(div == 0)
            if div:
                demote("audit")
        return task_state, task_node, task_seq, int(rounds)

    def restore_carry(self) -> None:
        d = self.device
        d.idle, d.releasing, d.n_tasks, d.nz_req = self.shadow


def solve_cycle_async(device, inputs) -> Optional[PendingSolve]:
    """solve_cycle's future-shaped twin: same decline gates, same fault
    seam, same audit cadence — but the dispatch returns a
    :class:`PendingSolve` instead of blocking on the readback. None
    when the engine declines (the caller runs the cycle
    sequentially)."""
    global _cycle_idx
    if _demoted:
        return None
    plan = prepare_activeset(device, inputs)
    if plan is None:
        return None
    if _faults_armed() and _should_fail("solve.activeset"):
        demote("fault")
        return None
    idx = _cycle_idx
    _cycle_idx += 1
    n = audit_every()
    audit = n > 0 and idx % n == 0
    count_activeset_cycle(audit)
    shadow = carry_shadow(device)
    if audit:
        aplan = prepare_activeset_audit(device, inputs)
        if aplan is None:                 # pragma: no cover — plan raced
            return None
        args, statics, _ = aplan
        t = inputs.task_valid.shape[0]
        with _span("activeset_audit_dispatch", cat="kernel"):
            final, packed = _activeset_audit_packed(*args, **statics)
    else:
        args, statics, g = plan
        t = g
        with _span("activeset_dispatch", cat="kernel"):
            if _donation_enabled():
                final, packed = _activeset_packed_donated(*args, **statics)
            else:
                final, packed = _activeset_packed(*args, **statics)
    # the carry chains forward as device-side futures — cycle N+1's
    # pack reads these without waiting for the solve to finish
    _commit(device, final)
    try:
        # start the device->host transfer now; consume()'s np.asarray a
        # cycle later then finds the bytes already on the host
        packed.copy_to_host_async()
    except Exception:                     # pragma: no cover — backend quirk
        pass
    return PendingSolve(packed, t, audit, shadow, device)


# ---------------------------------------------------------------------
# compilesvc signature provider — the churn-grain buckets (256 / 1024 /
# 4096) register for hier-scale node axes so steady churn jitter always
# lands on a compiled shape, plus the combined audit entry at the
# materials' natural grain
# ---------------------------------------------------------------------

@_register_provider("kernels.activeset")
def compile_signatures(materials):
    from ..actions.allocate import AUTO_HIER_MIN_NODES
    from ..compilesvc.registry import Signature, signature_key

    out = []
    inputs = materials.steady_inputs
    if inputs is None or isinstance(inputs, str):
        return out
    if len(inputs.device.state.names) < AUTO_HIER_MIN_NODES:
        return out      # flat engines own this node axis
    if getattr(inputs, "affinity", None) is not None:
        return out      # affinity gates to the flat engines
    pipes = ((False, True)
             if ("reclaim" in materials.actions
                 or "preempt" in materials.actions)
             else (bool(inputs.pipe_enabled),))
    for g in ACT_GRAINS:
        plan = prepare_activeset(inputs.device, inputs, grain=g)
        if plan is None:
            continue
        args, base, _ = plan
        for pipe in pipes:
            statics = dict(base, pipe_enabled=pipe)
            out.append(Signature(
                engine="activeset", entry="_activeset_packed",
                key=signature_key("_activeset_packed", args, statics),
                lower=lambda a=args, s=statics: _activeset_packed.lower(
                    *a, **s),
                run=lambda a=args, s=statics: _activeset_packed(*a, **s),
                note=(f"steady grain={g} N={inputs.device.n_padded} "
                      f"pool={statics['pool_size']} pipe={pipe}")))
            if _donation_enabled():
                # the pipelined twin compiles separately (donation is
                # part of the executable); the warm-up run hands it
                # COPIES of the carry so warming never invalidates the
                # shared materials arrays
                out.append(Signature(
                    engine="activeset", entry="_activeset_packed_donated",
                    key=signature_key("_activeset_packed_donated", args,
                                      statics),
                    lower=lambda a=args, s=statics:
                        _activeset_packed_donated.lower(*a, **s),
                    run=lambda a=args, s=statics:
                        _activeset_packed_donated(
                            *a[:3],
                            *(jnp.array(x, copy=True) for x in a[3:7]),
                            *a[7:], **s),
                    note=(f"steady-donated grain={g} "
                          f"N={inputs.device.n_padded} "
                          f"pool={statics['pool_size']} pipe={pipe}")))
    audit = prepare_activeset_audit(inputs.device, inputs)
    if audit is not None:
        args, base, g = audit
        for pipe in pipes:
            statics = dict(base, pipe_enabled=pipe)
            out.append(Signature(
                engine="activeset", entry="_activeset_audit_packed",
                key=signature_key("_activeset_audit_packed", args,
                                  statics),
                lower=lambda a=args, s=statics:
                    _activeset_audit_packed.lower(*a, **s),
                run=lambda a=args, s=statics: _activeset_audit_packed(
                    *a, **s),
                note=(f"audit grain={g} "
                      f"T={inputs.task_valid.shape[0]} "
                      f"N={inputs.device.n_padded} pipe={pipe}")))
    return out
