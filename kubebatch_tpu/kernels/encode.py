"""Static-term encoder: (task signature x node profile) -> dense matrices.

The reference evaluates predicates and node scores per (task, node) call
(plugins/predicates/predicates.go, plugins/nodeorder/nodeorder.go). Most of
those checks are *static* within a scheduling cycle — they read only pod
spec fields and node labels/taints, which no action mutates. This module
evaluates them once per (unique task signature, unique node profile) pair —
reusing the host matcher functions verbatim, so semantics cannot drift —
and broadcasts the results to dense ``[S, N_pad]`` matrices the solver
kernels index by ``task_sig``.

Why signatures/profiles: pods of one PodGroup share a template, and nodes
share label shapes, so S and P are tiny (≈ #jobs, #node-pools) while T x N
is huge (10k x 5k at the stress config). The Python cost is O(S x P); the
broadcast is a numpy gather.

Dynamic terms are NOT encoded here:
- least-requested / balanced-resource scores depend on each node's running
  request sum, which changes with every in-cycle assignment — the solver
  kernels compute them from the capacity carry (kernels/solver.py,
  kernels/fused.py), mirroring nodeorder.go's per-call recompute.
- inter-pod (anti-)affinity and host-port conflicts depend on in-cycle
  assignments; `dynamic_features` detects them. The BATCHED engine
  carries them as domain-count tensors in its round state
  (kernels/affinity.py); the VICTIM solvers keep their device kernels
  and apply an exact host-side node mask at choice time
  (affinity.SessionAffinityMasks — the features only gate the
  preemptor's node, never the victims); the per-visit/fused allocate
  engines fall back to the host path on them (actions/allocate.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api import TaskInfo
from ..objects import Pod
from ..plugins.predicates import match_node_selector, tolerates_node_taints
from .tensorize import NodeState


def _expr_key(e) -> Tuple:
    return (e.key, e.operator, tuple(e.values))


def _term_key(term) -> Tuple:
    return tuple(_expr_key(e) for e in term.match_expressions)


def _node_affinity_keys(pod: Pod) -> Tuple[Tuple, Tuple]:
    """(required, preferred) signature components of a pod's node affinity."""
    aff = pod.affinity
    if aff is None or aff.node_affinity is None:
        return (), ()
    req = tuple(_term_key(t) for t in aff.node_affinity.required)
    pref = tuple((w, _term_key(t)) for w, t in aff.node_affinity.preferred)
    return req, pref


def _toleration_key(pod: Pod) -> Tuple:
    return tuple((t.key, t.operator, t.value, t.effect)
                 for t in pod.tolerations)


#: the signature of a pod with no selectors/affinity/tolerations — the
#: overwhelmingly common shape; shared so the per-pod fast path is one
#: truthiness check per field
_EMPTY_SIG = ((), (), (), ())


def task_signature(pod: Pod) -> Tuple:
    """Everything the static predicate/score terms read from the pod.
    Cached on the pod object — pod spec fields are immutable for the pod's
    lifetime, and this runs per pending task per cycle otherwise."""
    sig = getattr(pod, "_kb_sig", None)
    if sig is None:
        if not (pod.node_selector or pod.affinity or pod.tolerations):
            sig = _EMPTY_SIG
        else:
            na_req, na_pref = _node_affinity_keys(pod)
            sig = (tuple(sorted(pod.node_selector.items())), na_req,
                   na_pref, _toleration_key(pod))
        pod._kb_sig = sig
    return sig


def referenced_label_keys(pods: Sequence[Pod]) -> Set[str]:
    """Label keys the pod set can observe on nodes — the node profile only
    needs to distinguish nodes on these keys."""
    keys: Set[str] = set()
    for pod in pods:
        keys.update(pod.node_selector)
        aff = pod.affinity
        if aff is not None and aff.node_affinity is not None:
            for term in aff.node_affinity.required:
                keys.update(e.key for e in term.match_expressions)
            for _, term in aff.node_affinity.preferred:
                keys.update(e.key for e in term.match_expressions)
    return keys


class _FakeNode:
    """Just enough node for tolerates_node_taints."""
    __slots__ = ("taints",)

    def __init__(self, taints):
        self.taints = taints


@dataclass
class StaticTerms:
    """Sig-indexed static predicate mask and score for one cycle.

    ``pred``/``score`` rows are per unique task signature; ``sig_of`` maps a
    TaskInfo uid to its row. Columns follow NodeState order (padded columns
    are masked by the kernels' node validity, not here).
    """
    pred: np.ndarray            # [S, N_pad] bool
    score: np.ndarray           # [S, N_pad] float32
    sig_of: Dict[str, int]      # task uid -> sig row

    def task_rows(self, tasks: Sequence[TaskInfo], t_pad: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """Gather [T_pad, N] score/pred matrices for a task batch."""
        sig = np.zeros(t_pad, np.int32)
        for i, t in enumerate(tasks):
            sig[i] = self.sig_of[t.uid]
        return self.score[sig], self.pred[sig]

    def task_sig(self, tasks: Sequence[TaskInfo], t_pad: int) -> np.ndarray:
        sig = np.zeros(t_pad, np.int32)
        for i, t in enumerate(tasks):
            sig[i] = self.sig_of[t.uid]
        return sig

    @property
    def n_sigs(self) -> int:
        return self.pred.shape[0]


def _build_profiles(names: Sequence[str], n_padded: int, rel_keys: Tuple,
                    labels_taints_of):
    """Dedup nodes into (restricted-labels, taints) profiles. Shared by
    the per-cycle builder and the persistent TermsCache — their contract
    is exact equality (test_terms_cache_matches_fresh_build_across_cycles),
    so the profile key lives in exactly one place.

    ``labels_taints_of(name) -> (labels, taints)`` resolves both fields in
    one lookup; the loop runs once per node per (re)build — O(5k) at the
    stress config — so the dominant plain-node shape (no referenced
    labels, no taints) takes the hoisted-key fast branch."""
    profile_of = np.zeros(n_padded, np.int32)
    profiles: List[Tuple[Dict[str, str], list]] = []
    prof_index: Dict[Tuple, int] = {}
    no_rel = not rel_keys
    plain_key = ((), ())
    plain_restricted: Dict[str, str] = {}
    for col, name in enumerate(names):
        labels, taints = labels_taints_of(name)
        if no_rel or not labels:
            restricted = plain_restricted
            key = (plain_key if not taints
                   else ((), tuple((t.key, t.value, t.effect)
                                   for t in taints)))
        else:
            restricted = {k: labels[k] for k in rel_keys if k in labels}
            key = (tuple(sorted(restricted.items())),
                   tuple((t.key, t.value, t.effect) for t in taints))
        p = prof_index.get(key)
        if p is None:
            p = len(profiles)
            prof_index[key] = p
            profiles.append((restricted, taints))
        profile_of[col] = p
    return profile_of, profiles


def _eval_sig_rows(pod: Pod, profiles, with_predicates: bool,
                   with_node_affinity_score: bool,
                   node_affinity_weight: int):
    """One signature's (pred, score) row over the node profiles, via the
    host matcher functions verbatim (shared, see _build_profiles)."""
    n_prof = max(1, len(profiles))
    pred_row = np.ones(n_prof, bool)
    score_row = np.zeros(n_prof, np.float32)
    aff = pod.affinity
    preferred = (aff.node_affinity.preferred
                 if (aff is not None and aff.node_affinity is not None)
                 else [])
    for p, (labels, taints) in enumerate(profiles):
        if with_predicates:
            pred_row[p] = (match_node_selector(pod, labels)
                           and tolerates_node_taints(pod, _FakeNode(taints)))
        if with_node_affinity_score and preferred:
            total = sum(w for w, term in preferred if term.matches(labels))
            score_row[p] = total * node_affinity_weight
    return pred_row, score_row


def build_static_terms(state: NodeState, tasks: Sequence[TaskInfo],
                       node_labels: Dict[str, Dict[str, str]],
                       node_taints: Dict[str, list],
                       with_predicates: bool,
                       with_node_affinity_score: bool,
                       node_affinity_weight: int = 1) -> StaticTerms:
    """Evaluate static terms per (signature, profile) and broadcast.

    node_labels/node_taints are keyed by node name (NodeState column order
    comes from state.names).
    """
    pods = [t.pod for t in tasks]
    rel_keys = tuple(sorted(referenced_label_keys(pods)))

    # --- unique task signatures --------------------------------------
    sig_of: Dict[str, int] = {}
    sig_pods: List[Pod] = []          # exemplar pod per signature
    sig_index: Dict[Tuple, int] = {}
    for t in tasks:
        key = task_signature(t.pod)
        s = sig_index.get(key)
        if s is None:
            s = len(sig_pods)
            sig_index[key] = s
            sig_pods.append(t.pod)
        sig_of[t.uid] = s
    n_sigs = max(1, len(sig_pods))

    # --- unique node profiles ----------------------------------------
    profile_of, profiles = _build_profiles(
        state.names, state.n_padded, rel_keys,
        lambda name: (node_labels.get(name, {}),
                      node_taints.get(name, [])))
    n_prof = max(1, len(profiles))

    # --- evaluate per (sig, profile) via the host matchers ------------
    pred_sp = np.ones((n_sigs, n_prof), bool)
    score_sp = np.zeros((n_sigs, n_prof), np.float32)
    for s, pod in enumerate(sig_pods):
        pred_sp[s], score_sp[s] = _eval_sig_rows(
            pod, profiles, with_predicates, with_node_affinity_score,
            node_affinity_weight)

    # --- broadcast to [S, N_pad] --------------------------------------
    return StaticTerms(pred=pred_sp[:, profile_of],
                       score=score_sp[:, profile_of], sig_of=sig_of)


# ---------------------------------------------------------------------
# persistent encoder state (cross-cycle)
# ---------------------------------------------------------------------

class TermsCache:
    """Static-term encoder state persisted across cycles.

    Owned by SchedulerCache.terms_cache and nulled there on ANY node
    shape change (labels/taints/unschedulable/allocatable, node add or
    delete — cache.py _mark_node_shape), so while it lives, the node
    profiles it encoded are exactly the snapshot's. Per cycle the only
    work left is mapping pending pods to signature rows (memoized on the
    pod) and evaluating rows for signatures never seen before.
    """

    #: new signatures beyond this force a full reset (degenerate churn of
    #: unique selector shapes must not grow the matrices unboundedly)
    MAX_SIGS = 4096

    def __init__(self):
        self.ready = False
        self.names: Optional[List[str]] = None
        self.rel_keys: frozenset = frozenset()
        self.flags: Optional[Tuple] = None
        self.profile_of: Optional[np.ndarray] = None
        self.profiles: List[Tuple[Dict[str, str], list]] = []
        self.sig_index: Dict[Tuple, int] = {}
        #: per-signature rows, stacked lazily (amortized growth — a
        #: full-matrix copy per new signature would be quadratic)
        self._pred_rows: List[np.ndarray] = []
        self._score_rows: List[np.ndarray] = []
        self._stacked: Optional[Tuple[np.ndarray, np.ndarray]] = None
        #: generation token for the per-pod sig-row memo; a fresh object()
        #: per rebuild invalidates every memo by identity
        self._gen = object()

    def _rebuild_profiles(self, state: NodeState, ssn,
                          rel_keys: frozenset) -> None:
        self.rel_keys = rel_keys
        self.names = list(state.names)
        nodes = ssn.nodes
        _empty_lt = ({}, [])

        def labels_taints_of(name):
            ni = nodes.get(name)
            nd = ni.node if ni is not None else None
            return (nd.labels, nd.taints) if nd is not None else _empty_lt

        self.profile_of, self.profiles = _build_profiles(
            state.names, state.n_padded, tuple(sorted(rel_keys)),
            labels_taints_of)
        self.sig_index = {}
        self._pred_rows = []
        self._score_rows = []
        self._stacked = None
        self._gen = object()    # identity token for the per-pod row memo
        self.ready = True

    def _sig_row(self, pod: Pod, with_predicates: bool,
                 with_node_affinity_score: bool,
                 node_affinity_weight: int) -> int:
        key = task_signature(pod)
        s = self.sig_index.get(key)
        if s is not None:
            return s
        pred_row, score_row = _eval_sig_rows(
            pod, self.profiles, with_predicates, with_node_affinity_score,
            node_affinity_weight)
        s = len(self.sig_index)
        self.sig_index[key] = s
        self._pred_rows.append(pred_row)
        self._score_rows.append(score_row)
        self._stacked = None
        return s

    def static_terms(self, state: NodeState, ssn,
                     tasks: Sequence[TaskInfo],
                     with_predicates: bool,
                     with_node_affinity_score: bool,
                     node_affinity_weight: int = 1) -> StaticTerms:
        """Same result as build_static_terms, amortized across cycles."""
        pods = [t.pod for t in tasks]
        rel = frozenset(referenced_label_keys(pods))
        flags = (with_predicates, with_node_affinity_score,
                 node_affinity_weight)
        if (not self.ready or self.flags != flags
                or not rel <= self.rel_keys
                or len(self.sig_index) > self.MAX_SIGS
                or self.names != list(state.names)):
            self.flags = flags
            self._rebuild_profiles(state, ssn, rel | self.rel_keys)
        # per-pod row memo: pod specs are immutable and sig_index only
        # grows within a generation, so (gen, row) cached on the pod
        # replaces the signature-tuple hash per task per cycle — 10k
        # pending share a handful of signatures at the stress configs
        gen = self._gen
        sig_of = {}
        for t in tasks:
            pod = t.pod
            memo = getattr(pod, "_kb_sigrow", None)
            if memo is not None and memo[0] is gen:
                sig_of[t.uid] = memo[1]
            else:
                s = self._sig_row(pod, with_predicates,
                                  with_node_affinity_score,
                                  node_affinity_weight)
                pod._kb_sigrow = (gen, s)
                sig_of[t.uid] = s
        if not self._pred_rows:             # no tasks at all
            self._sig_row(Pod(name="-empty-"), with_predicates,
                          with_node_affinity_score, node_affinity_weight)
        if self._stacked is None:
            self._stacked = (np.stack(self._pred_rows),
                             np.stack(self._score_rows))
        pred_sp, score_sp = self._stacked
        terms = StaticTerms(pred=pred_sp[:, self.profile_of],
                            score=score_sp[:, self.profile_of],
                            sig_of=sig_of)
        if len(self.sig_index) > self.MAX_SIGS:
            # a single cycle with many unique selector shapes can overshoot
            # the entry check's bound (it runs before this cycle's rows are
            # added); drop the oversized matrices now rather than carrying
            # them into the next cycle
            self.ready = False
            self.sig_index = {}
            self._pred_rows = []
            self._score_rows = []
            self._stacked = None
        return terms


# ---------------------------------------------------------------------
# dynamic-feature detection (forces the host path)
# ---------------------------------------------------------------------

def _has_pod_affinity(pod: Pod) -> bool:
    return pod.has_pod_affinity()


_DYN_MISS = object()


def dynamic_features(ssn, pending: Sequence[TaskInfo]) -> Optional[str]:
    """Why this snapshot can't use the static encoder, or None if it can.

    - a pending task with host ports can conflict with a port claimed by an
      assignment made earlier in the same cycle (predicates.go's session-
      backed host-port check);
    - any pod with inter-pod (anti-)affinity makes both the affinity
      predicate and nodeorder's interpod score allocation-dependent
      (including the symmetry checks that affect OTHER pods).

    The pending-dependent scans run fresh per call (callers pass
    differently-filtered pending lists — allocate drops BestEffort
    tasks, the victim solvers don't), EXCEPT when the caller hands the
    very same list object again (the cycle tensorizer asks twice per
    build: the engine-support gate, then the affinity screen) — that
    repeat is memoized by list identity. The SESSION-WIDE walk over
    jobs/nodes is memoized too: existing pods' affinity counters can
    only decrease in-session (no pod is added mid-session), so a cached
    positive is at worst over-conservative.
    """
    memo = getattr(ssn, "_dyn_pending_memo", None)
    if memo is not None and memo[0] is pending:
        return memo[1]
    result = _dynamic_features_uncached(ssn, pending)
    try:
        ssn._dyn_pending_memo = (pending, result)
    except Exception:       # slots-only fake sessions in tests
        pass
    return result


def _dynamic_features_uncached(ssn,
                               pending: Sequence[TaskInfo]) -> Optional[str]:
    for t in pending:
        if t.pod.has_host_ports():
            return "pending task with host ports"
    # the maintained per-job counters screen the O(pending) affinity walk:
    # every pending task belongs to a session job, so zero affinity tasks
    # across jobs proves no pending pod carries a term (the walk then runs
    # only on cycles that can actually hit)
    try:
        jobs_have_affinity = any(job.affinity_tasks
                                 for job in ssn.jobs.values())
    except Exception:       # slots-only fake sessions in tests
        jobs_have_affinity = True
    if jobs_have_affinity:
        for t in pending:
            if _has_pod_affinity(t.pod):
                return "pending task with pod (anti-)affinity"
    memo = getattr(ssn, "_dyn_session_aff_memo", _DYN_MISS)
    if memo is not _DYN_MISS:
        return memo
    result = _session_affinity_present(ssn)
    try:
        ssn._dyn_session_aff_memo = result
    except Exception:       # slots-only fake sessions in tests
        pass
    return result


def _session_affinity_present(ssn) -> Optional[str]:
    # the maintained per-entity counters (JobInfo/NodeInfo.affinity_tasks,
    # pinned by debug.audit_cache) replace the per-task cluster walk this
    # detection used to cost every cycle. Pods of jobs the snapshot
    # DROPPED (no PodGroup/PDB, missing queue) can still sit on nodes and
    # reject others through anti-affinity symmetry — the node counters
    # cover them, but that walk is only needed when such jobs exist
    # (ssn.jobs_excluded; shadow PodGroups give every pod a job, so the
    # count is normally 0). Existing pods' host PORTS only matter to
    # port-requesting pending tasks, screened above.
    if any(job.affinity_tasks for job in ssn.jobs.values()):
        return "existing pod with pod (anti-)affinity"
    excluded = getattr(ssn, "jobs_excluded", None)
    if (excluded is None or excluded) \
            and any(node.affinity_tasks for node in ssn.nodes.values()):
        return "existing pod with pod (anti-)affinity"
    return None
