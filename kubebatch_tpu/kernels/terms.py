"""Plugin tensor terms — how policy plugins feed the device solve.

The host dispatch evaluates predicate/node-order callbacks per (task, node)
pair with tier semantics AND / SUM (session_plugins.go:331-370). The device
solve needs the same information as tensors. `solver_terms` produces them
when every registered callback is expressible:

- the built-in `predicates` plugin's static chain (node selector, required
  node affinity, taints, unschedulable, pod count) becomes a sig-indexed
  mask via kernels/encode.py;
- the built-in `nodeorder` plugin splits into a static part (preferred
  node-affinity weights -> score matrix) and a dynamic part
  (least-requested + balanced-resource, computed in-kernel from the
  capacity carry; see DynamicScoreSpec);
- inter-pod affinity and host ports are the BATCHED engine's own
  vocabulary (kernels/affinity.py, via device_supported's
  allow_affinity) — other engines fall back to the host path on them;
- anything else (a third-party plugin callback) returns None and the
  allocate action keeps the reference-literal host path for the cycle.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from ..api import TaskInfo
from .encode import StaticTerms, build_static_terms, dynamic_features
from .tensorize import TaskBatch

#: plugin names whose predicate / node-order callbacks the encoder + kernels
#: fully express
_DEVICE_PREDICATE_PLUGINS = {"predicates"}
_DEVICE_NODE_ORDER_PLUGINS = {"nodeorder"}


@dataclass(frozen=True)
class DynamicScoreSpec:
    """In-kernel score terms and their nodeorder weights (0 = disabled)."""
    least_requested: float = 0.0
    balanced_resource: float = 0.0

    @property
    def enabled(self) -> bool:
        return self.least_requested != 0.0 or self.balanced_resource != 0.0


@dataclass
class SolverTerms:
    """Everything the device solve needs for one cycle's policy terms."""
    static: StaticTerms
    dynamic: DynamicScoreSpec

    def matrices(self, batch: TaskBatch) -> Tuple[np.ndarray, np.ndarray]:
        """[T_pad, N] static score / pred rows for a task batch."""
        return self.static.task_rows(batch.tasks, batch.t_padded)

    def task_sig(self, tasks: Sequence[TaskInfo], t_pad: int) -> np.ndarray:
        return self.static.task_sig(tasks, t_pad)


def _active(ssn, fns: dict, disable_attr: str):
    """Plugin names whose callback actually runs under the tier config."""
    names = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if getattr(opt, disable_attr) or opt.name not in fns:
                continue
            names.append(opt.name)
    return names


def device_supported(ssn, pending: Sequence[TaskInfo],
                     allow_affinity: bool = False) -> bool:
    """Cheap pre-check (no tensorization, no device work): can this cycle's
    registered callbacks run on device at all? Lets the action skip
    DeviceSession construction — a full-cluster upload — on snapshots that
    will take the host path anyway.

    ``allow_affinity``: the batched engine carries inter-pod affinity and
    host ports in its round state (kernels/affinity.py) — its builder
    passes True and the dynamic-feature check is skipped (the affinity
    encoder still falls back past its own vocabulary caps). The victim
    solvers also pass True and apply an exact host-side node mask at
    choice time (affinity.SessionAffinityMasks); scoring actions
    (preempt) additionally reproduce nodeorder's allocation-dependent
    interpod term in the wave chooser's host-side ordering, falling
    back only when waves are disabled (KUBEBATCH_VICTIM_WAVE=0). The
    per-visit/fused allocate paths keep the strict default."""
    from ..cache.interface import NullVolumeBinder

    # a real volume binder makes placement feasibility depend on per-node
    # volume state the kernels don't model; the host path handles its
    # try-next-node semantics
    if type(getattr(ssn.cache, "volume_binder", None)) \
            is not NullVolumeBinder:
        return False
    pred_plugins = _active(ssn, ssn.predicate_fns, "predicate_disabled")
    order_plugins = _active(ssn, ssn.node_order_fns, "node_order_disabled")
    if any(p not in _DEVICE_PREDICATE_PLUGINS for p in pred_plugins):
        return False
    if any(p not in _DEVICE_NODE_ORDER_PLUGINS for p in order_plugins):
        return False
    if not allow_affinity and (pred_plugins or order_plugins) \
            and dynamic_features(ssn, pending) is not None:
        return False
    return True


def solver_terms(ssn, device, pending: Sequence[TaskInfo],
                 assume_supported: bool = False) -> Optional[SolverTerms]:
    """Static+dynamic terms for the cycle, or None when some registered
    callback can't run on device (the action then takes the host path).
    ``assume_supported`` skips the re-check when the caller already ran
    device_supported on the same pending set (it walks every job's tasks)."""
    if not assume_supported and not device_supported(ssn, pending):
        return None
    pred_plugins = _active(ssn, ssn.predicate_fns, "predicate_disabled")
    order_plugins = _active(ssn, ssn.node_order_fns, "node_order_disabled")
    if not pred_plugins and not order_plugins:
        # nothing registered: trivial terms, no encoding needed
        state = device.state
        static = StaticTerms(
            pred=np.ones((1, state.n_padded), bool),
            score=np.zeros((1, state.n_padded), np.float32),
            sig_of={t.uid: 0 for t in pending})
        return SolverTerms(static=static, dynamic=DynamicScoreSpec())

    dyn = DynamicScoreSpec()
    node_aff_weight = 1
    if order_plugins:
        weights = getattr(ssn.plugins.get("nodeorder"), "weights", None) \
            or {"least": 1, "balanced": 1, "node_aff": 1}
        dyn = DynamicScoreSpec(least_requested=float(weights["least"]),
                               balanced_resource=float(weights["balanced"]))
        node_aff_weight = weights["node_aff"]

    # persistent encoder state: profiles/sig rows survive across cycles
    # (SchedulerCache nulls terms_cache on any node shape change); fake
    # caches without the slot fall back to the per-cycle build
    tc = getattr(ssn.cache, "terms_cache", False) \
        if ssn.cache is not None else False
    if tc is not False:
        if tc is None:
            from .encode import TermsCache
            tc = TermsCache()
            # persistence is refused if a node-shape event landed after
            # this session's snapshot (tc then stays session-local)
            offer = getattr(ssn.cache, "offer_terms_cache", None)
            if offer is not None:
                offer(tc)
        static = tc.static_terms(
            device.state, ssn, pending,
            with_predicates=bool(pred_plugins),
            with_node_affinity_score=bool(order_plugins),
            node_affinity_weight=node_aff_weight)
        return SolverTerms(static=static, dynamic=dyn)

    node_labels = {}
    node_taints = {}
    for name, ni in ssn.nodes.items():
        node_labels[name] = ni.node.labels if ni.node else {}
        node_taints[name] = ni.node.taints if ni.node else []

    static = build_static_terms(
        device.state, pending, node_labels, node_taints,
        with_predicates=bool(pred_plugins),
        with_node_affinity_score=bool(order_plugins),
        node_affinity_weight=node_aff_weight)
    return SolverTerms(static=static, dynamic=dyn)


def pred_and_score_matrices(ssn, device, batch: TaskBatch
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Pairwise host evaluation of registered callbacks into [T,N] matrices
    — the compatibility fallback for callers that want matrices regardless
    of device support (correct for static plugins only)."""
    t_pad, n_pad = batch.t_padded, device.n_padded
    scores = np.zeros((t_pad, n_pad), np.float32)
    pred = np.ones((t_pad, n_pad), bool)

    real_nodes = [(device.node_index(name), node)
                  for name, node in ssn.nodes.items()]

    for tier in ssn.tiers:
        for opt in tier.plugins:
            if not opt.predicate_disabled and opt.name in ssn.predicate_fns:
                fn = ssn.predicate_fns[opt.name]
                for ti, task in enumerate(batch.tasks):
                    for ni, node in real_nodes:
                        if ni is None or not pred[ti, ni]:
                            continue
                        try:
                            fn(task, node)
                        except Exception:
                            pred[ti, ni] = False

            if not opt.node_order_disabled and opt.name in ssn.node_order_fns:
                fn = ssn.node_order_fns[opt.name]
                for ti, task in enumerate(batch.tasks):
                    for ni, node in real_nodes:
                        if ni is not None:
                            scores[ti, ni] += fn(task, node)

    return scores, pred
