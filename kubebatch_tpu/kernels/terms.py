"""Plugin tensor terms — how plugins contribute to the device solve.

A plugin may implement two optional vectorized hooks alongside its per-pair
callbacks:

    predicate_mask(ssn, device, batch) -> bool[T, N] | None
    score_matrix(ssn, device, batch)  -> float32[T, N] | None

The solver combines them with the same tier semantics as the host dispatch
(AND for predicates, SUM for scores — session_plugins.go:331-370). A plugin
that registered a per-pair fn but provides no tensor hook is still honored:
its callback is evaluated pairwise on host into the matrix (correct but
slow — all seven built-in plugins provide tensor hooks).
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from .tensorize import TaskBatch


def pred_and_score_matrices(ssn, device, batch: TaskBatch
                            ) -> Tuple[np.ndarray, np.ndarray]:
    t_pad, n_pad = batch.t_padded, device.n_padded
    scores = np.zeros((t_pad, n_pad), np.float32)
    pred = np.ones((t_pad, n_pad), bool)

    real_nodes = [(device.node_index(name), node)
                  for name, node in ssn.nodes.items()]

    for tier in ssn.tiers:
        for opt in tier.plugins:
            plugin = ssn.plugins.get(opt.name)

            if not opt.predicate_disabled and opt.name in ssn.predicate_fns:
                mask = None
                if plugin is not None and hasattr(plugin, "predicate_mask"):
                    mask = plugin.predicate_mask(ssn, device, batch)
                if mask is not None:
                    pred &= mask
                else:
                    fn = ssn.predicate_fns[opt.name]
                    for ti, task in enumerate(batch.tasks):
                        for ni, node in real_nodes:
                            if ni is None or not pred[ti, ni]:
                                continue
                            try:
                                fn(task, node)
                            except Exception:
                                pred[ti, ni] = False

            if not opt.node_order_disabled and opt.name in ssn.node_order_fns:
                mat = None
                if plugin is not None and hasattr(plugin, "score_matrix"):
                    mat = plugin.score_matrix(ssn, device, batch)
                if mat is not None:
                    scores += mat
                else:
                    fn = ssn.node_order_fns[opt.name]
                    for ti, task in enumerate(batch.tasks):
                        for ni, node in real_nodes:
                            if ni is not None:
                                scores[ti, ni] += fn(task, node)

    return scores, pred
