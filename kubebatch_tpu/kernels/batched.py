"""Batched allocate solver — many placements per device step.

The fused kernel (kernels/fused.py) replays the reference's heap algorithm
one placement per ``while_loop`` iteration; at 10k pending tasks that is
10k+ sequential device steps (~100 us each).  This module is the
TPU-idiomatic alternative: a **round-based** solver where every round
places as many tasks as capacity allows, in parallel, and only the few
capacity *conflicts* spill to the next round.  A 10k-task cycle resolves
in a handful of rounds, and the whole round loop runs inside ONE device
dispatch (the axon tunnel charges ~70 ms per device->host transfer, so
the cycle performs exactly one blocking read).

Round structure (all tensor ops):

1. **Order** — queue shares (proportion water-fill state), DRF job shares
   and gang readiness are recomputed from the committed state, composed
   into the configured lexicographic job order (the same key vocabulary as
   kernels/fused.py), and flattened into a global task rank.
2. **Eligibility** — the exact per-(task, node) predicate+fit matrix
   against round-start capacity: sig-indexed static predicates AND
   task-count room AND (fits idle+backfilled OR fits releasing), mirroring
   allocate.go:153-184.  A participating task with no eligible node FAILs
   and (gang semantics) kills its job's later-ranked tasks — the batch
   equivalent of "job dropped on first unassignable task"
   (allocate.go:187-189).
3. **Proposals** — tasks pick target nodes.  Identical tasks must spread
   (argmax alone would pile every replica of a template onto one node and
   serialize into per-node rounds), so tasks of one cohort are
   *waterfalled*: nodes sorted by score, estimated integer capacities
   cumulated, and the cohort's m-th task proposes the node covering
   position m.  Tasks whose waterfall slot is infeasible for their exact
   request fall back to their individual masked argmax.  Cohorts are
   (signature, nonzero-request) PAIRS — scores, including the dynamic
   least-requested / balanced-resource terms, are evaluated with the
   cohort's own request, so same-sig pods of different sizes score
   per-task (CycleInputs.pair_terms; when a cycle carries more distinct
   request shapes than the pair budget, requests quantize onto a log2
   grid and scores deviate by at most the bucket width).
4. **Acceptance** — per node, proposers are taken in global-rank order
   while the cumulative exact requests fit the pool (segmented scans keep
   float error per-node, not global).  The top-ranked proposer on each
   node always fits (eligibility checked the full pool), so every round
   makes progress.  Rejected proposers simply retry next round against
   refreshed state.
5. **Commit** — accepted placements update capacity, fairness shares,
   and gang counters via per-node / per-job / per-queue segment sums.

Faithfulness contract (vs the reference allocate action):
- capacity, predicates, epsilon fit rules, AllocatedOverBackfill and
  Pipelined decisions are exact (same arithmetic as kernels/fused.py);
- gang all-or-nothing, job-drop-on-failure, overused-queue exclusion and
  the pipelined-inclusive readiness count are preserved;
- *ordering* is round-granular: fairness shares and the derived queue/job
  order refresh between rounds, not between every single placement, and a
  queue/job visit sequence is not materialized.  Under contention the
  task->node map can differ from the sequential heap schedule while
  satisfying the same policy constraints.  The fused and host modes remain
  the bit-exact engines; this is the throughput engine the north-star
  latency target is measured on (BASELINE.md).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compilesvc import instrument as _instrument
from ..compilesvc import register_provider as _register_provider
from ..metrics import count_blocking_readback
from ..obs import span as _span
from .fused import (ALLOC, ALLOC_OB, FAIL, K_DRF_SHARE, K_GANG_READY,
                    K_PRIORITY, K_PROP_SHARE, PIPELINE, SKIP, _share)
from .narrow import narrow_enabled, score_dtype
from .pack import pack_inputs
from .pack import unpack as _unpack
from .solver import dynamic_node_score
from .telemetry import ENGINE_BATCHED, decision_frame
from .tensorize import VEC_EPS

_IMAX = jnp.iinfo(jnp.int32).max


class RoundState(NamedTuple):
    """Device state carried across rounds."""
    idle: jnp.ndarray         # [N,R]
    releasing: jnp.ndarray    # [N,R]
    n_tasks: jnp.ndarray      # [N]
    nz_req: jnp.ndarray       # [N,2]
    q_allocated: jnp.ndarray  # [Q,R]
    j_allocated: jnp.ndarray  # [J,R]
    alloc_cnt: jnp.ndarray    # [J] allocated-family count (readiness)
    job_alive: jnp.ndarray    # [J] bool — not yet dropped on failure
    task_state: jnp.ndarray   # [T] SKIP while pending
    task_node: jnp.ndarray    # [T]
    task_seq: jnp.ndarray     # [T] round * T_pad + in-round rank
    # --- inter-pod affinity / host-port carry (kernels/affinity.py);
    # None when the cycle has no such features (the pytree structure is
    # part of the trace signature, so affinity-free cycles compile the
    # exact pre-affinity graphs) ---------------------------------------
    aff_grp_cnt: Optional[jnp.ndarray] = None    # [P,D] group members
    aff_anti_cnt: Optional[jnp.ndarray] = None   # [P,D] req-anti carriers
    aff_pref_w: Optional[jnp.ndarray] = None     # [P,D] preferred weight
    aff_grp_total: Optional[jnp.ndarray] = None  # [P] cluster-wide members
    port_claim: Optional[jnp.ndarray] = None     # [N,PT] bool (this cycle)


class CycleArrays(NamedTuple):
    """Arrays static across rounds (uploaded once per cycle)."""
    backfilled: jnp.ndarray       # [N,R]
    allocatable_cm: jnp.ndarray   # [N,2]
    max_task_num: jnp.ndarray     # [N]
    node_ok: jnp.ndarray          # [N]
    resreq: jnp.ndarray           # [T,R]
    init_resreq: jnp.ndarray      # [T,R]
    task_nz: jnp.ndarray          # [T,2]
    task_job: jnp.ndarray         # [T]
    task_rank: jnp.ndarray        # [T]
    task_sig: jnp.ndarray         # [T]  (predicate rows)
    task_pair: jnp.ndarray        # [T]  (scoring/waterfall cohorts)
    task_valid: jnp.ndarray       # [T]
    sig_scores: jnp.ndarray       # [S,N]
    sig_pred: jnp.ndarray         # [S,N]
    pair_sig: jnp.ndarray         # [P] pair -> sig
    pair_nz: jnp.ndarray          # [P,2] cohort nonzero-request
    order_min_available: jnp.ndarray  # [J]
    job_queue: jnp.ndarray        # [J]
    job_priority: jnp.ndarray     # [J]
    job_create_rank: jnp.ndarray  # [J]
    job_valid: jnp.ndarray        # [J]
    q_deserved: jnp.ndarray       # [Q,R]
    q_create_rank: jnp.ndarray    # [Q]
    cluster_total: jnp.ndarray    # [R]
    dyn_weights: jnp.ndarray      # [2]
    # --- static affinity/port vocabulary (kernels/affinity.py docs);
    # None on affinity-free cycles -------------------------------------
    node_dom: Optional[jnp.ndarray] = None       # [P,N] int32, -1 = none
    task_grp: Optional[jnp.ndarray] = None       # [T,P] bool
    task_req_aff: Optional[jnp.ndarray] = None   # [T,P] bool
    task_req_anti: Optional[jnp.ndarray] = None  # [T,P] bool
    task_self_ok: Optional[jnp.ndarray] = None   # [T,P] bool
    task_carry_w: Optional[jnp.ndarray] = None   # [T,P] f32
    task_pref_w: Optional[jnp.ndarray] = None    # [T,P] f32
    task_ports: Optional[jnp.ndarray] = None     # [T,PT] bool
    port_base: Optional[jnp.ndarray] = None      # [N,PT] bool
    ip_weight: Optional[jnp.ndarray] = None      # [] f32 (pod_aff weight)


def resource_eligibility(idle, releasing, n_tasks, a: CycleArrays,
                         pipe_enabled: bool, eps) -> jnp.ndarray:
    """[T, N] predicate + capacity eligibility (no affinity terms): the
    sig-indexed static predicate AND task-count room AND (fits
    idle+backfilled OR, with pipelining, fits releasing) against the
    given carry. THE shared definition — the round's eligibility phase,
    its same-round retry, and the two-level coarse pass
    (kernels/hier.py) all call it, so the FAIL-vs-WAIT semantics the
    coarse pass derives from it can never drift from what the round
    actually enforces."""
    accessible = idle + a.backfilled
    base = a.node_ok & (n_tasks < a.max_task_num)
    fit = jnp.all(a.init_resreq[:, None, :] <= accessible[None] + eps,
                  axis=-1)
    if pipe_enabled:
        fit = fit | jnp.all(
            a.init_resreq[:, None, :] <= releasing[None] + eps, axis=-1)
    return a.sig_pred[a.task_sig] & base[None, :] & fit


def _segmented_prefix(values: jnp.ndarray, starts: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix sums within segments of a sorted array.

    ``starts[i]`` is the index where row i's segment begins (rows sorted by
    segment).  An associative segmented scan keeps rounding error bounded
    by the segment length (a node's task count), not the global sum —
    float32 stays well inside the resource epsilons.
    """
    flag = jnp.arange(values.shape[0]) == starts          # segment head
    if values.ndim == 2:
        flag = flag[:, None]

    def comb(a, b):
        sa, fa = a
        sb, fb = b
        return jnp.where(fb, sb, sa + sb), fa | fb

    sums, _ = jax.lax.associative_scan(comb, (values, flag))
    return sums - values                                   # exclusive


# ---------------------------------------------------------------------
# inter-pod affinity / host ports (vocabulary: kernels/affinity.py)
# ---------------------------------------------------------------------

def _f32(x):
    return x.astype(jnp.float32)


def _aff_gather(state: RoundState, a: CycleArrays):
    """Per-(pair, node) views of the domain-count carry: group-member
    count, anti-carrier count, and the domain validity mask."""
    d_cap = state.aff_grp_cnt.shape[1]
    has_dom = a.node_dom >= 0
    domc = jnp.clip(a.node_dom, 0, d_cap - 1)
    gcnt = jnp.take_along_axis(state.aff_grp_cnt, domc, axis=1)   # [P,N]
    acnt = jnp.take_along_axis(state.aff_anti_cnt, domc, axis=1)  # [P,N]
    return has_dom, domc, gcnt, acnt


def _aff_eligibility(state: RoundState, a: CycleArrays):
    """[T,N] mask of the affinity + host-port predicates against the
    committed (round-start) carry, plus the wait mask for positive terms
    that a same-cycle placement could still satisfy.

    Three boolean matmuls mirror predicates.go's per-pair walk:
    - required-positive: fail where the group has no member in the node's
      domain, unless the first-pod bootstrap applies (empty group +
      self-matching term — upstream anySchedulable);
    - required-anti: fail where the group HAS a member in the domain;
    - symmetry: a group member fails where a required-anti *carrier* for
      its group sits in the domain (predicates.go:47-104's check of
      existing pods' anti terms against the incoming pod).
    """
    has_dom, _, gcnt, acnt = _aff_gather(state, a)
    present = has_dom & (gcnt > 0)                       # [P,N]
    boot = ((state.aff_grp_total <= 0)[None, :]
            & a.task_self_ok)                            # [T,P]
    need = _f32(a.task_req_aff & ~boot)                  # [T,P]
    pos_fail = need @ _f32(~present)                     # [T,N]
    anti_fail = _f32(a.task_req_anti) @ _f32(present)
    sym_fail = _f32(a.task_grp) @ _f32(has_dom & (acnt > 0))
    ok = (pos_fail < 0.5) & (anti_fail < 0.5) & (sym_fail < 0.5)
    if a.task_ports is not None:
        used = a.port_base | state.port_claim            # [N,PT]
        port_fail = _f32(a.task_ports) @ _f32(used).T    # [T,N]
        ok = ok & (port_fail < 0.5)

    # positive terms currently unsatisfiable ANYWHERE but whose group has
    # other still-pending members: the task WAITS (stays SKIP) instead of
    # failing its job — the sequential oracle may visit the member first
    # (cross-job ordering the batch cannot replicate). A task whose group
    # potential is only itself fails exactly like the oracle.
    pending_members = (a.task_valid & (state.task_state == SKIP))[:, None] \
        & a.task_grp                                     # [T,P]
    grp_pending = pending_members.sum(axis=0)            # [P]
    others_pending = (grp_pending[None, :]
                      - _f32(pending_members)) > 0.5     # [T,P]
    pair_unsat = ~jnp.any(present, axis=1)               # [P] nowhere
    could_wait = jnp.any(a.task_req_aff & ~boot & others_pending
                         & pair_unsat[None, :], axis=1)  # [T]
    return ok, could_wait


def _aff_serialize(state: RoundState, a: CycleArrays, accept, proposal,
                   global_rank):
    """In-round hazard removal: returns the accepted subset whose
    co-placement is sequentially legal (see kernels/affinity.py docs).

    Per (pair, domain): if a required-anti carrier is accepted, either it
    placed first (keep it alone — later members would be rejected by its
    anti/symmetry) or a member placed first (keep the members, reject
    the carriers — their anti term already matches). Per boot-active
    pair: the best-ranked bootstrapper fixes the group's domain; only
    co-located bootstrappers join it this round. Members without an
    accepted carrier in their domain are untouched — plain replicas
    never serialize."""
    d_cap = state.aff_grp_cnt.shape[1]
    dom_prop = jnp.take(a.node_dom, proposal, axis=1)    # [P,T]
    rank = global_rank.astype(jnp.int32)

    def per_pair(dom_p, carrier_p, member_p, req_p, boot_active_p):
        seg = jnp.where(dom_p >= 0, dom_p, d_cap)        # [T]
        acc_car = accept & carrier_p & (dom_p >= 0)
        # plain members only: a carrier that is also a member (the spread
        # pattern) must count once, as a carrier, or the one-per-domain
        # winner would block itself (cmin == mmin)
        acc_mem = accept & member_p & ~carrier_p & (dom_p >= 0)
        cmin = jax.ops.segment_min(
            jnp.where(acc_car, rank, _IMAX), seg, num_segments=d_cap + 1)
        mmin = jax.ops.segment_min(
            jnp.where(acc_mem, rank, _IMAX), seg, num_segments=d_cap + 1)
        cmin_t = cmin[seg]
        mmin_t = mmin[seg]
        has_car = cmin_t < _IMAX
        # carrier keeps iff it is the domain's best AND no member beat it;
        # member keeps unless a better-ranked carrier landed in the domain
        keep_car = (rank == cmin_t) & (cmin_t < mmin_t)
        keep_mem = ~has_car | (mmin_t < cmin_t)
        keep = jnp.where(carrier_p, keep_car,
                         jnp.where(member_p, keep_mem, True))
        # bootstrap: group empty cluster-wide — the best-ranked accepted
        # req-aff task fixes the domain; others join only co-located
        acc_req = accept & req_p
        bmin = jnp.min(jnp.where(acc_req, rank, _IMAX))
        bdom = jnp.max(jnp.where(acc_req & (rank == bmin), seg, -1))
        # co-location join requires a REAL domain: two bootstrappers on
        # domain-less nodes are not co-located (the host oracle places at
        # most one there — the second sees a cluster match it cannot
        # reach on any node)
        keep_boot = jnp.where(boot_active_p & req_p,
                              (rank == bmin)
                              | ((seg == bdom) & (bdom < d_cap)), True)
        return keep & keep_boot

    boot_active = state.aff_grp_total <= 0               # [P]
    keep_pt = jax.vmap(per_pair, in_axes=(0, 1, 1, 1, 0))(
        dom_prop, a.task_req_anti, a.task_grp, a.task_req_aff,
        boot_active)                                     # [P,T]
    keep = jnp.all(keep_pt, axis=0)

    if a.task_ports is not None:
        # one port-carrying accept per node per round (conflicts only
        # among overlapping ports; per-node is the cheap sound bound)
        any_port = jnp.any(a.task_ports, axis=1)
        node_seg = jnp.where(accept & any_port, proposal,
                             a.node_ok.shape[0])
        pmin = jax.ops.segment_min(
            jnp.where(accept & any_port, rank, _IMAX), node_seg,
            num_segments=a.node_ok.shape[0] + 1)
        keep = keep & (~any_port | (rank == pmin[node_seg]))
    return accept & keep


def _aff_involved(state: RoundState, a: CycleArrays):
    """[T] tasks excluded from the same-round retry phase: their
    acceptance could race a phase-1 winner in ways the between-round
    counts would have forbidden. Anti carriers, members of pairs where a
    carrier exists (pending or placed), bootstrap-reliant tasks, and
    port claimers; plain members of carrier-free pairs retry freely."""
    pair_has_carrier = (jnp.any(a.task_req_anti & a.task_valid[:, None],
                                axis=0)
                        | jnp.any(state.aff_anti_cnt > 0, axis=1))  # [P]
    boot_active = state.aff_grp_total <= 0
    inv = (jnp.any(a.task_req_anti, axis=1)
           | jnp.any(a.task_grp & pair_has_carrier[None, :], axis=1)
           | jnp.any(a.task_req_aff & boot_active[None, :], axis=1))
    if a.task_ports is not None:
        inv = inv | jnp.any(a.task_ports, axis=1)
    return inv


def _aff_delta(a: CycleArrays, mask, nodes, d_cap: int):
    """Scatter this round's placements (or reversals) into per-(pair,
    domain) deltas. ``mask`` selects tasks, ``nodes`` their node rows."""
    dom = jnp.take(a.node_dom, nodes, axis=1)            # [P,T]
    seg = jnp.where(mask[None, :] & (dom >= 0), dom, d_cap)

    def scat(vals):                                      # [T,P] -> [P,D]
        return jax.vmap(
            lambda s, v: jax.ops.segment_sum(v, s,
                                             num_segments=d_cap + 1)[:d_cap]
        )(seg, vals.T)

    mf = _f32(mask)
    d_grp = scat(_f32(a.task_grp) * mf[:, None])
    d_anti = scat(_f32(a.task_req_anti) * mf[:, None])
    d_pref = scat(a.task_carry_w * mf[:, None])
    d_total = (_f32(a.task_grp) * mf[:, None]).sum(axis=0)
    return d_grp, d_anti, d_pref, d_total


def _aff_commit(state: RoundState, a: CycleArrays, accept, proposal):
    d_cap = state.aff_grp_cnt.shape[1]
    d_grp, d_anti, d_pref, d_total = _aff_delta(a, accept, proposal, d_cap)
    upd = dict(aff_grp_cnt=state.aff_grp_cnt + d_grp,
               aff_anti_cnt=state.aff_anti_cnt + d_anti,
               aff_pref_w=state.aff_pref_w + d_pref,
               aff_grp_total=state.aff_grp_total + d_total)
    if a.task_ports is not None:
        n_pad = a.node_ok.shape[0]
        claims = jnp.zeros((n_pad, a.task_ports.shape[1]), bool)
        claims = claims.at[jnp.where(accept, proposal, n_pad - 1)].max(
            a.task_ports & accept[:, None], mode="drop")
        upd["port_claim"] = state.port_claim | claims
    return upd


def _aff_rollback(state: RoundState, a: CycleArrays, revert):
    """Exact inverse of _aff_commit for the stranded-gang rollback (task
    nodes come from the carried task_node). Port claims are exclusive
    among this cycle's placements (the predicate forbids double claims),
    so clearing the reverted tasks' bits is exact."""
    d_cap = state.aff_grp_cnt.shape[1]
    nodes = jnp.maximum(state.task_node, 0)
    d_grp, d_anti, d_pref, d_total = _aff_delta(a, revert, nodes, d_cap)
    upd = dict(aff_grp_cnt=state.aff_grp_cnt - d_grp,
               aff_anti_cnt=state.aff_anti_cnt - d_anti,
               aff_pref_w=state.aff_pref_w - d_pref,
               aff_grp_total=state.aff_grp_total - d_total)
    if a.task_ports is not None:
        n_pad = a.node_ok.shape[0]
        cleared = jnp.zeros((n_pad, a.task_ports.shape[1]), bool)
        cleared = cleared.at[jnp.where(revert, nodes, n_pad - 1)].max(
            a.task_ports & revert[:, None], mode="drop")
        upd["port_claim"] = state.port_claim & ~cleared
    return upd


def _ip_score(state: RoundState, a: CycleArrays):
    """The interpod-affinity node-order term against round-start counts
    (ref: nodeorder.go:305-313 / plugins/nodeorder.interpod_affinity_counts):
    own preferred terms weigh the group's domain counts; the symmetric
    half weighs the carried-preferred ledger the committed placements
    maintain. Normalized per task over the real nodes exactly like the
    host (10 * (c - cmin) / (cmax - cmin), floored, times the pod_aff
    weight). Tasks carrying a nonzero term leave the shared waterfall
    (their score rows are task-specific)."""
    has_dom, domc, gcnt, _ = _aff_gather(state, a)
    prefw = jnp.take_along_axis(state.aff_pref_w, domc, axis=1)  # [P,N]
    own = a.task_pref_w @ jnp.where(has_dom, gcnt, 0.0)          # [T,N]
    sym = _f32(a.task_grp) @ jnp.where(has_dom, prefw, 0.0)
    counts = own + sym
    valid = a.node_ok[None, :]
    cmin = jnp.min(jnp.where(valid, counts, jnp.inf), axis=1, keepdims=True)
    cmax = jnp.max(jnp.where(valid, counts, -jnp.inf), axis=1, keepdims=True)
    span = cmax - cmin
    term = jnp.where(span > 0,
                     jnp.floor(10.0 * (counts - cmin)
                               / jnp.where(span > 0, span, 1.0)),
                     0.0) * a.ip_weight
    scored = jnp.any(term != 0.0, axis=1)                        # [T]
    return jnp.where(valid, term, 0.0), scored


#: demand-window fraction: jobs whose exclusive cumulative demand prefix
#: stays under this fraction of the round's available capacity join the
#: round. Below 1.0 because aggregate capacity overstates what placement
#: can use (bin-packing fragmentation): admitting demand up to raw
#: capacity lets dozens of gangs start that cannot all finish, stranding
#: their partial allocations (gang all-or-nothing). The first engaged job
#: is always admitted (exclusive prefix 0), so rounds always progress.
_WINDOW_SLACK = 0.85


def _round(state: RoundState, a: CycleArrays, round_idx,
           job_keys: Tuple[str, ...], queue_keys: Tuple[str, ...],
           prop_overused: bool, dyn_enabled: bool,
           pipe_enabled: bool = True, seq_stride: int = 0,
           narrow: bool = False, elig_elsewhere=None, pair_init=None):
    """One allocation round.  Returns (new_state, progress).

    ``pipe_enabled`` is a static specialization: when the host saw no
    releasing resources anywhere at cycle start (the common case — and
    allocate never creates releasing), every pipeline-fit matrix folds to
    False at trace time, halving the [T,N] fit work per round.

    ``narrow`` (static) applies the kernels/narrow.py memory diet: the
    [T,N]-scale score gathers materialize in bfloat16 (decision-identical
    — scores are small integer-valued floats, exact in bf16) while every
    epsilon-compared resource quantity stays float32.

    ``elig_elsewhere`` ([T] bool, or None): the two-level solve's hook —
    when the round runs on one node-pool BLOCK (kernels/hier.py), a task
    with no eligible node in the block but an eligible node in some
    OTHER pool must WAIT for a later wave, not fail its job; the flat
    solve passes None and keeps the exact allocate.go drop semantics.

    ``pair_init`` ([P,R] f32, or None): the active-set engine's
    exact-pair fold. When set, the caller guarantees every valid task's
    ``init_resreq`` row is bit-identical to its pair representative
    (host-verified, see activeset._pair_init_rows) and that no affinity
    vocabulary is present — so ``eligible``, the score rows, and the
    fallback argmax are row-identical within a pair, and the round
    computes them once per PAIR ([P,N]) and gathers per task, never
    materializing a [T,N] object. Decision-identical by construction
    (identical rows -> identical argmax); the audit rung verifies it
    empirically every cadence."""
    eps = jnp.asarray(VEC_EPS)
    t_pad = a.task_valid.shape[0]
    n_pad = a.node_ok.shape[0]

    # ---- 1. ordering ----------------------------------------------------
    overused = jnp.zeros(a.q_deserved.shape[0], bool)
    if prop_overused:
        overused = jnp.all(a.q_deserved < state.q_allocated + eps, axis=-1)

    q_share = jnp.zeros(a.q_deserved.shape[0], jnp.float32)
    for k in queue_keys:
        if k == K_PROP_SHARE:
            q_share = _share(state.q_allocated, a.q_deserved)

    jkeys = []
    for k in job_keys:
        if k == K_PRIORITY:
            jkeys.append(-a.job_priority.astype(jnp.float32))
        elif k == K_GANG_READY:
            ready = (state.alloc_cnt >= a.order_min_available)
            jkeys.append(ready.astype(jnp.float32))
        elif k == K_DRF_SHARE:
            jkeys.append(_share(state.j_allocated, a.cluster_total[None, :]))
    # queue keys lead (the reference pops the best queue first), then the
    # configured job keys, then creation rank; lexsort's LAST key is primary
    keys = ([a.job_create_rank.astype(jnp.float32)]
            + list(reversed(jkeys))
            + [a.q_create_rank[a.job_queue].astype(jnp.float32),
               q_share[a.job_queue]])
    job_order = jnp.lexsort(keys)
    job_sort_rank = jnp.zeros_like(job_order).at[job_order].set(
        jnp.arange(job_order.shape[0]))

    engaged = (a.task_valid & (state.task_state == SKIP)
               & state.job_alive[a.task_job] & a.job_valid[a.task_job]
               & ~overused[a.job_queue[a.task_job]])

    # ---- demand window --------------------------------------------------
    # Under contention, unlimited round parallelism fragments capacity
    # across MANY incomplete gangs (every job places a few tasks, few
    # reach MinAvailable) — the sequential reference concentrates capacity
    # job-by-job instead (allocate.go: one job visit at a time). Emulate
    # that concentration without giving up the single dispatch: only the
    # best-ranked jobs whose cumulative remaining demand fits inside the
    # window fraction of the round's available capacity participate;
    # later jobs wait for a subsequent round, by which point earlier
    # gangs completed or died. With total demand under the window
    # fraction of capacity the window admits everyone and behavior is
    # unchanged; between the fraction and full capacity a small tail is
    # deferred a round (cheap insurance against stranding).
    j_pad = a.job_valid.shape[0]
    avail_pool = jnp.where((a.node_ok
                            & (state.n_tasks < a.max_task_num))[:, None],
                           jnp.maximum(state.idle + a.backfilled, 0.0), 0.0
                           ).sum(axis=0)                      # [R]
    if pipe_enabled:
        avail_pool = avail_pool + jnp.maximum(state.releasing, 0.0).sum(
            axis=0)
    job_demand = jax.ops.segment_sum(
        jnp.where(engaged[:, None], a.resreq, 0.0),
        jnp.maximum(a.task_job, 0), num_segments=j_pad)       # [J,R]
    eng_job = jnp.any(job_demand > 0, axis=-1)                # [J]
    # dominant normalized demand (0 when the cluster has no capacity in a
    # dimension nobody can place anyway)
    norm = jnp.max(
        jnp.where(avail_pool[None, :] > 0,
                  job_demand / jnp.maximum(avail_pool[None, :], 1e-9),
                  0.0), axis=-1)                              # [J]
    norm_ord = norm[job_order]
    cum_excl = jnp.cumsum(norm_ord) - norm_ord
    in_window = cum_excl <= _WINDOW_SLACK                     # [J] ord

    # per-queue budget: the sequential reference re-checks overuse at
    # every queue POP, so a queue only ever exceeds its deserved by the
    # one job in flight; a round that admits a whole queue's backlog at
    # round-start shares locks an overshoot in before ordering can react.
    # Admit each queue's jobs (rank order) while their cumulative demand
    # stays inside the queue's REMAINING deserved; the queue's first
    # engaged job is always admitted (= the pop in flight).
    if prop_overused:
        q_remaining = jnp.maximum(a.q_deserved - state.q_allocated, 0.0)
        qr_job = q_remaining[a.job_queue]                     # [J,R]
        # dims with zero remaining are unconstrained for pacing — the
        # overuse rule itself is all-dims (proportion.go:362-373), and a
        # queue exhausted in one dim but not others keeps receiving jobs
        # in the reference until overused actually flips
        qn = jnp.max(jnp.where(qr_job > 0,
                               job_demand / jnp.maximum(qr_job, 1e-9),
                               0.0),
                     axis=-1)                                 # [J]
        # group jobs by queue, rank-ordered inside each queue; segment
        # starts via the same searchsorted idiom as acceptance
        qperm = jnp.lexsort([job_sort_rank, a.job_queue])
        qj = a.job_queue[qperm]
        seg_start = jnp.searchsorted(qj, qj, side="left")
        q_prefix = _segmented_prefix(qn[qperm], seg_start)
        eng_cnt = _segmented_prefix(
            eng_job[qperm].astype(jnp.float32), seg_start)
        first_engaged = eng_job[qperm] & (eng_cnt == 0.0)
        q_ok_perm = (q_prefix <= 1.0) | first_engaged
        q_ok = jnp.zeros(j_pad, bool).at[qperm].set(q_ok_perm)
        # queue-rejected jobs must not count against the global window —
        # their demand is NOT consuming capacity this round
        norm_ord = norm_ord * q_ok[job_order]
        cum_excl = jnp.cumsum(norm_ord) - norm_ord
        in_window = cum_excl <= _WINDOW_SLACK
    else:
        q_ok = jnp.ones(j_pad, bool)

    admitted = jnp.zeros(j_pad, bool).at[job_order].set(in_window) & q_ok
    participating = engaged & admitted[a.task_job]

    # global task rank: (job order, task order); non-participants last
    jr = jnp.where(participating, job_sort_rank[a.task_job], _IMAX)
    order = jnp.lexsort([a.task_rank, jr])
    global_rank = jnp.zeros(t_pad, jnp.int32).at[order].set(
        jnp.arange(t_pad, dtype=jnp.int32))

    # ---- 2. exact eligibility ------------------------------------------
    # (the shared resource_eligibility definition; accessible/base
    # recomputed locally for the waterfall/retry — XLA CSEs the overlap)
    accessible = state.idle + a.backfilled
    base = a.node_ok & (state.n_tasks < a.max_task_num)
    aff = a.node_dom is not None   # static: pytree structure
    pair_level = pair_init is not None  # static: active-set fast path
    if pair_level:
        assert not aff, "pair-level rounds exclude affinity configs"
        # fold the task axis to pairs: eligibility reads exactly two
        # task-axis inputs (init_resreq, task_sig), both pair-constant
        pa = a._replace(init_resreq=pair_init, task_sig=a.pair_sig)
        tp = jnp.maximum(a.task_pair, 0)
        elig_p = resource_eligibility(state.idle, state.releasing,
                                      state.n_tasks, pa, pipe_enabled,
                                      eps)                 # [P,N]
        any_elig = jnp.any(elig_p, axis=1)[tp]
    else:
        eligible = resource_eligibility(state.idle, state.releasing,
                                        state.n_tasks, a, pipe_enabled,
                                        eps)               # [T,N]
        if aff:
            aff_ok, could_wait = _aff_eligibility(state, a)
            eligible = eligible & aff_ok
        any_elig = jnp.any(eligible, axis=1)

    fail_now = participating & ~any_elig
    if aff:
        # a positive-affinity task whose group a same-cycle placement can
        # still populate waits (stays SKIP) instead of killing its job
        fail_now = fail_now & ~could_wait
    if elig_elsewhere is not None:
        # block-restricted round (two-level solve): eligibility elsewhere
        # in the cluster means "wait for a later wave", never FAIL
        fail_now = fail_now & ~elig_elsewhere
    # first failing rank per job kills the job's later-ranked tasks; only
    # the breaking task itself is marked FAIL (allocate.go:187-189 — the
    # rest simply stay Pending once the job leaves the queue)
    fail_rank = jax.ops.segment_min(
        jnp.where(fail_now, global_rank, _IMAX),
        jnp.maximum(a.task_job, 0), num_segments=a.job_valid.shape[0])
    job_killed = fail_rank < _IMAX
    fail_first = fail_now & (global_rank == fail_rank[a.task_job])
    blocked = participating & (global_rank > fail_rank[a.task_job])
    # any_elig keeps affinity-waiting tasks (no eligible node, not
    # failed) out of the proposal/acceptance phases entirely
    part2 = participating & ~fail_now & ~blocked & any_elig

    # ---- 3. proposals ---------------------------------------------------
    # Scores run per (sig, nonzero-request) PAIR cohort: the dynamic terms
    # are evaluated with the cohort's own request (exact per-task when the
    # host built exact pairs), not a sig-wide mean.
    pair_pred = a.sig_pred[a.pair_sig]                    # [P,N]
    dyn_term = jnp.zeros_like(pair_pred, jnp.float32)
    if dyn_enabled:
        dyn_term = jax.vmap(
            lambda nz: dynamic_node_score(state.nz_req, nz,
                                          a.allocatable_cm,
                                          a.dyn_weights))(a.pair_nz)
    # accumulate in f32 (the narrow seam), then store the [P,N] matrix —
    # and its [T,N] task gather below — at the policy dtype
    sdt = score_dtype(narrow)
    sc = (a.sig_scores[a.pair_sig] + dyn_term).astype(sdt)  # [P,N]

    # The waterfall is ONE shared mass ledger (independent per-cohort
    # waterfalls over-propose the globally best nodes and serialize into
    # hundreds of conflict rounds): nodes in the demand-majority cohort's
    # score order, capacity cumulated as resource VECTORS, and each task
    # proposes the first node whose cumulative capacity covers the total
    # mass of all higher-ranked tasks plus its own request — the parallel
    # emulation of sequential fill. Placement spread is heuristic; fit,
    # predicates and acceptance stay exact per task (water_elig / phase
    # checks), and mismatched tasks fall back to their pair argmax.
    p_pad = a.pair_sig.shape[0]
    pair_demand = jax.ops.segment_sum(
        part2.astype(jnp.int32), a.task_pair, num_segments=p_pad)
    maj_pair = jnp.argmax(pair_demand)
    shared_sc = sc[maj_pair]                              # [N]
    ord_sh = jnp.argsort(-shared_sc, stable=True)         # [N]
    cap_mass = jnp.where(
        (pair_pred[maj_pair] & base)[:, None],
        jnp.maximum(accessible, 0.0), 0.0)                # [N,R]
    room_cnt = jnp.maximum(
        (a.max_task_num - state.n_tasks), 0).astype(jnp.float32)
    cum_mass = jnp.cumsum(cap_mass[ord_sh], axis=0)       # [N,R]
    cum_cnt = jnp.cumsum(jnp.where(pair_pred[maj_pair] & base,
                                   room_cnt, 0.0)[ord_sh])

    # exclusive prefix mass over part2 tasks in global-rank order
    rank_perm = jnp.argsort(global_rank)
    mass_sorted = jnp.where(part2, 1.0, 0.0)[rank_perm, None] \
        * a.resreq[rank_perm]
    prefix_sorted = jnp.cumsum(mass_sorted, axis=0) - mass_sorted
    cnt_sorted = jnp.where(part2, 1.0, 0.0)[rank_perm]
    cnt_prefix_sorted = jnp.cumsum(cnt_sorted) - cnt_sorted
    prefix = jnp.zeros_like(mass_sorted).at[rank_perm].set(prefix_sorted)
    cnt_prefix = jnp.zeros_like(cnt_sorted).at[rank_perm].set(
        cnt_prefix_sorted)

    need = prefix + a.resreq                              # [T,R]
    # per-dim searchsorted, max across dims (+ the task-count ledger)
    slots = [jnp.searchsorted(cum_mass[:, d], need[:, d], side="left")
             for d in range(need.shape[1])]
    slots.append(jnp.searchsorted(cum_cnt, cnt_prefix + 1.0, side="left"))
    slot = slots[0]
    for s in slots[1:]:
        slot = jnp.maximum(slot, s)
    slot_ok = slot < n_pad
    slot_c = jnp.minimum(slot, n_pad - 1)
    p_water = ord_sh[slot_c].astype(jnp.int32)
    if pair_level:
        # two [T]-gathers from the [P,N] pair objects replace the [T,N]
        # take_along_axis / score-row gather / row argmax — the three
        # per-round fusions that dominated the packed solve's dispatch
        water_elig = elig_p[tp, p_water] & slot_ok
        fb = jnp.argmax(jnp.where(elig_p, sc, -jnp.inf), axis=1)[tp]
    else:
        water_elig = jnp.take_along_axis(eligible, p_water[:, None],
                                         axis=1)[:, 0] & slot_ok
        sc_rows = sc[a.task_pair]                         # [T,N]
        if aff and a.ip_weight is not None:
            # interpod-affinity score term (nodeorder.go:305-313) against
            # round-start counts; scored tasks leave the shared waterfall
            # — their rows are task-specific, not cohort-wide. The term
            # is integer-valued (floor(10*x) * weight), so the
            # f32-accumulate / narrow-store round trip is exact.
            ip_term, ip_scored = _ip_score(state, a)
            sc_rows = (sc_rows.astype(jnp.float32) + ip_term).astype(sdt)
            water_elig = water_elig & ~ip_scored
        fb = jnp.argmax(jnp.where(eligible, sc_rows, -jnp.inf), axis=1)
    proposal1 = jnp.where(water_elig, p_water, fb).astype(jnp.int32)

    # ---- 4. acceptance (two phases) ------------------------------------
    # Phase 1 accepts waterfall/argmax proposals; rejected tasks get a
    # SECOND CHANCE in the same round, re-proposing their best node against
    # phase-1-committed capacity — recovering most of the packing quality
    # the sequential engine gets from per-placement state refresh, without
    # another round's ordering pass.
    def accept_phase(proposal, mask, idle_c, rel_c, ntasks_c):
        acc_c = idle_c + a.backfilled
        # fit at each task's PROPOSED node only: gather the [T,R] node rows
        # instead of materializing the full [T,N,R] fit matrix (identical
        # values, ~N x less HBM traffic)
        fit_alloc_c = jnp.all(a.init_resreq <= acc_c[proposal] + eps,
                              axis=-1)
        prop_alloc = fit_alloc_c                          # else pipeline
        node_key = jnp.where(mask, proposal, n_pad)
        perm2 = jnp.lexsort([global_rank, node_key])
        nid = node_key[perm2]
        seg_start = jnp.searchsorted(nid, nid, side="left")
        nid_c = jnp.minimum(nid, n_pad - 1)

        s_req = a.resreq[perm2]
        s_init = a.init_resreq[perm2]
        s_alloc = prop_alloc[perm2]
        s_part = mask[perm2]

        alloc_vals = jnp.where((s_alloc & s_part)[:, None], s_req, 0.0)
        pipe_vals = jnp.where((~s_alloc & s_part)[:, None], s_req, 0.0)
        cnt_vals = s_part.astype(jnp.int32)

        excl_alloc = _segmented_prefix(alloc_vals, seg_start)
        excl_pipe = _segmented_prefix(pipe_vals, seg_start)
        excl_cnt = _segmented_prefix(cnt_vals, seg_start)

        pool_acc = acc_c[nid_c]
        pool_idle = idle_c[nid_c]
        pool_rel = rel_c[nid_c]
        room_left = (a.max_task_num[nid_c] - ntasks_c[nid_c]
                     - excl_cnt) > 0

        ok_alloc = (s_alloc & s_part & room_left
                    & jnp.all(s_init <= pool_acc - excl_alloc + eps,
                              axis=-1))
        if pipe_enabled:
            ok_pipe = (~s_alloc & s_part & room_left
                       & jnp.all(s_init <= pool_rel - excl_pipe + eps,
                                 axis=-1))
        else:
            ok_pipe = jnp.zeros_like(ok_alloc)
        accept_s = ok_alloc | ok_pipe
        # over-backfill: the accepted launch request no longer fits what's
        # left of plain idle after earlier-ranked accepted alloc takes
        ob_s = ok_alloc & ~jnp.all(s_init <= pool_idle - excl_alloc + eps,
                                   axis=-1)

        inv2 = jnp.zeros(t_pad, jnp.int32).at[perm2].set(
            jnp.arange(t_pad, dtype=jnp.int32))
        return accept_s[inv2], ob_s[inv2], prop_alloc

    def commit_node(accept, is_alloc, is_pipe, proposal, idle_c, rel_c,
                    ntasks_c, nz_c):
        node_seg = jnp.where(accept, proposal, 0)
        take_alloc = jnp.where(is_alloc[:, None], a.resreq, 0.0)
        take_pipe = jnp.where(is_pipe[:, None], a.resreq, 0.0)
        idle_n = idle_c - jax.ops.segment_sum(take_alloc, node_seg,
                                              num_segments=n_pad)
        rel_n = rel_c - jax.ops.segment_sum(take_pipe, node_seg,
                                            num_segments=n_pad)
        ntasks_n = ntasks_c + jax.ops.segment_sum(
            accept.astype(jnp.int32), node_seg, num_segments=n_pad)
        nz_n = nz_c + jax.ops.segment_sum(
            jnp.where(accept[:, None], a.task_nz, 0.0), node_seg,
            num_segments=n_pad)
        return idle_n, rel_n, ntasks_n, nz_n

    accept1, ob1, prop_alloc1 = accept_phase(
        proposal1, part2, state.idle, state.releasing, state.n_tasks)
    if aff:
        # remove in-round affinity/port races BEFORE capacity commits
        # (rejected tasks simply retry next round against refreshed
        # counts; freeing their capacity here is conservative-exact)
        accept1 = _aff_serialize(state, a, accept1, proposal1, global_rank)
    idle1, rel1, ntasks1, nz1 = commit_node(
        accept1, prop_alloc1 & accept1, ~prop_alloc1 & accept1, proposal1,
        state.idle, state.releasing, state.n_tasks, state.nz_req)

    # retry phase: rejected tasks re-propose their argmax against the
    # committed mid-round state. ONE retry measures best: it recovers most
    # of the packing the sequential engine gets from per-placement state
    # refresh, while further same-round eagerness starts to lock in
    # placements the next round's refreshed fairness order would improve.
    accept, ob, proposal, prop_alloc = accept1, ob1, proposal1, prop_alloc1
    idle_c, rel_c, ntasks_c, nz_c = idle1, rel1, ntasks1, nz1
    for _ in range(1):
        retry = part2 & ~accept
        if aff:
            # affinity-involved tasks sit the retry out: their acceptance
            # could race a phase-1 winner in ways only the next round's
            # refreshed counts can adjudicate
            retry = retry & ~_aff_involved(state, a)
        if pair_level:
            elig_pr = resource_eligibility(idle_c, rel_c, ntasks_c, pa,
                                           pipe_enabled, eps)  # [P,N]
            fb_r = jnp.argmax(jnp.where(elig_pr, sc, -jnp.inf),
                              axis=1)[tp].astype(jnp.int32)
            retry = retry & jnp.any(elig_pr, axis=1)[tp]
        else:
            eligible_r = resource_eligibility(idle_c, rel_c, ntasks_c, a,
                                              pipe_enabled, eps)
            if aff:
                eligible_r = eligible_r & aff_ok
            fb_r = jnp.argmax(jnp.where(eligible_r, sc_rows, -jnp.inf),
                              axis=1).astype(jnp.int32)
            retry = retry & jnp.any(eligible_r, axis=1)
        accept_r, ob_r, prop_alloc_r = accept_phase(fb_r, retry, idle_c,
                                                    rel_c, ntasks_c)
        idle_c, rel_c, ntasks_c, nz_c = commit_node(
            accept_r, prop_alloc_r & accept_r, ~prop_alloc_r & accept_r,
            fb_r, idle_c, rel_c, ntasks_c, nz_c)
        accept = accept | accept_r
        ob = jnp.where(accept_r, ob_r, ob)
        proposal = jnp.where(accept_r, fb_r, proposal)
        prop_alloc = jnp.where(accept_r, prop_alloc_r, prop_alloc)
    new_idle, new_rel, new_ntasks, new_nz = idle_c, rel_c, ntasks_c, nz_c
    is_alloc = prop_alloc & accept
    is_pipe = ~prop_alloc & accept

    # ---- 5. commit (job / queue aggregates) -----------------------------

    job_seg = jnp.where(accept, a.task_job, 0)
    take_any = jnp.where(accept[:, None], a.resreq, 0.0)
    n_jobs = a.job_valid.shape[0]
    new_j_alloc = state.j_allocated + jax.ops.segment_sum(
        take_any, job_seg, num_segments=n_jobs)
    queue_seg = jnp.where(accept, a.job_queue[jnp.maximum(a.task_job, 0)], 0)
    new_q_alloc = state.q_allocated + jax.ops.segment_sum(
        take_any, queue_seg, num_segments=a.q_deserved.shape[0])
    # pipelined-inclusive readiness; over-backfill stays outside the quorum
    counted = accept & ~ob
    new_alloc_cnt = state.alloc_cnt + jax.ops.segment_sum(
        counted.astype(jnp.int32), job_seg, num_segments=n_jobs)

    decision = jnp.where(
        fail_first, FAIL,
        jnp.where(is_pipe, PIPELINE,
                  jnp.where(is_alloc & ob, ALLOC_OB,
                            jnp.where(is_alloc, ALLOC, SKIP))))
    changed = accept | fail_first
    new_task_state = jnp.where(changed, decision, state.task_state)
    new_task_node = jnp.where(accept, proposal, state.task_node)
    stride = seq_stride if seq_stride else t_pad
    new_task_seq = jnp.where(changed, round_idx * stride + global_rank,
                             state.task_seq)

    new_alive = state.job_alive & ~job_killed
    progress = jnp.any(changed)

    aff_upd = _aff_commit(state, a, accept, proposal) if aff else {}
    new_state = RoundState(
        idle=new_idle, releasing=new_rel, n_tasks=new_ntasks, nz_req=new_nz,
        q_allocated=new_q_alloc, j_allocated=new_j_alloc,
        alloc_cnt=new_alloc_cnt, job_alive=new_alive,
        task_state=new_task_state, task_node=new_task_node,
        task_seq=new_task_seq, **aff_upd)
    return new_state, progress


def _stranded_jobs(state: RoundState, a: CycleArrays,
                   include_killed: bool = True):
    """Jobs holding this-cycle placements but below quorum at a round
    fixpoint. Gang all-or-nothing means those placements can never
    dispatch this cycle, so the capacity they hold is dead weight that
    completable gangs could use. They come in two kinds: KILLED jobs (a
    task found no eligible node mid-contention — the batch analogue of
    allocate.go:187-189, but the batch kills more often because admitted
    competitors transiently consume capacity the sequential oracle would
    have spent on THIS job) and, rarer, alive jobs whose proposals were
    perpetually out-ranked."""
    placed = ((state.task_state == ALLOC) | (state.task_state == ALLOC_OB)
              | (state.task_state == PIPELINE)) & a.task_valid
    j_pad = a.job_valid.shape[0]
    job_placed = jax.ops.segment_max(
        placed.astype(jnp.int32), jnp.maximum(a.task_job, 0),
        num_segments=j_pad).astype(bool)
    # quorum here counts ALLOC_OB: a job at MinAvailable only via
    # over-backfill placements is the fork's AlmostReady state — its
    # placements persist undispatched BY DESIGN (types.go:63-80), they
    # are not stranded
    ob_cnt = jax.ops.segment_sum(
        ((state.task_state == ALLOC_OB) & a.task_valid).astype(jnp.int32),
        jnp.maximum(a.task_job, 0), num_segments=j_pad)
    ready = state.alloc_cnt + ob_cnt >= a.order_min_available
    stranded = a.job_valid & job_placed & ~ready
    if not include_killed:
        stranded = stranded & state.job_alive
    return stranded


def _rollback_stranded(state: RoundState, a: CycleArrays,
                       revive: bool = False):
    """Revert every this-cycle placement of stranded jobs (exact inverse
    of the round commit arithmetic). With ``revive`` the jobs re-enter
    the rounds for a clean retry against the freed capacity (their FAIL
    markers clear; a genuine misfit re-records on the retry) — this is
    the epilogue emulating the oracle's job-by-job concentration at the
    contended tail. Without it the jobs retire for the cycle and retry
    fresh next cycle, like a window-deferred job."""
    stranded = _stranded_jobs(state, a, include_killed=revive)
    placed = ((state.task_state == ALLOC) | (state.task_state == ALLOC_OB)
              | (state.task_state == PIPELINE)) & a.task_valid
    revert = placed & stranded[jnp.maximum(a.task_job, 0)]
    is_pipe = revert & (state.task_state == PIPELINE)
    n_pad = state.idle.shape[0]
    j_pad = a.job_valid.shape[0]
    node_seg = jnp.where(revert, state.task_node, 0)
    give_idle = jnp.where((revert & ~is_pipe)[:, None], a.resreq, 0.0)
    give_rel = jnp.where(is_pipe[:, None], a.resreq, 0.0)
    idle = state.idle + jax.ops.segment_sum(give_idle, node_seg,
                                            num_segments=n_pad)
    rel = state.releasing + jax.ops.segment_sum(give_rel, node_seg,
                                                num_segments=n_pad)
    ntasks = state.n_tasks - jax.ops.segment_sum(
        revert.astype(jnp.int32), node_seg, num_segments=n_pad)
    nz = state.nz_req - jax.ops.segment_sum(
        jnp.where(revert[:, None], a.task_nz, 0.0), node_seg,
        num_segments=n_pad)
    job_seg = jnp.where(revert, a.task_job, 0)
    take = jnp.where(revert[:, None], a.resreq, 0.0)
    j_alloc = state.j_allocated - jax.ops.segment_sum(
        take, job_seg, num_segments=j_pad)
    queue_seg = jnp.where(revert, a.job_queue[jnp.maximum(a.task_job, 0)],
                          0)
    q_alloc = state.q_allocated - jax.ops.segment_sum(
        take, queue_seg, num_segments=a.q_deserved.shape[0])
    counted = revert & (state.task_state != ALLOC_OB)
    alloc_cnt = state.alloc_cnt - jax.ops.segment_sum(
        counted.astype(jnp.int32), job_seg, num_segments=j_pad)
    if revive:
        alive = state.job_alive | stranded
        # clear the FAIL marker too so the retry starts clean (blocked
        # tasks stayed SKIP); a real misfit re-records on the retry
        clear = revert | ((state.task_state == FAIL)
                          & stranded[jnp.maximum(a.task_job, 0)])
    else:
        alive = state.job_alive & ~stranded
        clear = revert
    aff_upd = (_aff_rollback(state, a, revert)
               if a.node_dom is not None else {})
    return state._replace(
        idle=idle, releasing=rel, n_tasks=ntasks, nz_req=nz,
        q_allocated=q_alloc, j_allocated=j_alloc, alloc_cnt=alloc_cnt,
        job_alive=alive,
        task_state=jnp.where(clear, SKIP, state.task_state),
        **aff_upd), stranded


@partial(jax.jit, static_argnames=("job_keys", "queue_keys",
                                   "prop_overused", "dyn_enabled",
                                   "pipe_enabled", "narrow"))
def batched_round(state: RoundState, a: CycleArrays, round_idx,
                  job_keys: Tuple[str, ...] = (K_PRIORITY, K_GANG_READY,
                                               K_DRF_SHARE),
                  queue_keys: Tuple[str, ...] = (K_PROP_SHARE,),
                  prop_overused: bool = True,
                  dyn_enabled: bool = False,
                  pipe_enabled: bool = True,
                  narrow: bool = False):
    """Single-round entry point (tests / diagnostics)."""
    return _round(state, a, round_idx, job_keys, queue_keys, prop_overused,
                  dyn_enabled, pipe_enabled, narrow=narrow)


# accounted trace boundary (compilesvc); nested calls from the packed /
# sharded entries pass straight through to the pjit function
batched_round = _instrument("batched", "batched_round", batched_round)


#: task-axis fields of CycleArrays (compacted for the post-round-0 loop)
_TASK_FIELDS = ("resreq", "init_resreq", "task_nz", "task_job", "task_rank",
                "task_sig", "task_pair", "task_valid")
#: affinity task-axis fields, compacted only when the cycle carries them
_AFF_TASK_FIELDS = ("task_grp", "task_req_aff", "task_req_anti",
                    "task_self_ok", "task_carry_w", "task_pref_w",
                    "task_ports")


@partial(jax.jit, static_argnames=("job_keys", "queue_keys",
                                   "prop_overused", "dyn_enabled",
                                   "pipe_enabled", "max_rounds",
                                   "compact_bucket", "gang_enabled",
                                   "narrow"))
def batched_allocate(state: RoundState, a: CycleArrays,
                     job_keys: Tuple[str, ...] = (K_PRIORITY, K_GANG_READY,
                                                  K_DRF_SHARE),
                     queue_keys: Tuple[str, ...] = (K_PROP_SHARE,),
                     prop_overused: bool = True,
                     dyn_enabled: bool = False,
                     pipe_enabled: bool = True,
                     max_rounds: int = 64,
                     compact_bucket: int = 0,
                     gang_enabled: bool = True,
                     narrow: bool = False):
    """The whole allocate cycle: rounds run in a device-side while_loop
    until a round makes no progress — ONE dispatch, one readback.

    ``compact_bucket``: in the common low-contention cycle round 0
    resolves ~90%% of tasks; the leftovers are gathered into a bucket of
    this size and the remaining rounds run at [bucket, N] instead of
    [T, N] cost (1/8th the fit/score HBM traffic). If more than
    ``compact_bucket`` tasks survive round 0, a lax.cond falls back to
    the full-width loop — same results either way, task seqs stay
    globally ordered via the shared seq stride. NB: under contention the
    demand window intentionally defers whole jobs past round 0, so
    contended cycles routinely exceed the bucket and run full-width —
    the compaction is an optimization for the uncontended steady regime,
    not the contended one.

    Returns (final RoundState, rounds, epilogue retries, stranded gang
    count) — the trailing two are int32 telemetry scalars the packed
    entries fold into the device telemetry frame."""
    t_pad = a.task_valid.shape[0]

    def rounds_loop(st, arrays, start_round):
        def cond(carry):
            _, round_idx, progress = carry
            return progress & (round_idx < max_rounds)

        def body(carry):
            s, round_idx, _ = carry
            ns, progress = _round(s, arrays, round_idx, job_keys,
                                  queue_keys, prop_overused, dyn_enabled,
                                  pipe_enabled, seq_stride=t_pad,
                                  narrow=narrow)
            return ns, round_idx + 1, progress

        init = (st, jnp.int32(start_round), jnp.asarray(True))
        return jax.lax.while_loop(cond, body, init)

    loop = rounds_loop

    def epilogue(st, rounds):
        """Stranded-gang epilogue at FULL task width (the compact bucket
        holds only round-0 leftovers, but a stranded gang's placements
        can live outside it): roll back partial gangs — killed AND alive
        (capacity they hold can never dispatch, see _rollback_stranded)
        — revive them, and re-run rounds so the freed capacity completes
        whole gangs, up to 3 passes. The final non-reviving rollback
        retires any alive-partial gang so the cycle emits none (killed
        gangs keep their pre-kill placements + FitError, exactly like
        the oracle's drop-on-first-unassignable). Returns the retry-pass
        count and the finally-stranded gang count as telemetry."""

        def epi_cond(carry):
            s, _, k = carry
            return (k < 3) & jnp.any(_stranded_jobs(s, a))

        def epi_body(carry):
            s, rounds, k = carry
            s, _ = _rollback_stranded(s, a, revive=True)
            s, rounds, _ = rounds_loop(s, a, rounds)
            return s, rounds, k + 1

        st, rounds, retries = jax.lax.while_loop(epi_cond, epi_body,
                                                 (st, rounds,
                                                  jnp.int32(0)))
        st, stranded = _rollback_stranded(st, a, revive=False)
        return st, rounds, retries, stranded.sum().astype(jnp.int32)

    if not gang_enabled:
        # without a gang quorum every placement dispatches — partial jobs
        # are legitimate (non-gang reference semantics), nothing strands
        def epilogue(st, rounds):  # noqa: F811 — identity on purpose
            return st, rounds, jnp.int32(0), jnp.int32(0)
    if compact_bucket <= 0 or compact_bucket >= t_pad:
        final, rounds, _ = loop(state, a, 0)
        return epilogue(final, rounds)

    state, _ = _round(state, a, jnp.int32(0), job_keys, queue_keys,
                      prop_overused, dyn_enabled, pipe_enabled,
                      seq_stride=t_pad, narrow=narrow)
    unresolved = (a.task_valid & (state.task_state == SKIP)
                  & state.job_alive[jnp.maximum(a.task_job, 0)])
    if prop_overused:
        # queue overuse is monotone in-cycle (q_allocated only grows), so
        # tasks of queues overused after round 0 can never resolve — keep
        # them out of the bucket (and out of the overflow count)
        eps = jnp.asarray(VEC_EPS)
        overused0 = jnp.all(a.q_deserved < state.q_allocated + eps, axis=-1)
        unresolved = unresolved & ~overused0[
            a.job_queue[jnp.maximum(a.task_job, 0)]]
    cnt = unresolved.sum()
    idx = jnp.nonzero(unresolved, size=compact_bucket, fill_value=t_pad)[0]
    valid_k = idx < t_pad
    idx_c = jnp.minimum(idx, t_pad - 1)

    def done_path(st):
        return st, jnp.int32(1)

    def compact_path(st):
        fields = _TASK_FIELDS + tuple(
            f for f in _AFF_TASK_FIELDS if getattr(a, f) is not None)
        ca = a._replace(**{f: getattr(a, f)[idx_c] for f in fields})
        ca = ca._replace(task_valid=ca.task_valid & valid_k)
        cs = st._replace(task_state=st.task_state[idx_c],
                         task_node=st.task_node[idx_c],
                         task_seq=st.task_seq[idx_c])
        fs, rounds, _ = loop(cs, ca, 1)

        def put(full, comp):
            # unclipped indices + drop: fill slots (idx == t_pad) scatter
            # nowhere, so they can't collide with row t_pad-1
            return full.at[idx].set(comp, mode="drop")

        return fs._replace(
            task_state=put(st.task_state, fs.task_state),
            task_node=put(st.task_node, fs.task_node),
            task_seq=put(st.task_seq, fs.task_seq)), rounds

    def full_path(st):
        fs, rounds, _ = loop(st, a, 1)
        return fs, rounds

    merged, rounds = jax.lax.cond(
        cnt > compact_bucket, full_path,
        lambda s: jax.lax.cond(cnt == 0, done_path, compact_path, s),
        state)
    # the epilogue always runs at full width: a stranded gang's
    # placements can live outside the compact bucket (round 0)
    return epilogue(merged, rounds)


# accounted trace boundary (compilesvc); calls nested inside the packed
# or sharded entries' traces pass straight through
batched_allocate = _instrument("batched", "batched_allocate",
                               batched_allocate)


#: (buffer kind, CycleArrays/RoundState source) for the packed upload; the
#: order defines buffer layout.  Node-axis arrays live on the DeviceSession
#: (uploaded once per session), everything per-cycle ships as THREE host
#: buffers instead of ~20 individual transfers — each device_put through
#: the axon tunnel pays latency, so transfer count dominates, not bytes.
_PACK_F32 = ("resreq", "init_resreq", "task_nz", "sig_scores",
             "job_priority", "q_deserved", "cluster_total", "dyn_weights",
             "pair_nz", "q_alloc0", "j_alloc0")
_PACK_I32 = ("task_job", "task_rank", "task_sig", "task_pair",
             "order_min_available", "job_queue", "job_create_rank",
             "q_create_rank", "init_allocated", "pair_sig")
_PACK_BOOL = ("task_valid", "job_valid", "sig_pred")

#: affinity extensions (joined only when the cycle carries the features;
#: the packed layouts are static jit args, so affinity-free cycles keep
#: their pre-affinity compiled graphs)
_AFF_F32 = ("task_carry_w", "task_pref_w", "aff_grp_cnt0", "aff_anti_cnt0",
            "aff_pref_w0", "aff_grp_total0")
_AFF_I32 = ("node_dom",)
_AFF_BOOL = ("task_grp", "task_req_aff", "task_req_anti", "task_self_ok")
_PORT_BOOL = ("task_ports", "port_base")


@partial(jax.jit, static_argnames=("lay_f", "lay_i", "lay_b", "job_keys",
                                   "queue_keys", "prop_overused",
                                   "dyn_enabled", "pipe_enabled",
                                   "max_rounds", "compact_bucket",
                                   "gang_enabled", "narrow",
                                   "narrow_gate"))
def _batched_packed(buf_f, buf_i, buf_b, idle, releasing, n_tasks, nz_req,
                    backfilled, allocatable_cm, max_task_num, node_ok,
                    lay_f, lay_i, lay_b, job_keys, queue_keys,
                    prop_overused, dyn_enabled, pipe_enabled, max_rounds,
                    compact_bucket, gang_enabled=True, narrow=False,
                    narrow_gate=False):
    f = _unpack(buf_f, lay_f)
    i = _unpack(buf_i, lay_i)
    b = _unpack(buf_b, lay_b)
    t_pad = i["task_job"].shape[0]
    state = RoundState(
        idle=idle, releasing=releasing, n_tasks=n_tasks, nz_req=nz_req,
        q_allocated=f["q_alloc0"], j_allocated=f["j_alloc0"],
        alloc_cnt=i["init_allocated"], job_alive=b["job_valid"],
        task_state=jnp.full(t_pad, SKIP, jnp.int32),
        task_node=jnp.full(t_pad, -1, jnp.int32),
        task_seq=jnp.full(t_pad, _IMAX, jnp.int32),
        aff_grp_cnt=f.get("aff_grp_cnt0"),
        aff_anti_cnt=f.get("aff_anti_cnt0"),
        aff_pref_w=f.get("aff_pref_w0"),
        aff_grp_total=f.get("aff_grp_total0"),
        port_claim=(jnp.zeros_like(b["port_base"])
                    if "port_base" in b else None))
    final, rounds, retries, stranded = _run_batched(
        state, f, i, b, backfilled, allocatable_cm, max_task_num, node_ok,
        job_keys, queue_keys, prop_overused, dyn_enabled, pipe_enabled,
        max_rounds, compact_bucket, gang_enabled, narrow)
    frame = decision_frame(
        ENGINE_BATCHED, final.task_state, final.task_seq, b["task_valid"],
        waves=rounds, stride=t_pad, narrow=narrow, narrow_gate=narrow_gate,
        retries=retries, stranded=stranded)
    return _pack_result(final, rounds, frame)


# accounted trace boundary (compilesvc): the production whole-cycle entry
_batched_packed = _instrument("batched", "_batched_packed",
                              _batched_packed)


def _pack_result(final: RoundState, rounds, frame):
    """Decisions + round count + telemetry frame as ONE int32 buffer:
    every blocking device->host read pays full tunnel latency (~70 ms on
    axon), so the host reads back a single [3*T+1+TELEM_WIDTH] array
    instead of five."""
    return final, jnp.concatenate(
        [final.task_state, final.task_node, final.task_seq,
         rounds.astype(jnp.int32)[None], frame])


def _run_batched(state, f, i, b, backfilled, allocatable_cm, max_task_num,
                 node_ok, job_keys, queue_keys, prop_overused, dyn_enabled,
                 pipe_enabled, max_rounds, compact_bucket,
                 gang_enabled=True, narrow=False):
    arrays = CycleArrays(
        backfilled=backfilled, allocatable_cm=allocatable_cm,
        max_task_num=max_task_num, node_ok=node_ok,
        resreq=f["resreq"], init_resreq=f["init_resreq"],
        task_nz=f["task_nz"], task_job=i["task_job"],
        task_rank=i["task_rank"], task_sig=i["task_sig"],
        task_pair=i["task_pair"], task_valid=b["task_valid"],
        sig_scores=f["sig_scores"], sig_pred=b["sig_pred"],
        pair_sig=i["pair_sig"], pair_nz=f["pair_nz"],
        order_min_available=i["order_min_available"],
        job_queue=i["job_queue"], job_priority=f["job_priority"],
        job_create_rank=i["job_create_rank"], job_valid=b["job_valid"],
        q_deserved=f["q_deserved"], q_create_rank=i["q_create_rank"],
        cluster_total=f["cluster_total"], dyn_weights=f["dyn_weights"],
        node_dom=i.get("node_dom"), task_grp=b.get("task_grp"),
        task_req_aff=b.get("task_req_aff"),
        task_req_anti=b.get("task_req_anti"),
        task_self_ok=b.get("task_self_ok"),
        task_carry_w=f.get("task_carry_w"),
        task_pref_w=f.get("task_pref_w"),
        task_ports=b.get("task_ports"), port_base=b.get("port_base"),
        ip_weight=f.get("aff_ip_weight"))
    return batched_allocate(
        state, arrays, job_keys=job_keys, queue_keys=queue_keys,
        prop_overused=prop_overused, dyn_enabled=dyn_enabled,
        pipe_enabled=pipe_enabled, max_rounds=max_rounds,
        compact_bucket=compact_bucket, gang_enabled=gang_enabled,
        narrow=narrow)


def prepare_batched(device, inputs, max_rounds: int = 0,
                    compact_bucket=None):
    """Build the exact (args, statics) the packed entry dispatches for
    this (device, inputs) pair — shared by the live dispatch below and
    the compilesvc signature provider, so a registered signature can
    never drift from what the engine actually traces. Returns
    (args tuple, statics dict)."""
    t_pad = inputs.task_valid.shape[0]
    if max_rounds <= 0:
        # every productive round places >= 1 task or fails >= 1 job; the
        # bound is a safety net, not the expected round count
        max_rounds = int(t_pad) + 8
    task_pair, pair_sig, pair_nz, _ = inputs.pair_terms()
    extra = {"task_pair": task_pair, "pair_sig": pair_sig,
             "pair_nz": pair_nz}
    f32_names, i32_names, bool_names = _PACK_F32, _PACK_I32, _PACK_BOOL
    aff = getattr(inputs, "affinity", None)
    if aff is not None:
        extra.update(
            task_carry_w=aff.task_carry_w, task_pref_w=aff.task_pref_w,
            aff_grp_cnt0=aff.grp_cnt0, aff_anti_cnt0=aff.anti_cnt0,
            aff_pref_w0=aff.pref_w0, aff_grp_total0=aff.grp_total0,
            node_dom=aff.node_dom, task_grp=aff.task_grp,
            task_req_aff=aff.task_req_aff, task_req_anti=aff.task_req_anti,
            task_self_ok=aff.task_self_ok)
        f32_names = f32_names + _AFF_F32
        i32_names = i32_names + _AFF_I32
        bool_names = bool_names + _AFF_BOOL
        if np.any(aff.task_ports):
            extra.update(task_ports=aff.task_ports,
                         port_base=aff.port_base)
            bool_names = bool_names + _PORT_BOOL
        if aff.ip_enabled:
            extra["aff_ip_weight"] = np.float32(aff.ip_weight)
            f32_names = f32_names + ("aff_ip_weight",)
    buf_f, lay_f, buf_i, lay_i, buf_b, lay_b = pack_inputs(
        lambda n: extra[n] if n in extra else getattr(inputs, n),
        f32_names, i32_names, bool_names)
    # compact continuation pays off once the [T,N] matrices dwarf the
    # straggler count; below ~2k tasks the full-width rounds are cheap
    if compact_bucket is None:
        compact = max(256, t_pad // 8) if t_pad >= 2048 else 0
    else:
        compact = compact_bucket
    args = (buf_f, buf_i, buf_b,
            device.idle, device.releasing, device.n_tasks, device.nz_req,
            device.backfilled, device.allocatable_cm, device.max_task_num,
            device.node_ok)
    # shape-derived node bucket (``device`` may be the rpc wire's
    # duck-typed DeviceSession, no n_padded property); AUTO narrow
    # also requires the score scale to round-trip bf16 exactly
    narrow = narrow_enabled(
        int(device.node_ok.shape[0]), t_pad,
        static_scores=inputs.sig_scores,
        dyn_weights=(inputs.dyn_weights if inputs.dyn_enabled
                     else None),
        ip_weight=(aff.ip_weight
                   if aff is not None and aff.ip_enabled else 0.0))
    statics = dict(
        lay_f=lay_f, lay_i=lay_i, lay_b=lay_b,
        job_keys=inputs.job_keys, queue_keys=inputs.queue_keys,
        prop_overused=inputs.prop_overused,
        pipe_enabled=inputs.pipe_enabled,
        dyn_enabled=inputs.dyn_enabled,
        max_rounds=min(max_rounds, 4096),
        compact_bucket=compact,
        gang_enabled=inputs.gang_enabled,
        narrow=narrow,
        # telemetry: the exactness-gate hit — the shape thresholds alone
        # wanted the narrow diet but the score/weight scale refused it
        narrow_gate=(not narrow and narrow_enabled(
            int(device.node_ok.shape[0]), t_pad)))
    return args, statics


def solve_batched(device, inputs, max_rounds: int = 0,
                  compact_bucket=None):
    """Drive the round loop.  ``device`` is a solver.DeviceSession (its
    capacity arrays are committed on return); ``inputs`` a CycleInputs
    (actions/cycle_inputs.py).  Returns (task_state, task_node, task_seq)
    as numpy plus the round count.  ``compact_bucket``: None = auto-size
    the post-round-0 compaction (tests pass 0 to force the full-width
    loop for equivalence checks)."""
    t_pad = inputs.task_valid.shape[0]
    args, statics = prepare_batched(device, inputs, max_rounds,
                                    compact_bucket)
    with _span("batched_allocate", cat="kernel") as sp:
        final, packed = _batched_packed(*args, **statics)
        # ONE blocking transfer for everything the host needs; it stays
        # inside the kernel span (which carries the jax TraceAnnotation)
        # so a one-shot capture includes the device execution, not just
        # the async dispatch
        count_blocking_readback()
        with _span("readback", cat="readback"):
            out = np.asarray(packed)
        task_state = out[:t_pad]
        task_node = out[t_pad:2 * t_pad]
        task_seq = out[2 * t_pad:3 * t_pad]
        rounds = out[3 * t_pad]
        from ..obs import telemetry as _obs_telemetry
        _obs_telemetry.record(out[3 * t_pad + 1:], span=sp)

        device.idle = final.idle
        device.releasing = final.releasing
        device.n_tasks = final.n_tasks
        device.nz_req = final.nz_req
    return task_state, task_node, task_seq, int(rounds)


# ---------------------------------------------------------------------
# compilesvc signature provider — the packed whole-cycle entry at the
# config's canonical buckets (shapes/statics via prepare_batched, the
# SAME code the live dispatch runs)
# ---------------------------------------------------------------------

def _batched_signatures(inputs, regime: str, pipe_variants=(None,)):
    from ..compilesvc.registry import Signature, signature_key

    # ONE packed buffer set — only the statics differ between pipe
    # variants, and every lambda closing over `args` shares it (packing
    # the [T,N]-scale buffers per variant would double the warm-up
    # pass's work and peak memory for nothing)
    args, base = prepare_batched(inputs.device, inputs)
    out = []
    for pipe in pipe_variants:
        statics = (base if pipe is None
                   else dict(base, pipe_enabled=pipe))
        out.append(Signature(
            engine="batched", entry="_batched_packed",
            key=signature_key("_batched_packed", args, statics),
            lower=lambda a=args, s=statics: _batched_packed.lower(*a, **s),
            run=lambda a=args, s=statics: _batched_packed(*a, **s),
            note=(f"{regime} T={inputs.task_valid.shape[0]} "
                  f"N={inputs.device.n_padded} "
                  f"pipe={statics['pipe_enabled']}")))
    return out


@_register_provider("kernels.batched")
def compile_signatures(materials):
    from ..actions.allocate import AUTO_BATCHED_MIN, AUTO_HIER_MIN_NODES

    out = []
    for regime, inputs in (("cold", materials.cold_inputs),
                           ("steady", materials.steady_inputs)):
        if inputs is None or isinstance(inputs, str):
            continue
        if len(inputs.tasks) < AUTO_BATCHED_MIN:
            continue    # this regime dispatches the fused engine
        if len(inputs.device.state.names) >= AUTO_HIER_MIN_NODES \
                and getattr(inputs, "affinity", None) is None:
            # the two-level engine owns this regime (kernels/hier.py);
            # compiling the flat [T, N] graph here would be exactly the
            # unbounded cold-compile (and OOM) the hier split avoids
            continue
        # reclaim/preempt configs can open a batched cycle with releasing
        # capacity on the nodes (evictions pending) — pipe_enabled is a
        # static, so both variants are part of the registered surface
        pipes = ((False, True)
                 if ("reclaim" in materials.actions
                     or "preempt" in materials.actions)
                 else (bool(inputs.pipe_enabled),))
        out.extend(_batched_signatures(inputs, regime, pipes))
    return out
