"""Device-resident solve telemetry — one fixed-width int32 frame per
dispatch, riding the engine's EXISTING packed host result.

Every device engine already ships its decisions to the host as one
packed int32 block (the single blocking readback per cycle — each
read pays the full axon-tunnel RTT). This module defines a small
fixed-shape frame the engines append to that block, so wave counts,
eligibility census, pool occupancy, narrow-gate hits and the gang
epilogue's retry/stranded counters become visible on the host WITHOUT
a second transfer. The frame width is a compile-time constant and the
fields are int32 scalars computed from state the kernels already
carry, so appending it changes neither the dispatch count nor the
signature registration path (compilesvc providers derive keys through
the live prepare_* code, which now simply returns a slightly longer
output block).

The host-side decode lives in obs/telemetry.py; keep FIELDS and the
index constants below in sync with it (they import from here).

Decision codes are duplicated from kernels/solver.py — importing them
would create a cycle (solver -> obs -> telemetry decode -> solver).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["TELEM_WIDTH", "WAVE_SLOTS", "FIELDS", "ENGINE_NAMES",
           "ENGINE_VISIT", "ENGINE_BATCHED", "ENGINE_FUSED", "ENGINE_HIER",
           "ENGINE_SHARDED", "ENGINE_HIER_SHARDED", "ENGINE_VICTIM_WAVE",
           "ENGINE_VICTIM_VISIT", "ENGINE_ACTIVESET", "decision_frame",
           "host_frame"]

#: frame width in int32 words — static per config, part of every
#: engine's packed-output shape
TELEM_WIDTH = 20

#: per-wave bound-task histogram slots (wave index clips into the last)
WAVE_SLOTS = 4

# field indices ---------------------------------------------------------
F_ENGINE = 0        # engine id (ENGINE_* below)
F_WAVES = 1         # waves / rounds / iterations the solve ran
F_BOUND = 2         # tasks bound (ALLOC | ALLOC_OB | PIPELINE)
F_FAILED = 3        # tasks the solve marked FAIL
F_PENDING = 4       # valid tasks left SKIP (not visited / job dropped)
F_CENSUS = 5        # eligibility census: valid tasks presented
F_WAVE_BOUND0 = 6   # .. F_WAVE_BOUND0+WAVE_SLOTS-1: bound per wave slot
F_POOL_OCC = 10     # hier: pools with >=1 eligible candidate, wave 0
F_BUCKET_FILL = 11  # hier: candidate count in the winning pool, wave 0
F_NARROW = 12       # narrow dtype engaged for this dispatch (0/1)
F_NARROW_GATE = 13  # shape wanted narrow but the exactness gate refused
F_RETRIES = 14      # gang epilogue compaction retries taken
F_STRANDED = 15     # gangs still stranded after the final rollback
F_ACT_TASKS = 16    # activeset: active (pending) tasks in the packed set
F_ACT_NODES = 17    # activeset: candidate nodes (eligible pools x pool)
F_ACT_SCATTER = 18  # activeset: node rows scattered back (waves x pool)
F_ACT_DEMOTED = 19  # activeset: audit divergences (nonzero = demote) /
                    # demotion bit on host-assembled frames

#: decode order — index i of the frame is FIELDS[i]
FIELDS = ("engine", "waves", "bound", "failed", "pending", "census",
          "wave_bound0", "wave_bound1", "wave_bound2", "wave_bound3",
          "pool_occ", "bucket_fill", "narrow", "narrow_gate",
          "retries", "stranded", "act_tasks", "act_nodes", "act_scatter",
          "act_demoted")

# engine ids ------------------------------------------------------------
ENGINE_VISIT = 1
ENGINE_BATCHED = 2
ENGINE_FUSED = 3
ENGINE_HIER = 4
ENGINE_SHARDED = 5
ENGINE_HIER_SHARDED = 6
ENGINE_VICTIM_WAVE = 7
ENGINE_VICTIM_VISIT = 8
ENGINE_ACTIVESET = 9

ENGINE_NAMES = {
    ENGINE_VISIT: "visit",
    ENGINE_BATCHED: "batched",
    ENGINE_FUSED: "fused",
    ENGINE_HIER: "hier",
    ENGINE_SHARDED: "sharded",
    ENGINE_HIER_SHARDED: "hier_sharded",
    ENGINE_VICTIM_WAVE: "victim_wave",
    ENGINE_VICTIM_VISIT: "victim_visit",
    ENGINE_ACTIVESET: "activeset",
}

# decision codes (solver.py/fused.py agree on these)
_SKIP, _ALLOC, _ALLOC_OB, _PIPELINE, _FAIL = 0, 1, 2, 3, 4


def decision_frame(engine: int, task_state, task_seq, task_valid, waves,
                   stride: int, *, narrow: bool = False,
                   narrow_gate: bool = False, retries=0, stranded=0,
                   pool_occ=0, bucket_fill=0, act_tasks=0, act_nodes=0,
                   act_scatter=0, act_demoted=0):
    """Build the [TELEM_WIDTH] int32 frame inside a jitted solve.

    ``task_state``/``task_seq``/``task_valid`` are the engine's decision
    arrays; ``stride`` is the engine's task_seq round stride (static —
    seq // stride recovers the wave a placement landed in; engines
    without wave structure pass a stride that maps every placement to
    slot 0). Untouched tasks hold int32 max in task_seq — the clip
    below keeps their (zero-weight) scatter index in range.
    """
    i32 = jnp.int32

    def scal(x):
        return jnp.asarray(x, i32).reshape(())

    valid = jnp.asarray(task_valid, bool)
    state = jnp.asarray(task_state, i32)
    placed = valid & ((state == _ALLOC) | (state == _ALLOC_OB)
                      | (state == _PIPELINE))
    bound = placed.sum().astype(i32)
    failed = (valid & (state == _FAIL)).sum().astype(i32)
    pending = (valid & (state == _SKIP)).sum().astype(i32)
    census = valid.sum().astype(i32)
    slot = jnp.clip(jnp.asarray(task_seq, i32) // i32(max(int(stride), 1)),
                    0, WAVE_SLOTS - 1)
    wave_bound = jnp.zeros(WAVE_SLOTS, i32).at[slot].add(
        placed.astype(i32))
    return jnp.concatenate([
        jnp.stack([scal(engine), scal(waves), bound, failed, pending,
                   census]),
        wave_bound,
        jnp.stack([scal(pool_occ), scal(bucket_fill),
                   scal(1 if narrow else 0), scal(1 if narrow_gate else 0),
                   scal(retries), scal(stranded), scal(act_tasks),
                   scal(act_nodes), scal(act_scatter), scal(act_demoted)]),
    ])


def host_frame(engine: int, **fields) -> np.ndarray:
    """Numpy frame for engines whose telemetry is assembled host-side
    from the already-read-back packed block (the victim kernels: their
    result block is a bool bitmap, so the frame is derived from the
    same single readback instead of widening the transfer 4x).
    Unknown field names are a programming error."""
    out = np.zeros(TELEM_WIDTH, np.int32)
    out[F_ENGINE] = engine
    index = {name: i for i, name in enumerate(FIELDS)}
    for name, val in fields.items():
        out[index[name]] = int(val)
    return out
