"""Hierarchical two-level allocate — node-pool buckets, then the
waterfall within the winning bucket.

The round solver (kernels/batched.py) materializes [T, N]-scale fit and
score matrices every round. docs/SCALING.md budgets that layout to
~10x past cfg5; at cfg6/cfg7 (50-100k nodes x 50-100k pods) a single
[T, N] matrix is gigabytes even narrowed, and no shard of a practical
mesh can hold one. This module is the standard large-cluster move
(the Omega/Borg two-level lineage in PAPERS.md): decompose the node
axis into B contiguous POOLS of ``pool_size`` nodes and schedule in
WAVES —

1. **Coarse pass** (pool level, small): an exact per-(task, pool)
   eligibility fold — computed pool-by-pool at [T, pool_size] peak
   memory, never [T, N] — plus a pool score (the demand-majority
   cohort's best eligible node score per pool, the same cohort the
   waterfall ledgers). One small [T, B] problem.
2. **Winning bucket**: the best-scoring pool that still has eligible
   pending work. Ties break to the lowest pool index — the same
   direction the flat waterfall's stable node sort fills.
3. **Within-bucket waterfall**: the EXISTING round solver
   (batched._round — ordering, demand window, waterfall, two-phase
   acceptance, gang kill semantics, all unchanged) runs with every
   node-axis array dynamic-sliced to the winning bucket's block, so the
   big intermediates are [T, pool_size]. A task with no eligible node
   in the block but eligibility elsewhere WAITS for a later wave
   (the ``elig_elsewhere`` hook) instead of failing its job; a task
   eligible NOWHERE fails exactly like the flat solve (allocate.go's
   drop-on-first-unassignable, same global-rank first-fail per job).
4. Waves repeat — capacity consumed in one bucket re-ranks the next
   coarse pass — until no pool has eligible pending work. The
   stranded-gang epilogue (rollback + revive, then final retire) runs
   at full task width, exactly as the flat engine's; it touches only
   [T]- and [N]-scale state, never [T, N].

The whole wave loop runs INSIDE one jit dispatch (a ``while_loop`` over
waves around the existing ``while_loop`` over rounds), so the cycle
still performs exactly ONE blocking readback — the [3T+1] packed
decision buffer, identical to the flat entry's.

Faithfulness: within a wave the solve IS the batched round solver on a
node subset; across waves, ordering is wave-granular the same way the
flat engine's is round-granular. When one bucket covers every eligible
node of the cycle's demand (the regime the downsampled equality test
pins), decisions are bit-identical to the flat solve. Under
cross-bucket contention the task->node map can differ from the flat
schedule while satisfying the same policy constraints — the same
contract batched.py documents vs the sequential oracle, one level up.

Inter-pod affinity / host-port cycles are NOT expressible here (their
domain carries are cluster-global); the action layer falls back to the
flat engines for them, counted in ``engine_demotions_total``.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compilesvc import instrument as _instrument
from ..compilesvc import register_provider as _register_provider
from ..metrics import count_blocking_readback
from ..obs import span as _span
from .batched import (CycleArrays, RoundState, _IMAX, _PACK_BOOL, _PACK_F32,
                      _PACK_I32, _pack_result, _rollback_stranded, _round,
                      _stranded_jobs, resource_eligibility)
from .fused import (ALLOC, ALLOC_OB, K_DRF_SHARE, K_GANG_READY, K_PRIORITY,
                    K_PROP_SHARE, PIPELINE, SKIP)
from .narrow import narrow_enabled
from .pack import pack_inputs
from .telemetry import ENGINE_HIER, ENGINE_HIER_SHARDED, decision_frame
from .pack import unpack as _unpack
from .solver import dynamic_node_score
from .tensorize import VEC_EPS

_BIG_NEG = jnp.float32(-3.0e38)

#: placed-family decision codes (remap targets for block->global nodes)
_PLACED = (ALLOC, ALLOC_OB, PIPELINE)


def hier_pool_size(n_pad: int) -> int:
    """The pool (bucket) width for a padded node axis — must divide
    ``n_pad``. Large re-bucketed axes (multiples of the 4096 grain,
    kernels/tensorize.pad_to_bucket) use the grain itself; small pow2
    axes split in 8 so the equality tests exercise real multi-pool
    plans. KUBEBATCH_HIER_POOL overrides (clamped to a divisor)."""
    import os

    def divisor_at_most(p: int) -> int:
        p = max(1, min(p, n_pad))
        while n_pad % p:
            p -= 1
        return p

    env = os.environ.get("KUBEBATCH_HIER_POOL", "").strip()
    if env:
        return divisor_at_most(int(env))
    if n_pad % 4096 == 0 and n_pad > 4096:
        return 4096
    # non-grain-aligned axes (mesh-rounded shard buckets on 6/12-device
    # meshes) clamp down to the nearest divisor too
    return divisor_at_most(n_pad // 8) if n_pad >= 64 else n_pad


def _block_state(state: RoundState, off, pool: int):
    """RoundState with the node-axis carry sliced to one block."""
    r = state.idle.shape[1]
    return state._replace(
        idle=jax.lax.dynamic_slice(state.idle, (off, 0), (pool, r)),
        releasing=jax.lax.dynamic_slice(state.releasing, (off, 0),
                                        (pool, r)),
        n_tasks=jax.lax.dynamic_slice(state.n_tasks, (off,), (pool,)),
        nz_req=jax.lax.dynamic_slice(state.nz_req, (off, 0), (pool, 2)))


def _block_arrays(a: CycleArrays, off, pool: int):
    """CycleArrays with every node-axis array sliced to one block."""
    r = a.backfilled.shape[1]
    s = a.sig_scores.shape[0]
    return a._replace(
        backfilled=jax.lax.dynamic_slice(a.backfilled, (off, 0), (pool, r)),
        allocatable_cm=jax.lax.dynamic_slice(a.allocatable_cm, (off, 0),
                                             (pool, 2)),
        max_task_num=jax.lax.dynamic_slice(a.max_task_num, (off,), (pool,)),
        node_ok=jax.lax.dynamic_slice(a.node_ok, (off,), (pool,)),
        sig_scores=jax.lax.dynamic_slice(a.sig_scores, (0, off), (s, pool)),
        sig_pred=jax.lax.dynamic_slice(a.sig_pred, (0, off), (s, pool)))


def _merge_block(state: RoundState, bfinal: RoundState, off, pool: int):
    """Fold a finished wave's block state back into the full-width
    state: node carry via dynamic_update_slice, task/job/queue state
    carried whole (the block round updated them at full width), and the
    block-LOCAL node indices of this wave's new placements remapped to
    global rows."""
    newly = (bfinal.task_state != state.task_state)
    placed = ((bfinal.task_state == ALLOC) | (bfinal.task_state == ALLOC_OB)
              | (bfinal.task_state == PIPELINE))
    task_node = jnp.where(newly & placed,
                          bfinal.task_node + off.astype(jnp.int32),
                          state.task_node)
    return state._replace(
        idle=jax.lax.dynamic_update_slice(state.idle, bfinal.idle, (off, 0)),
        releasing=jax.lax.dynamic_update_slice(state.releasing,
                                               bfinal.releasing, (off, 0)),
        n_tasks=jax.lax.dynamic_update_slice(state.n_tasks, bfinal.n_tasks,
                                             (off,)),
        nz_req=jax.lax.dynamic_update_slice(state.nz_req, bfinal.nz_req,
                                            (off, 0)),
        q_allocated=bfinal.q_allocated, j_allocated=bfinal.j_allocated,
        alloc_cnt=bfinal.alloc_cnt, job_alive=bfinal.job_alive,
        task_state=bfinal.task_state, task_node=task_node,
        task_seq=bfinal.task_seq)


def _coarse_pass(state: RoundState, a: CycleArrays, pool: int,
                 pipe_enabled: bool, dyn_enabled: bool):
    """The pool-level pass: exact per-(task, pool) any-eligibility —
    the round solver's OWN resource_eligibility applied block by block
    at [T, pool] peak memory (one shared definition, so the
    FAIL-vs-WAIT semantics derived from it can never drift from what
    the round enforces) — plus the demand-majority cohort's best
    eligible score per pool.

    Returns (task_pool_elig [T, B] bool, pool_best [B] f32)."""
    eps = jnp.asarray(VEC_EPS)
    n_pad = a.node_ok.shape[0]
    t_pad = a.task_valid.shape[0]
    n_pools = n_pad // pool

    base = a.node_ok & (state.n_tasks < a.max_task_num)      # [N]

    def one_pool(p, acc_elig):
        off = p * pool
        bs = _block_state(state, off, pool)
        ba = _block_arrays(a, off, pool)
        elig = resource_eligibility(bs.idle, bs.releasing, bs.n_tasks,
                                    ba, pipe_enabled, eps)   # [T, pool]
        col = jnp.any(elig, axis=1)                          # [T]
        return jax.lax.dynamic_update_slice(acc_elig, col[:, None], (0, p))

    task_pool_elig = jax.lax.fori_loop(
        0, n_pools, one_pool, jnp.zeros((t_pad, n_pools), bool))

    # demand-majority cohort (the waterfall's shared-ledger cohort)
    engaged = (a.task_valid & (state.task_state == SKIP)
               & state.job_alive[jnp.maximum(a.task_job, 0)]
               & a.job_valid[jnp.maximum(a.task_job, 0)])
    pair_demand = jax.ops.segment_sum(
        engaged.astype(jnp.int32), a.task_pair,
        num_segments=a.pair_sig.shape[0])
    maj = jnp.argmax(pair_demand)
    sc_maj = a.sig_scores[a.pair_sig[maj]].astype(jnp.float32)
    if dyn_enabled:
        sc_maj = sc_maj + dynamic_node_score(state.nz_req, a.pair_nz[maj],
                                             a.allocatable_cm,
                                             a.dyn_weights)
    pred_maj = a.sig_pred[a.pair_sig[maj]]
    pool_best = jnp.where(pred_maj & base, sc_maj, _BIG_NEG
                          ).reshape(n_pools, pool).max(axis=1)
    return task_pool_elig, pool_best


def hier_allocate(state: RoundState, a: CycleArrays,
                  job_keys: Tuple[str, ...] = (K_PRIORITY, K_GANG_READY,
                                               K_DRF_SHARE),
                  queue_keys: Tuple[str, ...] = (K_PROP_SHARE,),
                  prop_overused: bool = True,
                  dyn_enabled: bool = False,
                  pipe_enabled: bool = True,
                  max_rounds: int = 64,
                  pool_size: int = 0,
                  max_waves: int = 0,
                  gang_enabled: bool = True,
                  narrow: bool = True):
    """The whole two-level allocate cycle — waves of (coarse pool pass →
    within-bucket round loop) in ONE device dispatch. Returns
    (final RoundState, rounds, epilogue retries, stranded gang count,
    first-wave pool occupancy, first-wave winning-bucket fill) — the
    trailing four are int32 telemetry scalars the packed entries fold
    into the device telemetry frame."""
    t_pad = a.task_valid.shape[0]
    n_pad = a.node_ok.shape[0]
    pool = pool_size if pool_size > 0 else hier_pool_size(n_pad)
    assert n_pad % pool == 0, (n_pad, pool)
    n_pools = n_pad // pool
    if max_waves <= 0:
        # every productive wave changes >= 1 task state, and between two
        # productive waves at most n_pools dead waves can run (each dead
        # wave quarantines a distinct pool; with every candidate pool
        # blocked the loop exits) — so this bound can never cut off
        # eligible pending work. It is a safety net like the flat
        # engine's max_rounds, not the expected wave count, and a large
        # value costs nothing (the loop exits on has_work).
        max_waves = (t_pad + 8) * (n_pools + 1)

    def block_rounds(st, barrays, rounds0, elig_elsewhere):
        def cond(carry):
            _, round_idx, progress = carry
            return progress & (round_idx < max_rounds)

        def body(carry):
            s, round_idx, _ = carry
            ns, progress = _round(s, barrays, round_idx, job_keys,
                                  queue_keys, prop_overused, dyn_enabled,
                                  pipe_enabled, seq_stride=t_pad,
                                  narrow=narrow,
                                  elig_elsewhere=elig_elsewhere)
            return ns, round_idx + 1, progress

        init = (st, rounds0, jnp.asarray(True))
        return jax.lax.while_loop(cond, body, init)

    def waves_loop(state, rounds0):
        def cond(carry):
            _, _, wave, _, has_work, _, _ = carry
            return has_work & (wave < max_waves)

        def body(carry):
            st, rounds, wave, blocked, _, occ0, fill0 = carry
            task_pool_elig, pool_best = _coarse_pass(st, a, pool,
                                                     pipe_enabled,
                                                     dyn_enabled)
            pending = (a.task_valid & (st.task_state == SKIP)
                       & st.job_alive[jnp.maximum(a.task_job, 0)]
                       & a.job_valid[jnp.maximum(a.task_job, 0)])
            cand_cnt = (task_pool_elig
                        & pending[:, None]).sum(axis=0)      # [B]
            key = jnp.where((cand_cnt > 0) & ~blocked, pool_best, -jnp.inf)
            has_work = jnp.any(key > -jnp.inf)
            winner = jnp.argmax(key)
            # telemetry: the FIRST wave's coarse-pass shape — pools with
            # any eligible pending work, and the winner's candidate fill
            first = wave == 0
            occ_n = jnp.where(first,
                              (cand_cnt > 0).sum().astype(jnp.int32), occ0)
            fill_n = jnp.where(first, cand_cnt[winner].astype(jnp.int32),
                               fill0)

            def run_block(args):
                st, rounds, blocked = args
                off = (winner * pool).astype(jnp.int32)
                elig_elsewhere = jnp.any(
                    task_pool_elig
                    & (jnp.arange(n_pools) != winner)[None, :], axis=1)
                bstate = _block_state(st, off, pool)
                barrays = _block_arrays(a, off, pool)
                bfinal, rounds_n, _ = block_rounds(bstate, barrays, rounds,
                                                   elig_elsewhere)
                merged = _merge_block(st, bfinal, off, pool)
                progressed = jnp.any(merged.task_state != st.task_state)
                # a dead wave quarantines its pool until the next
                # productive wave refreshes capacity; a productive wave
                # re-opens every pool
                blocked_n = jnp.where(
                    progressed, jnp.zeros_like(blocked),
                    blocked.at[winner].set(True))
                return merged, rounds_n, blocked_n

            st_out, rounds_out, blocked_out = jax.lax.cond(
                has_work, run_block, lambda args: args,
                (st, rounds, blocked))
            return (st_out, rounds_out, wave + 1, blocked_out, has_work,
                    occ_n, fill_n)

        init = (state, rounds0, jnp.int32(0),
                jnp.zeros(n_pools, bool), jnp.asarray(True),
                jnp.int32(0), jnp.int32(0))
        st, rounds, _, _, _, occ, fill = jax.lax.while_loop(cond, body,
                                                            init)

        # terminal FAIL sweep: with no pool left holding eligible
        # pending work, tasks eligible NOWHERE must still fail (and
        # gang-kill) exactly like the flat engine's round would — the
        # wave loop alone never runs a round for them (a cycle whose
        # every pending task is oversized would otherwise leave all
        # jobs alive). One block round on pool 0 with elig_elsewhere =
        # any-pool eligibility applies the ordering/window/first-fail
        # semantics; tasks eligible in some (possibly quarantined)
        # pool keep waiting for the next cycle.
        task_pool_elig, _ = _coarse_pass(st, a, pool, pipe_enabled,
                                         dyn_enabled)
        elig_any = jnp.any(task_pool_elig, axis=1)
        off0 = jnp.int32(0)
        bfinal, rounds, _ = block_rounds(
            _block_state(st, off0, pool), _block_arrays(a, off0, pool),
            rounds, elig_any)
        return _merge_block(st, bfinal, off0, pool), rounds, occ, fill

    final, rounds, pool_occ, bucket_fill = waves_loop(state, jnp.int32(0))

    retries = jnp.int32(0)
    stranded = jnp.int32(0)
    if gang_enabled:
        # stranded-gang epilogue at full task width, the flat engine's
        # exact structure (batched.batched_allocate): rollback + revive
        # up to 3 passes (freed capacity re-enters the WAVE loop), then
        # the final non-reviving rollback retires alive partial gangs
        def epi_cond(carry):
            s, _, k = carry
            return (k < 3) & jnp.any(_stranded_jobs(s, a))

        def epi_body(carry):
            s, rounds, k = carry
            s, _ = _rollback_stranded(s, a, revive=True)
            # epilogue waves keep their own coarse-pass stats out of the
            # frame — pool_occ/bucket_fill describe the cycle's opening
            s, rounds, _, _ = waves_loop(s, rounds)
            return s, rounds, k + 1

        final, rounds, retries = jax.lax.while_loop(
            epi_cond, epi_body, (final, rounds, jnp.int32(0)))
        final, stranded_mask = _rollback_stranded(final, a, revive=False)
        stranded = stranded_mask.sum().astype(jnp.int32)
    return final, rounds, retries, stranded, pool_occ, bucket_fill


@partial(jax.jit, static_argnames=("lay_f", "lay_i", "lay_b", "job_keys",
                                   "queue_keys", "prop_overused",
                                   "dyn_enabled", "pipe_enabled",
                                   "max_rounds", "pool_size", "max_waves",
                                   "gang_enabled", "narrow",
                                   "narrow_gate"))
def _hier_packed(buf_f, buf_i, buf_b, idle, releasing, n_tasks, nz_req,
                 backfilled, allocatable_cm, max_task_num, node_ok,
                 lay_f, lay_i, lay_b, job_keys, queue_keys,
                 prop_overused, dyn_enabled, pipe_enabled, max_rounds,
                 pool_size, max_waves=0, gang_enabled=True, narrow=True,
                 narrow_gate=False):
    f = _unpack(buf_f, lay_f)
    i = _unpack(buf_i, lay_i)
    b = _unpack(buf_b, lay_b)
    t_pad = i["task_job"].shape[0]
    state = RoundState(
        idle=idle, releasing=releasing, n_tasks=n_tasks, nz_req=nz_req,
        q_allocated=f["q_alloc0"], j_allocated=f["j_alloc0"],
        alloc_cnt=i["init_allocated"], job_alive=b["job_valid"],
        task_state=jnp.full(t_pad, SKIP, jnp.int32),
        task_node=jnp.full(t_pad, -1, jnp.int32),
        task_seq=jnp.full(t_pad, _IMAX, jnp.int32))
    arrays = CycleArrays(
        backfilled=backfilled, allocatable_cm=allocatable_cm,
        max_task_num=max_task_num, node_ok=node_ok,
        resreq=f["resreq"], init_resreq=f["init_resreq"],
        task_nz=f["task_nz"], task_job=i["task_job"],
        task_rank=i["task_rank"], task_sig=i["task_sig"],
        task_pair=i["task_pair"], task_valid=b["task_valid"],
        sig_scores=f["sig_scores"], sig_pred=b["sig_pred"],
        pair_sig=i["pair_sig"], pair_nz=f["pair_nz"],
        order_min_available=i["order_min_available"],
        job_queue=i["job_queue"], job_priority=f["job_priority"],
        job_create_rank=i["job_create_rank"], job_valid=b["job_valid"],
        q_deserved=f["q_deserved"], q_create_rank=i["q_create_rank"],
        cluster_total=f["cluster_total"], dyn_weights=f["dyn_weights"])
    final, rounds, retries, stranded, pool_occ, bucket_fill = \
        hier_allocate(
            state, arrays, job_keys=job_keys, queue_keys=queue_keys,
            prop_overused=prop_overused, dyn_enabled=dyn_enabled,
            pipe_enabled=pipe_enabled, max_rounds=max_rounds,
            pool_size=pool_size, max_waves=max_waves,
            gang_enabled=gang_enabled, narrow=narrow)
    frame = decision_frame(
        ENGINE_HIER, final.task_state, final.task_seq, b["task_valid"],
        waves=rounds, stride=t_pad, narrow=narrow, narrow_gate=narrow_gate,
        retries=retries, stranded=stranded, pool_occ=pool_occ,
        bucket_fill=bucket_fill)
    return _pack_result(final, rounds, frame)


# accounted trace boundary (compilesvc): the two-level whole-cycle entry
_hier_packed = _instrument("hier", "_hier_packed", _hier_packed)


def prepare_hier(device, inputs, max_rounds: int = 0,
                 pool_size: int = 0):
    """The exact (args, statics) the two-level packed entry dispatches
    for this (device, inputs) pair — shared by the live dispatch and the
    compilesvc signature provider (same can't-drift discipline as
    prepare_batched). Affinity cycles are NOT expressible here — the
    action layer gates them to the flat engines first."""
    assert getattr(inputs, "affinity", None) is None, \
        "hier requires an affinity-free cycle (action layer gates this)"
    t_pad = inputs.task_valid.shape[0]
    n_pad = int(device.node_ok.shape[0])   # wire devices lack n_padded
    if max_rounds <= 0:
        max_rounds = int(t_pad) + 8
    task_pair, pair_sig, pair_nz, _ = inputs.pair_terms()
    extra = {"task_pair": task_pair, "pair_sig": pair_sig,
             "pair_nz": pair_nz}
    buf_f, lay_f, buf_i, lay_i, buf_b, lay_b = pack_inputs(
        lambda n: extra[n] if n in extra else getattr(inputs, n),
        _PACK_F32, _PACK_I32, _PACK_BOOL)
    pool = pool_size if pool_size > 0 else hier_pool_size(n_pad)
    args = (buf_f, buf_i, buf_b,
            device.idle, device.releasing, device.n_tasks, device.nz_req,
            device.backfilled, device.allocatable_cm, device.max_task_num,
            device.node_ok)
    # narrow by the FULL [T, N] problem (the scale that forced the
    # two-level split), not the block — cfg6/cfg7 blocks ride bf16
    # when the score scale round-trips exactly
    narrow = narrow_enabled(
        n_pad, t_pad, static_scores=inputs.sig_scores,
        dyn_weights=(inputs.dyn_weights if inputs.dyn_enabled
                     else None))
    statics = dict(
        lay_f=lay_f, lay_i=lay_i, lay_b=lay_b,
        job_keys=inputs.job_keys, queue_keys=inputs.queue_keys,
        prop_overused=inputs.prop_overused,
        pipe_enabled=inputs.pipe_enabled,
        dyn_enabled=inputs.dyn_enabled,
        max_rounds=min(max_rounds, 4096),
        pool_size=pool,
        gang_enabled=inputs.gang_enabled,
        narrow=narrow,
        # telemetry: the exactness-gate hit — the shape thresholds alone
        # wanted the narrow diet but the score/weight scale refused it
        narrow_gate=(not narrow and narrow_enabled(n_pad, t_pad)))
    return args, statics


def solve_hier(device, inputs, max_rounds: int = 0, pool_size: int = 0):
    """Drive the two-level wave loop — the hier twin of
    kernels/batched.solve_batched: same CycleInputs in, same
    (task_state, task_node, task_seq, rounds) numpy out, ONE blocking
    readback, device carry committed on return."""
    t_pad = inputs.task_valid.shape[0]
    args, statics = prepare_hier(device, inputs, max_rounds, pool_size)
    with _span("hier_allocate", cat="kernel") as sp:
        final, packed = _hier_packed(*args, **statics)
        count_blocking_readback()
        with _span("readback", cat="readback"):
            out = np.asarray(packed)
        task_state = out[:t_pad]
        task_node = out[t_pad:2 * t_pad]
        task_seq = out[2 * t_pad:3 * t_pad]
        rounds = out[3 * t_pad]
        from ..obs import telemetry as _obs_telemetry
        _obs_telemetry.record(out[3 * t_pad + 1:], span=sp)

        device.idle = final.idle
        device.releasing = final.releasing
        device.n_tasks = final.n_tasks
        device.nz_req = final.nz_req
    return task_state, task_node, task_seq, int(rounds)


# ---------------------------------------------------------------------
# mesh twin — the wave loop with the node axis partitioned (GSPMD).
# The coarse fold and the block slices are plain lax ops on annotated
# arrays; XLA's SPMD partitioner inserts the collectives exactly as it
# does for the flat sharded entry. Used by the 1-D / 2-D mesh equality
# tests; cluster-scale runs pick hier OR sharded by topology.
# ---------------------------------------------------------------------

@partial(jax.jit, static_argnames=("job_keys", "queue_keys",
                                   "prop_overused", "dyn_enabled",
                                   "pipe_enabled", "max_rounds",
                                   "pool_size", "gang_enabled", "narrow",
                                   "narrow_gate"))
def _hier_sharded_entry(state: RoundState, arrays: CycleArrays, job_keys,
                        queue_keys, prop_overused, dyn_enabled,
                        pipe_enabled, max_rounds, pool_size,
                        gang_enabled=True, narrow=True, narrow_gate=False):
    final, rounds, retries, stranded, pool_occ, bucket_fill = \
        hier_allocate(
            state, arrays, job_keys=job_keys, queue_keys=queue_keys,
            prop_overused=prop_overused, dyn_enabled=dyn_enabled,
            pipe_enabled=pipe_enabled, max_rounds=max_rounds,
            pool_size=pool_size, gang_enabled=gang_enabled, narrow=narrow)
    frame = decision_frame(
        ENGINE_HIER_SHARDED, final.task_state, final.task_seq,
        arrays.task_valid, waves=rounds,
        stride=arrays.task_valid.shape[0], narrow=narrow,
        narrow_gate=narrow_gate, retries=retries, stranded=stranded,
        pool_occ=pool_occ, bucket_fill=bucket_fill)
    return final, jnp.concatenate(
        [final.task_state, final.task_node, final.task_seq,
         rounds.astype(jnp.int32)[None], frame])


_hier_sharded_entry = _instrument("hier", "_hier_sharded_entry",
                                  _hier_sharded_entry)


def solve_hier_sharded(mesh, device, inputs, max_rounds: int = 0,
                       pool_size: int = 0):
    """Two-level solve on the mesh: prepare/placement via the flat
    sharded twin's annotation recipe (batched_sharded.prepare_sharded —
    node axis split over every mesh axis, everything else replicated),
    then the wave loop as one GSPMD dispatch."""
    from .batched_sharded import prepare_sharded

    n_pad = device.n_padded
    t_pad = inputs.task_valid.shape[0]
    placed_state, placed_arrays, base = prepare_sharded(
        mesh, device, inputs, max_rounds)
    n_sh = placed_arrays.node_ok.shape[0]
    pool = pool_size if pool_size > 0 else hier_pool_size(n_sh)
    narrow = narrow_enabled(
        n_sh, t_pad, static_scores=inputs.sig_scores,
        dyn_weights=(inputs.dyn_weights if inputs.dyn_enabled
                     else None))
    statics = dict(
        job_keys=base["job_keys"], queue_keys=base["queue_keys"],
        prop_overused=base["prop_overused"],
        dyn_enabled=base["dyn_enabled"],
        pipe_enabled=base["pipe_enabled"],
        max_rounds=base["max_rounds"], pool_size=pool,
        gang_enabled=getattr(inputs, "gang_enabled", True),
        narrow=narrow,
        narrow_gate=(not narrow and narrow_enabled(n_sh, t_pad)))
    with _span("hier_allocate_sharded", cat="kernel") as sp:
        final, packed = _hier_sharded_entry(placed_state, placed_arrays,
                                            **statics)
        count_blocking_readback()
        with _span("readback", cat="readback"):
            out = np.asarray(packed)
        task_state = out[:t_pad]
        task_node = out[t_pad:2 * t_pad]
        task_seq = out[2 * t_pad:3 * t_pad]
        rounds = out[3 * t_pad]
        from ..obs import telemetry as _obs_telemetry
        _obs_telemetry.record(out[3 * t_pad + 1:], span=sp)
        count_blocking_readback(4)
        with _span("readback_carry", cat="readback", n=4):
            device.idle = jnp.asarray(np.asarray(final.idle)[:n_pad])
            device.releasing = jnp.asarray(
                np.asarray(final.releasing)[:n_pad])
            device.n_tasks = jnp.asarray(np.asarray(final.n_tasks)[:n_pad])
            device.nz_req = jnp.asarray(np.asarray(final.nz_req)[:n_pad])
    return task_state, task_node, task_seq, int(rounds)


# ---------------------------------------------------------------------
# compilesvc signature provider — the two-level entry registers for
# configs whose node axis crosses the hier threshold (cfg6/cfg7); the
# flat batched provider skips those same regimes, so the registered
# surface matches what auto mode actually dispatches and the warm-up
# never compiles a [T, N] flat graph the engine would refuse to run
# ---------------------------------------------------------------------

@_register_provider("kernels.hier")
def compile_signatures(materials):
    from ..actions.allocate import AUTO_HIER_MIN_NODES
    from ..compilesvc.registry import Signature, signature_key

    out = []
    for regime, inputs in (("cold", materials.cold_inputs),
                           ("steady", materials.steady_inputs)):
        if inputs is None or isinstance(inputs, str):
            continue
        if len(inputs.device.state.names) < AUTO_HIER_MIN_NODES:
            continue    # flat engines own this node axis
        # no task-count floor: auto mode keys on the persistent node
        # axis (ISSUE 15), so hier owns EVERY churn level here — the
        # steady sub-batched-threshold shapes are the audit fallback
        # surface behind the active-set engine
        if getattr(inputs, "affinity", None) is not None:
            continue    # affinity gates to the flat engines
        args, base = prepare_hier(inputs.device, inputs)
        pipes = ((False, True)
                 if ("reclaim" in materials.actions
                     or "preempt" in materials.actions)
                 else (bool(inputs.pipe_enabled),))
        for pipe in pipes:
            statics = dict(base, pipe_enabled=pipe)
            out.append(Signature(
                engine="hier", entry="_hier_packed",
                key=signature_key("_hier_packed", args, statics),
                lower=lambda a=args, s=statics: _hier_packed.lower(*a, **s),
                run=lambda a=args, s=statics: _hier_packed(*a, **s),
                note=(f"{regime} T={inputs.task_valid.shape[0]} "
                      f"N={inputs.device.n_padded} "
                      f"pool={statics['pool_size']} pipe={pipe}")))
    return out
