"""Snapshot tensorization — ClusterInfo becomes dense device arrays.

This is the layer with no reference counterpart: the per-entity structs of
pkg/scheduler/api (Resource rows, NodeInfo accounting, TaskInfo requests)
are projected onto fixed-shape float32/int32 arrays so the scheduling inner
loops run as XLA programs on TPU. Axis conventions:

- node axis: order of ``NodeState.names`` (padded to a pow2 bucket so jit
  traces are reused across cycles; padded rows are masked invalid)
- resource axis: [cpu_milli, mem_MiB, gpu_milli] (api.resource.RESOURCE_NAMES)

The epsilon-fit rule on device is elementwise ``req <= avail + VEC_EPS``
(strictly mirroring Resource.less_equal: ``r < R or |R - r| < eps`` equals
``r < R + eps`` for the operands we produce, since requests and availability
are finite floats).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..api import NodeInfo, TaskInfo
from ..util import env_on
from ..api.resource import RESOURCE_DIM, VEC_EPS, VEC_SCALE

__all__ = ["NodeState", "TaskBatch", "pad_to_bucket", "sticky_bucket",
           "VEC_EPS", "batch_clone_tasks", "batch_set_attr",
           "NONZERO_MILLI_CPU", "NONZERO_MEM_MIB", "nz_request_vec"]

#: upstream DefaultNonZeroRequest (priorityutil.GetNonzeroRequests) in
#: device units: 100m CPU, 200MB memory (= 200 MiB exactly)
NONZERO_MILLI_CPU = 100.0
NONZERO_MEM_MIB = 200.0


def nz_request_vec(resreq_vec: np.ndarray) -> np.ndarray:
    """[cpu_milli, mem_MiB] with upstream NonZero defaults applied."""
    cpu = resreq_vec[0] if resreq_vec[0] != 0 else NONZERO_MILLI_CPU
    mem = resreq_vec[1] if resreq_vec[1] != 0 else NONZERO_MEM_MIB
    return np.array([cpu, mem], np.float32)


def pack_node_raw(nodes_seq) -> np.ndarray:
    """[k, 4, RESOURCE_DIM] float64 HOST-unit idle/releasing/backfilled/
    allocatable rows for a list of NodeInfo — THE node extraction, shared
    by the fresh build (NodeState.from_nodes) and the incremental repack
    (DeviceSession.update_rows) so the two can never drift. Uses the
    native packer when built."""
    k = len(nodes_seq)
    pack = load_kb_pack()
    if pack is not None:
        raw = np.empty((k, len(_NODE_PATHS)), np.float64)
        pack.extract_f64(nodes_seq, _NODE_PATHS, raw)
        return raw.reshape(k, 4, RESOURCE_DIM)
    return np.array(
        [(ni.idle.milli_cpu, ni.idle.memory, ni.idle.milli_gpu,
          ni.releasing.milli_cpu, ni.releasing.memory,
          ni.releasing.milli_gpu,
          ni.backfilled.milli_cpu, ni.backfilled.memory,
          ni.backfilled.milli_gpu,
          ni.allocatable.milli_cpu, ni.allocatable.memory,
          ni.allocatable.milli_gpu) for ni in nodes_seq],
        np.float64).reshape(k, 4, RESOURCE_DIM)


def accumulate_nz(tasks, rows, n_rows: int) -> np.ndarray:
    """[n_rows, 2] float32 per-row sums of nonzero (cpu_milli, mem_MiB)
    requests — upstream GetNonzeroRequests semantics, accumulated in
    float64 and cast ONCE. Shared by NodeState.from_nodes,
    DeviceSession.update_rows, and VictimState so refreshed rows stay
    bit-identical to fresh builds."""
    out = np.zeros((n_rows, 2), np.float64)
    if tasks:
        pack = load_kb_pack()
        res = np.empty((len(tasks), 2), np.float64)
        if pack is not None:
            pack.extract_f64(tasks, _NZ_PATHS, res)
        else:
            for i, t in enumerate(tasks):
                res[i] = (t.resreq.milli_cpu, t.resreq.memory)
        nz = np.empty((len(tasks), 2), np.float64)
        nz[:, 0] = np.where(res[:, 0] != 0, res[:, 0], NONZERO_MILLI_CPU)
        mem_mib = res[:, 1] / (1024.0 * 1024.0)
        nz[:, 1] = np.where(mem_mib != 0, mem_mib, NONZERO_MEM_MIB)
        np.add.at(out, np.asarray(rows, np.int64), nz)
    return out.astype(np.float32)


#: above this, buckets re-grain from pow2 to multiples of LARGE_GRAIN:
#: pow2 padding wastes up to 2x, and at cfg6/cfg7 axis sizes (50-100k)
#: that waste is [T, N]-squared — 100k nodes would pad to 131072 (+31%)
#: where the 4096 grain pads to 102400 (+2.4%). Every config at or
#: below cfg5 scale (axes <= 16384) keeps its historical pow2 bucket,
#: so existing compile signatures don't move.
LARGE_BUCKET = 16384
LARGE_GRAIN = 4096


def pad_to_bucket(n: int, minimum: int = 8) -> int:
    """Next bucket >= max(n, minimum) — keeps jit cache hits across
    cycles while cluster size drifts. Power-of-two up to LARGE_BUCKET;
    past it, the next multiple of LARGE_GRAIN (the cfg6/cfg7 re-bucket:
    fewer, denser buckets so one cluster-size step costs one bounded
    compile, and [T, N] padding waste stays a few percent, not 2x)."""
    if n > LARGE_BUCKET:
        return -(-n // LARGE_GRAIN) * LARGE_GRAIN
    b = minimum
    while b < n:
        b *= 2
    return b


#: sticky_bucket state: key -> [held bucket, consecutive one-below calls]
_STICKY: Dict[str, list] = {}


def sticky_bucket(key: str, n: int, minimum: int = 8,
                  decay: int = 12, store: Optional[dict] = None) -> int:
    """pad_to_bucket with one-bucket hysteresis per call-site ``key``.

    A steady churn regime whose entity count oscillates across a pow2
    boundary (e.g. 250..260 pending around 256) would otherwise flip the
    jit shape every few cycles — each flip a fresh XLA compile, which is
    exactly the 1 s p95 tail the steady benches showed. Holding the
    larger bucket while the count sits ONE bucket below pins the shape;
    after ``decay`` consecutive one-below cycles the hold steps down. A
    drop of two or more buckets (a genuinely different workload, e.g. a
    small scenario after a stress test in the same process) snaps down
    immediately so big shapes never leak onto small runs.

    Once the compile manager has declared the process warm
    (compilesvc.mark_warm — AOT warm-up done, or a steady bench's
    measured window started), the one-below decay FREEZES: stepping
    down to the tighter bucket would trace a shape the warm set never
    compiled — a counted recompile — to save at most 2x padding waste,
    exactly the trade the recompiles==0 invariant forbids (the cfg2
    steady bench caught the decay firing its compile inside the
    measured window). The two-bucket snap-down still applies: that is a
    genuinely different workload, and the resulting compile SHOULD
    surface as recompiles_total{reason="unregistered"}.

    ``store``: optional per-stream state dict (e.g. one per
    SchedulerCache) so interleaved streams of different sizes in one
    process don't fight over a shared hold; defaults to the
    process-global map."""
    st = _STICKY if store is None else store
    b = pad_to_bucket(n, minimum)
    ent = st.get(key)
    if ent is None or b >= ent[0]:
        st[key] = [b, 0]
        return b
    # "one bucket below": the pow2 half-step, or one LARGE_GRAIN step
    # when the HELD bucket sits on the re-grained axis (covers the
    # 16384 <-> 20480 boundary, where b itself is still pow2-sized)
    one_below = (b * 2 == ent[0]
                 or (ent[0] > LARGE_BUCKET and ent[0] - b == LARGE_GRAIN))
    if one_below:
        ent[1] += 1
        if ent[1] >= decay and not _shape_hold():
            ent[0], ent[1] = b, 0
            return b
        return ent[0]
    st[key] = [b, 0]
    return b


def _shape_hold() -> bool:
    """True when the compile manager forbids voluntary shape changes
    (post-warm-up). Lazy import: compilesvc.monitor imports nothing
    heavy, but tensorize must stay importable standalone."""
    from ..compilesvc.monitor import is_warm

    return is_warm()


# ---------------------------------------------------------------------
# optional native attribute packer (native/kb_pack.c)
# ---------------------------------------------------------------------

_kb_pack = None
_kb_pack_failed = False
_kb_pack_lock = None


def load_kb_pack():
    """The C attribute packer, or None (pure-Python fallback). Built on
    first use via native/Makefile; KUBEBATCH_NATIVE=0 disables. Lives
    here (not kubebatch_tpu.native) because native.py imports this
    module."""
    global _kb_pack, _kb_pack_failed, _kb_pack_lock
    if _kb_pack is not None or _kb_pack_failed:
        return _kb_pack
    import importlib.util
    import os
    import subprocess
    import sys
    import sysconfig
    import threading

    if not env_on("KUBEBATCH_NATIVE"):
        _kb_pack_failed = True
        return None
    if _kb_pack_lock is None:
        _kb_pack_lock = threading.Lock()
    with _kb_pack_lock:
        if _kb_pack is not None or _kb_pack_failed:
            return _kb_pack
        return _load_kb_pack_locked(importlib, os, subprocess, sys,
                                    sysconfig)


def _load_kb_pack_locked(importlib, os, subprocess, sys, sysconfig):
    global _kb_pack, _kb_pack_failed
    try:
        native_dir = os.path.join(os.path.dirname(__file__), os.pardir,
                                  os.pardir, "native")
        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        path = os.path.join(native_dir, f"kb_pack{suffix}")
        if not os.path.exists(path):
            # build with THIS interpreter's headers/suffix, not whatever
            # python3 is on make's PATH
            subprocess.run(["make", "-C", native_dir, "-s",
                            f"PYTHON={sys.executable}"], check=True,
                           capture_output=True, timeout=120)
        spec = importlib.util.spec_from_file_location("kb_pack", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # smoke the contract once before trusting it for every snapshot
        probe = np.zeros((1, 1), np.float64)

        class _P:
            x = 1.5
        mod.extract_f64([_P()], (("x", None),), probe)
        if probe[0, 0] != 1.5:
            raise RuntimeError("kb_pack probe mismatch")
        _kb_pack = mod
    except Exception:
        _kb_pack_failed = True
    return _kb_pack


def _intern_paths(*paths):
    import sys

    return tuple(tuple(sys.intern(a) if isinstance(a, str) else a
                       for a in p) for p in paths)


_TASK_PATHS = _intern_paths(
    ("resreq", "milli_cpu"), ("resreq", "memory"), ("resreq", "milli_gpu"),
    ("init_resreq", "milli_cpu"), ("init_resreq", "memory"),
    ("init_resreq", "milli_gpu"))

_NODE_PATHS = _intern_paths(
    ("idle", "milli_cpu"), ("idle", "memory"), ("idle", "milli_gpu"),
    ("releasing", "milli_cpu"), ("releasing", "memory"),
    ("releasing", "milli_gpu"),
    ("backfilled", "milli_cpu"), ("backfilled", "memory"),
    ("backfilled", "milli_gpu"),
    ("allocatable", "milli_cpu"), ("allocatable", "memory"),
    ("allocatable", "milli_gpu"))

_NZ_PATHS = _intern_paths(("resreq", "milli_cpu"), ("resreq", "memory"))

_RESREQ_PATHS = _intern_paths(
    ("resreq", "milli_cpu"), ("resreq", "memory"), ("resreq", "milli_gpu"))

#: TaskInfo slots copied verbatim by batch_clone_tasks; status/node_name
#: arrive as overrides so the C pass writes each slot exactly once
_TASK_CLONE_COPY = tuple(s for s in TaskInfo.__slots__
                         if s not in ("status", "node_name"))
_CLONE_OVERRIDES = ("status", "node_name")


def batch_clone_tasks(tasks, statuses, node_names):
    """TaskInfo.clone over a whole decision batch, with status/node_name
    overridden in the same pass — the decision replay inserts one clone
    per placement into the node task maps (NodeInfo's COW contract), 10k+
    per cold stress cycle. ``statuses``: a list (per task) or one shared
    status; ``node_names``: a list of hostnames. Runs in C when the
    packer module carries clone_with (kb_pack.c); the Python fallback is
    semantically identical."""
    pack = load_kb_pack()
    if pack is not None and hasattr(pack, "clone_with"):
        return pack.clone_with(tasks, _TASK_CLONE_COPY, _CLONE_OVERRIDES,
                               (statuses, node_names))
    per_task = isinstance(statuses, list)
    out = []
    for i, t in enumerate(tasks):
        c = t.clone()
        c.status = statuses[i] if per_task else statuses
        c.node_name = node_names[i]
        out.append(c)
    return out


def extract_resreq(tasks) -> np.ndarray:
    """[n, 3] float64 host-unit resreq rows for a task list — one native
    pass when the packer is built (cache.bind_many batches its per-job /
    per-node arithmetic from these)."""
    n = len(tasks)
    out = np.empty((n, RESOURCE_DIM), np.float64)
    if n:
        pack = load_kb_pack()
        if pack is not None:
            pack.extract_f64(tasks, _RESREQ_PATHS, out)
        else:
            for i, t in enumerate(tasks):
                rr = t.resreq
                out[i] = (rr.milli_cpu, rr.memory, rr.milli_gpu)
    return out


def batch_set_attr(objs, name: str, values) -> None:
    """objs[i].name = values[i] (list) or = values (shared), in C when
    available — the replay's status/node_name flips over 10k+ tasks."""
    pack = load_kb_pack()
    if pack is not None and hasattr(pack, "set_attr"):
        pack.set_attr(objs, name, values)
        return
    if isinstance(values, list):
        for o, v in zip(objs, values):
            setattr(o, name, v)
    else:
        for o in objs:
            setattr(o, name, values)


@dataclass
class NodeState:
    """Device-side mirror of the mutable node accounting.

    Carried through assignment scans and updated functionally; the host
    NodeInfo structs remain the source of truth between actions
    (see kernels/solver.py sync discipline).
    """
    names: List[str]
    #: [N,R] float32 arrays (MiB-scaled memory)
    idle: np.ndarray
    releasing: np.ndarray
    backfilled: np.ndarray
    allocatable: np.ndarray
    #: [N,2] float32 — nonzero-request (cpu_milli, mem_MiB) sums over the
    #: node's tasks, upstream GetNonzeroRequests semantics (feeds the
    #: in-kernel least-requested / balanced-resource scores)
    nz_requested: np.ndarray
    #: [N] int32 / bool
    max_task_num: np.ndarray
    n_tasks: np.ndarray
    schedulable: np.ndarray   # NOT unschedulable and real (non-padded) node
    valid: np.ndarray         # non-padded row
    index: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_nodes(cls, nodes: Dict[str, NodeInfo],
                   min_bucket: int = 8) -> "NodeState":
        ordered = sorted(nodes.values(), key=lambda ni: ni.name)
        n = len(ordered)
        n_pad = pad_to_bucket(n, min_bucket)
        shape = (n_pad, RESOURCE_DIM)
        idle = np.zeros(shape, np.float32)
        releasing = np.zeros(shape, np.float32)
        backfilled = np.zeros(shape, np.float32)
        allocatable = np.zeros(shape, np.float32)
        nz_requested = np.zeros((n_pad, 2), np.float32)
        max_task_num = np.zeros(n_pad, np.int32)
        n_tasks = np.zeros(n_pad, np.int32)
        schedulable = np.zeros(n_pad, bool)
        valid = np.zeros(n_pad, bool)
        index: Dict[str, int] = {}
        if n:
            # one packed pass instead of per-Resource to_vec array
            # allocations — this runs over every node each snapshot; the
            # shared pack_node_raw/accumulate_nz helpers keep this path
            # bit-identical to DeviceSession.update_rows' repack
            raw = pack_node_raw(ordered)
            raw *= VEC_SCALE
            raw32 = raw.astype(np.float32)
            idle[:n] = raw32[:, 0]
            releasing[:n] = raw32[:, 1]
            backfilled[:n] = raw32[:, 2]
            allocatable[:n] = raw32[:, 3]
            max_task_num[:n] = [ni.allocatable.max_task_num for ni in ordered]
            n_tasks[:n] = [len(ni.tasks) for ni in ordered]
            schedulable[:n] = [not (bool(ni.node.unschedulable) if ni.node
                                    else True) for ni in ordered]
            valid[:n] = True
            all_tasks = []
            t_row = []
            for i, ni in enumerate(ordered):
                all_tasks.extend(ni.tasks.values())
                t_row.extend([i] * len(ni.tasks))
            nz_requested[:n] = accumulate_nz(all_tasks, t_row, n)
        for i, ni in enumerate(ordered):
            index[ni.name] = i
        return cls(names=[ni.name for ni in ordered], idle=idle,
                   releasing=releasing, backfilled=backfilled,
                   allocatable=allocatable, nz_requested=nz_requested,
                   max_task_num=max_task_num, n_tasks=n_tasks,
                   schedulable=schedulable, valid=valid, index=index)

    @property
    def n_padded(self) -> int:
        return self.idle.shape[0]


@dataclass
class TaskBatch:
    """A job's pending tasks, in task-order, padded to a pow2 bucket."""
    tasks: List[TaskInfo]
    resreq: np.ndarray        # [T,R] steady-state request (node accounting)
    init_resreq: np.ndarray   # [T,R] launch request (fit checks)
    nz_req: np.ndarray        # [T,2] nonzero (cpu,mem) for dynamic scoring
    valid: np.ndarray         # [T] non-padded row
    #: [T,R] float64 HOST units (memory in bytes) — the exact values the
    #: Resource arithmetic uses; the bulk decision replay sums these per
    #: node/job instead of calling per-task Resource methods
    resreq_raw: np.ndarray = None

    @classmethod
    def from_tasks(cls, tasks: Sequence[TaskInfo],
                   min_bucket: int = 8) -> "TaskBatch":
        t = len(tasks)
        raw = None
        if t:
            # one packed pass (see NodeState.from_nodes)
            pack = load_kb_pack()
            if pack is not None:
                raw = np.empty((t, len(_TASK_PATHS)), np.float64)
                pack.extract_f64(tasks, _TASK_PATHS, raw)
            else:
                raw = np.array(
                    [(tk.resreq.milli_cpu, tk.resreq.memory,
                      tk.resreq.milli_gpu,
                      tk.init_resreq.milli_cpu, tk.init_resreq.memory,
                      tk.init_resreq.milli_gpu) for tk in tasks],
                    np.float64)
        return cls._from_extracted(tasks, raw, min_bucket)

    @classmethod
    def from_raw(cls, tasks: Sequence[TaskInfo], raw6: np.ndarray,
                 min_bucket: int = 8) -> "TaskBatch":
        """Build from a pre-extracted [T, 6] float64 (resreq, init_resreq)
        host-unit matrix in task order — the bulk cycle gather extracts
        once for its filter/sort and hands the columns straight here,
        skipping a second native pass over the backlog. ``raw6`` is
        consumed (scaled in place); pass a private copy."""
        assert raw6.shape == (len(tasks), 2 * RESOURCE_DIM)
        return cls._from_extracted(tasks, raw6, min_bucket)

    @classmethod
    def _from_extracted(cls, tasks, raw, min_bucket: int) -> "TaskBatch":
        t = len(tasks)
        t_pad = pad_to_bucket(t, min_bucket)
        resreq = np.zeros((t_pad, RESOURCE_DIM), np.float32)
        init_resreq = np.zeros((t_pad, RESOURCE_DIM), np.float32)
        nz_req = np.zeros((t_pad, 2), np.float32)
        valid = np.zeros(t_pad, bool)
        resreq_raw = np.zeros((t_pad, RESOURCE_DIM), np.float64)
        if t:
            raw = np.ascontiguousarray(raw).reshape(t, 2, RESOURCE_DIM)
            resreq_raw[:t] = raw[:, 0]
            raw *= VEC_SCALE
            raw32 = raw.astype(np.float32)
            resreq[:t] = raw32[:, 0]
            init_resreq[:t] = raw32[:, 1]
            nz_req[:t, 0] = np.where(resreq[:t, 0] != 0, resreq[:t, 0],
                                     NONZERO_MILLI_CPU)
            nz_req[:t, 1] = np.where(resreq[:t, 1] != 0, resreq[:t, 1],
                                     NONZERO_MEM_MIB)
            valid[:t] = True
        return cls(tasks=list(tasks), resreq=resreq,
                   init_resreq=init_resreq, nz_req=nz_req, valid=valid,
                   resreq_raw=resreq_raw)

    @property
    def t_padded(self) -> int:
        return self.resreq.shape[0]
