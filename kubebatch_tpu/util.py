"""Priority queue + node selection helpers
(ref: pkg/scheduler/util/priority_queue.go, sort.go)."""
from __future__ import annotations

import heapq
import itertools
import os
from typing import Callable, Dict, List

from .api import NodeInfo

LessFn = Callable[[object, object], bool]


def env_on(name: str, default: str = "1") -> bool:
    """Shared parser for the package's on-by-default feature flags:
    anything except "0"/"false" counts as enabled."""
    return os.environ.get(name, default) not in ("0", "false")


class _Entry:
    __slots__ = ("item", "less", "seq")

    def __init__(self, item, less: LessFn, seq: int):
        self.item = item
        self.less = less
        self.seq = seq

    def __lt__(self, other: "_Entry") -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq  # stable for equal elements


class PriorityQueue:
    """Heap ordered by a LessFn (ref: priority_queue.go:224-287)."""

    def __init__(self, less: LessFn):
        self._less = less
        self._heap: List[_Entry] = []
        self._seq = itertools.count()

    def push(self, item) -> None:
        heapq.heappush(self._heap, _Entry(item, self._less, next(self._seq)))

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).item

    def peek(self):
        if not self._heap:
            return None
        return self._heap[0].item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)


def select_best_node(node_scores: Dict[float, List[NodeInfo]]) -> List[NodeInfo]:
    """Flatten score buckets in descending score order
    (ref: util/sort.go:312-324)."""
    out: List[NodeInfo] = []
    for score in sorted(node_scores, reverse=True):
        out.extend(node_scores[score])
    return out
