"""Streaming event source + PV/PVC volume world — the sim's informer layer.

The reference ingests cluster state through 9 client-go informers (watch
streams for pods, nodes, PodGroups, Queues, PDBs, PriorityClasses, PVs,
PVCs, StorageClasses — ref: pkg/scheduler/cache/cache.go:217-295). This
module provides the simulated equivalent with the same shape:

- ``StreamingEventSource``: LIST+WATCH semantics over the cache's handler
  surface. ``start(cache)`` replays the current world as adds (LIST),
  then a pump thread drains queued watch events into the same handlers
  the push surface exposes — the cache code path is identical whether
  events arrive by direct call (unit tests) or by stream (e2e). Producers
  (``emit_*``) are thread-safe and can run while scheduling cycles are
  open, like real informers do.
- ``PVVolumeBinder``: a PV/PVC-aware implementation of the VolumeBinder
  seam (ref: cache.go:164-184 wrapping the upstream volumebinder).
  ``allocate_volumes`` ASSUMES a matching PersistentVolume per claim of
  the pod (class + capacity + optional node pinning for local volumes)
  and fails when none fits; ``bind_volumes`` COMMITS the assumed
  bindings, enforcing the reference's bind timeout (30 s default,
  cache.go:228): an assumption older than the timeout has expired and
  raises — the bind error lands the task on the cache's err_tasks queue
  and the resync repair loop re-drives it, exactly the reference's
  failure path.
- failure injection: ``FlakyBinder``/``FlakyEvictor`` wrap real seams and
  fail the first N attempts per pod — the e2e suite uses them to prove
  injected API failures heal through the rate-limited resync loop while
  cycles keep running (ref: cache.go:377-382,423-429,494-513).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import TaskInfo
from ..faults import check as _fault_check
from ..objects import (Node, Pod, PodDisruptionBudget, PodGroup,
                       PriorityClass, Queue)

log = logging.getLogger("kubebatch.sim")

GiB = 1024 ** 3


# ---------------------------------------------------------------------
# volume world
# ---------------------------------------------------------------------

@dataclass
class StorageClass:
    name: str
    provisioner: str = "sim"


@dataclass
class PersistentVolume:
    name: str
    capacity_bytes: float = GiB
    storage_class: str = "standard"
    #: local volumes: only usable from this node ("" = any node)
    node_name: str = ""
    claim_ref: str = ""       # bound claim uid ("" = available)


@dataclass
class PersistentVolumeClaim:
    name: str
    namespace: str = "default"
    storage_class: str = "standard"
    request_bytes: float = GiB
    volume_name: str = ""     # bound PV ("" = unbound)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


class PVVolumeBinder:
    """VolumeBinder seam over the PV/PVC world (see module docstring)."""

    def __init__(self, bind_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.bind_timeout = bind_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self.volumes: Dict[str, PersistentVolume] = {}
        self.claims: Dict[str, PersistentVolumeClaim] = {}
        self.classes: Dict[str, StorageClass] = {}
        #: task uid -> (assumed (claim_key, pv_name) pairs, assume stamp)
        self._assumed: Dict[str, Tuple[List[Tuple[str, str]], float]] = {}

    # ---- informer handlers (PV / PVC / StorageClass events) -----------
    def add_volume(self, pv: PersistentVolume) -> None:
        with self._lock:
            self.volumes[pv.name] = pv

    def delete_volume(self, pv: PersistentVolume) -> None:
        with self._lock:
            self.volumes.pop(pv.name, None)

    def add_claim(self, pvc: PersistentVolumeClaim) -> None:
        with self._lock:
            self.claims[pvc.key] = pvc

    def delete_claim(self, pvc: PersistentVolumeClaim) -> None:
        with self._lock:
            self.claims.pop(pvc.key, None)

    def add_storage_class(self, sc: StorageClass) -> None:
        with self._lock:
            self.classes[sc.name] = sc

    # ---- the VolumeBinder seam ----------------------------------------
    def _claims_of(self, task: TaskInfo) -> List[PersistentVolumeClaim]:
        out = []
        for name in task.pod.pvc_names:
            pvc = self.claims.get(f"{task.namespace}/{name}")
            if pvc is None:
                raise RuntimeError(
                    f"claim {task.namespace}/{name} not found for pod "
                    f"{task.namespace}/{task.name}")
            out.append(pvc)
        return out

    def _prune_expired(self) -> None:
        """Assumptions older than the bind timeout no longer reserve their
        PVs — a gang that never reached readiness must not leak the
        cluster's volumes forever (the upstream assume cache expires the
        same way). Callers hold the lock."""
        now = self._clock()
        for uid in [u for u, (_, stamp) in self._assumed.items()
                    if now - stamp > self.bind_timeout]:
            del self._assumed[uid]

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        """AssumePodVolumes: reserve a fitting PV per unbound claim; all
        or nothing. No-op (volume_ready) for pods without claims. A task
        re-allocating replaces its own previous assumption."""
        with self._lock:
            self._prune_expired()
            picks: List[Tuple[str, str]] = []
            taken = set()
            for pvc in self._claims_of(task):
                if pvc.volume_name:      # already bound (static binding)
                    continue
                pv = self._find_pv(pvc, hostname, taken, task.uid)
                if pv is None:
                    raise RuntimeError(
                        f"no PersistentVolume fits claim {pvc.key} "
                        f"(class={pvc.storage_class}, "
                        f"req={pvc.request_bytes:.0f}B) on {hostname}")
                taken.add(pv.name)
                picks.append((pvc.key, pv.name))
            self._assumed.pop(task.uid, None)
            if picks:
                self._assumed[task.uid] = (picks, self._clock())
            task.volume_ready = True

    def _find_pv(self, pvc: PersistentVolumeClaim, hostname: str,
                 taken: set, own_uid: str) -> Optional[PersistentVolume]:
        assumed_pvs = {pv for uid, (picks, _) in self._assumed.items()
                       if uid != own_uid for _, pv in picks}
        best = None
        for pv in self.volumes.values():
            if pv.name in taken or pv.name in assumed_pvs or pv.claim_ref:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if pv.capacity_bytes < pvc.request_bytes:
                continue
            if pv.node_name and pv.node_name != hostname:
                continue
            # smallest fitting volume wins (upstream's size-based order)
            if best is None or pv.capacity_bytes < best.capacity_bytes:
                best = pv
        return best

    def bind_volumes(self, task: TaskInfo) -> None:
        """BindPodVolumes: commit assumptions. An expired assumption (older
        than the bind timeout) raises AND resets volume_ready, so the
        resync re-drive must re-allocate — it cannot silently bind a
        claim-carrying pod with no PV committed."""
        if not task.volume_ready:
            raise RuntimeError(
                f"volumes for {task.namespace}/{task.name} were never "
                f"allocated")
        with self._lock:
            entry = self._assumed.get(task.uid)
            if entry is None:
                # nothing to commit is only legitimate when every claim is
                # already bound (or the pod has none)
                unbound = [pvc.key for pvc in self._claims_of(task)
                           if not pvc.volume_name]
                if unbound:
                    task.volume_ready = False
                    raise RuntimeError(
                        f"no volume assumption for {task.namespace}/"
                        f"{task.name} (claims {unbound}); re-allocate")
                return
            pairs, stamp = entry
            if self._clock() - stamp > self.bind_timeout:
                del self._assumed[task.uid]
                task.volume_ready = False
                raise RuntimeError(
                    f"volume binding for {task.namespace}/{task.name} "
                    f"timed out after {self.bind_timeout:.0f}s")
            for claim_key, pv_name in pairs:
                pv = self.volumes.get(pv_name)
                pvc = self.claims.get(claim_key)
                if pv is None or pvc is None:
                    del self._assumed[task.uid]
                    task.volume_ready = False
                    raise RuntimeError(
                        f"assumed volume {pv_name} / claim {claim_key} "
                        f"vanished before bind")
                pv.claim_ref = claim_key
                pvc.volume_name = pv_name
            del self._assumed[task.uid]

    def unassume(self, task: TaskInfo) -> None:
        """Drop assumptions for a task whose placement was rolled back."""
        with self._lock:
            self._assumed.pop(task.uid, None)


# ---------------------------------------------------------------------
# failure-injecting seams
# ---------------------------------------------------------------------

class FlakyBinder:
    """Fails the first ``failures`` bind attempts per pod, then delegates.
    The sim stand-in for transient API-server write failures."""

    def __init__(self, inner, failures: int = 1):
        self.inner = inner
        self.failures = failures
        self.attempts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def bind(self, pod: Pod, hostname: str) -> None:
        with self._lock:
            n = self.attempts.get(pod.uid, 0)
            self.attempts[pod.uid] = n + 1
        if n < self.failures:
            raise RuntimeError(f"injected bind failure #{n + 1} for "
                               f"{pod.namespace}/{pod.name}")
        self.inner.bind(pod, hostname)


class FlakyEvictor:
    def __init__(self, inner, failures: int = 1):
        self.inner = inner
        self.failures = failures
        self.attempts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def evict(self, pod: Pod) -> None:
        with self._lock:
            n = self.attempts.get(pod.uid, 0)
            self.attempts[pod.uid] = n + 1
        if n < self.failures:
            raise RuntimeError(f"injected evict failure #{n + 1} for "
                               f"{pod.namespace}/{pod.name}")
        self.inner.evict(pod)


# ---------------------------------------------------------------------
# the streaming source
# ---------------------------------------------------------------------

@dataclass
class _Event:
    kind: str            # "pod" | "node" | "group" | "queue" | "pdb" |
    #                      "priority_class" | "pv" | "pvc" | "storage_class"
    verb: str            # "add" | "update" | "delete"
    obj: object
    old: object = None
    #: delivery attempts so far (the pump redelivers failed events)
    attempts: int = 0


class StreamingEventSource:
    """Informer-style LIST+WATCH adapter over the cache handler surface.

    The world (pods/nodes/groups/queues/...) lives here, keyed like the
    API server would key it; ``start(cache)`` LISTs it into the cache and
    then pumps watch events from a queue in a background thread. The
    ``emit_*`` producers mutate the world AND enqueue the event, so a
    restarted scheduler can re-LIST the same source and rebuild — the
    statelessness contract the reference gets from informer replay.
    """

    def __init__(self, volume_binder: Optional[PVVolumeBinder] = None):
        self._lock = threading.Lock()
        self._queue: List[_Event] = []
        self._wake = threading.Condition(self._lock)
        self._cache = None
        self._pump: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.volume_binder = volume_binder

        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.groups: Dict[str, PodGroup] = {}
        self.queues: Dict[str, Queue] = {}
        self.pdbs: Dict[str, PodDisruptionBudget] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}

    # ---- ground truth (the resync loop's GET) -------------------------
    def pod_lister(self, ns: str, name: str) -> Optional[Pod]:
        with self._lock:
            return self.pods.get(f"{ns}/{name}")

    # ---- lifecycle ----------------------------------------------------
    def start(self, cache) -> None:
        """LIST the world into the cache, then start the watch pump."""
        self._cache = cache
        cache.pod_lister = self.pod_lister
        with self._lock:
            for q in self.queues.values():
                cache.add_queue(q)
            for pc in self.priority_classes.values():
                cache.add_priority_class(pc)
            for n in self.nodes.values():
                cache.add_node(n)
            for g in self.groups.values():
                cache.add_pod_group(g)
            for pdb in self.pdbs.values():
                cache.add_pdb(pdb)
            for p in self.pods.values():
                cache.add_pod(p)
        self._stop.clear()
        self._pump = threading.Thread(target=self._pump_loop,
                                      name="kb-sim-informer", daemon=True)
        self._pump.start()

    def stop(self) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify_all()
        if self._pump is not None:
            self._pump.join(timeout=5.0)

    def sync(self, timeout: float = 5.0) -> bool:
        """Barrier: wait for the watch queue to drain (tests)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue:
                    return True
            time.sleep(0.002)
        return False

    #: delivery attempts before an event is dropped for good — transient
    #: handler failures (injected or real) redeliver and heal; an event
    #: the cache permanently rejects cannot wedge the stream forever
    MAX_DELIVERY_ATTEMPTS = 8

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            with self._wake:
                while not self._queue and not self._stop.is_set():
                    self._wake.wait(timeout=0.05)
                events, self._queue = self._queue, []
            requeue: List[_Event] = []
            for i, ev in enumerate(events):
                try:
                    self._deliver(ev)
                except Exception:   # a bad event must not kill the stream
                    ev.attempts += 1
                    if ev.attempts < self.MAX_DELIVERY_ATTEMPTS:
                        # a real informer gets redelivery from relist; the
                        # sim stream requeues the delta itself. Delivery
                        # STOPS at the failure: the failed event and
                        # everything after it go back in order, because
                        # delivering later events first would reorder
                        # same-key deltas (a retried update landing after
                        # its object's delete would resurrect it).
                        log.warning(
                            "event delivery failed (%s %s, attempt %d); "
                            "requeueing it and %d later events", ev.kind,
                            ev.verb, ev.attempts, len(events) - i - 1,
                            exc_info=True)
                        requeue = events[i:]
                    else:
                        log.exception(
                            "event %s %s dropped after %d delivery "
                            "attempts", ev.kind, ev.verb, ev.attempts)
                        requeue = events[i + 1:]
                    break
            if requeue:
                with self._wake:
                    # front of the queue, ahead of anything enqueued
                    # meanwhile: global order is preserved exactly
                    self._queue[:0] = requeue
                    self._wake.notify_all()
                # let the failure clear instead of spinning hot on an
                # event that fails deterministically
                self._stop.wait(0.002)

    def _deliver(self, ev: _Event) -> None:
        # injection seam: a delivery fault rides the same redelivery
        # path as a real handler failure
        _fault_check("source.deliver")
        cache = self._cache
        vb = self.volume_binder
        route = {
            ("pod", "add"): lambda: cache.add_pod(ev.obj),
            ("pod", "update"): lambda: cache.update_pod(ev.old, ev.obj),
            ("pod", "delete"): lambda: cache.delete_pod(ev.obj),
            ("node", "add"): lambda: cache.add_node(ev.obj),
            ("node", "update"): lambda: cache.update_node(ev.old, ev.obj),
            ("node", "delete"): lambda: cache.delete_node(ev.obj),
            ("group", "add"): lambda: cache.add_pod_group(ev.obj),
            ("group", "update"): lambda: cache.update_pod_group(ev.old,
                                                                ev.obj),
            ("group", "delete"): lambda: cache.delete_pod_group(ev.obj),
            ("queue", "add"): lambda: cache.add_queue(ev.obj),
            ("queue", "update"): lambda: cache.update_queue(ev.old, ev.obj),
            ("queue", "delete"): lambda: cache.delete_queue(ev.obj),
            ("pdb", "add"): lambda: cache.add_pdb(ev.obj),
            ("pdb", "delete"): lambda: cache.delete_pdb(ev.obj),
            ("priority_class", "add"):
                lambda: cache.add_priority_class(ev.obj),
            ("priority_class", "delete"):
                lambda: cache.delete_priority_class(ev.obj),
        }
        if vb is not None:
            route.update({
                ("pv", "add"): lambda: vb.add_volume(ev.obj),
                ("pv", "delete"): lambda: vb.delete_volume(ev.obj),
                ("pvc", "add"): lambda: vb.add_claim(ev.obj),
                ("pvc", "delete"): lambda: vb.delete_claim(ev.obj),
                ("storage_class", "add"):
                    lambda: vb.add_storage_class(ev.obj),
            })
        fn = route.get((ev.kind, ev.verb))
        if fn is not None:
            fn()

    # ---- producers ----------------------------------------------------
    def _emit(self, kind: str, verb: str, obj, old=None) -> None:
        with self._wake:
            self._queue.append(_Event(kind, verb, obj, old))
            self._wake.notify_all()

    def emit_pod(self, pod: Pod) -> None:
        with self._lock:
            self.pods[f"{pod.namespace}/{pod.name}"] = pod
        self._emit("pod", "add", pod)

    def emit_pod_update(self, old: Pod, new: Pod) -> None:
        with self._lock:
            self.pods[f"{new.namespace}/{new.name}"] = new
        self._emit("pod", "update", new, old)

    def emit_pod_delete(self, pod: Pod) -> None:
        with self._lock:
            self.pods.pop(f"{pod.namespace}/{pod.name}", None)
        self._emit("pod", "delete", pod)

    def emit_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
        self._emit("node", "add", node)

    def emit_node_update(self, old: Node, new: Node) -> None:
        with self._lock:
            self.nodes[new.name] = new
        self._emit("node", "update", new, old)

    def emit_node_delete(self, node: Node) -> None:
        with self._lock:
            self.nodes.pop(node.name, None)
        self._emit("node", "delete", node)

    def emit_group(self, pg: PodGroup) -> None:
        with self._lock:
            self.groups[f"{pg.namespace}/{pg.name}"] = pg
        self._emit("group", "add", pg)

    def emit_group_update(self, old: PodGroup, new: PodGroup) -> None:
        with self._lock:
            self.groups[f"{new.namespace}/{new.name}"] = new
        self._emit("group", "update", new, old)

    def emit_group_delete(self, pg: PodGroup) -> None:
        with self._lock:
            self.groups.pop(f"{pg.namespace}/{pg.name}", None)
        self._emit("group", "delete", pg)

    def emit_queue(self, q: Queue) -> None:
        with self._lock:
            self.queues[q.name] = q
        self._emit("queue", "add", q)

    def emit_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self.priority_classes[pc.name] = pc
        self._emit("priority_class", "add", pc)

    def emit_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._lock:
            self.pdbs[f"{pdb.namespace}/{pdb.name}"] = pdb
        self._emit("pdb", "add", pdb)

    def emit_volume(self, pv: PersistentVolume) -> None:
        self._emit("pv", "add", pv)

    def emit_claim(self, pvc: PersistentVolumeClaim) -> None:
        self._emit("pvc", "add", pvc)

    def emit_storage_class(self, sc: StorageClass) -> None:
        self._emit("storage_class", "add", sc)
