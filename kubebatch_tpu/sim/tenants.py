"""Multi-tenant simulation: N simulated clusters through ONE sidecar
pool, plus the saturation driver behind ``bench.py --tenants N``.

Two drivers:

- :func:`run_multi_tenant` — the ISSUE 8 done-bar: every tenant is an
  independent simulated cluster (the "t" spec, seeded by tenant index)
  driving real scheduling cycles with ``AllocateAction(mode="rpc")``
  against one shared sidecar, one thread per tenant (so the service's
  combining dispatcher sees real concurrency and coalesces
  opportunistically). Each tenant's end state is compared bit-identical
  against a DEDICATED in-process run of the same seeded cluster — the
  shared sidecar must be observationally indistinguishable from a
  private solver.

- :func:`run_saturation` — the capacity evidence: per-tenant clients
  fire pre-built solve requests closed-loop to measure solves/sec at
  capacity, then an open-loop pass offers 2x that rate and records the
  p99 latency of completed solves plus the shed census (rejected /
  stale-served) — the admission-control story measured, not asserted.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

import numpy as np

from .cluster import BASELINE_SPECS, build_cluster

__all__ = ["run_multi_tenant", "run_saturation", "drive_tenant_cycles",
           "TENANT_CONFIG"]

#: the per-tenant cluster spec key (sim/cluster.py BASELINE_SPECS)
TENANT_CONFIG = "t"

#: canonical churn per steady tick for the tenant spec (whole cluster
#: recycles — matches compilesvc/profile.py's clamped STEADY_CHURN)
_TENANT_CHURN = 32


class _Binder:
    def __init__(self):
        self.binds: Dict[str, str] = {}
        self.fresh: List = []

    def bind(self, pod, hostname):
        self.binds[pod.uid] = hostname
        pod.node_name = hostname
        self.fresh.append(pod)

    def evict(self, pod):
        pod.deletion_timestamp = 1.0


def _tenant_cluster(idx: int, config=TENANT_CONFIG):
    from ..cache import SchedulerCache

    spec = replace(BASELINE_SPECS[config], seed=idx)
    sim = build_cluster(spec)
    binder = _Binder()
    cache = SchedulerCache(binder=binder, evictor=binder,
                           async_writeback=False)
    sim.populate(cache)
    return sim, cache, binder


def drive_tenant_cycles(sim, cache, binder, cycles: int, mode: str,
                        tiers=None) -> Dict[str, tuple]:
    """Run ``cycles`` scheduling cycles (kubelet tick + canonical churn
    between cycles — the steady regime) and return the final task state
    map {task_key: (status, node)} — the bit-identity comparand."""
    from ..actions.allocate import AllocateAction
    from ..conf import shipped_tiers
    from ..framework import CloseSession, OpenSession
    from ..objects import PodPhase

    tiers = tiers or shipped_tiers()
    act = AllocateAction(mode=mode)
    state: Dict[str, tuple] = {}
    for cyc in range(cycles):
        for pod in binder.fresh:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                cache.update_pod(pod, pod)
        binder.fresh.clear()
        if cyc:
            sim.churn_tick(cache, _TENANT_CHURN)
        ssn = OpenSession(cache, tiers)
        act.execute(ssn)
        state = {t.key: (str(t.status), t.node_name)
                 for job in ssn.jobs.values() for t in job.tasks.values()}
        CloseSession(ssn)
    return state


@dataclass
class MultiTenantReport:
    tenants: int
    cycles: int
    bit_identical: bool
    mismatched: List[str] = field(default_factory=list)
    solves_by_tenant: Dict[str, int] = field(default_factory=dict)
    mega_dispatches: int = 0
    mega_lanes: int = 0
    rpc_errors: List[str] = field(default_factory=list)


def run_multi_tenant(n_tenants: int = 4, cycles: int = 4,
                     address: Optional[str] = None,
                     config=TENANT_CONFIG) -> MultiTenantReport:
    """N seeded tenant clusters, one thread each, through one sidecar at
    ``address`` (spawned in-process when None); per-tenant end states
    compared bit-identical to dedicated in-process runs."""
    from .. import metrics
    from ..rpc.client import set_tenant

    server = None
    if address is None:
        from ..rpc.server import make_server

        server, port = make_server("127.0.0.1:0")
        server.start()
        address = f"127.0.0.1:{port}"
    prev_addr = os.environ.get("KUBEBATCH_SOLVER_ADDR")
    os.environ["KUBEBATCH_SOLVER_ADDR"] = address

    mega0 = metrics.mega_dispatches_total()
    lanes0 = metrics.mega_lanes_total()
    try:
        # dedicated oracle runs (same seeds, in-process auto engine)
        dedicated = {}
        for i in range(n_tenants):
            sim, cache, binder = _tenant_cluster(i, config)
            dedicated[f"tenant-{i}"] = drive_tenant_cycles(
                sim, cache, binder, cycles, mode="auto")

        shared: Dict[str, Dict] = {}
        errors: List[str] = []

        def worker(i: int):
            tenant = f"tenant-{i}"
            set_tenant(tenant)
            try:
                sim, cache, binder = _tenant_cluster(i, config)
                shared[tenant] = drive_tenant_cycles(
                    sim, cache, binder, cycles, mode="rpc")
            except Exception as e:  # noqa: BLE001 — reported, not raised
                errors.append(f"{tenant}: {type(e).__name__}: {e}")
            finally:
                set_tenant(None)

        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"kb-tenant-{i}")
                   for i in range(n_tenants)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)

        mismatched = [t for t in dedicated
                      if shared.get(t) != dedicated[t]]
        per_tenant = metrics.tenant_counters()
        return MultiTenantReport(
            tenants=n_tenants, cycles=cycles,
            bit_identical=not mismatched and not errors,
            mismatched=mismatched,
            solves_by_tenant={t: per_tenant.get(t, {}).get("solves", 0)
                              for t in dedicated},
            mega_dispatches=metrics.mega_dispatches_total() - mega0,
            mega_lanes=metrics.mega_lanes_total() - lanes0,
            rpc_errors=errors)
    finally:
        if prev_addr is None:
            os.environ.pop("KUBEBATCH_SOLVER_ADDR", None)
        else:
            os.environ["KUBEBATCH_SOLVER_ADDR"] = prev_addr
        if server is not None:
            server.stop(grace=None)


# ---------------------------------------------------------------------
# saturation
# ---------------------------------------------------------------------

def _tenant_requests(n_tenants: int, config=TENANT_CONFIG) -> list:
    """One pre-built SnapshotRequest per tenant (seeded numerics, one
    shape class — the coalescible mix)."""
    from ..framework import CloseSession, OpenSession
    from ..conf import shipped_tiers
    from ..rpc.client import build_snapshot

    out = []
    tiers = shipped_tiers()
    for i in range(n_tenants):
        _, cache, _ = _tenant_cluster(i, config)
        ssn = OpenSession(cache, tiers)
        req, _ = build_snapshot(ssn)
        CloseSession(ssn)
        out.append(req)
    return out


@dataclass
class SaturationReport:
    tenants: int
    capacity_solves_per_sec: float
    capacity_p50_ms: float
    capacity_solves: int
    overload_offered_per_sec: float
    overload_completed_per_sec: float
    overload_p99_ms: float
    overload_rejected: int
    overload_stale_served: int
    #: NON-admission failures during the overload phase (timeouts, wire
    #: errors, handler crashes) — kept apart from rejected so a failing
    #: sidecar can never masquerade as healthy load shedding
    overload_errors: int = 0
    shed_modes_seen: Dict[str, int] = field(default_factory=dict)


def run_saturation(n_tenants: int = 4, address: str = "",
                   duration_s: float = 3.0,
                   config=TENANT_CONFIG) -> SaturationReport:
    """Closed-loop capacity, then 2x-offered overload, through the live
    sidecar at ``address``. Bench-facing: clients accept stale answers
    (they measure service behavior, they schedule nothing)."""
    from .. import metrics
    from ..rpc.client import AdmissionRejected, SolverClient

    reqs = _tenant_requests(n_tenants, config)
    clients = [SolverClient(address, tenant=f"tenant-{i}", lane="batch",
                            accept_stale=True)
               for i in range(n_tenants)]
    # warm the wire + dispatch caches off the clock
    for client, req in zip(clients, reqs):
        client.solve(req)

    # ---- phase 1: closed-loop capacity ------------------------------
    lat: List[float] = []
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration_s

    def cap_worker(i: int):
        client, req = clients[i], reqs[i]
        mine = []
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            client.solve(req)
            mine.append(time.perf_counter() - t0)
        with lock:
            lat.extend(mine)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=cap_worker, args=(i,))
               for i in range(n_tenants)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    capacity = len(lat) / wall if wall else 0.0

    # ---- phase 2: 2x offered overload -------------------------------
    shed0 = metrics.load_shed_total()
    offered_rate = 2.0 * max(1.0, capacity)
    n_workers = 2 * n_tenants
    per_worker_interval = n_workers / offered_rate
    over_lat: List[float] = []
    rejected = [0]
    errored = [0]
    stale = [0]
    stop2 = time.perf_counter() + duration_s

    def over_worker(k: int):
        client = clients[k % n_tenants]
        req = reqs[k % n_tenants]
        mine = []
        next_fire = time.perf_counter() + (k / n_workers) \
            * per_worker_interval
        while True:
            now = time.perf_counter()
            if now >= stop2:
                break
            if now < next_fire:
                time.sleep(min(next_fire - now, 0.005))
                continue
            next_fire += per_worker_interval   # offered schedule, not
            t0 = time.perf_counter()           # completion-paced
            try:
                resp = client.solve(req)
                mine.append(time.perf_counter() - t0)
                del resp
            except AdmissionRejected:
                with lock:
                    rejected[0] += 1
            except Exception:   # noqa: BLE001 — NOT shedding: a wedged
                with lock:      # sidecar must not read as admission
                    errored[0] += 1
        with lock:
            over_lat.extend(mine)

    threads = [threading.Thread(target=over_worker, args=(k,))
               for k in range(n_workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall2 = time.perf_counter() - t0
    shed_delta = {k: v - shed0.get(k, 0)
                  for k, v in metrics.load_shed_total().items()
                  if v - shed0.get(k, 0)}
    stale[0] = shed_delta.get("serve-stale", 0)

    for client in clients:
        client.close()
    return SaturationReport(
        tenants=n_tenants,
        capacity_solves_per_sec=round(capacity, 1),
        capacity_p50_ms=round(float(np.percentile(lat, 50)) * 1e3, 3)
        if lat else 0.0,
        capacity_solves=len(lat),
        overload_offered_per_sec=round(offered_rate, 1),
        overload_completed_per_sec=round(len(over_lat) / wall2, 1)
        if wall2 else 0.0,
        overload_p99_ms=round(float(np.percentile(over_lat, 99)) * 1e3, 3)
        if over_lat else 0.0,
        overload_rejected=rejected[0],
        overload_stale_served=stale[0],
        overload_errors=errored[0],
        shed_modes_seen=shed_delta)
