"""Multi-tenant simulation: N simulated clusters through ONE sidecar
pool, plus the saturation driver behind ``bench.py --tenants N``.

Two drivers:

- :func:`run_multi_tenant` — the ISSUE 8 done-bar: every tenant is an
  independent simulated cluster (the "t" spec, seeded by tenant index)
  driving real scheduling cycles with ``AllocateAction(mode="rpc")``
  against one shared sidecar, one thread per tenant (so the service's
  combining dispatcher sees real concurrency and coalesces
  opportunistically). Each tenant's end state is compared bit-identical
  against a DEDICATED in-process run of the same seeded cluster — the
  shared sidecar must be observationally indistinguishable from a
  private solver.

- :func:`run_saturation` — the capacity evidence: per-tenant clients
  fire pre-built solve requests closed-loop to measure solves/sec at
  capacity, then an open-loop pass offers 2x that rate and records the
  p99 latency of completed solves plus the shed census (rejected /
  stale-served) — the admission-control story measured, not asserted.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

import numpy as np

from .cluster import BASELINE_SPECS, build_cluster

__all__ = ["run_multi_tenant", "run_saturation", "drive_tenant_cycles",
           "run_fleet", "TENANT_CONFIG"]

#: the per-tenant cluster spec key (sim/cluster.py BASELINE_SPECS)
TENANT_CONFIG = "t"

#: canonical churn per steady tick for the tenant spec (whole cluster
#: recycles — matches compilesvc/profile.py's clamped STEADY_CHURN)
_TENANT_CHURN = 32


class _Binder:
    def __init__(self):
        self.binds: Dict[str, str] = {}
        self.fresh: List = []

    def bind(self, pod, hostname):
        self.binds[pod.uid] = hostname
        pod.node_name = hostname
        self.fresh.append(pod)

    def evict(self, pod):
        pod.deletion_timestamp = 1.0


def _tenant_cluster(idx: int, config=TENANT_CONFIG):
    from ..cache import SchedulerCache

    spec = replace(BASELINE_SPECS[config], seed=idx)
    sim = build_cluster(spec)
    binder = _Binder()
    cache = SchedulerCache(binder=binder, evictor=binder,
                           async_writeback=False)
    sim.populate(cache)
    return sim, cache, binder


def drive_tenant_cycles(sim, cache, binder, cycles: int, mode: str,
                        tiers=None) -> Dict[str, tuple]:
    """Run ``cycles`` scheduling cycles (kubelet tick + canonical churn
    between cycles — the steady regime) and return the final task state
    map {task_key: (status, node)} — the bit-identity comparand."""
    from ..actions.allocate import AllocateAction
    from ..conf import shipped_tiers
    from ..framework import CloseSession, OpenSession
    from ..objects import PodPhase

    tiers = tiers or shipped_tiers()
    act = AllocateAction(mode=mode)
    state: Dict[str, tuple] = {}
    for cyc in range(cycles):
        for pod in binder.fresh:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                cache.update_pod(pod, pod)
        binder.fresh.clear()
        if cyc:
            sim.churn_tick(cache, _TENANT_CHURN)
        ssn = OpenSession(cache, tiers)
        act.execute(ssn)
        state = {t.key: (str(t.status), t.node_name)
                 for job in ssn.jobs.values() for t in job.tasks.values()}
        CloseSession(ssn)
    return state


@dataclass
class MultiTenantReport:
    tenants: int
    cycles: int
    bit_identical: bool
    mismatched: List[str] = field(default_factory=list)
    solves_by_tenant: Dict[str, int] = field(default_factory=dict)
    mega_dispatches: int = 0
    mega_lanes: int = 0
    rpc_errors: List[str] = field(default_factory=list)


def run_multi_tenant(n_tenants: int = 4, cycles: int = 4,
                     address: Optional[str] = None,
                     config=TENANT_CONFIG) -> MultiTenantReport:
    """N seeded tenant clusters, one thread each, through one sidecar at
    ``address`` (spawned in-process when None); per-tenant end states
    compared bit-identical to dedicated in-process runs."""
    from .. import metrics
    from ..rpc.client import set_tenant

    server = None
    if address is None:
        from ..rpc.server import make_server

        server, port = make_server("127.0.0.1:0")
        server.start()
        address = f"127.0.0.1:{port}"
    prev_addr = os.environ.get("KUBEBATCH_SOLVER_ADDR")
    os.environ["KUBEBATCH_SOLVER_ADDR"] = address

    mega0 = metrics.mega_dispatches_total()
    lanes0 = metrics.mega_lanes_total()
    try:
        # dedicated oracle runs (same seeds, in-process auto engine)
        dedicated = {}
        for i in range(n_tenants):
            sim, cache, binder = _tenant_cluster(i, config)
            dedicated[f"tenant-{i}"] = drive_tenant_cycles(
                sim, cache, binder, cycles, mode="auto")

        shared: Dict[str, Dict] = {}
        errors: List[str] = []

        def worker(i: int):
            tenant = f"tenant-{i}"
            set_tenant(tenant)
            try:
                sim, cache, binder = _tenant_cluster(i, config)
                shared[tenant] = drive_tenant_cycles(
                    sim, cache, binder, cycles, mode="rpc")
            except Exception as e:  # noqa: BLE001 — reported, not raised
                errors.append(f"{tenant}: {type(e).__name__}: {e}")
            finally:
                set_tenant(None)

        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"kb-tenant-{i}")
                   for i in range(n_tenants)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)

        mismatched = [t for t in dedicated
                      if shared.get(t) != dedicated[t]]
        per_tenant = metrics.tenant_counters()
        return MultiTenantReport(
            tenants=n_tenants, cycles=cycles,
            bit_identical=not mismatched and not errors,
            mismatched=mismatched,
            solves_by_tenant={t: per_tenant.get(t, {}).get("solves", 0)
                              for t in dedicated},
            mega_dispatches=metrics.mega_dispatches_total() - mega0,
            mega_lanes=metrics.mega_lanes_total() - lanes0,
            rpc_errors=errors)
    finally:
        if prev_addr is None:
            os.environ.pop("KUBEBATCH_SOLVER_ADDR", None)
        else:
            os.environ["KUBEBATCH_SOLVER_ADDR"] = prev_addr
        if server is not None:
            server.stop(grace=None)


# ---------------------------------------------------------------------
# saturation
# ---------------------------------------------------------------------

def _tenant_requests(n_tenants: int, config=TENANT_CONFIG) -> list:
    """One pre-built SnapshotRequest per tenant (seeded numerics, one
    shape class — the coalescible mix)."""
    from ..framework import CloseSession, OpenSession
    from ..conf import shipped_tiers
    from ..rpc.client import build_snapshot

    out = []
    tiers = shipped_tiers()
    for i in range(n_tenants):
        _, cache, _ = _tenant_cluster(i, config)
        ssn = OpenSession(cache, tiers)
        req, _ = build_snapshot(ssn)
        CloseSession(ssn)
        out.append(req)
    return out


@dataclass
class SaturationReport:
    tenants: int
    capacity_solves_per_sec: float
    capacity_p50_ms: float
    capacity_solves: int
    overload_offered_per_sec: float
    overload_completed_per_sec: float
    overload_p99_ms: float
    overload_rejected: int
    overload_stale_served: int
    #: NON-admission failures during the overload phase (timeouts, wire
    #: errors, handler crashes) — kept apart from rejected so a failing
    #: sidecar can never masquerade as healthy load shedding
    overload_errors: int = 0
    shed_modes_seen: Dict[str, int] = field(default_factory=dict)


def run_saturation(n_tenants: int = 4, address: str = "",
                   duration_s: float = 3.0,
                   config=TENANT_CONFIG) -> SaturationReport:
    """Closed-loop capacity, then 2x-offered overload, through the live
    sidecar at ``address``. Bench-facing: clients accept stale answers
    (they measure service behavior, they schedule nothing)."""
    from .. import metrics
    from ..rpc.client import AdmissionRejected, SolverClient

    reqs = _tenant_requests(n_tenants, config)
    clients = [SolverClient(address, tenant=f"tenant-{i}", lane="batch",
                            accept_stale=True)
               for i in range(n_tenants)]
    # warm the wire + dispatch caches off the clock
    for client, req in zip(clients, reqs):
        client.solve(req)

    # ---- phase 1: closed-loop capacity ------------------------------
    lat: List[float] = []
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration_s

    def cap_worker(i: int):
        client, req = clients[i], reqs[i]
        mine = []
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            client.solve(req)
            mine.append(time.perf_counter() - t0)
        with lock:
            lat.extend(mine)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=cap_worker, args=(i,))
               for i in range(n_tenants)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    capacity = len(lat) / wall if wall else 0.0

    # ---- phase 2: 2x offered overload -------------------------------
    shed0 = metrics.load_shed_total()
    offered_rate = 2.0 * max(1.0, capacity)
    n_workers = 2 * n_tenants
    per_worker_interval = n_workers / offered_rate
    over_lat: List[float] = []
    rejected = [0]
    errored = [0]
    stale = [0]
    stop2 = time.perf_counter() + duration_s

    def over_worker(k: int):
        client = clients[k % n_tenants]
        req = reqs[k % n_tenants]
        mine = []
        next_fire = time.perf_counter() + (k / n_workers) \
            * per_worker_interval
        while True:
            now = time.perf_counter()
            if now >= stop2:
                break
            if now < next_fire:
                time.sleep(min(next_fire - now, 0.005))
                continue
            next_fire += per_worker_interval   # offered schedule, not
            t0 = time.perf_counter()           # completion-paced
            try:
                resp = client.solve(req)
                mine.append(time.perf_counter() - t0)
                del resp
            except AdmissionRejected:
                with lock:
                    rejected[0] += 1
            except Exception:   # noqa: BLE001 — NOT shedding: a wedged
                with lock:      # sidecar must not read as admission
                    errored[0] += 1
        with lock:
            over_lat.extend(mine)

    threads = [threading.Thread(target=over_worker, args=(k,))
               for k in range(n_workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall2 = time.perf_counter() - t0
    shed_delta = {k: v - shed0.get(k, 0)
                  for k, v in metrics.load_shed_total().items()
                  if v - shed0.get(k, 0)}
    stale[0] = shed_delta.get("serve-stale", 0)

    for client in clients:
        client.close()
    return SaturationReport(
        tenants=n_tenants,
        capacity_solves_per_sec=round(capacity, 1),
        capacity_p50_ms=round(float(np.percentile(lat, 50)) * 1e3, 3)
        if lat else 0.0,
        capacity_solves=len(lat),
        overload_offered_per_sec=round(offered_rate, 1),
        overload_completed_per_sec=round(len(over_lat) / wall2, 1)
        if wall2 else 0.0,
        overload_p99_ms=round(float(np.percentile(over_lat, 99)) * 1e3, 3)
        if over_lat else 0.0,
        overload_rejected=rejected[0],
        overload_stale_served=stale[0],
        overload_errors=errored[0],
        shed_modes_seen=shed_delta)


# ---------------------------------------------------------------------
# fleet: N sidecars, kill one mid-saturation (ISSUE 14)
# ---------------------------------------------------------------------

@dataclass
class FleetReport:
    """The ``bench.py --fleet N`` evidence. Hard invariants (the bench
    exits 1 on any): parity + standby-mega bit-identity, zero
    cross-tenant shed/errors, zero lost failovers, blip under bound."""

    sidecars: int
    tenants: int
    killed_addr: str = ""
    affected_tenants: List[str] = field(default_factory=list)
    pre_kill_p99_ms: float = 0.0
    post_kill_p99_ms: float = 0.0
    #: affected tenants' post-kill-window p99 minus their pre-kill p99 —
    #: the failover cost, which the bench pins under a stated bound
    failover_p99_blip_ms: float = 0.0
    cross_tenant_added_p99_ms: float = 0.0
    cross_tenant_shed: int = 0
    cross_tenant_errors: int = 0
    failovers: int = 0
    failover_lost: int = 0
    solves_total: int = 0
    parity_bit_identical: bool = False
    parity_mismatched: List[str] = field(default_factory=list)
    standby_mega_bit_identical: bool = False
    rpc_errors: List[str] = field(default_factory=list)


def _decision_key(resp) -> tuple:
    """The bit-identity comparand of one DecisionsResponse — decisions
    only (solve_ms is wall time, never compared)."""
    return tuple(sorted((d.task_uid, d.node_name, d.kind, d.order)
                        for d in resp.decisions))


def run_fleet(n_tenants: int = 4, sidecars: int = 3,
              duration_s: float = 3.0, kill_after_frac: float = 0.4,
              post_window_s: float = 1.0,
              config=TENANT_CONFIG) -> FleetReport:
    """N tenants across a fleet of in-process sidecars; one sidecar is
    killed abruptly (stop with no grace — kill -9 semantics) mid-
    saturation. Three phases:

    1. **parity**: every tenant's seeded cluster driven through the
       fleet (mode="rpc", router placement) must end bit-identical to
       a dedicated in-process oracle run;
    2. **saturation + kill**: closed-loop per-tenant solve load; at
       ``kill_after_frac * duration_s`` the victim (the address
       serving the most tenants) dies, the router marks it dead, and
       its tenants fail over through the replication handshake —
       per-request latencies bucket into pre/post-kill windows for the
       blip measurement;
    3. **post-kill parity + standby mega**: an affected tenant re-runs
       its cluster through its standby (bit-identity survives the
       move), and the standby's coalesced mega-solve lanes are checked
       bit-identical to dedicated single dispatches.
    """
    from .. import faults, metrics
    from ..rpc import client as rpc_client
    from ..rpc.client import SolverClientPool
    from ..rpc.server import make_server
    from ..tenantsvc import ReplicationLagError, ReplicationPlane, TenantRouter
    from ..tenantsvc import router as router_mod
    from ..tenantsvc.service import TenantSolveService
    from ..tenantsvc.sessions import TenantRegistry

    report = FleetReport(sidecars=sidecars, tenants=n_tenants)
    tenants = [f"tenant-{i}" for i in range(n_tenants)]

    servers: Dict[str, object] = {}
    svcs: Dict[str, TenantSolveService] = {}
    plane = None
    prev_addr = os.environ.get("KUBEBATCH_SOLVER_ADDR")
    try:
        for _ in range(sidecars):
            svc = TenantSolveService(TenantRegistry())
            server, port = make_server("127.0.0.1:0", tenant_service=svc)
            server.start()
            addr = f"127.0.0.1:{port}"
            servers[addr] = server
            svcs[addr] = svc
        addrs = list(servers)
        router = TenantRouter(addrs)
        router_mod.install(router)
        plane = ReplicationPlane(router)
        for addr, svc in svcs.items():
            plane.attach(addr, svc.registry)
        plane.start()

        lost_lock = threading.Lock()

        def failover_cb(tenant: str, dead_addr: str) -> None:
            # only the tenant's ring primary failing matters; a retry
            # against an already-drained address must not re-fail-over
            walk_primary = next(iter(router._walk(tenant)))
            if walk_primary != dead_addr:
                return
            if router.snapshot()["overrides"].get(tenant):
                return
            try:
                plane.failover(tenant, reason=f"partition:{dead_addr}")
            except ReplicationLagError:
                with lost_lock:
                    report.failover_lost += 1

        rpc_client.set_failover_callback(failover_cb)

        # ---- phase 1: fleet parity vs dedicated oracles -------------
        from ..rpc.client import set_tenant

        dedicated = {}
        for i, tenant in enumerate(tenants):
            sim, cache, binder = _tenant_cluster(i, config)
            dedicated[tenant] = drive_tenant_cycles(
                sim, cache, binder, 3, mode="auto")

        fleet_state: Dict[str, Dict] = {}

        def parity_worker(i: int, tenant: str):
            set_tenant(tenant)
            try:
                sim, cache, binder = _tenant_cluster(i, config)
                fleet_state[tenant] = drive_tenant_cycles(
                    sim, cache, binder, 3, mode="rpc")
            except Exception as e:  # noqa: BLE001 — reported below
                report.rpc_errors.append(
                    f"{tenant}: {type(e).__name__}: {e}")
            finally:
                set_tenant(None)

        threads = [threading.Thread(target=parity_worker,
                                    args=(i, t), name=f"kb-fleet-{i}")
                   for i, t in enumerate(tenants)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        report.parity_mismatched = [
            t for t in tenants if fleet_state.get(t) != dedicated[t]]

        # ---- phase 2: saturation + kill -----------------------------
        reqs = _tenant_requests(n_tenants, config)
        pools = [SolverClientPool(addrs, tenant=t, lane="batch",
                                  accept_stale=True, router=router)
                 for t in tenants]
        for pool, req in zip(pools, reqs):     # warm off the clock
            pool.solve(req)

        primary = {t: next(iter(router._walk(t))) for t in tenants}
        by_primary: Dict[str, int] = {}
        for t, a in primary.items():
            by_primary[a] = by_primary.get(a, 0) + 1
        victim = max(by_primary, key=lambda a: by_primary[a])
        report.killed_addr = victim
        report.affected_tenants = sorted(
            t for t, a in primary.items() if a == victim)

        shed0 = sum(metrics.load_shed_total().values())
        fo0 = metrics.failovers_total()
        samples: Dict[str, List[tuple]] = {t: [] for t in tenants}
        errors: Dict[str, int] = {t: 0 for t in tenants}
        lock = threading.Lock()
        t_start = time.perf_counter()
        kill_at = t_start + duration_s * kill_after_frac
        stop_at = t_start + duration_s

        def sat_worker(i: int):
            pool, req, tenant = pools[i], reqs[i], tenants[i]
            mine, errs = [], 0
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                try:
                    pool.solve(req)
                    mine.append((time.perf_counter() - t_start,
                                 time.perf_counter() - t0))
                except Exception:  # noqa: BLE001 — counted, pinned 0
                    errs += 1      # for unaffected tenants by the bench
            with lock:
                samples[tenant].extend(mine)
                errors[tenant] += errs

        def killer():
            now = time.perf_counter()
            if kill_at > now:
                time.sleep(kill_at - now)
            servers[victim].stop(grace=None)     # kill -9 semantics
            router.mark_dead(victim)
            for t in report.affected_tenants:
                if router.snapshot()["overrides"].get(t):
                    continue                     # cb already moved it
                try:
                    plane.failover(t, reason="fleet.kill")
                except ReplicationLagError:
                    with lost_lock:
                        report.failover_lost += 1

        threads = [threading.Thread(target=sat_worker, args=(i,))
                   for i in range(n_tenants)]
        kthread = threading.Thread(target=killer, name="kb-fleet-killer")
        for t in threads:
            t.start()
        kthread.start()
        for t in threads:
            t.join(timeout=600)
        kthread.join(timeout=600)

        kill_rel = duration_s * kill_after_frac

        def p99(vals: List[float]) -> float:
            return (round(float(np.percentile(vals, 99)) * 1e3, 3)
                    if vals else 0.0)

        aff = set(report.affected_tenants)
        pre_aff = [rtt for t in aff for ts, rtt in samples[t]
                   if ts < kill_rel]
        post_aff = [rtt for t in aff for ts, rtt in samples[t]
                    if kill_rel <= ts < kill_rel + post_window_s]
        pre_un = [rtt for t in tenants if t not in aff
                  for ts, rtt in samples[t] if ts < kill_rel]
        post_un = [rtt for t in tenants if t not in aff
                   for ts, rtt in samples[t]
                   if kill_rel <= ts < kill_rel + post_window_s]
        report.pre_kill_p99_ms = p99(pre_aff)
        report.post_kill_p99_ms = p99(post_aff)
        report.failover_p99_blip_ms = round(
            max(0.0, report.post_kill_p99_ms - report.pre_kill_p99_ms), 3)
        report.cross_tenant_added_p99_ms = round(
            max(0.0, p99(post_un) - p99(pre_un)), 3)
        report.cross_tenant_errors = sum(
            errors[t] for t in tenants if t not in aff)
        report.cross_tenant_shed = max(
            0, sum(metrics.load_shed_total().values()) - shed0)
        report.failovers = metrics.failovers_total() - fo0
        report.solves_total = sum(len(v) for v in samples.values())

        # ---- phase 3: post-kill parity + standby mega ---------------
        if report.affected_tenants:
            t0_name = report.affected_tenants[0]
            idx = tenants.index(t0_name)
            set_tenant(t0_name)
            try:
                sim, cache, binder = _tenant_cluster(idx, config)
                post_state = drive_tenant_cycles(
                    sim, cache, binder, 3, mode="rpc")
            finally:
                set_tenant(None)
            if post_state != dedicated[t0_name]:
                report.parity_mismatched.append(f"{t0_name} (post-kill)")

        # standby mega: the survivor coalesces same-shape lanes into
        # one mega dispatch; decisions must match dedicated singles
        standby_addr = next(a for a in addrs if a != victim)
        standby_svc = svcs[standby_addr]
        mega_reqs = [(t, "batch", reqs[i])
                     for i, t in enumerate(tenants) if i < 3]
        mega0 = metrics.mega_dispatches_total()
        coalesced = standby_svc.solve_many(mega_reqs)
        single_svc = TenantSolveService(TenantRegistry())
        singles = [single_svc.solve_many([one])[0] for one in mega_reqs]
        report.standby_mega_bit_identical = (
            metrics.mega_dispatches_total() > mega0
            and all(_decision_key(a) == _decision_key(b)
                    for a, b in zip(coalesced, singles)))

        report.parity_bit_identical = (not report.parity_mismatched
                                       and not report.rpc_errors)
        for pool in pools:
            pool.close()
        return report
    finally:
        rpc_client.set_failover_callback(None)
        rpc_client.reset_solver_pools()
        router_mod.install(None)
        if plane is not None:
            plane.stop()
        for server in servers.values():
            server.stop(grace=None)
        faults.SIDECAR_QUARANTINE.reset()
        if prev_addr is None:
            os.environ.pop("KUBEBATCH_SOLVER_ADDR", None)
        else:
            os.environ["KUBEBATCH_SOLVER_ADDR"] = prev_addr
