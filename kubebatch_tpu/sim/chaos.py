"""Chaos soak — hundreds of scheduler cycles under a seeded fault plan.

The executable form of the robustness claim (docs/ROBUSTNESS.md): drive
a live scheduler — streaming event source, async cache write-back,
leader lease, optionally a real gRPC sidecar — through a seeded
randomized fault schedule spanning every seam family (faults.SEAMS),
and ASSERT the invariants instead of trusting the error handling:

- the loop never exits (every cycle runs through the guarded
  ``Scheduler.run_cycle``; a raising cycle is a counted failure, never
  a dead scheduler);
- no task is lost or double-bound (ground truth vs cache vs the
  recording binder; ``debug.audit_cache`` holds every cycle);
- fairness shares are conserved (job-side allocated == node-side used);
- once faults stop, the degradation ladder re-promotes to the original
  engine and the recovered process produces decisions BIT-IDENTICAL to
  a fault-free run of the same seed (the pre-chaos fingerprint).

Entry points: ``bench.py --chaos`` (the committed evidence line) and
tests/test_chaos.py (tier-1 smoke + the full ``slow`` soak).
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from dataclasses import dataclass, field
from dataclasses import replace as _dc_replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import faults
from ..api import TaskStatus
from ..cache import SchedulerCache
from ..debug import audit_cache
from ..objects import (Container, GROUP_NAME_ANNOTATION, Pod, PodGroup,
                       PodPhase, resource_list)
from ..runtime.leaderelection import FileLease, LeaderElector
from ..runtime.scheduler import Scheduler
from .cluster import ClusterSpec, build_cluster
from .source import StreamingEventSource

log = logging.getLogger("kubebatch.chaos")

GiB = 1024 ** 3

#: the soak cluster: small enough that a cycle is milliseconds on any
#: backend, rich enough that every layer runs (two queues for fairness,
#: full gangs for the barrier). Capacity exceeds demand so a quiesced
#: fault-free scheduler MUST bind everything — "pending remains" is a
#: real violation, not a capacity artifact.
def chaos_spec(seed: int = 0) -> ClusterSpec:
    return ClusterSpec(n_nodes=12, node_cpu_millis=8000,
                       node_mem_bytes=16 * GiB, n_groups=20,
                       pods_per_group=4, pod_cpu_millis=1000,
                       pod_mem_bytes=2 * GiB, n_queues=2, seed=seed)


#: default per-crossing fault rates for the full soak — every one of the
#: five seam families (device / rpc / cache / source / lease)
DEFAULT_RATES: Dict[str, float] = {
    "device.dispatch": 0.25,
    "rpc.solve": 0.4,
    "rpc.victim": 0.4,
    "cache.bind": 0.3,
    "cache.resync": 0.2,
    "source.deliver": 0.2,
    "lease.renew": 0.3,
}

#: deterministic fail-first-N counts armed next to the rates: the
#: cache.fold seam fires exactly once per soak, proving the event-fold
#: demotion rung (fold -> snapshot-primary full clones) lands mid-churn
#: with zero invariant violations — every cache event crosses the seam,
#: so a rate would demote on the first faulted event every run anyway
DEFAULT_COUNTS: Dict[str, int] = {
    "cache.fold": 1,
    # same fail-first-once discipline for the active-set demotion rung
    # (ISSUE 15): the solve.activeset seam fires once, the engine
    # demotes to the full-width solve, and the soak's invariant bar
    # (zero double-binds, zero lost decisions) must still hold — the
    # seam only engages on configs where the engine does, so arming it
    # everywhere is free on small soaks
    "solve.activeset": 1,
    # pipelined-consume invalidation (ISSUE 16): one forced conflict at
    # the consume check — the in-flight result is discarded, the cycle
    # re-solves sequentially, and nothing double-binds or goes missing.
    # The seam only engages when the soak runs with ``pipeline=True``
    # (otherwise the consume path never crosses it), so arming it in
    # the default plan is free
    "pipeline.conflict": 1,
    # SLO-plane breach path (ISSUE 17): the obs.slo seam fires once in
    # the evaluation tick, forcing a synthetic breach through the real
    # fire path (slo_breaches_total + flight dump) — the soak proves the
    # breach machinery itself cannot corrupt a cycle, and the report
    # pins every breach in the run to exactly the injected ones
    "obs.slo": 1,
    # elastic-workload seam (ISSUE 19): fires once between cycles,
    # forcing a grow on a live gang — desired rises above the bound
    # membership via a group update + a fresh pod, mid-flight when the
    # soak pipelines, and the bar stays: audit-clean cache every cycle,
    # no double-binds, and the grown pod MUST bind by quiesce (it joins
    # pods_by_uid, so a lost grow shows up as pending-remains)
    "workload.elastic": 1,
}

#: the smoke-test subset: no device/rpc seams, so the ladder never
#: demotes and the tier-1 run compiles no extra engines
SMOKE_RATES: Dict[str, float] = {
    "cache.bind": 0.3,
    "cache.resync": 0.2,
    "source.deliver": 0.2,
    "lease.renew": 0.3,
}


class _RecordingSeams:
    """Binder/evictor that records write-backs and flags double-binds.

    A successful bind for a uid already bound (and not deleted since) is
    the double-bind violation the soak exists to catch; failed binds
    (injected upstream at the cache.bind seam) never reach here, so a
    retry that finally lands records exactly once."""

    def __init__(self):
        self.bound: Dict[str, str] = {}
        self.bind_calls = 0
        self.evicted: List[str] = []
        self.violations: List[str] = []
        #: (namespace/name, hostname) in successful-bind order — the
        #: decision fingerprint (deterministic under
        #: async_writeback=False; pod NAMES are deterministic per spec
        #: where auto-assigned uids are process-global counters)
        self.decisions: List[Tuple[str, str]] = []
        self._lock = threading.Lock()

    def bind(self, pod, hostname):
        with self._lock:
            self.bind_calls += 1
            if pod.uid in self.bound:
                self.violations.append(
                    f"double bind: {pod.namespace}/{pod.name} already on "
                    f"{self.bound[pod.uid]}, re-bound to {hostname}")
            self.bound[pod.uid] = hostname
            self.decisions.append((f"{pod.namespace}/{pod.name}",
                                   hostname))
            pod.node_name = hostname

    def evict(self, pod):
        with self._lock:
            self.evicted.append(pod.uid)
            self.bound.pop(pod.uid, None)
            pod.deletion_timestamp = 1.0

    def forget(self, uid: str):
        with self._lock:
            self.bound.pop(uid, None)

    def snapshot_bound(self) -> Dict[str, str]:
        """A locked copy — the async write-back pool mutates ``bound``
        concurrently with the soak thread's reads."""
        with self._lock:
            return dict(self.bound)

    def take_violations(self) -> List[str]:
        """Swap-and-clear under the lock: a violation appended by a
        write-back thread mid-harvest must reach SOME harvest, never be
        wiped between an unlocked read and clear."""
        with self._lock:
            taken, self.violations = self.violations, []
            return taken


@dataclass
class ChaosReport:
    cycles: int = 0
    seed: int = 0
    failures: int = 0                 # guarded cycles that failed
    faults_injected: Dict[str, int] = field(default_factory=dict)
    families_injected: List[str] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    max_ladder_level: int = 0
    final_ladder_level: int = -1
    baseline_engine: str = ""
    final_engine: str = ""
    engines_seen: List[str] = field(default_factory=list)
    recovered_bit_identical: bool = False
    degraded_p50_ms: float = 0.0
    healthy_p50_ms: float = 0.0
    pods_bound: int = 0
    #: pipelined soak (``pipeline=True``): overlapped commits, consume
    #: invalidations, and whether the storm rung demoted mid-soak
    #: (legitimate under heavy churn — recorded, not a violation)
    pipeline_cycles: int = 0
    pipeline_conflicts: int = 0
    pipeline_demoted: bool = False
    lease_lost: bool = False
    lease_renew_attempts: int = 0
    #: decision-ledger audit (ISSUE 17): closed records for the soak's
    #: bound pods, deferred (pipelined-consume) closes among them, and
    #: the SLO-breach accounting — every breach in the run must be one
    #: the armed obs.slo seam injected (2 window counts per fire)
    ledger_closed: int = 0
    ledger_deferred_closed: int = 0
    slo_breaches: int = 0
    slo_injected: int = 0
    #: unschedulability-explainer lines for pods still pending after the
    #: quiesce window (obs/explain.py) — the sim-summary form of
    #: kube-batch's per-pod Unschedulable events
    explain: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def _fingerprint(seed: int) -> Tuple[List[Tuple[str, str]], str]:
    """Decisions of ONE fault-free scheduling pass over a fresh cluster
    built from ``seed`` — (uid, node) pairs in bind order, plus the
    engine that ran. Called before the chaos (the oracle) and after
    recovery (the recovered process must reproduce it bit-identically)."""
    from ..actions import allocate as _alloc_mod

    sim = build_cluster(chaos_spec(seed))
    seams = _RecordingSeams()
    cache = SchedulerCache(binder=seams, evictor=seams,
                           async_writeback=False)
    sim.populate(cache)
    sched = Scheduler(cache, schedule_period=0.01)
    # schedule to quiescence (the gang barrier may take two passes)
    for _ in range(3):
        sched.run_once()
    return seams.decisions, _alloc_mod.last_cycle_engine


def run_chaos(cycles: int = 200, seed: int = 0,
              rates: Optional[Dict[str, float]] = None,
              rpc_sidecar: bool = False,
              fault_start: int = 3,
              fault_stop: Optional[int] = None,
              churn_gangs: int = 1,
              pipeline: bool = False) -> ChaosReport:
    """Run the soak and return the report (callers assert ``report.ok``).

    ``fault_stop`` defaults to leaving ~the last fifth of the cycles
    (min 12) fault-free so quarantines expire, the ladder re-promotes,
    and the bit-identical recovery check runs against a fully healthy
    scheduler. ``rpc_sidecar`` starts an in-process gRPC solver sidecar
    and routes allocate through it (KUBEBATCH_SOLVER=rpc) so the rpc
    seams are crossed by real wire calls. ``pipeline=True`` runs the
    soak scheduler on the pipelined executor (runtime/pipeline.py) —
    the armed ``pipeline.conflict`` seam plus the soak's own churn then
    exercise the consume-time invalidation rung under the full
    invariant bar.
    """
    from ..actions import allocate as _alloc_mod
    from .. import metrics
    from ..metrics import (pipeline_conflicts_total, pipeline_cycles_total)
    from ..obs import ledger as _ledger
    from ..obs import slo as _slo
    from ..runtime import pipeline as _pipeline_mod

    report = ChaosReport(cycles=cycles, seed=seed)
    # the deterministic counts (cache.fold: demote-the-fold rung) ride
    # ONLY the default full-soak plan: a caller-scoped rate set (the
    # tier-1 smoke's SMOKE_RATES) must not have extra seams armed
    # behind its back — the smoke relies on the folded path staying
    # engaged for its whole window
    counts = dict(DEFAULT_COUNTS) if rates is None else {}
    rates = dict(rates if rates is not None else DEFAULT_RATES)
    if fault_stop is None:
        fault_stop = max(fault_start + 1, cycles - max(12, cycles // 5))

    # ---- process-wide robustness state: start clean, run on a fast
    # quarantine policy (cooldowns sized to cycles, not minutes), and
    # restore everything on the way out ------------------------------
    saved_policy = faults.backoff_policy()
    saved_env = {k: os.environ.get(k) for k in
                 ("KUBEBATCH_SOLVER", "KUBEBATCH_SOLVER_ADDR",
                  "KUBEBATCH_NO_BACKEND_PROBE")}
    faults.reset()
    faults.set_backoff_policy(faults.BackoffPolicy(
        base_delay=0.002, max_delay=0.05, cooldown=0.25,
        probe_backoff=1.5, max_cooldown=1.0))
    # ladder re-promotion probes must not spawn jax subprocesses here —
    # the soak measures ladder logic; the wedge probe has its own tests
    os.environ["KUBEBATCH_NO_BACKEND_PROBE"] = "1"

    server = None
    lease_stop = threading.Event()
    lease_thread = None
    source = None
    cache = None
    try:
        if rpc_sidecar:
            from ..rpc.server import make_server
            server, port = make_server("127.0.0.1:0")
            server.start()
            os.environ["KUBEBATCH_SOLVER"] = "rpc"
            os.environ["KUBEBATCH_SOLVER_ADDR"] = f"127.0.0.1:{port}"
        elif pipeline:
            # the executor only pipelines the activeset/hier family, and
            # the 12-node soak cluster auto-selects the flat engines —
            # force the solver so the overlap path actually engages
            # (both fingerprints run under the same env, so the
            # bit-identical oracle stays apples-to-apples)
            os.environ["KUBEBATCH_SOLVER"] = "activeset"

        # ---- the fault-free oracle, recorded BEFORE any chaos ------
        baseline_decisions, baseline_engine = _fingerprint(seed)
        report.baseline_engine = baseline_engine
        if not baseline_decisions:
            report.violations.append("baseline run bound nothing")
            return report

        # ---- the live stack: source -> cache -> scheduler ----------
        # ledger audit mode AFTER the baseline fingerprint (its binds
        # must not pollute the soak's closed-record set): every pod the
        # live stack binds must close a ledger record — checked against
        # seams.snapshot_bound() in the final invariants
        _ledger.reset()
        _ledger.retain()
        sim = build_cluster(chaos_spec(seed))
        seams = _RecordingSeams()
        cache = SchedulerCache(binder=seams, evictor=seams,
                               async_writeback=True)
        source = StreamingEventSource()
        pods_by_uid: Dict[str, Pod] = {}
        with source._lock:
            for q in sim.queues:
                source.queues[q.name] = q
            for n in sim.nodes:
                source.nodes[n.name] = n
            for g in sim.groups:
                source.groups[f"{g.namespace}/{g.name}"] = g
            for p in sim.pods:
                source.pods[f"{p.namespace}/{p.name}"] = p
                pods_by_uid[p.uid] = p
        source.start(cache)
        cache.run()                      # resync/cleanup repair worker
        # audit_every: the fold audit (snapshot_diff == 0 between the
        # folded state and a fresh full clone) runs INSIDE the soak —
        # the ISSUE 9 acceptance gate; failures surface as violations
        # below via metrics.audit_failures_total
        if pipeline:
            _pipeline_mod.reset()     # soak starts un-demoted
        pc0 = pipeline_cycles_total()
        cf0 = pipeline_conflicts_total()
        slo0 = metrics.slo_breaches_total()
        sched = Scheduler(cache, schedule_period=0.01,
                          cycle_deadline=30.0, audit_every=5,
                          pipeline=pipeline)
        # SLO plane with chaos-calibrated ledger thresholds: injected
        # fault windows legitimately hold pods pending for seconds
        # (retry backoff, recovery sleeps), which the production
        # arrival bounds would count as organic breaches — here the
        # gate is "no breach beyond the armed obs.slo seam's", so the
        # arrival objectives must only ever fire through the seam
        _slo.arm(tuple(
            _dc_replace(o, threshold_ms=max(o.threshold_ms, 120000.0))
            if o.kind == "ledger" else o
            for o in _slo.DEFAULT_OBJECTIVES))

        # ---- the leader lease, renewed throughout the soak ---------
        lease_dir = tempfile.mkdtemp(prefix="kb-chaos-lease-")
        lease = FileLease(os.path.join(lease_dir, "leader.lock"),
                          lease_duration=30.0, renew_deadline=20.0,
                          retry_period=0.1)
        elector = LeaderElector(lease, 30.0, 20.0, 0.1)
        lease_lost: List[bool] = []

        def _workload(workload_stop: threading.Event) -> None:
            while not lease_stop.is_set() and not workload_stop.is_set():
                workload_stop.wait(0.1)

        lease_thread = threading.Thread(
            target=lambda: elector.run(_workload,
                                       lambda: lease_lost.append(True),
                                       lease_stop),
            name="kb-chaos-lease", daemon=True)
        lease_thread.start()

        # ---- churn + kubelet helpers -------------------------------
        churn_seq = [0]

        def kubelet_tick() -> None:
            """Successfully bound pods start Running (via the source,
            like real status updates arrive)."""
            for uid, host in seams.snapshot_bound().items():
                pod = pods_by_uid.get(uid)
                if pod is None or pod.phase != PodPhase.PENDING \
                        or not pod.node_name:
                    continue
                pod.phase = PodPhase.RUNNING
                source.emit_pod_update(pod, pod)

        def churn() -> None:
            """Oldest fully-Running gangs complete; equal fresh gangs
            arrive — all through the event stream."""
            by_group: Dict[str, List[Pod]] = {}
            for pod in pods_by_uid.values():
                by_group.setdefault(
                    pod.annotations.get(GROUP_NAME_ANNOTATION, ""),
                    []).append(pod)
            done = 0
            for key in sorted(source.groups):
                if done >= churn_gangs:
                    break
                pg = source.groups.get(key)
                if pg is None or not pg.name.startswith("job-"):
                    continue
                pods = by_group.get(pg.name, [])
                if not pods or any(p.phase != PodPhase.RUNNING
                                   for p in pods):
                    continue
                for pod in pods:
                    source.emit_pod_delete(pod)
                    pods_by_uid.pop(pod.uid, None)
                    seams.forget(pod.uid)
                source.emit_group_delete(pg)
                done += 1
            spec = chaos_spec(seed)
            base_ts = 1e9 + churn_seq[0]
            for k in range(done):
                gid = churn_seq[0]
                churn_seq[0] += 1
                queue = sim.queues[gid % len(sim.queues)].name
                pg = PodGroup(name=f"job-churn-{gid:06d}", namespace="sim",
                              min_member=spec.pods_per_group, queue=queue,
                              creation_timestamp=base_ts + k)
                source.emit_group(pg)
                for p in range(spec.pods_per_group):
                    pod = Pod(
                        name=f"{pg.name}-{p:03d}", namespace="sim",
                        annotations={GROUP_NAME_ANNOTATION: pg.name},
                        containers=[Container(requests=resource_list(
                            cpu=spec.pod_cpu_millis,
                            memory=spec.pod_mem_bytes))],
                        creation_timestamp=base_ts + k + p / 1000.0)
                    source.emit_pod(pod)   # also records it in the world
                    pods_by_uid[pod.uid] = pod

        # ---- elastic-workload injection (workload.elastic seam) ----
        from ..workloads import ElasticDriver
        elastic = ElasticDriver(source)
        espec = chaos_spec(seed)

        def elastic_tick() -> None:
            """When the workload.elastic seam fires, grow one live gang
            by a pod: desired rises via a group update and the fresh pod
            rides the event stream like any arrival. It joins
            pods_by_uid, so the quiesce gate requires it to BIND — a
            grow the scheduler drops is a soak violation, not noise."""
            by_group: Dict[str, List[Pod]] = {}
            for pod in pods_by_uid.values():
                by_group.setdefault(
                    pod.annotations.get(GROUP_NAME_ANNOTATION, ""),
                    []).append(pod)
            for key in sorted(source.groups):
                pg = source.groups.get(key)
                if pg is None or not pg.name.startswith("job-"):
                    continue
                pods = by_group.get(pg.name, [])
                if not pods or any(p.phase != PodPhase.RUNNING
                                   for p in pods):
                    continue

                def make_pod(idx: int, _pg=pg) -> Pod:
                    return Pod(
                        name=f"{_pg.name}-{idx:03d}", namespace="sim",
                        annotations={GROUP_NAME_ANNOTATION: _pg.name},
                        containers=[Container(requests=resource_list(
                            cpu=espec.pod_cpu_millis,
                            memory=espec.pod_mem_bytes))],
                        creation_timestamp=2e9 + elastic.grows)

                # monotonic member index: churn may have deleted a
                # mid-list member, so len(pods) can equal a LIVE pod's
                # suffix — name from the high-water suffix instead
                suffixes = []
                for p in pods:
                    tail = p.name.rsplit("-", 1)[-1]
                    if tail.isdigit():
                        suffixes.append(int(tail))
                grown = elastic.maybe_inject(
                    pg, pods, make_pod,
                    next_index=max(suffixes, default=len(pods) - 1) + 1)
                if grown is not None:
                    _, added = grown
                    for pod in added:
                        pods_by_uid[pod.uid] = pod
                return   # one candidate gang per tick: the seam decides

        def check_invariants(where: str) -> None:
            before = len(report.violations)
            with cache._lock:
                problems = audit_cache(cache)
            for p in problems:
                report.violations.append(f"{where}: {p}")
            # fairness conservation: job-side allocated == node-side used
            with cache._lock:
                job_cpu = sum(j.allocated.milli_cpu
                              for j in cache.jobs.values())
                job_mem = sum(j.allocated.memory
                              for j in cache.jobs.values())
                node_cpu = sum(n.used.milli_cpu
                               for n in cache.nodes.values())
                node_mem = sum(n.used.memory
                               for n in cache.nodes.values())
            if abs(job_cpu - node_cpu) > 1e-3 \
                    or abs(job_mem - node_mem) > 64.0:
                report.violations.append(
                    f"{where}: fairness shares diverged — jobs allocated "
                    f"({job_cpu:.3f}m, {job_mem:.0f}B) != nodes used "
                    f"({node_cpu:.3f}m, {node_mem:.0f}B)")
            report.violations.extend(
                f"{where}: {v}" for v in seams.take_violations())
            if len(report.violations) > before:
                # a violated invariant is exactly what the flight
                # recorder exists for: dump the last cycles' span trees
                # + counters + ladder state (no-op unless armed)
                from ..obs import flight as _flight
                _flight.dump(f"chaos_invariant-{where.split(':')[0]}")

        # ---- the soak loop -----------------------------------------
        from ..metrics import audit_failures_total
        audit_fail0 = audit_failures_total()
        plan = faults.FaultPlan(rates=rates, counts=counts, seed=seed)
        degraded_s: List[float] = []
        healthy_s: List[float] = []
        engines: set = set()
        for cycle in range(cycles):
            if cycle == fault_start:
                faults.arm(plan)
            if cycle == fault_stop:
                faults.disarm()
            in_window = fault_start <= cycle < fault_stop
            kubelet_tick()
            churn()
            elastic_tick()
            source.sync(timeout=15.0)
            t0 = time.perf_counter()
            try:
                ok = sched.run_cycle()
            except BaseException as e:   # run_cycle must NEVER raise
                report.violations.append(
                    f"cycle {cycle}: guarded cycle raised {e!r} — the "
                    f"loop would have died")
                break
            dt = time.perf_counter() - t0
            (degraded_s if in_window else healthy_s).append(dt)
            if not ok:
                report.failures += 1
            engines.add(_alloc_mod.last_cycle_engine)
            report.max_ladder_level = max(report.max_ladder_level,
                                          faults.LADDER.level)
            kubelet_tick()
            if not in_window:
                # the cache must be internally consistent every healthy
                # cycle; inside the window the SAME check runs — faults
                # land between cycles as retries, never as corruption
                check_invariants(f"cycle {cycle}")
            else:
                check_invariants(f"cycle {cycle} (faulted)")
            if not in_window and cycle > fault_stop:
                # recovery phase: give the ladder's cooldown real time
                time.sleep(0.05)

        faults.disarm()
        report.faults_injected = dict(plan.injected)
        report.families_injected = sorted(
            {s.split(".", 1)[0] for s in plan.injected})

        # ---- quiesce fault-free: retries drain, pending rebinds ----
        for settle in range(20):
            cache.drain(timeout=10.0)
            kubelet_tick()
            source.sync(timeout=10.0)
            sched.run_cycle()
            kubelet_tick()
            source.sync(timeout=10.0)
            cache.drain(timeout=10.0)
            with cache._lock:
                pending = sum(
                    len(j.task_status_index.get(TaskStatus.PENDING, {}))
                    for j in cache.jobs.values())
            if pending == 0:
                break
            time.sleep(0.05)
        engines.add(_alloc_mod.last_cycle_engine)
        report.engines_seen = sorted(engines)
        report.final_engine = _alloc_mod.last_cycle_engine
        report.final_ladder_level = faults.LADDER.level
        report.pods_bound = len(seams.snapshot_bound())
        report.pipeline_cycles = pipeline_cycles_total() - pc0
        report.pipeline_conflicts = pipeline_conflicts_total() - cf0
        report.pipeline_demoted = _pipeline_mod.demoted()
        if pipeline and not report.pipeline_cycles:
            report.violations.append(
                "pipelined soak never committed an overlapped cycle — "
                "the executor never engaged (engine gates too strict?)")

        # ---- final invariants --------------------------------------
        check_invariants("final")
        # fold audit (ISSUE 9): any in-soak snapshot_diff != 0 between
        # the folded state and the full-clone oracle is a violation,
        # and the final state must audit clean too (regardless of
        # whether the injected cache.fold seam demoted mid-soak)
        audit_fails = audit_failures_total() - audit_fail0
        if audit_fails:
            report.violations.append(
                f"fold audit diverged {audit_fails} time(s) during the "
                f"soak (snapshot_diff != 0; see scheduler log)")
        if hasattr(cache, "audited_snapshot"):
            _, final_diffs = cache.audited_snapshot()
            for d in final_diffs[:8]:
                report.violations.append(f"final fold audit: {d}")
        if report.final_ladder_level != 0:
            report.violations.append(
                f"ladder failed to re-promote: level "
                f"{report.final_ladder_level} after recovery window")
        with cache._lock:
            cache_uids = {uid for j in cache.jobs.values()
                          for uid in j.tasks}
        never_bound = 0
        for uid, pod in pods_by_uid.items():
            if uid not in cache_uids:
                report.violations.append(
                    f"task lost: {pod.namespace}/{pod.name} in ground "
                    f"truth but absent from the cache")
            if not pod.node_name:
                never_bound += 1
                report.violations.append(
                    f"task never bound after quiesce: "
                    f"{pod.namespace}/{pod.name}")
        if never_bound:
            # the sim-summary form of kube-batch's per-pod Unschedulable
            # events: WHY are those pods still pending (host-oracle pass;
            # a broken soak must not depend on another device dispatch)
            try:
                from ..framework import CloseSession, OpenSession
                from ..obs import explain as _explain
                ssn = OpenSession(cache, sched.tiers)
                snap = _explain.explain_session(ssn, device_pass=False)
                CloseSession(ssn)
                report.explain = _explain.summarize(snap)
                for line in report.explain:
                    log.warning("explain: %s", line)
            except Exception:      # diagnostics must not mask the soak
                log.exception("unschedulability explainer failed")
        report.lease_renew_attempts = elector.renew_attempts
        report.lease_lost = bool(lease_lost)
        if lease_lost:
            report.violations.append(
                "leadership lost during the soak (injected renew faults "
                "must heal inside the deadline, never accumulate to loss)")

        # ---- decision-ledger audit (ISSUE 17) ----------------------
        # BEFORE the recovery fingerprint: its fresh stack would pour
        # unrelated closes into the retained ring. Every pod this soak
        # bound must hold ONE closed record with monotone stage stamps;
        # deferred closes must appear iff the pipelined path committed.
        records = {r["uid"]: r for r in _ledger.retained()}
        report.ledger_closed = len(records)
        report.ledger_deferred_closed = sum(
            1 for r in records.values() if r["deferred"])
        for uid in seams.snapshot_bound():
            rec = records.get(uid)
            if rec is None:
                pod = pods_by_uid.get(uid)
                name = (f"{pod.namespace}/{pod.name}" if pod is not None
                        else uid)
                report.violations.append(
                    f"bound pod has no closed ledger record: {name}")
                continue
            ts = rec["arrival"]
            for stage, v in rec["stages"]:
                if v < ts:
                    report.violations.append(
                        f"ledger stamps not monotone for {uid}: "
                        f"{stage} at {v} after {ts}")
                ts = v
            if rec["bind"] < ts:
                report.violations.append(
                    f"ledger bind precedes last stage for {uid}")
        if (report.pipeline_cycles
                and not report.ledger_deferred_closed):
            report.violations.append(
                "pipelined cycles committed but no ledger record was "
                "closed as deferred — the attribution context never "
                "reached replay_decisions")
        # SLO accounting: each obs.slo seam fire forces one synthetic
        # breach = 2 window counts; anything beyond that is a real
        # (unexplained) breach of the conservative default objectives
        report.slo_breaches = metrics.slo_breaches_total() - slo0
        report.slo_injected = report.faults_injected.get("obs.slo", 0)
        unexplained = report.slo_breaches - 2 * report.slo_injected
        if unexplained:
            report.violations.append(
                f"unexplained SLO breaches during the soak: "
                f"{unexplained} window counts beyond the "
                f"{report.slo_injected} injected fire(s) "
                f"({metrics.slo_breaches_by_objective()})")

        # ---- recovery fingerprint: bit-identical decisions ---------
        recovered_decisions, recovered_engine = _fingerprint(seed)
        report.recovered_bit_identical = (
            recovered_decisions == baseline_decisions
            and recovered_engine == baseline_engine)
        if not report.recovered_bit_identical:
            report.violations.append(
                f"post-recovery decisions diverged from the fault-free "
                f"oracle (engine {recovered_engine} vs {baseline_engine}, "
                f"{len(recovered_decisions)} vs {len(baseline_decisions)} "
                f"binds)")

        if degraded_s:
            report.degraded_p50_ms = round(
                float(np.percentile(degraded_s, 50) * 1e3), 3)
        if healthy_s:
            report.healthy_p50_ms = round(
                float(np.percentile(healthy_s, 50) * 1e3), 3)
        return report
    finally:
        faults.disarm()
        faults.set_backoff_policy(saved_policy)
        faults.LADDER.reset()
        faults.SIDECAR_QUARANTINE.reset()
        _slo.disarm()
        _ledger.stop_retention()
        if pipeline:
            _pipeline_mod.reset()    # demotion is process-sticky
        lease_stop.set()
        if lease_thread is not None:
            lease_thread.join(timeout=5.0)
        if source is not None:
            source.stop()
        if cache is not None:
            cache.stop()
        if server is not None:
            server.stop(grace=None)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------
# fleet chaos (ISSUE 14): N sidecars, seeded partitions / slow peers /
# one abrupt kill, per-tenant invariants throughout
# ---------------------------------------------------------------------

#: per-crossing rates for the fleet soak — the rpc + fleet families.
#: Deliberately NO cache/source/lease/device seams: the fleet soak's
#: per-tenant stacks are synchronous sims (no streaming source, no
#: write-back pool), so those families' retry machinery isn't in the
#: loop; the five-family soak (run_chaos) owns them.
DEFAULT_FLEET_RATES: Dict[str, float] = {
    "rpc.solve": 0.15,
    "rpc.partition": 0.2,
    "fleet.slowpeer": 0.25,
}

#: exactly one abrupt sidecar death per soak (deterministic count, like
#: cache.fold in the five-family soak): the kill is the event under
#: test — its failovers must land clean — and killing more than
#: sidecars-1 would leave no fleet to assert anything about
DEFAULT_FLEET_COUNTS: Dict[str, int] = {
    "fleet.kill": 1,
}


class _FleetSeams(_RecordingSeams):
    """_RecordingSeams plus the tenant sim's kubelet contract: freshly
    bound pods queue in ``fresh`` until the next tick flips them to
    Running (sim/tenants._Binder's shape, with double-bind detection)."""

    def __init__(self):
        super().__init__()
        self.fresh: List = []

    def bind(self, pod, hostname):
        super().bind(pod, hostname)
        with self._lock:
            self.fresh.append(pod)


@dataclass
class FleetChaosReport:
    cycles: int = 0
    seed: int = 0
    sidecars: int = 0
    tenants: int = 0
    failures: int = 0
    faults_injected: Dict[str, int] = field(default_factory=dict)
    families_injected: List[str] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    killed: List[str] = field(default_factory=list)
    failovers: int = 0
    final_ladder_level: int = -1

    @property
    def ok(self) -> bool:
        return not self.violations


def run_fleet_chaos(cycles: int = 200, seed: int = 0,
                    sidecars: int = 3, tenants: int = 3,
                    rates: Optional[Dict[str, float]] = None,
                    counts: Optional[Dict[str, int]] = None,
                    fault_start: int = 3,
                    fault_stop: Optional[int] = None
                    ) -> FleetChaosReport:
    """The fleet soak: per-tenant seeded clusters scheduling through a
    router-placed sidecar fleet under seeded partitions, injected slow
    peers, and one abrupt sidecar death — with the standing invariants
    asserted per tenant every cycle: no task lost or double-bound
    (audit_cache + the recording binder), fairness shares conserved,
    and the degradation ladder back at level 0 once faults stop. The
    kill's tenants must fail over through the replication handshake
    (``report.failovers`` counts them; zero after a kill is a
    violation). Runs on the jittered backoff policy so fleet breakers
    never re-probe in lockstep — the satellite (b) schedule, exercised
    live."""
    from ..actions.allocate import AllocateAction
    from ..conf import shipped_tiers
    from ..framework import CloseSession, OpenSession
    from ..metrics import failovers_total
    from ..objects import PodPhase
    from ..rpc import client as rpc_client
    from ..rpc.server import make_server
    from ..tenantsvc import (ReplicationLagError, ReplicationPlane,
                             TenantRouter)
    from ..tenantsvc import router as router_mod
    from ..tenantsvc.service import TenantSolveService
    from ..tenantsvc.sessions import TenantRegistry
    from .cluster import BASELINE_SPECS
    from .tenants import TENANT_CONFIG, _TENANT_CHURN

    report = FleetChaosReport(cycles=cycles, seed=seed,
                              sidecars=sidecars, tenants=tenants)
    rates = dict(rates if rates is not None else DEFAULT_FLEET_RATES)
    counts = dict(counts if counts is not None else DEFAULT_FLEET_COUNTS)
    if fault_stop is None:
        fault_stop = max(fault_start + 1, cycles - max(12, cycles // 5))

    saved_policy = faults.backoff_policy()
    saved_env = {k: os.environ.get(k) for k in
                 ("KUBEBATCH_SOLVER", "KUBEBATCH_SOLVER_ADDR",
                  "KUBEBATCH_NO_BACKEND_PROBE")}
    faults.reset()
    # fast cooldowns sized to cycles — WITH decorrelated jitter, so the
    # soak runs the schedule a fleet actually deploys
    faults.set_backoff_policy(faults.BackoffPolicy(
        base_delay=0.002, max_delay=0.05, cooldown=0.25,
        probe_backoff=1.5, max_cooldown=1.0,
        jitter=0.5, jitter_seed=seed))
    os.environ["KUBEBATCH_NO_BACKEND_PROBE"] = "1"

    servers: Dict[str, object] = {}
    plane = None
    try:
        svcs: Dict[str, TenantSolveService] = {}
        for _ in range(sidecars):
            svc = TenantSolveService(TenantRegistry())
            server, port = make_server("127.0.0.1:0", tenant_service=svc)
            server.start()
            addr = f"127.0.0.1:{port}"
            servers[addr] = server
            svcs[addr] = svc
        addrs = list(servers)
        router = TenantRouter(addrs)
        router_mod.install(router)
        plane = ReplicationPlane(router)
        for addr, svc in svcs.items():
            plane.attach(addr, svc.registry)
        plane.start()

        names = [f"tenant-{i}" for i in range(tenants)]

        def failover_cb(tenant: str, dead_addr: str) -> None:
            if next(iter(router._walk(tenant))) != dead_addr:
                return
            if router.snapshot()["overrides"].get(tenant):
                return
            try:
                plane.failover(tenant, reason=f"partition:{dead_addr}")
            except ReplicationLagError as e:
                report.violations.append(
                    f"failover refused for {tenant}: {e}")

        rpc_client.set_failover_callback(failover_cb)

        # per-tenant stacks: seeded cluster + recording binder + cache
        from ..cache import SchedulerCache
        from dataclasses import replace as _dc_replace

        stacks = []
        for i in range(tenants):
            spec = _dc_replace(BASELINE_SPECS[TENANT_CONFIG], seed=i)
            sim = build_cluster(spec)
            seams = _FleetSeams()
            cache = SchedulerCache(binder=seams, evictor=seams,
                                   async_writeback=False)
            sim.populate(cache)
            stacks.append((sim, cache, seams))

        tiers = shipped_tiers()
        act = AllocateAction(mode="rpc")
        fo0 = failovers_total()
        plan = faults.FaultPlan(rates=rates, counts=counts, seed=seed)

        def kubelet(cache, seams) -> None:
            for pod in seams.fresh:
                if pod.phase == PodPhase.PENDING:
                    pod.phase = PodPhase.RUNNING
                    cache.update_pod(pod, pod)
            seams.fresh.clear()

        def check_invariants(where: str, cache, seams) -> None:
            before = len(report.violations)
            with cache._lock:
                problems = audit_cache(cache)
            for p in problems:
                report.violations.append(f"{where}: {p}")
            with cache._lock:
                job_cpu = sum(j.allocated.milli_cpu
                              for j in cache.jobs.values())
                job_mem = sum(j.allocated.memory
                              for j in cache.jobs.values())
                node_cpu = sum(n.used.milli_cpu
                               for n in cache.nodes.values())
                node_mem = sum(n.used.memory
                               for n in cache.nodes.values())
            if abs(job_cpu - node_cpu) > 1e-3 \
                    or abs(job_mem - node_mem) > 64.0:
                report.violations.append(
                    f"{where}: fairness shares diverged — jobs "
                    f"({job_cpu:.3f}m, {job_mem:.0f}B) != nodes "
                    f"({node_cpu:.3f}m, {node_mem:.0f}B)")
            report.violations.extend(
                f"{where}: {v}" for v in seams.take_violations())
            if len(report.violations) > before:
                from ..obs import flight as _flight
                _flight.dump(f"fleet_chaos-{where.split(':')[0]}")

        def maybe_kill() -> None:
            alive = [a for a in addrs if a not in report.killed]
            if len(alive) <= 1 or not faults.should_fail("fleet.kill"):
                return
            primary = {t: next(iter(router._walk(t))) for t in names}
            by_primary: Dict[str, int] = {}
            for t, a in primary.items():
                if a in alive:
                    by_primary[a] = by_primary.get(a, 0) + 1
            victim = (max(by_primary, key=lambda a: by_primary[a])
                      if by_primary else alive[0])
            servers[victim].stop(grace=None)      # abrupt, no grace
            router.mark_dead(victim)
            report.killed.append(victim)
            for t in names:
                if primary.get(t) != victim:
                    continue
                if router.snapshot()["overrides"].get(t):
                    continue
                try:
                    plane.failover(t, reason="fleet.kill")
                except ReplicationLagError as e:
                    report.violations.append(
                        f"failover refused for {t} after kill: {e}")

        from ..rpc.client import set_tenant

        for cycle in range(cycles):
            if cycle == fault_start:
                faults.arm(plan)
            if cycle == fault_stop:
                faults.disarm()
            in_window = fault_start <= cycle < fault_stop
            if in_window:
                maybe_kill()
            for i, tenant in enumerate(names):
                sim, cache, seams = stacks[i]
                set_tenant(tenant)
                try:
                    kubelet(cache, seams)
                    if cycle:
                        sim.churn_tick(cache, _TENANT_CHURN)
                    ssn = OpenSession(cache, tiers)
                    try:
                        act.execute(ssn)
                    finally:
                        CloseSession(ssn)
                except BaseException as e:  # the loop must never die
                    report.failures += 1
                    report.violations.append(
                        f"cycle {cycle} tenant {tenant}: raised {e!r}")
                finally:
                    set_tenant(None)
                kubelet(cache, seams)
                check_invariants(
                    f"cycle {cycle}{' (faulted)' if in_window else ''} "
                    f"{tenant}", cache, seams)
            if not in_window and cycle > fault_stop:
                time.sleep(0.02)   # real time for cooldown expiry

        faults.disarm()
        report.faults_injected = dict(plan.injected)
        report.families_injected = sorted(
            {s.split(".", 1)[0] for s in plan.injected})
        report.failovers = failovers_total() - fo0
        report.final_ladder_level = faults.LADDER.level
        if report.final_ladder_level != 0:
            report.violations.append(
                f"ladder failed to recover: level "
                f"{report.final_ladder_level}")
        if report.killed and report.failovers == 0:
            report.violations.append(
                f"sidecar {report.killed} died but no tenant failed "
                f"over — the kill's tenants were stranded")
        return report
    finally:
        faults.disarm()
        faults.set_backoff_policy(saved_policy)
        faults.LADDER.reset()
        faults.SIDECAR_QUARANTINE.reset()
        from ..rpc import client as _rc
        from ..tenantsvc import router as _rt_mod
        _rc.set_failover_callback(None)
        _rc.reset_solver_pools()
        _rt_mod.install(None)
        if plane is not None:
            plane.stop()
        for server in servers.values():
            try:
                server.stop(grace=None)
            except Exception:
                pass
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
