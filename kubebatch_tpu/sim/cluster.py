"""Synthetic cluster generation — the simulated e2e substrate.

Plays the role the reference's kubemark/DIND harness plays (SURVEY.md
sect. 4 tier 3) without needing a real k8s cluster: deterministic
generators for nodes, queues, PodGroups and pods sized to the BASELINE.md
benchmark configs.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..cache import SchedulerCache
from ..objects import (Affinity, Node, Pod, PodAffinityTerm, PodGroup,
                       PodPhase, PriorityClass, Queue, Container, Taint,
                       TaintEffect, Toleration, GROUP_NAME_ANNOTATION,
                       resource_list)

GiB = 1024 ** 3


@dataclass
class _GroupShape:
    """Per-group predicate template (pods of a group share it, like a
    real workload's pod template)."""
    selector_zone: Optional[str] = None
    tolerate: bool = False
    anti_self: bool = False
    zone_affine: bool = False
    pref_label: Optional[str] = None
    host_port: Optional[int] = None
    app: str = ""

    def apply(self, pod: Pod) -> None:
        if self.app:
            pod.labels["app"] = self.app
        if self.selector_zone is not None:
            pod.node_selector["zone"] = self.selector_zone
        if self.tolerate:
            pod.tolerations.append(Toleration(
                key="dedicated", operator="Equal", value="batch",
                effect=TaintEffect.NO_SCHEDULE.value))
        terms = Affinity()
        used = False
        if self.anti_self:
            terms.pod_anti_affinity_required.append(PodAffinityTerm(
                match_labels={"app": self.app},
                topology_key="kubernetes.io/hostname"))
            used = True
        if self.zone_affine:
            terms.pod_affinity_required.append(PodAffinityTerm(
                match_labels={"app": self.app}, topology_key="zone"))
            used = True
        if self.pref_label is not None:
            terms.pod_affinity_preferred.append((10, PodAffinityTerm(
                match_labels={"app": self.pref_label},
                topology_key="kubernetes.io/hostname")))
            used = True
        if used:
            pod.affinity = terms
        if self.host_port is not None:
            pod.containers[0].ports = [self.host_port]


def group_shape(spec: "ClusterSpec", rng, g: int) -> Optional[_GroupShape]:
    """Roll one group's predicate template from the spec fractions.
    Features are exclusive per group (a group gets at most one affinity
    kind) so the fractions compose predictably."""
    shape = _GroupShape(app=f"app-{g % 16}")
    if spec.selector_frac > 0 and rng.random() < spec.selector_frac:
        shape.selector_zone = f"z{int(rng.integers(max(1, spec.n_zones)))}"
    if spec.toleration_frac > 0 and rng.random() < spec.toleration_frac:
        shape.tolerate = True
    roll = rng.random()
    if roll < spec.anti_affinity_frac:
        shape.anti_self = True
    elif roll < spec.anti_affinity_frac + spec.zone_affinity_frac:
        shape.zone_affine = True
    elif roll < (spec.anti_affinity_frac + spec.zone_affinity_frac
                 + spec.pref_affinity_frac):
        shape.pref_label = f"app-{int(rng.integers(16))}"
    if spec.hostport_frac > 0 and rng.random() < spec.hostport_frac:
        shape.host_port = 30000 + int(rng.integers(16))
    return shape


@dataclass
class ClusterSpec:
    n_nodes: int = 50
    node_cpu_millis: int = 8000
    node_mem_bytes: float = 16 * GiB
    node_pods: int = 110
    n_groups: int = 100
    pods_per_group: int = 8
    min_member: Optional[int] = None     # default: pods_per_group (full gang)
    pod_cpu_millis: int = 1000
    pod_mem_bytes: float = 2 * GiB
    n_queues: int = 1
    queue_weights: Tuple[int, ...] = ()
    priority_classes: Tuple[Tuple[str, int], ...] = ()
    #: fraction of cluster pre-filled with running pods
    running_fill: float = 0.0
    seed: int = 0
    jitter: float = 0.0                  # relative size jitter on requests
    # --- predicate-rich knobs (VERDICT r4 directive 3: the sig-matrix
    # static path and the affinity/port device vocabulary must be
    # perf-measured, not only semantics-tested). Nodes get hostname +
    # zone labels whenever any knob is set. Fractions are of GROUPS —
    # pods of one group share a template, like real workloads. ----------
    n_zones: int = 0                     # zone label cardinality
    selector_frac: float = 0.0           # node-selector on a zone
    taint_frac: float = 0.0              # NoSchedule-tainted node fraction
    toleration_frac: float = 0.0         # groups tolerating the taint
    anti_affinity_frac: float = 0.0      # self anti-affinity on hostname
    zone_affinity_frac: float = 0.0      # required self-affinity on zone
    pref_affinity_frac: float = 0.0      # preferred co-location (score)
    hostport_frac: float = 0.0           # one host port per group

    @property
    def predicate_rich(self) -> bool:
        return any((self.n_zones, self.selector_frac, self.taint_frac,
                    self.anti_affinity_frac, self.zone_affinity_frac,
                    self.pref_affinity_frac, self.hostport_frac))


@dataclass
class SimCluster:
    spec: ClusterSpec
    nodes: List[Node] = field(default_factory=list)
    queues: List[Queue] = field(default_factory=list)
    groups: List[PodGroup] = field(default_factory=list)
    pods: List[Pod] = field(default_factory=list)
    priority_classes: List[PriorityClass] = field(default_factory=list)

    def populate(self, cache: SchedulerCache) -> None:
        for q in self.queues:
            cache.add_queue(q)
        for pc in self.priority_classes:
            cache.add_priority_class(pc)
        for n in self.nodes:
            cache.add_node(n)
        for g in self.groups:
            cache.add_pod_group(g)
        for p in self.pods:
            cache.add_pod(p)

    _pod_index: Optional[Dict[Tuple[str, str], Pod]] = None
    _churn_seq: int = 0

    def churn_tick(self, cache: SchedulerCache, n_pods: int,
                   arrival_queue: Optional[int] = None) -> int:
        """Steady-state churn trickle: the oldest fully-bound gangs finish
        (pod + PodGroup delete events) and the same number of fresh gangs
        arrives pending — the regime the 1 s schedule-period loop lives in
        once the cluster is mostly scheduled (the kubemark plan's
        density/latency scenario, ref
        doc/design/Benchmark/kubemark/kubemark-benchmarking.md:40-42).
        Returns the number of pods actually recycled.

        ``arrival_queue`` pins ALL of this tick's fresh gangs onto one
        queue index instead of the round-robin default — alternating it
        between ticks sustains cross-queue imbalance (the arriving
        queue's allocated sits below its deserved while others sit at or
        above), the regime where reclaim's provably-idle gates correctly
        do NOT fire and the victim wave path stays hot every cycle
        (bench.py --steady-skew; VERDICT r4 directive 4)."""
        spec = self.spec
        per = max(1, spec.pods_per_group)
        n_groups = max(1, n_pods // per)
        by_group: Dict[str, List[Pod]] = {}
        for p in self.pods:
            by_group.setdefault(p.annotations.get(GROUP_NAME_ANNOTATION, ""),
                                []).append(p)
        recycled = 0
        done = 0
        doomed_pods: set = set()
        doomed_groups: set = set()
        for g in self.groups:
            if done >= n_groups:
                break
            if not g.name.startswith("job-"):
                continue        # leave cfg4's running fill alone
            pods = by_group.get(g.name, [])
            if not pods or not all(p.node_name for p in pods):
                continue
            for p in pods:
                cache.delete_pod(p)
                doomed_pods.add(p.uid)
            cache.delete_pod_group(g)
            doomed_groups.add(g.name)
            recycled += len(pods)
            done += 1
        if doomed_pods:
            # one rebuild instead of per-pod list.remove (each remove is a
            # field-by-field dataclass scan of the full 10k+ pod list)
            self.pods = [p for p in self.pods if p.uid not in doomed_pods]
            self.groups = [g for g in self.groups
                           if g.name not in doomed_groups]
        self._pod_index = None
        base_ts = 1e9 + self._churn_seq
        rich = spec.predicate_rich
        rng = np.random.default_rng(spec.seed + 7919 + self._churn_seq) \
            if rich else None
        for k in range(done):
            gid = self._churn_seq
            self._churn_seq += 1
            qi = (arrival_queue if arrival_queue is not None
                  else gid) % len(self.queues)
            queue = self.queues[qi].name
            # named job-* so the next tick can recycle churn gangs too
            pg = PodGroup(name=f"job-churn-{gid:06d}", namespace="sim",
                          min_member=per, queue=queue,
                          creation_timestamp=base_ts + k)
            self.groups.append(pg)
            cache.add_pod_group(pg)
            shape = group_shape(spec, rng, gid) if rich else None
            for p in range(per):
                pod = Pod(
                    name=f"{pg.name}-{p:03d}", namespace="sim",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[Container(requests=resource_list(
                        cpu=spec.pod_cpu_millis,
                        memory=spec.pod_mem_bytes))],
                    creation_timestamp=base_ts + k + p / 1000.0)
                if shape is not None:
                    shape.apply(pod)
                self.pods.append(pod)
                cache.add_pod(pod)
        # let the deleted-job GC run (no repair worker in benchmarks)
        cache.process_cleanup_jobs()
        return recycled

    def pod_lister(self, ns: str, name: str) -> Optional[Pod]:
        """O(1) ground-truth lookup for the resync repair loop (every
        err_tasks retry calls this; a linear scan walks 10k pods at the
        stress config)."""
        index = self._pod_index
        if index is None or len(index) != len(self.pods):
            index = {(p.namespace, p.name): p for p in self.pods}
            self._pod_index = index
        return index.get((ns, name))


def build_cluster(spec: ClusterSpec) -> SimCluster:
    rng = np.random.default_rng(spec.seed)
    sim = SimCluster(spec)

    n_queues = max(1, spec.n_queues)
    weights = (spec.queue_weights if spec.queue_weights
               else tuple([1] * n_queues))
    for i in range(n_queues):
        sim.queues.append(Queue(name=f"q{i + 1}", weight=weights[i]))
    for name, value in spec.priority_classes:
        sim.priority_classes.append(PriorityClass(name=name, value=value))

    def _jit(v: float) -> float:
        if spec.jitter <= 0:
            return v
        return float(v * (1.0 + rng.uniform(-spec.jitter, spec.jitter)))

    rich = spec.predicate_rich
    n_zones = max(1, spec.n_zones) if rich else 0
    for i in range(spec.n_nodes):
        alloc = resource_list(cpu=_jit(spec.node_cpu_millis),
                              memory=_jit(spec.node_mem_bytes),
                              pods=spec.node_pods)
        name = f"node-{i:05d}"
        labels = {}
        taints = []
        if rich:
            labels = {"kubernetes.io/hostname": name,
                      "zone": f"z{i % n_zones}"}
            if spec.taint_frac > 0 and rng.random() < spec.taint_frac:
                taints = [Taint(key="dedicated", value="batch",
                                effect=TaintEffect.NO_SCHEDULE)]
        sim.nodes.append(Node(name=name, allocatable=alloc, labels=labels,
                              taints=taints))

    pc_names = [name for name, _ in spec.priority_classes]
    min_member = (spec.min_member if spec.min_member is not None
                  else spec.pods_per_group)
    for g in range(spec.n_groups):
        queue = sim.queues[g % n_queues].name
        pg = PodGroup(name=f"job-{g:05d}", namespace="sim",
                      min_member=min_member, queue=queue,
                      creation_timestamp=float(g))
        if pc_names:
            pg.priority_class_name = pc_names[g % len(pc_names)]
        sim.groups.append(pg)
        shape = group_shape(spec, rng, g) if rich else None
        for p in range(spec.pods_per_group):
            pod = Pod(
                name=f"job-{g:05d}-{p:03d}", namespace="sim",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[Container(requests=resource_list(
                    cpu=_jit(spec.pod_cpu_millis),
                    memory=_jit(spec.pod_mem_bytes)))],
                creation_timestamp=float(g * 10000 + p))
            if shape is not None:
                shape.apply(pod)
            sim.pods.append(pod)

    # pre-fill part of the cluster with running pods (for preempt/reclaim
    # scenarios): round-robin placement until the fill fraction is reached,
    # skipping nodes whose remaining capacity can't hold another fill pod
    # (a real cluster never runs pods past allocatable)
    if spec.running_fill > 0:
        budget = spec.running_fill * spec.n_nodes * spec.node_cpu_millis
        cpu_room = [n.allocatable.get("cpu", spec.node_cpu_millis)
                    for n in sim.nodes]
        mem_room = [n.allocatable.get("memory", spec.node_mem_bytes)
                    for n in sim.nodes]
        pod_room = [n.allocatable.get("pods", spec.node_pods)
                    for n in sim.nodes]
        used = 0.0
        i = 0
        misses = 0
        while used + spec.pod_cpu_millis <= budget \
                and misses < spec.n_nodes:
            k = i % spec.n_nodes
            if (cpu_room[k] < spec.pod_cpu_millis
                    or mem_room[k] < spec.pod_mem_bytes
                    or pod_room[k] < 1):
                misses += 1
                i += 1
                continue
            misses = 0
            cpu_room[k] -= spec.pod_cpu_millis
            mem_room[k] -= spec.pod_mem_bytes
            pod_room[k] -= 1
            node = sim.nodes[k]
            pg_name = f"fill-{i:05d}"
            sim.groups.append(PodGroup(
                name=pg_name, namespace="sim", min_member=1,
                queue=sim.queues[i % n_queues].name,
                creation_timestamp=-1.0))
            sim.pods.append(Pod(
                name=f"fill-{i:05d}", namespace="sim",
                node_name=node.name, phase=PodPhase.RUNNING,
                annotations={GROUP_NAME_ANNOTATION: pg_name},
                containers=[Container(requests=resource_list(
                    cpu=spec.pod_cpu_millis,
                    memory=spec.pod_mem_bytes))]))
            used += spec.pod_cpu_millis
            i += 1
    return sim


#: BASELINE.md benchmark configs (sect. "Metrics to measure")
BASELINE_SPECS: Dict[int, ClusterSpec] = {
    1: ClusterSpec(n_nodes=1, node_cpu_millis=8000, node_mem_bytes=16 * GiB,
                   n_groups=1, pods_per_group=3, pod_cpu_millis=1000,
                   pod_mem_bytes=GiB),
    2: ClusterSpec(n_nodes=50, n_groups=100, pods_per_group=8),
    3: ClusterSpec(n_nodes=500, n_groups=1000, pods_per_group=4,
                   n_queues=4, queue_weights=(1, 2, 3, 4),
                   pod_cpu_millis=800, pod_mem_bytes=GiB),
    4: ClusterSpec(n_nodes=2000, n_groups=625, pods_per_group=8,
                   min_member=4, running_fill=0.6,
                   priority_classes=(("low", 10), ("mid", 100),
                                     ("high", 1000)),
                   pod_cpu_millis=1000, pod_mem_bytes=2 * GiB),
    5: ClusterSpec(n_nodes=5000, n_groups=1250, pods_per_group=8,
                   n_queues=4, queue_weights=(1, 2, 3, 4),
                   pod_cpu_millis=1000, pod_mem_bytes=2 * GiB,
                   jitter=0.2),
    # --- the order-of-magnitude scale axis (ROADMAP item 2): cluster
    # sizes where no flat engine materializes [T, N] inside the HBM
    # budget — auto mode dispatches the two-level solve (kernels/hier.py)
    # with narrowed intermediates (kernels/narrow.py). Allocate-only on
    # purpose: these configs pin the SOLVER scale axis; the 4-action
    # stack at this scale rides the scenario item. jitter=0 keeps the
    # downsampled host-oracle equality check exact (bench.py). ---------
    6: ClusterSpec(n_nodes=50000, n_groups=6250, pods_per_group=8,
                   n_queues=4, queue_weights=(1, 2, 3, 4),
                   pod_cpu_millis=1000, pod_mem_bytes=2 * GiB),
    7: ClusterSpec(n_nodes=100000, n_groups=13000, pods_per_group=8,
                   n_queues=4, queue_weights=(1, 2, 3, 4),
                   pod_cpu_millis=1000, pod_mem_bytes=2 * GiB),
}

#: predicate-rich variants (VERDICT r4 directive 3): same scale as the
#: base configs, with node labels/taints, selectors, tolerations, both
#: affinity kinds, preferred co-location scores, and host ports at
#: real-workload-ish fractions. "2p"/"3p"/"5p" on the bench CLI.
BASELINE_SPECS["2p"] = ClusterSpec(
    n_nodes=50, n_groups=100, pods_per_group=8,
    n_zones=4, selector_frac=0.15, taint_frac=0.1, toleration_frac=0.15,
    anti_affinity_frac=0.08, zone_affinity_frac=0.06,
    pref_affinity_frac=0.08, hostport_frac=0.05)
BASELINE_SPECS["3p"] = ClusterSpec(
    n_nodes=500, n_groups=1000, pods_per_group=4,
    n_queues=4, queue_weights=(1, 2, 3, 4),
    pod_cpu_millis=800, pod_mem_bytes=GiB,
    n_zones=8, selector_frac=0.15, taint_frac=0.1, toleration_frac=0.15,
    anti_affinity_frac=0.08, zone_affinity_frac=0.05,
    pref_affinity_frac=0.08, hostport_frac=0.04)
#: the multi-tenant per-cluster spec (ISSUE 8, tenantsvc): one tenant's
#: simulated cluster in the shared-sidecar mix. Deliberately BELOW the
#: batched threshold in both cold and steady regimes (32 pods pending)
#: so every tenant solve takes the fused branch — the mega-coalescible
#: shape class the cross-tenant dispatcher batches. The per-tenant
#: variation in the mix is the SEED (tenant index), which changes
#: resource numerics but not shapes — exactly the condition for lanes
#: to share one compile signature.
BASELINE_SPECS["t"] = ClusterSpec(
    n_nodes=12, n_groups=16, pods_per_group=2,
    n_queues=2, queue_weights=(1, 3),
    pod_cpu_millis=900, pod_mem_bytes=GiB)

BASELINE_SPECS["5p"] = ClusterSpec(
    n_nodes=5000, n_groups=1250, pods_per_group=8,
    n_queues=4, queue_weights=(1, 2, 3, 4),
    pod_cpu_millis=1000, pod_mem_bytes=2 * GiB, jitter=0.2,
    n_zones=16, selector_frac=0.15, taint_frac=0.1, toleration_frac=0.15,
    anti_affinity_frac=0.05, zone_affinity_frac=0.03,
    pref_affinity_frac=0.05, hostport_frac=0.02)


def baseline_cluster(config) -> SimCluster:
    return build_cluster(BASELINE_SPECS[config])
