"""Synthetic cluster generation + simulated e2e harness."""
from .cluster import (BASELINE_SPECS, ClusterSpec, SimCluster,
                      baseline_cluster, build_cluster)
from .source import (FlakyBinder, FlakyEvictor, PersistentVolume,
                     PersistentVolumeClaim, PVVolumeBinder, StorageClass,
                     StreamingEventSource)
from .tenants import run_multi_tenant, run_saturation  # noqa: F401

__all__ = ["BASELINE_SPECS", "ClusterSpec", "SimCluster", "baseline_cluster",
           "build_cluster", "FlakyBinder", "FlakyEvictor",
           "PersistentVolume", "PersistentVolumeClaim", "PVVolumeBinder",
           "StorageClass", "StreamingEventSource", "run_multi_tenant",
           "run_saturation"]
