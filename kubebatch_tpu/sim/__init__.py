"""Synthetic cluster generation + simulated e2e harness."""
from .cluster import (BASELINE_SPECS, ClusterSpec, SimCluster,
                      baseline_cluster, build_cluster)

__all__ = ["BASELINE_SPECS", "ClusterSpec", "SimCluster", "baseline_cluster",
           "build_cluster"]
