"""ctypes bindings for the native runtime library (native/kb_native.cpp).

Builds the shared library on first use (g++ via native/Makefile) and
exposes the per-visit allocate solver over packed numpy arrays — the
native HOST backend (allocate mode "native") and the large-scale
differential oracle for the JAX kernels. Falls back gracefully when no
compiler is available (KUBEBATCH_NATIVE=0 disables explicitly).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .api import NodeInfo
from .util import env_on
from .kernels.solver import ALLOC, ALLOC_OB, FAIL, PIPELINE, Decision
from .kernels.tensorize import NodeState, TaskBatch

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "kb_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if not env_on("KUBEBATCH_NATIVE"):
        _load_failed = True
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                               check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.kb_abi_version.restype = ctypes.c_int32
            if lib.kb_abi_version() != 1:
                raise OSError("kb_native ABI mismatch")
            lib.kb_pack_resources.argtypes = [_f64p, ctypes.c_int64, _f32p]
            lib.kb_solve_job.restype = ctypes.c_int32
            lib.kb_solve_job.argtypes = [
                _f32p, _f32p, _f32p, _i32p, _i32p, _u8p, ctypes.c_int64,
                _f32p, _f32p, _u8p, ctypes.c_int64, _f32p, _u8p,
                ctypes.c_int32, ctypes.c_int32, _i32p, _i32p]
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def native_available() -> bool:
    return load_native() is not None


class NativeSession:
    """Per-session native node state — the host-backend twin of
    kernels.solver.DeviceSession (same solve_job contract)."""

    def __init__(self, nodes: Dict[str, NodeInfo], min_bucket: int = 8):
        lib = load_native()
        if lib is None:
            raise RuntimeError("kb_native library unavailable")
        self._lib = lib
        self.state = NodeState.from_nodes(nodes, min_bucket)
        self.idle = np.ascontiguousarray(self.state.idle)
        self.releasing = np.ascontiguousarray(self.state.releasing)
        self.backfilled = np.ascontiguousarray(self.state.backfilled)
        self.max_task_num = np.ascontiguousarray(self.state.max_task_num)
        self.n_tasks = np.ascontiguousarray(self.state.n_tasks)
        self.node_ok = np.ascontiguousarray(
            (self.state.schedulable & self.state.valid).astype(np.uint8))

    @property
    def n_padded(self) -> int:
        return self.state.n_padded

    def node_name(self, idx: int) -> str:
        return self.state.names[idx]

    def node_index(self, name: str) -> Optional[int]:
        return self.state.index.get(name)

    def resync(self, nodes: Dict[str, NodeInfo]) -> None:
        fresh = NativeSession(nodes, min_bucket=self.n_padded)
        self.__dict__.update(fresh.__dict__)

    def solve_job(self, batch: TaskBatch, min_available: int,
                  init_allocated: int,
                  scores: Optional[np.ndarray] = None,
                  pred_mask: Optional[np.ndarray] = None,
                  dyn=None) -> Tuple[List[Decision], bool]:
        # the native solver has no dynamic-score support; the action only
        # routes here when no node-order callback is registered (dyn None)
        t_pad, n_pad = batch.t_padded, self.n_padded
        if scores is None:
            scores = np.zeros((t_pad, n_pad), np.float32)
        if pred_mask is None:
            pred_mask = np.ones((t_pad, n_pad), bool)
        decisions = np.zeros(t_pad, np.int32)
        node_idx = np.zeros(t_pad, np.int32)
        ready = self._lib.kb_solve_job(
            self.idle, self.releasing, self.backfilled, self.max_task_num,
            self.n_tasks, self.node_ok, n_pad,
            np.ascontiguousarray(batch.resreq),
            np.ascontiguousarray(batch.init_resreq),
            np.ascontiguousarray(batch.valid.astype(np.uint8)), t_pad,
            np.ascontiguousarray(scores.astype(np.float32)),
            np.ascontiguousarray(pred_mask.astype(np.uint8)),
            np.int32(min_available), np.int32(init_allocated),
            decisions, node_idx)
        out: List[Decision] = []
        for i in range(len(batch.tasks)):
            kind = int(decisions[i])
            name = (self.state.names[int(node_idx[i])]
                    if kind in (ALLOC, ALLOC_OB, PIPELINE) else "")
            out.append(Decision(kind, name))
        return out, bool(ready)
