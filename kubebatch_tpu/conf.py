"""Scheduler policy configuration schema.

ref: pkg/scheduler/conf/scheduler_conf.go. YAML layout is identical to the
reference's (`actions` string + `tiers` of plugins with per-plugin disable
flags and free-form string arguments) so existing kube-batch config files
parse unchanged.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class PluginOption:
    """ref: scheduler_conf.go:210-231."""
    name: str
    job_order_disabled: bool = False
    job_ready_disabled: bool = False
    task_order_disabled: bool = False
    preemptable_disabled: bool = False
    reclaimable_disabled: bool = False
    queue_order_disabled: bool = False
    predicate_disabled: bool = False
    node_order_disabled: bool = False
    arguments: Dict[str, str] = field(default_factory=dict)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class SchedulerConfiguration:
    actions: str = ""
    tiers: List[Tier] = field(default_factory=list)


_YAML_FLAG_KEYS = {
    "disableJobOrder": "job_order_disabled",
    "disableJobReady": "job_ready_disabled",
    "disableTaskOrder": "task_order_disabled",
    "disablePreemptable": "preemptable_disabled",
    "disableReclaimable": "reclaimable_disabled",
    "disableQueueOrder": "queue_order_disabled",
    "disablePredicate": "predicate_disabled",
    "disableNodeOrder": "node_order_disabled",
}


def parse_scheduler_conf(conf_str: str) -> SchedulerConfiguration:
    """Parse the reference-compatible YAML policy file."""
    import yaml

    raw = yaml.safe_load(conf_str) or {}
    tiers: List[Tier] = []
    for tier_raw in raw.get("tiers") or []:
        plugins: List[PluginOption] = []
        for p in tier_raw.get("plugins") or []:
            opt = PluginOption(name=p["name"])
            for yaml_key, attr in _YAML_FLAG_KEYS.items():
                if yaml_key in p:
                    setattr(opt, attr, bool(p[yaml_key]))
            opt.arguments = {str(k): str(v)
                             for k, v in (p.get("arguments") or {}).items()}
            plugins.append(opt)
        tiers.append(Tier(plugins=plugins))
    return SchedulerConfiguration(actions=raw.get("actions", ""), tiers=tiers)


#: the shipped policy (config/kube-batch-conf.yaml, mirroring the
#: reference's config file): actions + the two-tier plugin stack
SHIPPED_CONF = """
actions: "reclaim, allocate, backfill, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def shipped_tiers() -> List[Tier]:
    """The shipped two-tier plugin stack as parsed Tier objects — the
    single construction point benches, the multichip dryrun, and the
    equivalence suites share."""
    return parse_scheduler_conf(SHIPPED_CONF).tiers


#: per-config action order (BASELINE.md scenarios; cfg4/cfg5 use the
#: shipped config/kube-batch-conf.yaml order). "2p"/"3p"/"5p" are the
#: predicate-rich variants. ONE definition shared by bench.py and
#: compilesvc/profile.py — the registered compile surface must describe
#: the same cycles the bench drives.
CONFIG_ACTIONS = {
    1: ("allocate",),
    2: ("allocate",),
    3: ("allocate", "backfill"),
    4: ("reclaim", "allocate", "backfill", "preempt"),
    5: ("reclaim", "allocate", "backfill", "preempt"),
    # cfg6/cfg7 (50k / 100k nodes, ROADMAP item 2): allocate-only — the
    # scale axis pins the SOLVER (two-level hier engine); the 4-action
    # stack at this scale rides the scenario item (ROADMAP item 5)
    6: ("allocate",),
    7: ("allocate",),
    "2p": ("allocate",),
    "3p": ("allocate", "backfill"),
    "5p": ("reclaim", "allocate", "backfill", "preempt"),
    # "t": the per-tenant cluster of the multi-tenant sidecar mix
    # (ISSUE 8) — sized so its steady cycles stay BELOW the batched
    # threshold, i.e. the fused/mega-coalescible regime the tenantsvc
    # dispatcher batches across tenants
    "t": ("allocate",),
}
