"""reclaim — cross-queue resource recovery toward weighted fair share.

ref: pkg/scheduler/actions/reclaim/reclaim.go. Victims are Running tasks
of jobs in OTHER queues; evictions go straight through the session (no
Statement — reclaim.go:159-173); the reclaimer is pipelined onto the node
once enough resource is being released.

Two engines share the identical outer control flow (see actions/preempt.py
for the same split): the device path analyses a whole node visit — nodes
in host iteration order, tiered gang/conformance/proportion victim masks —
in one kernel dispatch (kernels/victims.py) and replays the chosen node's
eviction walk through ssn.evict in float64; nodes where proportion's
sequential skip-guard trips are handed to the exact host block.
KUBEBATCH_VICTIM_SOLVER=host forces the reference-literal loops.
"""
from __future__ import annotations

from typing import Dict

from ..api import Resource, TaskStatus
from ..framework import Action, Session, register_action
from ..util import PriorityQueue
from .preempt import validate_victims


class ReclaimAction(Action):
    @property
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn: Session) -> None:
        # cross-queue reclaim needs at least two distinct queues; with
        # one, no task can ever be a victim (the filter requires a
        # DIFFERENT queue) — observably a no-op, skipped before paying
        # the solver build. Session jobs' queues are always a subset of
        # ssn.queues (the snapshot drops jobs with missing queues,
        # cache.py snapshot), so the queue map alone decides.
        if len(ssn.queues) <= 1:
            return

        from ..kernels.victims import SKIP_ACTION, build_action_solver
        solver = build_action_solver(ssn, "reclaimable_fns",
                                     "reclaimable_disabled",
                                     score_nodes=False)
        if solver is SKIP_ACTION:
            return

        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map = {}
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)
            if job.count(TaskStatus.PENDING) != 0:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index.get(TaskStatus.PENDING,
                                                      {}).values():
                    tasks.push(task)
                preemptor_tasks[job.uid] = tasks

        if solver is not None:
            # the first visit per queue is knowable up front (top task of
            # the top job); one prefetch wave answers the whole steady
            # cycle's reclaim visits in a single kernel dispatch
            tops = []
            for quid, jobs_pq in preemptors_map.items():
                q = queue_map.get(quid)
                if q is None or ssn.overused(q):
                    continue
                top_job = jobs_pq.peek()
                if top_job is None:
                    continue
                tq = preemptor_tasks.get(top_job.uid)
                top_task = tq.peek() if tq is not None else None
                if top_task is not None:
                    tops.append(top_task)
            solver.prefetch(tops, "other_queue")

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            if solver is not None:
                assigned = self._reclaim_one_device(ssn, solver, task, job)
            else:
                assigned = self._reclaim_one_host(ssn, task, job)

            if assigned:
                queues.push(queue)

    # ------------------------------------------------------------------
    # host path — the reference algorithm verbatim (the oracle)
    # ------------------------------------------------------------------
    def _reclaim_one_host(self, ssn: Session, task, job) -> bool:
        for node in ssn.nodes.values():
            try:
                ssn.predicate_fn(task, node)
            except Exception:
                continue

            reclaimees = []
            for t in node.tasks.values():
                if t.status != TaskStatus.RUNNING:
                    continue
                j = ssn.jobs.get(t.job)
                if j is not None and j.queue != job.queue:
                    # clone so session status flips don't corrupt the
                    # node's accounting (reclaim.go:137)
                    reclaimees.append(t.clone())
            victims = ssn.reclaimable(task, reclaimees)
            if not validate_victims(victims, task.init_resreq):
                continue

            if self._evict_walk(ssn, task, victims, None):
                ssn.pipeline(task, node.name)
                return True
        return False

    # ------------------------------------------------------------------
    # device path
    # ------------------------------------------------------------------
    def _reclaim_one_device(self, ssn: Session, solver, task, job) -> bool:
        import numpy as np

        state = solver.state
        visited = np.zeros(state.n_pad, bool)
        while True:
            res = solver.visit(task, "other_queue", visited)
            if not res.found:
                return False
            node = ssn.nodes.get(res.node_name)
            if node is None:  # pragma: no cover — names come from the snapshot
                return False

            if res.prop_guard:
                # proportion's skip-guard tripped: victim set for this node
                # is sequential-only — evaluate the node with the exact
                # host block (real plugin callbacks)
                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is not None and j.queue != job.queue:
                        reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if not validate_victims(victims, task.init_resreq):
                    visited[res.node_idx] = True
                    continue
                covered = self._evict_walk(ssn, task, victims, state)
            else:
                victims = [state.victims[row].task.clone()
                           for row in res.victim_rows]
                covered = self._evict_walk(ssn, task, victims, state)

            if covered:
                ssn.pipeline(task, res.node_name)
                state.apply_pipeline(task, res.node_idx)
                return True
            visited[res.node_idx] = True   # evictions stand; state changed

    # ------------------------------------------------------------------
    def _evict_walk(self, ssn: Session, task, victims, state) -> bool:
        """The reference's cumulative eviction loop (reclaim.go:159-176):
        evict victims in candidate order until the remaining request fits
        inside the current victim; a failed evict is skipped without
        advancing the cumulative bookkeeping. Mirrors (device path) track
        successful evictions only."""
        resreq = task.init_resreq.clone()
        reclaimed = Resource.empty()
        for reclaimee in victims:
            try:
                ssn.evict(reclaimee, "reclaim")
            except Exception:
                continue
            if state is not None:
                row = state.row_of.get(reclaimee.uid)
                if row is not None:
                    state.apply_evict(row)
            reclaimed.add(reclaimee.resreq)
            if resreq.less_equal(reclaimee.resreq):
                break
            resreq.sub(reclaimee.resreq)
        return task.init_resreq.less_equal(reclaimed)


def new() -> ReclaimAction:
    return ReclaimAction()


register_action(ReclaimAction())
