"""reclaim — cross-queue resource recovery toward weighted fair share.

ref: pkg/scheduler/actions/reclaim/reclaim.go. Victims are Running tasks
of jobs in OTHER queues; evictions go straight through the session (no
Statement — reclaim.go:159-173); the reclaimer is pipelined onto the node
once enough resource is being released.

Two engines share the identical outer control flow (see actions/preempt.py
for the same split): the device path analyses a whole node visit — nodes
in host iteration order, tiered gang/conformance/proportion victim masks —
in one kernel dispatch (kernels/victims.py) and replays the chosen node's
eviction walk through ssn.evict in float64; nodes where proportion's
sequential skip-guard trips are handed to the exact host block.
KUBEBATCH_VICTIM_SOLVER=host forces the reference-literal loops.
KUBEBATCH_RECLAIM_FASTPATH=0 disables the provably-idle gates (both
engines then always pay the full evaluation — the debug/equivalence
mode the fastpath fuzz test runs against).
"""
from __future__ import annotations

from typing import Dict

from ..api import Resource, TaskStatus
from ..framework import Action, Session, register_action
from ..util import PriorityQueue, env_on
from .preempt import validate_victims

#: reclaimable fns whose "could any victim pass?" question has a cheap
#: whole-session over-approximation below; an unknown owner in a tier
#: makes that tier unprovable and disables the skip
_PROVABLE_RECLAIM_FNS = frozenset({"gang", "conformance", "proportion"})


def _no_possible_reclaim_victim(ssn: Session) -> bool:
    """True when the tiered Reclaimable evaluation provably yields no
    victim for ANY (reclaimer, reclaimees) call this session — the
    saturated steady regime, where every gang is exactly at quorum and
    every queue at/below its deserved share.

    Soundness: a tier's intersection is non-empty only if SOME victim is
    allowed by EVERY member fn (session_plugins.go:67-106). Each member
    check below over-approximates "this fn could allow at least one
    victim" (conformance, which can only subtract critical pods, is
    taken as always-possible), so `not possible` for every tier implies
    the real evaluation returns nil everywhere and the action's node
    loop can never evict or pipeline. Member semantics matched:
    gang.go:108-129 (stays >= MinAvailable after losing one, or the
    MinAvailable==1 quirk), proportion.go:159-184 (queue stays at/above
    deserved after losing the victim — impossible when allocated is
    already below deserved, victim resreq >= 0)."""
    possible_memo: Dict[str, bool] = {}

    def member_possible(name: str) -> bool:
        got = possible_memo.get(name)
        if got is not None:
            return got
        if name == "gang":
            from ..plugins.gang import can_lose_one
            ok = any(can_lose_one(job) for job in ssn.jobs.values()
                     if TaskStatus.RUNNING in job.task_status_index)
        elif name == "proportion":
            prop = ssn.plugins.get("proportion")
            # plugin state missing while its fn is registered: can't
            # reason about it — treat as possible (no skip). The floor
            # itself lives WITH the plugin (could_allow_any_victim is
            # documented against reclaimable_fn in proportion.py) so the
            # two evolve together.
            ok = (prop is None
                  or not hasattr(prop, "could_allow_any_victim")
                  or prop.could_allow_any_victim())
        else:           # conformance: only ever subtracts critical pods
            ok = True
        possible_memo[name] = ok
        return ok

    fns = ssn.reclaimable_fns
    # cost-ordered evaluation: a tier fires (-> return False) only when
    # ALL its members are possible, and ANY firing tier decides — so
    # check cheap members (conformance: constant; proportion: O(queues))
    # before gang's O(jobs) scan, and cheap tiers before expensive ones.
    # Pure reordering of short-circuit evaluation, same result.
    cost = {"conformance": 0, "proportion": 1, "gang": 2}
    tiers = []
    for tier in ssn.tiers:
        members = [opt.name for opt in tier.plugins
                   if not opt.reclaimable_disabled and opt.name in fns]
        if not members:
            continue
        if any(m not in _PROVABLE_RECLAIM_FNS for m in members):
            return False
        members.sort(key=lambda m: cost[m])
        tiers.append(members)
    tiers.sort(key=lambda ms: cost[ms[-1]])
    for members in tiers:
        if all(member_possible(m) for m in members):
            return False
    return True


class ReclaimAction(Action):
    @property
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn: Session) -> None:
        # cross-queue reclaim needs at least two distinct queues; with
        # one, no task can ever be a victim (the filter requires a
        # DIFFERENT queue) — observably a no-op, skipped before paying
        # the solver build. Session jobs' queues are always a subset of
        # ssn.queues (the snapshot drops jobs with missing queues,
        # cache.py snapshot), so the queue map alone decides.
        if len(ssn.queues) <= 1:
            return

        # ONE walk over the job map feeds everything below (the gate's
        # queue membership, the solver's pending set, the preemptor PQs)
        # — this setup used to walk 10k jobs four separate times per
        # cycle in the victim-hot steady regime
        jobs_pending = [job for job in ssn.jobs.values()
                        if TaskStatus.PENDING in job.task_status_index]

        # Provably-idle fast path: the reference loop pops each queue and
        # skips it when ssn.Overused(queue) (reclaim.go:95-99) — if EVERY
        # queue holding pending work is overused up front, the loop ends
        # without a single visit or mutation, because skipped queues are
        # never re-pushed and nothing else in the loop body runs. In the
        # saturated steady regime proportion marks every queue overused
        # (allocated == deserved, proportion.go:186-200), so this cheap
        # membership check replaces the full solver build + wave analysis
        # the cycle would spend proving the no-op. Evaluating before the
        # loop is exact: overused_fns are pure reads of plugin state, and
        # the all-overused case performs no mutation that could change a
        # later answer. Queues absent from the session can't reclaim
        # (their jobs never enter preemptorsMap) and don't count.
        if env_on("KUBEBATCH_RECLAIM_FASTPATH"):
            pending_queues = {job.queue for job in jobs_pending}
            reclaimer_queues = [q for quid in pending_queues
                                if (q := ssn.queues.get(quid)) is not None]
            if all(ssn.overused(q) for q in reclaimer_queues):
                return

            # Second provably-idle gate, one level deeper: even with
            # eligible reclaimer queues, the node loop can only act if
            # SOME victim passes the tiered Reclaimable evaluation. In
            # the steady regime every gang sits exactly at quorum (tier
            # 1 nil by gang's stays-at-MinAvailable rule) and pending
            # demand holds deserved above allocated for the reclaimer
            # queues while victims' queues sit below (tier 2 nil by
            # proportion's floor) — the whole action is a no-op that
            # used to cost the full solver build + a wave dispatch per
            # cycle to discover.
            if _no_possible_reclaim_victim(ssn):
                return

        from ..kernels.victims import SKIP_ACTION, build_action_solver
        pending_tasks = [t for job in jobs_pending
                         for t in job.task_status_index[
                             TaskStatus.PENDING].values()]
        solver = build_action_solver(ssn, "reclaimable_fns",
                                     "reclaimable_disabled",
                                     score_nodes=False,
                                     pending=pending_tasks)
        if solver is SKIP_ACTION:
            return

        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map = {}
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        # only queues holding PENDING jobs enter the PQ: the reference
        # builds its PQ from all jobs' queues (reclaim.go:88-99), but a
        # pop without preemptors mutates nothing, so restricting to the
        # pending set is outcome-identical without the O(jobs) walk.
        # Queues of jobless/pending-less sessions must NOT be pushed —
        # proportion's queue_order_fn indexes queue_opts, which only
        # holds queues that have jobs.
        for job in jobs_pending:
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)
            preemptors_map.setdefault(
                job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
            tasks = PriorityQueue(ssn.task_order_fn)
            for task in job.task_status_index.get(TaskStatus.PENDING,
                                                  {}).values():
                tasks.push(task)
            preemptor_tasks[job.uid] = tasks

        if solver is not None:
            # the first visit per queue is knowable up front (top task of
            # the top job); one prefetch wave answers the whole steady
            # cycle's reclaim visits in a single kernel dispatch
            tops = []
            for quid, jobs_pq in preemptors_map.items():
                q = queue_map.get(quid)
                if q is None or ssn.overused(q):
                    continue
                top_job = jobs_pq.peek()
                if top_job is None:
                    continue
                tq = preemptor_tasks.get(top_job.uid)
                top_task = tq.peek() if tq is not None else None
                if top_task is not None:
                    tops.append(top_task)
            solver.prefetch(tops, "other_queue")

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            if solver is not None:
                assigned = self._reclaim_one_device(ssn, solver, task, job)
            else:
                assigned = self._reclaim_one_host(ssn, task, job)

            if assigned:
                queues.push(queue)

    # ------------------------------------------------------------------
    # host path — the reference algorithm verbatim (the oracle)
    # ------------------------------------------------------------------
    def _reclaim_one_host(self, ssn: Session, task, job) -> bool:
        for node in ssn.nodes.values():
            try:
                ssn.predicate_fn(task, node)
            except Exception:
                continue

            reclaimees = []
            for t in node.tasks.values():
                if t.status != TaskStatus.RUNNING:
                    continue
                j = ssn.jobs.get(t.job)
                if j is not None and j.queue != job.queue:
                    # clone so session status flips don't corrupt the
                    # node's accounting (reclaim.go:137)
                    reclaimees.append(t.clone())
            victims = ssn.reclaimable(task, reclaimees)
            if not validate_victims(victims, task.init_resreq):
                continue

            if self._evict_walk(ssn, task, victims, None):
                ssn.pipeline(task, node.name)
                return True
        return False

    # ------------------------------------------------------------------
    # device path
    # ------------------------------------------------------------------
    def _reclaim_one_device(self, ssn: Session, solver, task, job) -> bool:
        import numpy as np

        state = solver.state
        visited = np.zeros(state.n_pad, bool)
        while True:
            res = solver.visit(task, "other_queue", visited)
            if not res.found:
                return False
            node = ssn.nodes.get(res.node_name)
            if node is None:  # pragma: no cover — names come from the snapshot
                return False

            if res.prop_guard:
                # proportion's skip-guard tripped: victim set for this node
                # is sequential-only — evaluate the node with the exact
                # host block (real plugin callbacks)
                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is not None and j.queue != job.queue:
                        reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if not validate_victims(victims, task.init_resreq):
                    visited[res.node_idx] = True
                    continue
                covered = self._evict_walk(ssn, task, victims, state)
            else:
                victims = [state.victims[row].task.clone()
                           for row in res.victim_rows]
                covered = self._evict_walk(ssn, task, victims, state)

            if covered:
                ssn.pipeline(task, res.node_name)
                state.apply_pipeline(task, res.node_idx)
                return True
            visited[res.node_idx] = True   # evictions stand; state changed

    # ------------------------------------------------------------------
    def _evict_walk(self, ssn: Session, task, victims, state) -> bool:
        """The reference's cumulative eviction loop (reclaim.go:159-176):
        evict victims in candidate order until the remaining request fits
        inside the current victim; a failed evict is skipped without
        advancing the cumulative bookkeeping. Mirrors (device path) track
        successful evictions only."""
        resreq = task.init_resreq.clone()
        reclaimed = Resource.empty()
        for reclaimee in victims:
            try:
                ssn.evict(reclaimee, "reclaim")
            except Exception:
                continue
            if state is not None:
                row = state.row_of.get(reclaimee.uid)
                if row is not None:
                    state.apply_evict(row)
            reclaimed.add(reclaimee.resreq)
            if resreq.less_equal(reclaimee.resreq):
                break
            resreq.sub(reclaimee.resreq)
        return task.init_resreq.less_equal(reclaimed)


def new() -> ReclaimAction:
    return ReclaimAction()


register_action(ReclaimAction())
