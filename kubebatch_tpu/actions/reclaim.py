"""reclaim — cross-queue resource recovery toward weighted fair share.

ref: pkg/scheduler/actions/reclaim/reclaim.go. Victims are Running tasks
of jobs in OTHER queues; evictions go straight through the session (no
Statement — reclaim.go:159-173); the reclaimer is pipelined onto the node
once enough resource is being released.
"""
from __future__ import annotations

from typing import Dict

from ..api import Resource, TaskStatus
from ..framework import Action, Session, register_action
from ..util import PriorityQueue
from .preempt import validate_victims


class ReclaimAction(Action):
    @property
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn: Session) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map = {}
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)
            if job.count(TaskStatus.PENDING) != 0:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index.get(TaskStatus.PENDING,
                                                      {}).values():
                    tasks.push(task)
                preemptor_tasks[job.uid] = tasks

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            for node in ssn.nodes.values():
                try:
                    ssn.predicate_fn(task, node)
                except Exception:
                    continue

                resreq = task.init_resreq.clone()
                reclaimed = Resource.empty()
                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is not None and j.queue != job.queue:
                        # clone so session status flips don't corrupt the
                        # node's accounting (reclaim.go:137)
                        reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if not validate_victims(victims, resreq):
                    continue

                for reclaimee in victims:
                    try:
                        ssn.evict(reclaimee, "reclaim")
                    except Exception:
                        continue
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimee.resreq):
                        break
                    resreq.sub(reclaimee.resreq)

                if task.init_resreq.less_equal(reclaimed):
                    ssn.pipeline(task, node.name)
                    assigned = True
                    break

            if assigned:
                queues.push(queue)


def new() -> ReclaimAction:
    return ReclaimAction()


register_action(ReclaimAction())
