"""backfill — fit small/BestEffort work into holes, lend reserved-but-
idle capacity, and reclaim it when the owed gang completes its quorum.

ref: pkg/scheduler/actions/backfill/backfill.go. Three layers:

1. Active reference behavior (backfill.go:45-70): every Pending task with
   an EMPTY launch request (BestEffort) is allocated to the first
   predicate-passing node.
2. The fork's "backfill over reserved resources" (backfill.go:72-147,
   commented out upstream with live helpers): jobs whose tasks are ALL
   pending (BackFillEligible via gang) are backfilled onto idle
   resources with IsBackfill=true, after unready "top dog" jobs release
   their session-reserved Allocated/AllocatedOverBackfill resources.
3. The completion of the fork's half-built state machine (ISSUE 19):

   - **over-reserve**: a gang that cannot reach its quorum on idle
     capacity places its remaining min-quorum tasks over
     ``node.accessible()`` (idle + lent ``backfilled``) as
     ``ALLOCATED_OVER_BACKFILL`` — the gang becomes AlmostReady, and
     the reservation is session-only (released at action end, never
     written back).
   - **reclaim**: per AlmostReady gang, a Statement transaction evicts
     the backfill tenants on the hosting nodes, promotes the
     over-backfill placements to Allocated, and commits + dispatches
     iff the gang reaches Ready — tenants are evicted atomically with
     the gang's promotion, or not at all (discard restores them).
     Reclaim evictions are counted in their own ledger
     (backfill_tenants_evicted_total), NOT as preemptions.

   Guard counters (normally zero; tools/bench_regression.py hard-pins
   them on trace soak lines): ``backfill_double_binds_total`` — a task
   reached dispatch in a state other than Allocated, or a promotion
   target was no longer over-backfill; ``lost_reservations_total`` — an
   over-backfill placement survived the end-of-action release sweep.

Enabled with KUBEBATCH_RESERVED_BACKFILL=1 or BackfillAction(
reserved=True); off by default, matching the shipped binary.
"""
from __future__ import annotations

import os
from typing import Optional

from ..api import JobInfo, TaskStatus
from ..framework import (Action, Session, VolumeAllocationError,
                         register_action)
from ..objects import BACKFILL_ANNOTATION
from ..metrics import (count_backfill_double_bind, count_backfill_reclaim,
                       count_lost_reservation)

#: tenant states a reclaim may evict: cache-real placements (bound or in
#: flight to the API). Session-only Allocated backfill tenants never
#: reach a reclaim — their jobs either dispatched (Binding) or released
#: their placements in backfill_job above.
_EVICTABLE = (TaskStatus.RUNNING, TaskStatus.BOUND, TaskStatus.BINDING)


def release_reserved_resources(ssn: Session, job: JobInfo) -> None:
    """Return a job's session-only reservations to the cluster
    (ref: backfill.go:98-118)."""
    for task in list(job.tasks.values()):
        if task.status in (TaskStatus.ALLOCATED,
                           TaskStatus.ALLOCATED_OVER_BACKFILL):
            ssn.touched_jobs.add(job.uid)
            ssn.touched_nodes.add(task.node_name)
            job.update_task_status(task, TaskStatus.PENDING)
            node = ssn.nodes.get(task.node_name)
            if node is not None:
                node.remove_task(task)
            task.node_name = ""


def backfill_job(ssn: Session, job: JobInfo) -> None:
    """Backfill an all-pending job onto idle resources, marking tasks
    IsBackfill (ref: backfill.go:120-147)."""
    for task in list(job.task_status_index.get(TaskStatus.PENDING,
                                               {}).values()):
        # CoW: is_backfill is written in place — resolve to the job's
        # canonical task first (JobInfo.own_task)
        task = job.own_task(task)
        for node in ssn.nodes.values():
            try:
                ssn.predicate_fn(task, node)
            except Exception:
                continue
            if task.resreq.less_equal(node.idle):
                task.is_backfill = True
                # the mark must survive the session: stamp the SHARED
                # pod's annotation so cache.bind / resync rebuilds carry
                # it into NodeInfo.backfilled (objects.is_backfill_pod)
                task.pod.annotations[BACKFILL_ANNOTATION] = "true"
                try:
                    ssn.allocate(task, node.name, False)
                except Exception:
                    continue
                break
    if not ssn.job_ready(job):
        release_reserved_resources(ssn, job)


def over_reserve_job(ssn: Session, job: JobInfo) -> int:
    """Reserve the rest of an unready gang's quorum OVER lent capacity:
    pending tasks that do not fit any node's idle go onto the first
    predicate-passing node whose ``accessible()`` (idle + backfilled)
    holds them, as ALLOCATED_OVER_BACKFILL — until the gang reports
    AlmostReady. Returns the number of over-placements made."""
    placed = 0
    for task in list(job.task_status_index.get(TaskStatus.PENDING,
                                               {}).values()):
        if ssn.job_ready(job) or ssn.job_almost_ready(job):
            break
        task = job.own_task(task)
        if task.init_resreq.is_empty() or task.is_backfill:
            continue
        for node in ssn.nodes.values():
            try:
                ssn.predicate_fn(task, node)
            except Exception:
                continue
            if task.resreq.less_equal(node.idle):
                # plain capacity — the allocate action's business, and
                # ssn.allocate(..., False) next cycle will take it
                continue
            if not task.resreq.less_equal(node.accessible()):
                continue
            try:
                # counted in Session.allocate with every other
                # over-placement entry path
                ssn.allocate(task, node.name, True)
            except Exception:
                continue
            placed += 1
            break
    return placed


def reclaim_over_backfill(ssn: Session, job: JobInfo) -> bool:
    """Promote an AlmostReady gang to Ready by atomically evicting the
    backfill tenants under its over-backfill placements.

    One Statement transaction: evict every evictable backfill tenant on
    the hosting nodes, promote each ALLOCATED_OVER_BACKFILL task to
    ALLOCATED, and — iff the gang now reports Ready — commit the
    evictions and dispatch the gang. Anything short of Ready discards:
    tenants come back, promotions flip back, the reservation stands for
    a later cycle. Statement has no "promote" op, so the status flips
    are reversed manually on the failure path."""
    over = list(job.task_status_index.get(
        TaskStatus.ALLOCATED_OVER_BACKFILL, {}).values())
    if not over:
        return False
    stmt = ssn.statement()
    evicted = 0
    promoted = []
    ok = True
    for task in over:
        node = ssn.nodes.get(task.node_name)
        if node is None:
            ok = False
            break
        # deterministic tenant order; the list() snapshot matters —
        # stmt.evict replaces entries in node.tasks via update_task
        for tenant in sorted(node.tasks.values(), key=lambda t: t.uid):
            if not tenant.is_backfill or tenant.job == job.uid:
                continue
            if tenant.status not in _EVICTABLE:
                continue
            stmt.evict(tenant, "reclaimed: lent capacity owed to gang "
                               f"<{job.namespace}/{job.name}>")
            evicted += 1
    if ok:
        for task in over:
            task = job.own_task(task)
            if task.status != TaskStatus.ALLOCATED_OVER_BACKFILL:
                # the placement changed under us within one session —
                # promoting would dispatch against capacity we no longer
                # hold
                count_backfill_double_bind()
                ok = False
                break
            job.update_task_status(task, TaskStatus.ALLOCATED)
            promoted.append(task)
    if ok and ssn.job_ready(job):
        stmt.commit()
        count_backfill_reclaim(evicted)
        for task in list(job.task_status_index.get(TaskStatus.ALLOCATED,
                                                   {}).values()):
            if task.status != TaskStatus.ALLOCATED:
                count_backfill_double_bind()
                continue
            ssn.dispatch(task)
        return True
    for task in promoted:
        job.update_task_status(task, TaskStatus.ALLOCATED_OVER_BACKFILL)
    stmt.discard()
    return False


class BackfillAction(Action):
    def __init__(self, reserved: Optional[bool] = None):
        self._reserved = reserved

    @property
    def name(self) -> str:
        return "backfill"

    @property
    def reserved_enabled(self) -> bool:
        if self._reserved is not None:
            return self._reserved
        return os.environ.get("KUBEBATCH_RESERVED_BACKFILL", "") in (
            "1", "true", "True")

    def execute(self, ssn: Session) -> None:
        # active path: BestEffort tasks onto any predicate-passing node
        for job in ssn.jobs.values():
            for task in list(job.task_status_index.get(TaskStatus.PENDING,
                                                       {}).values()):
                if not task.init_resreq.is_empty():
                    continue
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception:
                        continue
                    try:
                        ssn.allocate(task, node.name, False)
                    except VolumeAllocationError:
                        # pre-mutation failure only; post-mutation errors
                        # propagate (see actions/allocate.py host path)
                        continue
                    break

        if not self.reserved_enabled:
            return

        # fork path: collect eligible (all-pending) jobs, release unready
        # top dogs' reservations, then backfill (backfill.go:74-94)
        candidates = [job for job in ssn.jobs.values()
                      if ssn.backfill_eligible(job)]
        for job in ssn.jobs.values():
            if not ssn.job_almost_ready(job) and not ssn.job_ready(job):
                release_reserved_resources(ssn, job)
        for job in candidates:
            backfill_job(ssn, job)

        # over-reserve: gangs still short of quorum on idle reach over
        # the lent capacity; reclaim: AlmostReady gangs try to complete
        # their quorum by evicting their tenants atomically
        for job in ssn.jobs.values():
            if job.min_available <= 0 or ssn.job_ready(job):
                continue
            if not ssn.job_almost_ready(job):
                over_reserve_job(ssn, job)
            if ssn.job_almost_ready(job):
                reclaim_over_backfill(ssn, job)

        # the reservation is session-only: whatever was not promoted is
        # handed back before session close so the cache never sees an
        # over-backfill placement. A placement the sweep cannot clear is
        # a LOST reservation — the guard counter trips the bench pins.
        for job in ssn.jobs.values():
            idx = job.task_status_index.get(
                TaskStatus.ALLOCATED_OVER_BACKFILL, {})
            if not idx:
                continue
            release_reserved_resources(ssn, job)
            leftover = len(job.task_status_index.get(
                TaskStatus.ALLOCATED_OVER_BACKFILL, {}))
            if leftover:
                count_lost_reservation(leftover)


def new() -> BackfillAction:
    return BackfillAction()


register_action(BackfillAction())
