"""backfill — fit small/BestEffort work into holes.

ref: pkg/scheduler/actions/backfill/backfill.go. Two layers:

1. Active reference behavior (backfill.go:45-70): every Pending task with
   an EMPTY launch request (BestEffort) is allocated to the first
   predicate-passing node.
2. The fork's partially-finished "backfill over reserved resources"
   (backfill.go:72-147, commented out upstream with live helpers): jobs
   whose tasks are ALL pending (BackFillEligible via gang) are backfilled
   onto idle resources with IsBackfill=true, after unready "top dog" jobs
   release their session-reserved Allocated/AllocatedOverBackfill
   resources. Enabled with KUBEBATCH_RESERVED_BACKFILL=1 or
   BackfillAction(reserved=True); off by default, matching the shipped
   binary.
"""
from __future__ import annotations

import os
from typing import Optional

from ..api import JobInfo, TaskStatus
from ..framework import (Action, Session, VolumeAllocationError,
                         register_action)


def release_reserved_resources(ssn: Session, job: JobInfo) -> None:
    """Return a job's session-only reservations to the cluster
    (ref: backfill.go:98-118)."""
    for task in list(job.tasks.values()):
        if task.status in (TaskStatus.ALLOCATED,
                           TaskStatus.ALLOCATED_OVER_BACKFILL):
            ssn.touched_jobs.add(job.uid)
            ssn.touched_nodes.add(task.node_name)
            job.update_task_status(task, TaskStatus.PENDING)
            node = ssn.nodes.get(task.node_name)
            if node is not None:
                node.remove_task(task)
            task.node_name = ""


def backfill_job(ssn: Session, job: JobInfo) -> None:
    """Backfill an all-pending job onto idle resources, marking tasks
    IsBackfill (ref: backfill.go:120-147)."""
    for task in list(job.task_status_index.get(TaskStatus.PENDING,
                                               {}).values()):
        # CoW: is_backfill is written in place — resolve to the job's
        # canonical task first (JobInfo.own_task)
        task = job.own_task(task)
        for node in ssn.nodes.values():
            try:
                ssn.predicate_fn(task, node)
            except Exception:
                continue
            if task.resreq.less_equal(node.idle):
                task.is_backfill = True
                try:
                    ssn.allocate(task, node.name, False)
                except Exception:
                    continue
                break
    if not ssn.job_ready(job):
        release_reserved_resources(ssn, job)


class BackfillAction(Action):
    def __init__(self, reserved: Optional[bool] = None):
        self._reserved = reserved

    @property
    def name(self) -> str:
        return "backfill"

    @property
    def reserved_enabled(self) -> bool:
        if self._reserved is not None:
            return self._reserved
        return os.environ.get("KUBEBATCH_RESERVED_BACKFILL", "") in (
            "1", "true", "True")

    def execute(self, ssn: Session) -> None:
        # active path: BestEffort tasks onto any predicate-passing node
        for job in ssn.jobs.values():
            for task in list(job.task_status_index.get(TaskStatus.PENDING,
                                                       {}).values()):
                if not task.init_resreq.is_empty():
                    continue
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception:
                        continue
                    try:
                        ssn.allocate(task, node.name, False)
                    except VolumeAllocationError:
                        # pre-mutation failure only; post-mutation errors
                        # propagate (see actions/allocate.py host path)
                        continue
                    break

        if not self.reserved_enabled:
            return

        # fork path: collect eligible (all-pending) jobs, release unready
        # top dogs' reservations, then backfill (backfill.go:74-94)
        candidates = [job for job in ssn.jobs.values()
                      if ssn.backfill_eligible(job)]
        for job in ssn.jobs.values():
            if not ssn.job_almost_ready(job) and not ssn.job_ready(job):
                release_reserved_resources(ssn, job)
        for job in candidates:
            backfill_job(ssn, job)


def new() -> BackfillAction:
    return BackfillAction()


register_action(BackfillAction())
