"""allocate — the primary scheduling action.

Solver modes (KUBEBATCH_SOLVER env or constructor arg):
- "auto" (default): "batched" when the cycle carries at least
  AUTO_BATCHED_MIN pending tasks, else "fused" — the big configs get the
  throughput engine without env vars while small/exact cycles keep the
  bit-exact one.
- "batched": the round-based throughput solver (kernels/batched.py) —
  many placements per device step, fairness refreshed between rounds;
  the engine the north-star latency target is measured on.
- "fused": the whole cycle in ONE device dispatch
  (kernels/fused.py) — queue/job/task selection and fairness state live
  in-kernel, bit-exact vs the host heap algorithm; host replays the
  decisions through Session.allocate/pipeline so plugins and the gang
  barrier observe identical events.
- "jax": one device scan per job visit (kernels/solver.py) — more
  dispatches, used when the configured plugins fall outside the fused
  kernel's key vocabulary.
- "host": the reference-literal per-pair loops — the semantic oracle.
- "rpc": the whole action through the gRPC solver sidecar (rpc/), which
  picks its engine by snapshot size like auto mode; falls back to the
  in-process auto path when the sidecar is unreachable or the snapshot
  exceeds its vocabulary.


ref: pkg/scheduler/actions/allocate/allocate.go. Control flow is preserved
exactly (queue PQ with one entry per job, overused queues dropped, one job
per queue visit, job re-pushed only when it crosses readiness, job dropped
on first unassignable task, queue re-pushed after every visit).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..api import JobInfo, TaskInfo, TaskStatus
from ..faults import LADDER as _LADDER, check as _fault_check
from ..framework import (Action, Session, VolumeAllocationError,
                         register_action)
from ..kernels.solver import (ALLOC, ALLOC_OB, FAIL, PIPELINE, SKIP,
                              DeviceSession, ensure_device_snapshot)
from ..kernels.tensorize import TaskBatch
from ..kernels.terms import (device_supported, pred_and_score_matrices,
                             solver_terms)
from ..util import PriorityQueue, select_best_node

#: auto mode switches to the batched engine at this many pending tasks —
#: below it the fused engine's one-placement-per-step while_loop is cheap
#: and keeps bind-for-bind ordering exactness
AUTO_BATCHED_MIN = 512

#: auto mode further upgrades batched -> sharded when more than one
#: device is visible AND the node axis is at least this large — below it
#: the per-device shard is too small for the partitioning to pay for its
#: collectives (on a single chip sharded degenerates to batched anyway)
AUTO_SHARDED_MIN_NODES = 512

#: auto mode switches to the hierarchical two-level engine
#: (kernels/hier.py) at this many nodes: past it a flat [T, N] round
#: materializes intermediates beyond the per-shard HBM budget
#: (docs/SCALING.md "cfg6/cfg7 and the two-level solve"), so the node
#: axis decomposes into pool buckets and the waterfall runs per bucket
AUTO_HIER_MIN_NODES = 16384

#: engine that actually consumed the last allocate cycle in this process
#: ("batched" / "sharded" / "fused" / "jax-visit" / "host-visit" /
#: "rpc") — observability for bench.py, so a silent fallback off the
#: device engines is visible in the recorded JSON, not just slower
last_cycle_engine: str = ""


def _effective_min_available(ssn: Session, job: JobInfo) -> int:
    """The readiness threshold the kernel enforces in-scan. With a job-ready
    fn installed (gang), readiness = allocated-family count reaching
    MinAvailable; with none, the session defaults to Ready (ref:
    session_plugins.go:167-186) which the kernel encodes as threshold 0."""
    for tier in ssn.tiers:
        for plugin in tier.plugins:
            if plugin.job_ready_disabled:
                continue
            if plugin.name in ssn.job_ready_fns:
                return int(job.min_available)
    return 0


def _init_allocated(job: JobInfo) -> int:
    """Initial ready-task count for the kernels' in-scan readiness."""
    from ..api import ready_statuses
    return job.count(*ready_statuses())


class AllocateAction(Action):
    def __init__(self, mode: Optional[str] = None):
        self._mode = mode

    @property
    def name(self) -> str:
        return "allocate"

    @property
    def mode(self) -> str:
        return self._mode or os.environ.get("KUBEBATCH_SOLVER", "auto")

    @staticmethod
    def _auto_mode(ssn: Session) -> str:
        """Size-based engine selection (the shipped default and the
        rpc-unavailable fallback share it). Keyed on the PERSISTENT
        problem shape (the node axis) before per-cycle work: a
        cluster-scale config keeps the same engine family across churn
        levels, so same-config steady bench lines are comparable
        (ISSUE 15 fixed the flap where cfg6 churn 256 measured the
        fused engine while churn 1024 measured hier)."""
        if len(ssn.nodes) >= AUTO_HIER_MIN_NODES:
            # cluster-scale node axis: no flat engine (single-chip OR
            # per-shard) materializes [T, N] inside the HBM budget —
            # the two-level bucketed solve is the only fit, at EVERY
            # churn level (steady cycles ride its active-set twin,
            # kernels/activeset.py, which engages inside
            # execute_batched)
            return "hier"
        pending = sum(
            len(j.task_status_index.get(TaskStatus.PENDING, {}))
            for j in ssn.jobs.values())
        if pending < AUTO_BATCHED_MIN:
            return "fused"
        if len(ssn.nodes) >= AUTO_SHARDED_MIN_NODES:
            import jax
            if len(jax.devices()) > 1:
                # multi-chip host, big node axis: the shipped default
                # partitions the round engine over the mesh
                # (SURVEY §2.9 row 43)
                return "sharded"
        return "batched"

    def execute(self, ssn: Session) -> None:
        global last_cycle_engine
        mode = self.mode
        if mode == "auto":
            mode = self._auto_mode(ssn)
        # the degradation ladder's engine cap (faults.py): after repeated
        # cycle failures the scheduler loop demotes the tier — sharded ->
        # batched -> fused -> host — and this is the single consult site
        # (cap_engine counts the demotion in engine_demotions_total)
        wanted = mode
        mode = _LADDER.cap_engine(mode)
        if wanted in ("hier", "activeset") and mode == "batched" \
                and len(ssn.nodes) >= AUTO_HIER_MIN_NODES:
            # a demoted hier cycle must NOT land on the flat batched
            # engine: its [T, N] graph at this node count is exactly the
            # unbounded compile/OOM the two-level split exists to avoid
            # (its provider refuses to even register it). Skip to the
            # fused tier — slow but memory-bounded ([N]-sized state per
            # step), which is what a degraded cycle is for.
            from ..metrics import count_engine_demotion
            count_engine_demotion("batched", "fused")
            mode = "fused"
        if mode == "rpc":
            # route the whole action through the gRPC solver sidecar
            # (KUBEBATCH_SOLVER=rpc; address from KUBEBATCH_SOLVER_ADDR).
            # The sidecar picks its engine by snapshot size like auto
            # mode; on connection failure or an out-of-vocabulary
            # snapshot the action falls back to the in-process auto path
            # (the reference's convergence-by-rescheduling spirit: a
            # degraded cycle beats a skipped one)
            if self._execute_rpc(ssn):
                last_cycle_engine = "rpc"
                return
            from ..metrics import count_engine_demotion
            count_engine_demotion("rpc", "in-process")
            mode = self._auto_mode(ssn)
        if mode in ("batched", "sharded", "hier", "activeset"):
            from .allocate_batched import batched_supported, execute_batched
            # execute_batched returns the engine that actually ran
            # ("activeset" / "hier" / "sharded" / "batched"; the
            # remaining degradations — sharded->batched on a 1-device
            # host, hier->batched/sharded on an affinity cycle — are
            # counted) or False — without consuming state — when the
            # snapshot carries unsupported features. The active-set
            # steady engine engages on auto-selected hier cycles (its
            # own gates decide per cycle) and is forced by
            # KUBEBATCH_SOLVER=activeset for the dryrun/test harnesses.
            # activeset= is passed only when the engine may engage, so
            # plain batched/sharded calls keep the pre-activeset call
            # shape (test spies wrap execute_batched with the old
            # signature)
            act_kw = {"activeset": True} \
                if (mode == "activeset"
                    or (self.mode == "auto" and mode == "hier")) else {}
            ran = batched_supported(ssn) \
                and execute_batched(
                    ssn, sharded=(mode == "sharded"),
                    hier=(mode in ("hier", "activeset")), **act_kw)
            if ran:
                last_cycle_engine = ran
                return
            from ..metrics import count_engine_demotion
            count_engine_demotion(mode, "visit")
            mode = "batched"   # device fallback path below
        elif mode == "fused":
            from .allocate_fused import execute_fused, fused_supported
            # execute_fused itself returns False (without consuming state)
            # when the snapshot carries features the kernel can't model
            if fused_supported(ssn) and execute_fused(ssn):
                last_cycle_engine = "fused"
                return
            # configured plugins exceed the fused vocabulary; fall back to
            # the per-visit device solver
            from ..metrics import count_engine_demotion
            count_engine_demotion("fused", "visit")
        self._execute_queued(ssn, mode)

    def _execute_rpc(self, ssn: Session) -> bool:
        """One remote solve through the sidecar; False = fall back.

        Fallback is only legal BEFORE any session mutation: snapshot
        encoding and the remote call can fail over to in-process safely,
        but replay errors propagate (a partially-replayed session must
        not be re-solved by another engine on inconsistent state)."""
        import logging

        from ..rpc.client import (AdmissionRejected, current_tenant,
                                  get_solver_client)
        from ..rpc.victims_wire import (breaker_open, breaker_target,
                                        clear_breaker, trip_breaker)
        from ..tenantsvc import router as _router

        tenant = current_tenant()
        rt = _router.active()
        if rt is not None:
            # a fleet is armed: placement, partition retry, health
            # feedback, and breaker strikes all live in the client pool
            return self._execute_rpc_fleet(ssn, rt, tenant)
        addr = os.environ.get("KUBEBATCH_SOLVER_ADDR", "127.0.0.1:50061")
        target = breaker_target(addr, tenant)
        if breaker_open(target):
            # the sidecar failed recently (process-wide breaker shared
            # with the victim path, keyed per (address, tenant)): go
            # straight in-process, re-probe after the cooldown — a
            # wedged sidecar must not stall every cycle on the rpc
            # deadline, and one tenant's quarantine must not block its
            # in-process neighbors
            return False
        try:
            client = get_solver_client(addr, tenant=tenant)
            req, tasks_by_uid = client.snapshot_from_session(ssn)
        except ValueError:
            # snapshot exceeds the sidecar vocabulary — known, quiet
            return False
        except Exception as e:
            logging.getLogger("kubebatch").warning(
                "solver sidecar %s unavailable (%s); running in-process",
                addr, e)
            trip_breaker(target)
            return False
        try:
            resp = client.solve(req)
        except AdmissionRejected as e:
            # the tenant service shed this request (overload, queue
            # bound, quarantine) — run in-process for the cycle but do
            # NOT trip the breaker: the sidecar is alive and the next
            # cycle should try again
            logging.getLogger("kubebatch").info(
                "solver sidecar %s shed tenant %s (%s); running "
                "in-process this cycle", addr, tenant, e)
            return False
        except Exception as e:
            # a solve()-side ValueError is a sidecar/response bug, not an
            # out-of-vocabulary snapshot — fall back, but say so
            logging.getLogger("kubebatch").warning(
                "solver sidecar %s solve failed (%s); running in-process",
                addr, e)
            trip_breaker(target)
            return False
        # a successful solve answers the quarantine's recovery probe:
        # reset the strike escalation for this sidecar
        clear_breaker(target)
        client.apply_decisions(ssn, resp, tasks_by_uid)
        return True

    def _execute_rpc_fleet(self, ssn: Session, rt, tenant: str) -> bool:
        """One remote solve through the fleet: the router resolves
        placement (health-drained + failover overrides) and the client
        pool owns the wire bookkeeping — rpc.partition retry onto a
        re-resolved target, rtt feedback into the health score, and the
        per-(address, tenant) breaker strikes. Same fallback contract
        as the single-sidecar path: False only BEFORE any mutation."""
        import logging

        from ..rpc.client import (AdmissionRejected, SolverClient,
                                  build_snapshot, get_solver_pool)
        from ..rpc.victims_wire import breaker_open, breaker_target

        addr = rt.route(tenant)
        if breaker_open(breaker_target(addr, tenant)):
            return False
        try:
            req, tasks_by_uid = build_snapshot(ssn)
        except ValueError:
            # snapshot exceeds the sidecar vocabulary — known, quiet
            return False
        try:
            resp = get_solver_pool(tenant).solve(req)
        except AdmissionRejected as e:
            logging.getLogger("kubebatch").info(
                "fleet shed tenant %s (%s); running in-process this "
                "cycle", tenant, e)
            return False
        except Exception as e:
            # the pool already struck the breaker and drained the
            # router's health for every target it tried
            logging.getLogger("kubebatch").warning(
                "fleet solve failed for tenant %s (%s); running "
                "in-process", tenant, e)
            return False
        SolverClient.apply_decisions(ssn, resp, tasks_by_uid)
        return True

    def _execute_queued(self, ssn: Session, mode: Optional[str] = None) -> None:
        if mode is None:
            mode = self.mode
        queues = PriorityQueue(ssn.queue_order_fn)
        jobs_map: Dict[str, PriorityQueue] = {}
        pending_all: List[TaskInfo] = []
        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            # one queue entry per job, as the reference does (allocate.go:50)
            queues.push(queue)
            jobs_map.setdefault(job.queue, PriorityQueue(ssn.job_order_fn))
            jobs_map[job.queue].push(job)
            pending_all.extend(
                t for t in job.task_status_index.get(TaskStatus.PENDING,
                                                     {}).values()
                if not t.resreq.is_empty())

        pending_tasks: Dict[str, PriorityQueue] = {}
        # registered predicate/node-order callbacks run on device when
        # kernels/terms can express them (static sig matrices + in-kernel
        # least-requested/balanced terms); snapshots with features the
        # kernels can't model (inter-pod affinity, pending host ports,
        # third-party callbacks) take the reference-literal host path
        device = None
        terms = None
        if mode in ("jax", "fused", "batched") \
                and device_supported(ssn, pending_all):
            # the cheap gate above keeps fallback cycles from paying the
            # full-cluster tensorize + device upload
            device_snap = ensure_device_snapshot(ssn)
            terms = solver_terms(ssn, device_snap, pending_all,
                                 assume_supported=True)
            if terms is not None:
                device = device_snap
        elif mode == "native" and not (ssn.predicate_fns
                                       or ssn.node_order_fns):
            from ..native import NativeSession, native_available
            if native_available():
                device = NativeSession(ssn.nodes)

        global last_cycle_engine
        last_cycle_engine = (f"{mode}-visit" if device is not None
                             else "host-visit")

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = jobs_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            if job.uid not in pending_tasks:
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index.get(TaskStatus.PENDING,
                                                      {}).values():
                    if task.resreq.is_empty():
                        continue  # BestEffort handled by backfill
                    tasks.push(task)
                pending_tasks[job.uid] = tasks
            tasks = pending_tasks[job.uid]

            if not tasks.empty():
                if device is not None:
                    self._visit_job_device(ssn, device, job, tasks, jobs,
                                           terms)
                else:
                    self._visit_job_host(ssn, job, tasks, jobs)

            queues.push(queue)

    # ------------------------------------------------------------------
    # device path
    # ------------------------------------------------------------------
    def _visit_job_device(self, ssn: Session, device: DeviceSession,
                          job: JobInfo, tasks: PriorityQueue,
                          jobs: PriorityQueue, terms=None) -> None:
        # injection seam: before the dispatch AND before any session
        # mutation, so a device fault fails the cycle without leaving
        # half-applied decisions behind
        _fault_check("device.dispatch")
        ordered: List[TaskInfo] = []
        while not tasks.empty():
            ordered.append(tasks.pop())
        batch = TaskBatch.from_tasks(ordered)
        if terms is not None:
            scores, pred = terms.matrices(batch)
            dyn = terms.dynamic
        else:
            scores, pred = pred_and_score_matrices(ssn, device, batch)
            dyn = None
        decisions, _ = device.solve_job(
            batch, _effective_min_available(ssn, job), _init_allocated(job),
            scores=scores, pred_mask=pred, dyn=dyn)
        try:
            for task, dec in zip(ordered, decisions):
                if dec.kind == ALLOC:
                    ssn.allocate(task, dec.node_name, False)
                elif dec.kind == ALLOC_OB:
                    ssn.allocate(task, dec.node_name, True)
                elif dec.kind == PIPELINE:
                    ssn.pipeline(task, dec.node_name)
                elif dec.kind == FAIL:
                    self._record_fit_deltas(ssn, job, task)
                    return  # job dropped (allocate.go:187-189)
                elif dec.kind == SKIP:
                    tasks.push(task)  # not processed; next visit
            if ssn.job_ready(job):
                jobs.push(job)
        except Exception:
            # host apply diverged (e.g. volume binder failure): device state
            # no longer matches host truth; rebuild before the next visit
            device.resync(ssn.nodes)
            raise

    def _record_fit_deltas(self, ssn: Session, job: JobInfo,
                           task: TaskInfo) -> None:
        """NodesFitDelta for the breaking task (ref: allocate.go:124-126 and
        164-170: the map holds deltas of the last task that failed)."""
        ssn.touched_jobs.add(job.uid)   # nodes_fit_delta isn't cloned
        job.nodes_fit_delta = {}
        for node in ssn.nodes.values():
            delta = node.idle.clone()
            delta.fit_delta(task.resreq)
            job.nodes_fit_delta[node.name] = delta

    # ------------------------------------------------------------------
    # host path — the reference algorithm verbatim (the oracle)
    # ------------------------------------------------------------------
    def _visit_job_host(self, ssn: Session, job: JobInfo,
                        tasks: PriorityQueue, jobs: PriorityQueue) -> None:
        while not tasks.empty():
            task = tasks.pop()
            assigned = False
            if job.nodes_fit_delta:
                job.nodes_fit_delta = {}

            predicate_nodes = []
            for node in ssn.nodes.values():
                try:
                    ssn.predicate_fn(task, node)
                except Exception:
                    continue
                predicate_nodes.append(node)

            node_scores: Dict[float, list] = {}
            for node in predicate_nodes:
                score = ssn.node_order_fn(task, node)
                node_scores.setdefault(score, []).append(node)

            for node in select_best_node(node_scores):
                if task.init_resreq.less_equal(node.accessible()):
                    try:
                        ssn.allocate(task, node.name,
                                     not task.init_resreq.less_equal(
                                         node.idle))
                    except VolumeAllocationError:
                        # pre-mutation volume failure: try the next node
                        # (ref: allocate.go:157-161). Post-mutation errors
                        # propagate — retrying elsewhere would double-place
                        # the task.
                        continue
                    assigned = True
                    break
                else:
                    delta = node.idle.clone()
                    delta.fit_delta(task.resreq)
                    job.nodes_fit_delta[node.name] = delta
                    ssn.touched_jobs.add(job.uid)
                if task.init_resreq.less_equal(node.releasing):
                    ssn.pipeline(task, node.name)
                    assigned = True
                    break

            if not assigned:
                break
            if ssn.job_ready(job):
                jobs.push(job)
                break


def new() -> AllocateAction:
    return AllocateAction()


register_action(AllocateAction())
