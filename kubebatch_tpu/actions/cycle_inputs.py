"""Cycle tensorization shared by the whole-cycle device solvers.

Builds every array the fused (kernels/fused.py) and batched
(kernels/batched.py) allocate kernels consume from an open Session:
queue / job / task index spaces, fairness seeds (proportion deserved +
allocated, DRF allocated + cluster total), order-key specs, and the
sig-indexed static predicate/score terms.  Returns None when the session
carries plugins/features outside the device vocabulary — callers fall
back to the per-visit or host paths.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import JobInfo, TaskInfo, TaskStatus, ready_statuses
from ..framework import Session
from ..kernels.fused import (K_DRF_SHARE, K_GANG_READY, K_PRIORITY,
                             K_PROP_SHARE)
from ..kernels.solver import DeviceSession, ensure_device_snapshot
from ..kernels.tensorize import TaskBatch, pad_to_bucket, sticky_bucket
from ..kernels.terms import device_supported, solver_terms

#: job-order plugins the kernels can express, in any tier order
_JOB_KEYS = {"priority": K_PRIORITY, "gang": K_GANG_READY,
             "drf": K_DRF_SHARE}
_QUEUE_KEYS = {"proportion": K_PROP_SHARE}

#: build_cycle_inputs result when the cycle has no schedulable pending
#: tasks at all — callers succeed without doing any work (distinct from
#: None, which means "unsupported, fall back")
EMPTY_CYCLE = "empty-cycle"


def job_order_spec(ssn: Session) -> Tuple[Tuple[str, ...], bool]:
    keys: List[str] = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.job_order_disabled or opt.name not in ssn.job_order_fns:
                continue
            key = _JOB_KEYS.get(opt.name)
            if key is None:
                return (), False
            keys.append(key)
    return tuple(keys), True


def queue_order_spec(ssn: Session) -> Tuple[Tuple[str, ...], bool]:
    keys: List[str] = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.queue_order_disabled or opt.name not in ssn.queue_order_fns:
                continue
            key = _QUEUE_KEYS.get(opt.name)
            if key is None:
                return (), False
            keys.append(key)
    return tuple(keys), True


def cycle_supported(ssn: Session) -> bool:
    """The whole-cycle kernels express the built-in order/fairness plugins;
    any custom job/queue order, overused, or ready fn falls back to the
    per-visit path.  Predicate / node-order callbacks are checked later by
    kernels/terms (static sig matrices + in-kernel dynamic terms)."""
    _, ok_j = job_order_spec(ssn)
    _, ok_q = queue_order_spec(ssn)
    custom_overused = any(name != "proportion" for name in ssn.overused_fns)
    custom_ready = any(name != "gang" for name in ssn.job_ready_fns)
    return ok_j and ok_q and not custom_overused and not custom_ready


def gang_enabled(ssn: Session) -> bool:
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if not opt.job_ready_disabled and opt.name in ssn.job_ready_fns:
                return True
    return False


def fast_task_sort_spec(ssn: Session) -> Optional[bool]:
    """Whether the session's task order is expressible as a tuple key:
    True = (-priority, creation_timestamp, uid), False = (creation, uid),
    None = a custom task-order fn is registered (per-item cmp path)."""
    names = [opt.name for tier in ssn.tiers for opt in tier.plugins
             if not opt.task_order_disabled
             and opt.name in ssn.task_order_fns]
    if any(n != "priority" for n in names):
        return None
    return bool(names)


def fast_task_sort_key(ssn: Session):
    """A tuple sort key equivalent to ``ssn.task_order_fn`` when the only
    enabled task-order callback is the built-in priority plugin's
    (descending priority, then the session's creation-timestamp/uid
    tie-break) — a key sort is ~10x a cmp_to_key sort over 10k tasks.
    Returns None when a custom task-order fn is registered."""
    spec = fast_task_sort_spec(ssn)
    if spec is None:
        return None
    if spec:
        return lambda t: (-t.priority, t.pod.creation_timestamp, t.uid)
    return lambda t: (t.pod.creation_timestamp, t.uid)


from ..kernels.tensorize import _intern_paths

#: one native pass per cycle pulls every float the task gather + sort +
#: TaskBatch need: resreq (host units), init_resreq, priority, creation
_GATHER_PATHS = _intern_paths(
    ("resreq", "milli_cpu"), ("resreq", "memory"), ("resreq", "milli_gpu"),
    ("init_resreq", "milli_cpu"), ("init_resreq", "memory"),
    ("init_resreq", "milli_gpu"),
    ("priority", None), ("pod", "creation_timestamp"))

_CREATION_PATH = _intern_paths(("pod", "creation_timestamp"))

def _gather_pending_bulk(jobs: List[JobInfo], use_priority: bool):
    """Columnar pending-task gather: one native attribute pass over the
    whole backlog, empty-request filter and (job, task-order) sort as
    array ops — the per-job Python filter+sort loop is O(tasks)
    interpreter work, the single largest tensorize term at 10k pods.

    Returns (tasks, task_job_idx, task_ranks, raw6) where raw6 is the
    [T, 6] float64 (resreq, init_resreq) host-unit matrix in final task
    order (TaskBatch.from_raw consumes it — no second extraction), or
    None when the native packer is unavailable / the bulk path is
    disabled (callers fall back to the per-item gather)."""
    from ..api.resource import MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_GPU
    from ..kernels.tensorize import load_kb_pack
    from ..util import env_on

    pack = load_kb_pack()
    if pack is None or not env_on("KB_BULK_TENSORIZE"):
        return None
    raw_tasks: List[TaskInfo] = []
    counts = np.empty(len(jobs), np.int64)
    pending = TaskStatus.PENDING
    for k, j in enumerate(jobs):
        n0 = len(raw_tasks)
        raw_tasks.extend(j.task_status_index[pending].values())
        counts[k] = len(raw_tasks) - n0
    t0 = len(raw_tasks)
    if not t0:
        return [], [], [], None
    raw = np.empty((t0, 8), np.float64)
    pack.extract_f64(raw_tasks, _GATHER_PATHS, raw)
    job_col = np.repeat(np.arange(len(jobs), dtype=np.int64), counts)
    # not resreq.is_empty(), the exact epsilon rule
    nonempty = ~((raw[:, 0] < MIN_MILLI_CPU) & (raw[:, 1] < MIN_MEMORY)
                 & (raw[:, 2] < MIN_MILLI_GPU))
    sel = np.nonzero(nonempty)[0]
    if sel.size == 0:
        return [], [], [], None
    # per-job task order as ONE lexsort over the NUMERIC keys (primary
    # key last). The uid tie-break is applied lazily: building a 10k-row
    # fixed-width numpy string column costs more than the whole numeric
    # sort, and creation timestamps disambiguate almost every real pair
    # — so only runs whose numeric keys collide pay a (tiny) Python sort
    # by uid, which compares by code point exactly as numpy would
    if use_priority:
        keys = (raw[sel, 7], -raw[sel, 6], job_col[sel])
    else:
        keys = (raw[sel, 7], job_col[sel])
    order = np.lexsort(keys)
    tie = np.ones(order.size, bool)
    tie[0] = False
    for k in keys:
        ks = k[order]
        tie[1:] &= ks[1:] == ks[:-1]
    tied_rows = np.nonzero(tie)[0]
    if tied_rows.size:
        # each run of consecutive tied rows (plus the row before it) is
        # one numeric-key collision group; uid-sort those groups only
        order_l = order.tolist()
        sel_l = sel.tolist()
        breaks = np.nonzero(np.diff(tied_rows) > 1)[0] + 1
        for grp in np.split(tied_rows, breaks):
            s, e = int(grp[0]) - 1, int(grp[-1]) + 1
            run = order_l[s:e]
            run.sort(key=lambda i: raw_tasks[sel_l[i]].uid)
            order_l[s:e] = run
        order = np.asarray(order_l, dtype=order.dtype)
    sel = sel[order]
    job_sorted = job_col[sel]
    counts_f = np.bincount(job_sorted, minlength=len(jobs))
    starts = np.concatenate(([0], np.cumsum(counts_f)[:-1]))
    ranks = np.arange(sel.size, dtype=np.int64) - np.repeat(starts, counts_f)
    tasks = [raw_tasks[i] for i in sel.tolist()]
    return (tasks, job_sorted.astype(np.int32), ranks.astype(np.int32),
            raw[sel, :6])


def _gather_pending_per_item(ssn: Session, jobs: List[JobInfo]):
    """Reference-shaped per-job gather+sort (the fallback the bulk path
    is pinned equivalent to; also the only path that can run a custom
    task-order fn)."""
    from ..metrics import count_slow_path_items

    tasks: List[TaskInfo] = []
    task_job_idx: List[int] = []
    task_ranks: List[int] = []
    fast_key = fast_task_sort_key(ssn)
    for ji, j in enumerate(jobs):
        pend = [t for t in j.task_status_index.get(TaskStatus.PENDING,
                                                   {}).values()
                if not t.resreq.is_empty()]
        if fast_key is not None:
            pend.sort(key=fast_key)
        else:
            pend.sort(key=functools.cmp_to_key(
                lambda a, b: -1 if ssn.task_order_fn(a, b) else 1))
        for rank, t in enumerate(pend):
            tasks.append(t)
            task_job_idx.append(ji)
            task_ranks.append(rank)
    count_slow_path_items("tensorize", len(tasks))
    return tasks, task_job_idx, task_ranks, None


@dataclass
class CycleInputs:
    """Everything a whole-cycle kernel needs, plus the host-side indexes
    to map decisions back to Session objects."""
    # host-side indexes
    queue_ids: List[str]
    jobs: List[JobInfo]
    tasks: List[TaskInfo]
    device: DeviceSession
    # task arrays ([T_pad])
    resreq: np.ndarray
    init_resreq: np.ndarray
    resreq_raw: np.ndarray        # [T,R] f64 host units (bytes memory)
    task_nz: np.ndarray
    task_job: np.ndarray
    task_rank: np.ndarray
    task_sig: np.ndarray
    task_valid: np.ndarray
    # sig arrays ([S_pad, N])
    sig_scores: np.ndarray
    sig_pred: np.ndarray
    # job arrays ([J_pad])
    min_available: np.ndarray
    order_min_available: np.ndarray
    init_allocated: np.ndarray
    job_queue: np.ndarray
    job_priority: np.ndarray
    job_create_rank: np.ndarray
    job_valid: np.ndarray
    # queue arrays ([Q_pad])
    q_weight: np.ndarray
    q_entries: np.ndarray
    q_create_rank: np.ndarray
    q_deserved: np.ndarray
    q_alloc0: np.ndarray
    # drf
    j_alloc0: np.ndarray
    cluster_total: np.ndarray
    # dynamic nodeorder terms
    dyn_weights: np.ndarray
    dyn_enabled: bool
    # order/flag specs
    job_keys: Tuple[str, ...]
    queue_keys: Tuple[str, ...]
    gang_enabled: bool
    prop_overused: bool
    #: False when no node carries releasing resources at cycle start —
    #: lets the batched kernel fold away all pipeline-fit work statically
    pipe_enabled: bool = True
    #: inter-pod affinity / host-port vocabulary (kernels/affinity.py);
    #: None when the snapshot has none (or the builder was told not to
    #: encode them — only the batched engine consumes these)
    affinity: Optional[object] = None
    # lazy cache for pair_terms(): (max_pairs budget, result)
    _pair_terms: Optional[tuple] = None

    @property
    def n_tasks_real(self) -> int:
        return len(self.tasks)

    def pair_terms(self, max_pairs: int = 2048):
        """Cohorts for the batched kernel's scoring/waterfall at (sig,
        nonzero-request) granularity: tasks in one pair share the static
        sig AND (exactly or within a quantization bucket) the nonzero
        request, so per-pair dynamic node scores equal per-task scores —
        fixing the cohort-mean divergence a sig-only grouping has for
        heterogeneous same-sig pods.

        Returns (task_pair [T_pad] int32, pair_sig [P_pad] int32,
        pair_nz [P_pad,2] f32 member mean, exact: bool). When the exact
        pair count exceeds
        ``max_pairs``, nz is bucketed on a log2 grid, coarsening by octave
        fractions until the count fits — scores then deviate by at most
        the bucket width instead of by cohort heterogeneity. The result is
        cached per budget value."""
        if self._pair_terms is not None and self._pair_terms[0] == max_pairs:
            return self._pair_terms[1]
        n_real = len(self.tasks)
        t_pad = self.task_sig.shape[0]
        sig = self.task_sig[:n_real].astype(np.int64)
        nz = self.task_nz[:n_real]
        exact = True
        # bucket fractions: exact first, then 16ths of an octave downward
        for steps in (0, 16, 8, 4, 2, 1):
            if steps == 0:
                key_nz = nz
            else:
                exact = False
                with np.errstate(divide="ignore"):
                    key_nz = np.exp2(
                        np.round(np.log2(np.maximum(nz, 1e-9)) * steps)
                        / steps).astype(np.float32)
            keys = np.concatenate(
                [sig[:, None].astype(np.float64),
                 key_nz.astype(np.float64)], axis=1)
            uniq, inverse = np.unique(keys, axis=0, return_inverse=True)
            if uniq.shape[0] <= max_pairs:
                break
        else:  # pragma: no cover — 1-octave buckets always fit max_pairs
            uniq, inverse = np.unique(keys, axis=0, return_inverse=True)
        p = uniq.shape[0]
        p_pad = pad_to_bucket(p, 4)
        pair_sig = np.zeros(p_pad, np.int32)
        pair_sig[:p] = uniq[:, 0].astype(np.int32)
        # member means (exact pairs: mean of identical values = the value)
        counts = np.bincount(inverse, minlength=p_pad).astype(np.float64)
        denom = np.maximum(counts, 1.0)
        pair_nz = np.zeros((p_pad, 2), np.float32)
        for c in range(2):
            pair_nz[:, c] = (np.bincount(inverse, weights=nz[:, c],
                                         minlength=p_pad) / denom)
        task_pair = np.zeros(t_pad, np.int32)
        task_pair[:n_real] = inverse.astype(np.int32)
        result = (task_pair, pair_sig, pair_nz, exact)
        self._pair_terms = (max_pairs, result)
        return result


def build_cycle_inputs(ssn: Session,
                       allow_affinity: bool = False) -> Optional[CycleInputs]:
    """Tensorize the session for a whole-cycle solve, or None when some
    registered callback / snapshot feature can't run on device (callers
    then fall back without having paid the device upload).

    ``allow_affinity``: encode inter-pod affinity / host ports into the
    batched engine's vocabulary (kernels/affinity.py) instead of falling
    back on them; the fused engine passes False — its one-placement scan
    has no affinity carry."""
    from ..obs import span as _span

    with _span("tensorize", cat="phase"):
        return _build_cycle_inputs(ssn, allow_affinity)


def _build_cycle_inputs(ssn: Session,
                        allow_affinity: bool) -> Optional[CycleInputs]:
    # ---- queues ----------------------------------------------------------
    queue_ids = sorted(ssn.queues)          # uid order = order fallback
    q_index = {q: i for i, q in enumerate(queue_ids)}
    q_pad = pad_to_bucket(len(queue_ids), 4)

    # ---- jobs ------------------------------------------------------------
    # Only jobs with pending tasks occupy kernel job rows: the reference
    # pushes every job into its queue PQ (allocate.go:45-63), but popping
    # a job with no pending tasks changes no state — it only burns a queue
    # entry, and q_entries below counts exactly the rows built here. Keeps
    # the job axis at the pending-job count instead of the cluster job
    # count (cfg4: 625 rows instead of 10k+ when running fill pods each
    # carry their own PodGroup).
    jobs: List[JobInfo] = [
        j for j in ssn.jobs.values()
        if j.queue in q_index and TaskStatus.PENDING in j.task_status_index]
    # creation-rank tie-break (creation_timestamp, uid)
    jobs_sorted = sorted(jobs, key=lambda j: (j.creation_timestamp, j.uid))
    j_rank = {j.uid: r for r, j in enumerate(jobs_sorted)}
    # per-cache sticky store (SchedulerCache.pad_sticky): interleaved
    # schedulers in one process must not fight over a shared shape hold;
    # cache fakes without the field fall back to the process-global map
    pad_store = getattr(ssn.cache, "pad_sticky", None)
    j_pad = sticky_bucket("cycle_jobs", len(jobs), 4, store=pad_store)
    j_index = {j.uid: i for i, j in enumerate(jobs)}

    # ---- tasks (pending, non-BestEffort, in task-order per job) ----------
    gathered = None
    sort_spec = fast_task_sort_spec(ssn)
    if sort_spec is not None:
        gathered = _gather_pending_bulk(jobs, sort_spec)
    if gathered is None:
        gathered = _gather_pending_per_item(ssn, jobs)
    tasks, task_job_idx, task_ranks, task_raw = gathered
    if not tasks:
        return EMPTY_CYCLE
    # cheap feature gates BEFORE tensorizing/uploading the cluster — a
    # fallback cycle must not pay the device transfer
    if not device_supported(ssn, tasks, allow_affinity=allow_affinity):
        return None
    aff_wanted = False
    if allow_affinity:
        from ..kernels.affinity import (affinity_features_present,
                                        affinity_within_vocabulary)
        from ..metrics import count_affinity_host_fallback
        if affinity_features_present(ssn, tasks):
            if not affinity_within_vocabulary(ssn, tasks):
                # raw vocabulary past even the compaction window —
                # reference-literal host path, recorded by counter
                count_affinity_host_fallback("allocate-raw-window")
                return None
            aff_wanted = True
    device = ensure_device_snapshot(ssn)
    terms = solver_terms(ssn, device, tasks, assume_supported=True)
    if terms is None:
        return None
    # sticky task-axis bucket: steady churn oscillating across a pow2
    # boundary must not recompile the whole-cycle kernels every few
    # cycles (the 1 s p95 tail in the steady benches)
    t_bucket = sticky_bucket("cycle_tasks", len(tasks), 8, store=pad_store)
    if task_raw is not None:
        batch = TaskBatch.from_raw(tasks, task_raw, min_bucket=t_bucket)
    else:
        batch = TaskBatch.from_tasks(tasks, min_bucket=t_bucket)
    t_pad = batch.t_padded

    # ---- inter-pod affinity / host ports (batched engine only) -----------
    aff_inputs = None
    if aff_wanted:
        from ..kernels.affinity import build_affinity_inputs
        from ..metrics import count_affinity_host_fallback
        aff_inputs = build_affinity_inputs(ssn, tasks, device, t_pad)
        if aff_inputs is None:
            # inside the raw window but still over MAX_PAIRS/MAX_PORTS
            # after compaction — host path (the cached device snapshot
            # was touched, but it is incremental and reused next cycle)
            count_affinity_host_fallback("allocate-compact-cap")
            return None

    # ---- job arrays ------------------------------------------------------
    gang = gang_enabled(ssn)
    min_av = np.zeros(j_pad, np.int32)
    order_min_av = np.zeros(j_pad, np.int32)
    init_alloc = np.zeros(j_pad, np.int32)
    job_queue = np.zeros(j_pad, np.int32)
    job_priority = np.zeros(j_pad, np.float32)
    job_create_rank = np.zeros(j_pad, np.int32)
    job_valid = np.zeros(j_pad, bool)
    for i, j in enumerate(jobs):
        min_av[i] = j.min_available if gang else 0
        order_min_av[i] = j.min_available
        init_alloc[i] = j.count(*ready_statuses())
        job_queue[i] = q_index[j.queue]
        job_priority[i] = j.priority
        job_create_rank[i] = j_rank[j.uid]
        job_valid[i] = True

    # ---- task arrays -----------------------------------------------------
    task_job = np.full(t_pad, -1, np.int32)
    task_rank = np.zeros(t_pad, np.int32)
    task_job[:len(tasks)] = task_job_idx
    task_rank[:len(tasks)] = task_ranks

    # ---- queue arrays ----------------------------------------------------
    q_weight = np.zeros(q_pad, np.float32)
    q_entries = np.zeros(q_pad, np.int32)
    q_create_rank = np.arange(q_pad, dtype=np.int32)
    q_deserved = np.zeros((q_pad, 3), np.float32)
    q_alloc0 = np.zeros((q_pad, 3), np.float32)
    for q, i in q_index.items():
        q_weight[i] = ssn.queues[q].weight
    for j in jobs:
        q_entries[q_index[j.queue]] += 1

    prop = ssn.plugins.get("proportion")
    queue_keys, _ = queue_order_spec(ssn)
    prop_overused = ("proportion" in ssn.overused_fns
                     and any(opt.name == "proportion"
                             for tier in ssn.tiers
                             for opt in tier.plugins))
    if prop is not None and getattr(prop, "queue_opts", None):
        for q, attr in prop.queue_opts.items():
            i = q_index.get(q)
            if i is not None:
                q_deserved[i] = attr.deserved.to_vec()
                q_alloc0[i] = attr.allocated.to_vec()

    # ---- drf arrays ------------------------------------------------------
    job_keys, _ = job_order_spec(ssn)
    j_alloc0 = np.zeros((j_pad, 3), np.float32)
    cluster_total = np.ones(3, np.float32)
    drf = ssn.plugins.get("drf")
    if K_DRF_SHARE in job_keys and drf is not None:
        cluster_total = drf.total_resource.to_vec()
        for j in jobs:
            attr = drf.job_opts.get(j.uid)
            if attr is not None:
                j_alloc0[j_index[j.uid]] = attr.allocated.to_vec()

    # ---- scores / predicates (sig-indexed static + in-kernel dynamic) ---
    task_sig = terms.task_sig(tasks, t_pad)
    s_pad = pad_to_bucket(terms.static.n_sigs, 4)
    sig_scores = np.zeros((s_pad, device.n_padded), np.float32)
    sig_pred = np.zeros((s_pad, device.n_padded), bool)
    sig_scores[:terms.static.n_sigs] = terms.static.score
    sig_pred[:terms.static.n_sigs] = terms.static.pred
    dyn_enabled = terms.dynamic.enabled
    dyn_weights = np.asarray([terms.dynamic.least_requested,
                              terms.dynamic.balanced_resource], np.float32)

    return CycleInputs(
        queue_ids=queue_ids, jobs=jobs, tasks=tasks, device=device,
        resreq=batch.resreq, init_resreq=batch.init_resreq,
        resreq_raw=batch.resreq_raw,
        task_nz=batch.nz_req, task_job=task_job, task_rank=task_rank,
        task_sig=task_sig, task_valid=batch.valid,
        sig_scores=sig_scores, sig_pred=sig_pred,
        min_available=min_av, order_min_available=order_min_av,
        init_allocated=init_alloc, job_queue=job_queue,
        job_priority=job_priority, job_create_rank=job_create_rank,
        job_valid=job_valid,
        q_weight=q_weight, q_entries=q_entries, q_create_rank=q_create_rank,
        q_deserved=q_deserved, q_alloc0=q_alloc0,
        j_alloc0=j_alloc0, cluster_total=cluster_total,
        dyn_weights=dyn_weights, dyn_enabled=dyn_enabled,
        job_keys=job_keys, queue_keys=queue_keys, gang_enabled=gang,
        prop_overused=prop_overused, affinity=aff_inputs,
        # the DeviceSession's numpy mirror holds every node's releasing
        # vector in lock-step with host truth — one vectorized check
        # instead of a 5k-node attribute walk per cycle
        pipe_enabled=bool(np.any(device.state.releasing > 0.0)))


def _segment_lists(cols: np.ndarray):
    """Group array positions by value: [(value, [positions...]), ...] with
    positions ascending within each group. One argsort + one tolist +
    list slicing — building a numpy array per group (np.split) costs more
    than the whole grouped pass at a few thousand groups."""
    n = len(cols)
    if not n:
        return []
    order = np.argsort(cols, kind="stable")
    sorted_cols = cols[order]
    cuts = (np.nonzero(np.diff(sorted_cols))[0] + 1).tolist()
    order_l = order.tolist()
    starts = [0] + cuts
    ends = cuts + [n]
    vals = sorted_cols[starts].tolist()
    return [(v, order_l[a:b]) for v, a, b in zip(vals, starts, ends)]


#: event-handler owners the bulk replay can apply as aggregates (drf /
#: proportion: share sums) or collapse to one call (nodeorder / predicates:
#: idempotent memo invalidation)
_BULK_EVENT_OWNERS = frozenset({"drf", "proportion", "nodeorder",
                                "predicates"})


def replay_decisions(ssn: Session, inputs: CycleInputs,
                     task_state: np.ndarray, task_node: np.ndarray,
                     task_seq: np.ndarray) -> None:
    """Apply a whole-cycle kernel's decisions through the Session so host
    plugin state, event handlers, and the gang dispatch barrier end up in
    the same state the per-visit path would produce.

    Two implementations with identical final state: the exact per-event
    replay (one ssn.allocate/pipeline per decision, in kernel assignment
    order) and a bulk path that applies the same mutations as per-job /
    per-node / per-queue aggregates. The bulk path only runs when every
    registered event handler is a recognized built-in and the volume
    binder is the no-op default — anything custom gets the per-event
    ordering it may depend on."""
    from ..obs import span as _span

    with _span("replay", cat="phase", bulk=_bulk_replay_supported(ssn)):
        if _bulk_replay_supported(ssn):
            _replay_bulk(ssn, inputs, task_state, task_node, task_seq)
        else:
            _replay_ordered(ssn, inputs, task_state, task_node, task_seq)


def rebase_inputs(ssn: Session, inputs: CycleInputs,
                  task_state: np.ndarray) -> bool:
    """Re-point ``inputs``' host-side object indexes (jobs, tasks) at
    THIS session's clones before a deferred replay.

    The pipelined executor replays cycle N's decisions into session N+1
    — but ``build_cycle_inputs`` captured session N's job/task clones,
    and OpenSession re-clones from cache truth, so session N+1 holds
    DIFFERENT object instances for the same uids. Replaying through the
    stale references would mutate orphaned objects while the live
    session still enumerates the placed tasks as pending. Identity is
    by uid (``ssn.jobs[job.uid]``, ``job.tasks[task.uid]`` — the same
    lookup the ordered replay's Session mutators use).

    Returns False — caller must invalidate instead of replaying — when
    a PLACED task (or its job) no longer resolves as pending in this
    session: the cache moved underneath the flight in a way the
    conflict fingerprint did not catch (e.g. a delete whose job mark
    was echo-suppressed). Non-placed rows are only ever read (FAIL fit
    deltas), so a vanished one keeps its stale object."""
    from ..api.types import TaskStatus
    from ..kernels.fused import ALLOC, ALLOC_OB, PIPELINE

    state = np.asarray(task_state)[:len(inputs.tasks)]
    placed = ((state == ALLOC) | (state == ALLOC_OB)
              | (state == PIPELINE)).tolist()
    jobs = [ssn.jobs.get(j.uid, j) for j in inputs.jobs]
    tasks = list(inputs.tasks)
    pending = TaskStatus.PENDING
    for i, t in enumerate(tasks):
        job = ssn.jobs.get(t.job)
        cur = None if job is None else job.tasks.get(t.uid)
        if cur is None or (placed[i] and cur.status != pending):
            if placed[i]:
                return False
            continue
        tasks[i] = cur
    inputs.jobs = jobs
    inputs.tasks = tasks
    return True


def _bulk_replay_supported(ssn: Session) -> bool:
    from ..cache.interface import NullVolumeBinder

    if type(getattr(ssn.cache, "volume_binder", None)) is not NullVolumeBinder:
        return False
    if not hasattr(ssn.cache, "bind_many"):
        return False
    return all(eh.owner in _BULK_EVENT_OWNERS for eh in ssn.event_handlers)


def _replay_ordered(ssn: Session, inputs: CycleInputs,
                    task_state: np.ndarray, task_node: np.ndarray,
                    task_seq: np.ndarray) -> None:
    from ..kernels.fused import ALLOC, ALLOC_OB, FAIL, PIPELINE, SKIP
    from ..metrics import count_slow_path_items

    device = inputs.device
    tasks = inputs.tasks
    order = [i for i in range(len(tasks)) if task_state[i] != SKIP]
    order.sort(key=lambda i: task_seq[i])
    count_slow_path_items("replay", len(order))
    try:
        for i in order:
            task = tasks[i]
            kind = int(task_state[i])
            if kind in (ALLOC, ALLOC_OB, PIPELINE):
                node_name = device.node_name(int(task_node[i]))
                if kind == PIPELINE:
                    ssn.pipeline(task, node_name)
                else:
                    ssn.allocate(task, node_name, kind == ALLOC_OB)
            elif kind == FAIL:
                # fit-delta diagnostics for the task that broke its job,
                # against node state at failure time (host nodes mirror the
                # kernel here)
                job = ssn.jobs.get(task.job)
                if job is not None:
                    ssn.touched_jobs.add(job.uid)
                    job.nodes_fit_delta = {}
                    for node in ssn.nodes.values():
                        delta = node.idle.clone()
                        delta.fit_delta(task.resreq)
                        job.nodes_fit_delta[node.name] = delta
    except Exception:
        # host replay stopped mid-way (e.g. volume allocation failure):
        # device state holds phantom allocations — rebuild from host truth
        device.resync(ssn.nodes)
        raise


def _replay_bulk(ssn: Session, inputs: CycleInputs,
                 task_state: np.ndarray, task_node: np.ndarray,
                 task_seq: np.ndarray) -> None:
    """Aggregate application of kernel decisions. Per decision it performs
    exactly the mutations Session.allocate/pipeline/dispatch would, inlined
    (no per-task validate / net-zero arithmetic / per-bind locking), with
    the gang dispatch barrier precomputed per job (readiness is monotone in
    this replay, so the final count decides) — a task of a ready job flips
    PENDING -> ALLOCATED -> BINDING in one index move. Event-handler
    effects apply as per-job / per-queue sums afterwards."""
    from ..api import Resource
    from ..api.types import TaskStatus
    from ..kernels.fused import ALLOC, ALLOC_OB, FAIL, PIPELINE

    device = inputs.device
    tasks = inputs.tasks
    n = len(tasks)
    state = task_state[:n]
    placed_sel = np.nonzero((state == ALLOC) | (state == ALLOC_OB)
                            | (state == PIPELINE))[0]
    placed_sel = placed_sel[np.argsort(task_seq[placed_sel], kind="stable")]
    fail_sel = np.nonzero(state == FAIL)[0]

    # incremental-snapshot bookkeeping: this path inlines the Session
    # mutators, so it must record the touched entities itself. List
    # materialization once, then bulk set updates — numpy scalar
    # indexing per decision measured ~2x the cost of the adds
    names = device.state.names
    placed_list = placed_sel.tolist()
    placed_nodes_l = task_node[placed_sel].tolist()
    ssn.touched_jobs.update(tasks[i].job for i in placed_list)
    ssn.touched_nodes.update(names[n] for n in placed_nodes_l)
    ssn.touched_jobs.update(tasks[i].job for i in fail_sel.tolist())

    # --- per-job dispatch barrier, vectorized (gang semantics) ----------
    # The ordered path only checks readiness inside ssn.allocate, so the
    # deciding count is readiness AS OF THE JOB'S LAST ALLOCATE EVENT —
    # a PIPELINE event that crosses the quorum afterwards must NOT cause
    # a dispatch (session.pipeline has no dispatch step). ready_task_num
    # = count at session open (init_allocated is built as exactly that) +
    # ALLOC/PIPELINE events up to that seq (ALLOC_OB counts toward
    # AlmostReady only). cycle_supported() guarantees the only possible
    # job-ready fn is gang's.
    placed_states = state[placed_sel]
    placed_job_idx = inputs.task_job[placed_sel]
    placed_seq = task_seq[placed_sel]
    j_pad = inputs.order_min_available.shape[0]
    if gang_enabled(ssn):
        alloc_ev = (placed_states == ALLOC) | (placed_states == ALLOC_OB)
        last_alloc_seq = np.full(j_pad, np.iinfo(np.int64).min, np.int64)
        np.maximum.at(last_alloc_seq, placed_job_idx[alloc_ev],
                      placed_seq[alloc_ev].astype(np.int64))
        ready_ev = (placed_states == ALLOC) | (placed_states == PIPELINE)
        re_jobs = placed_job_idx[ready_ev]
        in_time = (placed_seq[ready_ev].astype(np.int64)
                   <= last_alloc_seq[re_jobs])
        ready_count = inputs.init_allocated + np.bincount(
            re_jobs[in_time], minlength=j_pad).astype(np.int32)
        job_ready = ready_count >= inputs.order_min_available
    else:
        # no enabled ready fn: every job is Ready (session.py:190-192)
        job_ready = np.ones(j_pad, bool)

    binding = TaskStatus.BINDING
    status_of = {int(ALLOC): TaskStatus.ALLOCATED,
                 int(ALLOC_OB): TaskStatus.ALLOCATED_OVER_BACKFILL,
                 int(PIPELINE): TaskStatus.PIPELINED}
    nodes = ssn.nodes
    pending = TaskStatus.PENDING

    # --- vectorized arithmetic: per-node / per-job float64 sums ---------
    # The ordered path applies one Resource.add/sub per placement; the sums
    # here are the same values in a different addition order (f64, far
    # below the fit epsilons). Memory stays in BYTES via resreq_raw.
    p_nodes = task_node[placed_sel].astype(np.int64)
    p_jobs_idx = placed_job_idx.astype(np.int64)
    is_pipe = placed_states == PIPELINE
    n_cols = int(p_nodes.max()) + 1 if len(p_nodes) else 0
    sub_idle = np.zeros((n_cols, 3))
    sub_rel = np.zeros((n_cols, 3))
    add_used = np.zeros((n_cols, 3))
    p_raw = inputs.resreq_raw[placed_sel]
    np.add.at(sub_idle, p_nodes[~is_pipe], p_raw[~is_pipe])
    np.add.at(sub_rel, p_nodes[is_pipe], p_raw[is_pipe])
    np.add.at(add_used, p_nodes, p_raw)
    # job.allocated counts the allocated-status family: ALLOC stays in it
    # whether or not it dispatches to BINDING (both allocated statuses)
    is_alloc_ev2 = placed_states == ALLOC
    j_cols = int(p_jobs_idx.max()) + 1 if len(p_jobs_idx) else 0
    job_alloc_add = np.zeros((j_cols, 3))
    np.add.at(job_alloc_add, p_jobs_idx[is_alloc_ev2], p_raw[is_alloc_ev2])
    # event handlers see every placement (pipeline fires allocate events
    # too, session.py:321) — keyed by placement COUNT, not value, so
    # zero-resource placements still fire the epoch-memo handlers
    job_event_add = np.zeros((j_cols, 3))
    np.add.at(job_event_add, p_jobs_idx, p_raw)
    job_event_cnt = np.bincount(p_jobs_idx, minlength=j_cols)

    #: job uid -> (JobInfo, job index) for jobs that saw >=1 ALLOC/ALLOC_OB
    alloc_jobs: Dict[str, tuple] = {}
    #: (task, hostname) for cache.bind_many, in assignment order
    bindings: List[tuple] = []
    #: rare: backfill-annotated placements (per-task Resource add)
    backfill_adds: List[tuple] = []

    try:
        from ..kernels.tensorize import batch_clone_tasks, batch_set_attr

        placed_tasks = [tasks[i] for i in placed_list]
        # CoW ownership: the gathered task objects may still be shared
        # with cache truth (JobInfo.clone is copy-on-write) — own every
        # placed job ONCE and rebind to its canonical task objects
        # before the first attribute write below (batch_set_attr)
        p_jobs_l = p_jobs_idx.tolist()
        for ji in set(p_jobs_l):
            inputs.jobs[int(ji)]._own_tasks()
        placed_tasks = [inputs.jobs[int(ji)].tasks.get(t.uid, t)
                        for ji, t in zip(p_jobs_l, placed_tasks)]
        placed_kinds_l = placed_states.tolist()
        is_pipe_l = is_pipe.tolist()
        node_names_l = [names[c] for c in placed_nodes_l]
        placed_keys = [t.key for t in placed_tasks]
        placed_uids = [t.uid for t in placed_tasks]

        # --- pre-validation: resolve every lookup BEFORE any mutation so
        #     a bad decision (vanished node, duplicate key) cannot leave
        #     the batch half-applied with the arithmetic sums never
        #     landing; inside the try so the failure path still resyncs
        #     the device snapshot (it holds the kernel's placements).
        #     Tasks come from the jobs the tensorizer indexed, so the job
        #     objects resolve by construction (inputs.jobs) -------------
        node_by_col = {c: nodes.get(names[c])
                       for c in np.unique(p_nodes).tolist()}
        for k, col in enumerate(placed_nodes_l):
            if node_by_col[col] is None and not is_pipe_l[k]:
                raise KeyError(f"failed to find node {node_names_l[k]}")
        # duplicate-key check as set ops per node (in-batch + vs the
        # existing map); only a detected conflict pays a per-item walk to
        # reproduce the ordered path's error message. Segment index lists
        # come from ONE tolist + slicing — a numpy array per segment
        # costs more than the whole grouped pass
        segments = _segment_lists(p_nodes)
        for col, seg_l in segments:
            node = node_by_col[col]
            if node is None:
                continue
            key_set = {placed_keys[i] for i in seg_l}
            if len(key_set) != len(seg_l) or (key_set & node.tasks.keys()):
                seen: set = set()
                for i in seg_l:
                    t = placed_tasks[i]
                    if t.key in node.tasks or t.key in seen:
                        raise KeyError(f"task <{t.namespace}/{t.name}> "
                                       f"already on node <{node.name}>")
                    seen.add(t.key)

        # --- batch mutation: per-placement attribute flips and clones as
        #     native column ops (kernels/tensorize batch helpers); dict
        #     index moves grouped per node / per job --------------------
        pre_status = [status_of[k] for k in placed_kinds_l]
        disp = (placed_states == ALLOC) & job_ready[placed_job_idx]
        disp_l = disp.tolist()
        final_status = [binding if d else s
                        for s, d in zip(pre_status, disp_l)]
        nonpipe_tasks = (placed_tasks if not is_pipe.any()
                         else [t for t, p in zip(placed_tasks, is_pipe_l)
                               if not p])
        if nonpipe_tasks:
            # allocate_volumes: the bulk gate guarantees the Null volume
            # binder, whose only effect is this flag
            batch_set_attr(nonpipe_tasks, "volume_ready", True)
        for ji in np.unique(p_jobs_idx[~is_pipe]).tolist():
            job = inputs.jobs[int(ji)]
            alloc_jobs[job.uid] = (job, int(ji))

        # the node clones carry allocation-time status, like the ordered
        # path where dispatch happens after add_task; the session tasks
        # then flip to their final (possibly dispatched) status
        clones = batch_clone_tasks(placed_tasks, pre_status, node_names_l)
        batch_set_attr(placed_tasks, "node_name", node_names_l)
        batch_set_attr(placed_tasks, "status", final_status)
        # bind_volumes is a no-op on the Null volume binder
        bindings.extend((placed_tasks[i], node_names_l[i])
                        for i, d in enumerate(disp_l) if d)

        # --- node task maps (NodeInfo.add_task minus the arithmetic,
        #     which the vectorized sums above cover) --------------------
        backfill_l = [t.is_backfill for t in placed_tasks]
        has_backfill = True in backfill_l
        # the per-pod affinity walk runs only when a placed pod CAN carry
        # a term: inputs.affinity is None alone does not prove that (with
        # the predicates AND nodeorder plugins disabled the affinity
        # build is skipped regardless of pod specs), so screen with the
        # maintained per-job counters, like bind_many does
        aff_l = None
        if inputs.affinity is not None or any(
                inputs.jobs[int(ji)].affinity_tasks
                for ji in np.unique(p_jobs_idx).tolist()):
            aff_l = [t.pod.has_pod_affinity() for t in placed_tasks]
        for col, seg_l in segments:
            node = node_by_col[col]
            if node is None:
                continue
            if has_backfill and node.node is not None:
                for i in seg_l:
                    if backfill_l[i]:
                        backfill_adds.append((node, placed_tasks[i].resreq))
            if aff_l is not None:
                node.affinity_tasks += sum(aff_l[i] for i in seg_l)
            node._own_tasks()
            node.tasks.update((placed_keys[i], clones[i]) for i in seg_l)

        # --- job status index moves + priority restamp, grouped --------
        for jcol, seg_l in _segment_lists(p_jobs_idx):
            job = inputs.jobs[jcol]
            index = job.task_status_index
            pend = index.get(pending)
            if pend is not None:
                if len(seg_l) == len(pend) and all(
                        placed_uids[i] in pend for i in seg_l):
                    # the batch drains the job's whole pending bucket (a
                    # full gang placing at once — the steady common
                    # case): one dict drop instead of per-task pops
                    del index[pending]
                else:
                    for i in seg_l:
                        pend.pop(placed_uids[i], None)
                    if not pend:
                        del index[pending]
            for i in seg_l:
                st = final_status[i]
                bucket = index.get(st)
                if bucket is None:
                    bucket = index[st] = {}
                bucket[placed_uids[i]] = placed_tasks[i]
            # the ordered path restamps job.priority at every placement
            # whose pod carries an explicit priority — the last one (in
            # kernel seq order) wins
            for i in reversed(seg_l):
                if placed_tasks[i].pod.priority is not None:
                    job.priority = placed_tasks[i].priority
                    break

        # --- apply the vectorized sums --------------------------------
        for col in np.nonzero(add_used.any(axis=1))[0]:
            node = nodes.get(device.node_name(int(col)))
            if node is None or node.node is None:
                continue
            node.idle.sub_vec(sub_idle[col])
            node.releasing.sub_vec(sub_rel[col])
            node.used.add_vec(add_used[col])
        for node, rr in backfill_adds:
            node.backfilled.add(rr)
        job_event_sum: Dict[str, Resource] = {}
        for col in np.nonzero(job_event_cnt)[0]:
            job = inputs.jobs[int(col)]
            job.allocated.add_vec(job_alloc_add[col])
            job_event_sum[job.uid] = Resource.empty().add_vec(
                job_event_add[col])

        if bindings:
            ssn.cache.bind_many(bindings)
            _observe_dispatch_latency(bindings)
        _apply_event_aggregates(ssn, job_event_sum)
        _dispatch_ready_jobs(ssn, alloc_jobs, job_ready)
        if len(fail_sel):
            _record_fit_deltas(ssn, inputs, state, task_node, task_seq,
                               placed_sel, fail_sel)
    except Exception:
        device.resync(ssn.nodes)
        raise


def _observe_dispatch_latency(bindings) -> None:
    """Creation -> bind latency for every dispatched task, batched
    (ordered-path parity: Session.dispatch observes per task,
    ref session.go:319)."""
    import time as _time

    from ..kernels.tensorize import load_kb_pack
    from ..metrics import update_task_schedule_durations

    now = _time.time()
    pack = load_kb_pack()
    if pack is not None:
        ages = np.empty((len(bindings), 1), np.float64)
        pack.extract_f64([t for t, _ in bindings], _CREATION_PATH, ages)
        durations = np.maximum(0.0, now - ages[:, 0]).tolist()
    else:
        durations = [max(0.0, now - t.pod.creation_timestamp)
                     for t, _ in bindings]
    update_task_schedule_durations(durations)


def _apply_event_aggregates(ssn: Session,
                            job_event_sum: Dict[str, "Resource"]) -> None:
    """Net effect of the built-in drf/proportion allocate handlers: shares
    recompute from sums, so applying per-job / per-queue totals and
    updating each touched share once matches the per-event final state."""
    if not job_event_sum:
        return
    owners = {eh.owner for eh in ssn.event_handlers}
    drf = ssn.plugins.get("drf") if "drf" in owners else None
    prop = ssn.plugins.get("proportion") if "proportion" in owners else None
    # nodeorder/predicates handlers only invalidate per-epoch memos — one
    # firing is equivalent to one per event
    for eh in ssn.event_handlers:
        if eh.owner in ("nodeorder", "predicates") and eh.allocate_func:
            from ..framework.event import Event
            eh.allocate_func(Event(None))
    if drf is not None:
        touched_attrs = []
        for job_uid, total in job_event_sum.items():
            attr = drf.job_opts.get(job_uid)
            if attr is not None:
                attr.allocated.add(total)
                touched_attrs.append(attr)
        if touched_attrs:
            # dominant_share over all touched jobs as one array op; the
            # f64 divisions/max are bitwise the per-attr Python values
            # (share semantics: 0/0 -> 0, x/0 -> 1)
            alloc = np.array(
                [(a.allocated.milli_cpu, a.allocated.memory,
                  a.allocated.milli_gpu) for a in touched_attrs])
            tot = drf.total_resource
            denom = np.array([tot.milli_cpu, tot.memory, tot.milli_gpu])
            zero_d = denom == 0.0
            sh = np.where(zero_d, np.where(alloc == 0.0, 0.0, 1.0),
                          alloc / np.where(zero_d, 1.0, denom))
            for a, s in zip(touched_attrs, sh.max(axis=1).tolist()):
                a.share = s
    if prop is not None:
        touched = {}
        for job_uid, total in job_event_sum.items():
            job = ssn.jobs.get(job_uid)
            if job is None or job.queue not in prop.queue_opts:
                continue
            attr = prop.queue_opts[job.queue]
            attr.allocated.add(total)
            touched[job.queue] = attr
        for attr in touched.values():
            prop._update_share(attr)


def _dispatch_ready_jobs(ssn: Session, alloc_jobs: Dict[str, tuple],
                         job_ready: np.ndarray):
    """Straggler sweep of the gang dispatch barrier: tasks this replay
    placed are dispatched inline by _replay_bulk, but a job that became
    Ready may still hold ALLOCATED tasks from an EARLIER action of the same
    session — the ordered path's per-allocation dispatch loop
    (session.py:340-343) would bind those too. Readiness comes from the
    same as-of-last-allocate flags the inline dispatch used, NOT the final
    session state (a later PIPELINE crossing must not dispatch)."""
    from ..api.types import TaskStatus

    bindings = []
    flips = []
    for job, ji in alloc_jobs.values():
        allocated = job.task_status_index.get(TaskStatus.ALLOCATED)
        if not allocated or not job_ready[ji]:
            continue
        for task in allocated.values():
            ssn.cache.bind_volumes(task)
            bindings.append((task, task.node_name))
            flips.append((job, task))
    if not bindings:
        return
    ssn.cache.bind_many(bindings)
    _observe_dispatch_latency(bindings)
    binding = TaskStatus.BINDING
    for job, task in flips:
        index = job.task_status_index
        bucket = index.get(TaskStatus.ALLOCATED)
        if bucket is not None:
            bucket.pop(task.uid, None)
            if not bucket:
                del index[TaskStatus.ALLOCATED]
        task.status = binding
        index.setdefault(binding, {})[task.uid] = task
        # ALLOCATED and BINDING both count as allocated: job.allocated is
        # net-unchanged, and skipping the sub/add avoids float drift


def _record_fit_deltas(ssn: Session, inputs: CycleInputs, state: np.ndarray,
                       task_node: np.ndarray, task_seq: np.ndarray,
                       placed_sel: np.ndarray, fail_sel: np.ndarray) -> None:
    """nodes_fit_delta diagnostics with ordered-replay parity: the ordered
    path overwrites job.nodes_fit_delta at every FAIL, so only the LAST
    failed task per job (by kernel seq) is visible, measured against node
    idle state at that point of the replay. Reconstructs those intermediate
    idle states by walking placements backward from the final state."""
    from ..api import Resource
    from ..api.resource import (MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_GPU)
    from ..kernels.fused import PIPELINE

    tasks = inputs.tasks
    device = inputs.device

    # last FAIL per job, processed in descending seq
    last_fail: Dict[str, int] = {}
    for i in sorted(fail_sel, key=lambda i: task_seq[i]):
        if ssn.jobs.get(tasks[i].job) is not None:
            last_fail[tasks[i].job] = i
    if not last_fail:
        return
    fails = sorted(last_fail.values(), key=lambda i: -task_seq[i])

    node_list = list(ssn.nodes.values())
    row = {node.name: r for r, node in enumerate(node_list)}
    idle = np.array([[nd.idle.milli_cpu, nd.idle.memory, nd.idle.milli_gpu]
                     for nd in node_list], dtype=np.float64)
    max_tasks = [nd.idle.max_task_num for nd in node_list]

    # placements that consumed idle (pipeline reuses releasing instead),
    # walked backward
    idle_placed = [i for i in placed_sel if int(state[i]) != int(PIPELINE)]
    p = len(idle_placed) - 1
    eps = np.array([MIN_MILLI_CPU, MIN_MEMORY, MIN_MILLI_GPU])
    for fi in fails:
        fseq = task_seq[fi]
        while p >= 0 and task_seq[idle_placed[p]] > fseq:
            t = tasks[idle_placed[p]]
            r = row.get(device.node_name(int(task_node[idle_placed[p]])))
            if r is not None:
                idle[r, 0] += t.resreq.milli_cpu
                idle[r, 1] += t.resreq.memory
                idle[r, 2] += t.resreq.milli_gpu
            p -= 1
        task = tasks[fi]
        req = np.array([task.resreq.milli_cpu, task.resreq.memory,
                        task.resreq.milli_gpu])
        delta = np.where(req > 0, idle - (req + eps), idle)
        job = ssn.jobs[task.job]
        job.nodes_fit_delta = {}
        for r, node in enumerate(node_list):
            d = object.__new__(Resource)
            d.milli_cpu = float(delta[r, 0])
            d.memory = float(delta[r, 1])
            d.milli_gpu = float(delta[r, 2])
            d.max_task_num = max_tasks[r]
            job.nodes_fit_delta[node.name] = d
