"""Cycle tensorization shared by the whole-cycle device solvers.

Builds every array the fused (kernels/fused.py) and batched
(kernels/batched.py) allocate kernels consume from an open Session:
queue / job / task index spaces, fairness seeds (proportion deserved +
allocated, DRF allocated + cluster total), order-key specs, and the
sig-indexed static predicate/score terms.  Returns None when the session
carries plugins/features outside the device vocabulary — callers fall
back to the per-visit or host paths.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import JobInfo, TaskInfo, TaskStatus, ready_statuses
from ..framework import Session
from ..kernels.fused import (K_DRF_SHARE, K_GANG_READY, K_PRIORITY,
                             K_PROP_SHARE)
from ..kernels.solver import DeviceSession
from ..kernels.tensorize import TaskBatch, pad_to_bucket
from ..kernels.terms import device_supported, solver_terms

#: job-order plugins the kernels can express, in any tier order
_JOB_KEYS = {"priority": K_PRIORITY, "gang": K_GANG_READY,
             "drf": K_DRF_SHARE}
_QUEUE_KEYS = {"proportion": K_PROP_SHARE}

#: build_cycle_inputs result when the cycle has no schedulable pending
#: tasks at all — callers succeed without doing any work (distinct from
#: None, which means "unsupported, fall back")
EMPTY_CYCLE = "empty-cycle"


def job_order_spec(ssn: Session) -> Tuple[Tuple[str, ...], bool]:
    keys: List[str] = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.job_order_disabled or opt.name not in ssn.job_order_fns:
                continue
            key = _JOB_KEYS.get(opt.name)
            if key is None:
                return (), False
            keys.append(key)
    return tuple(keys), True


def queue_order_spec(ssn: Session) -> Tuple[Tuple[str, ...], bool]:
    keys: List[str] = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.queue_order_disabled or opt.name not in ssn.queue_order_fns:
                continue
            key = _QUEUE_KEYS.get(opt.name)
            if key is None:
                return (), False
            keys.append(key)
    return tuple(keys), True


def cycle_supported(ssn: Session) -> bool:
    """The whole-cycle kernels express the built-in order/fairness plugins;
    any custom job/queue order, overused, or ready fn falls back to the
    per-visit path.  Predicate / node-order callbacks are checked later by
    kernels/terms (static sig matrices + in-kernel dynamic terms)."""
    _, ok_j = job_order_spec(ssn)
    _, ok_q = queue_order_spec(ssn)
    custom_overused = any(name != "proportion" for name in ssn.overused_fns)
    custom_ready = any(name != "gang" for name in ssn.job_ready_fns)
    return ok_j and ok_q and not custom_overused and not custom_ready


def gang_enabled(ssn: Session) -> bool:
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if not opt.job_ready_disabled and opt.name in ssn.job_ready_fns:
                return True
    return False


@dataclass
class CycleInputs:
    """Everything a whole-cycle kernel needs, plus the host-side indexes
    to map decisions back to Session objects."""
    # host-side indexes
    queue_ids: List[str]
    jobs: List[JobInfo]
    tasks: List[TaskInfo]
    device: DeviceSession
    # task arrays ([T_pad])
    resreq: np.ndarray
    init_resreq: np.ndarray
    task_nz: np.ndarray
    task_job: np.ndarray
    task_rank: np.ndarray
    task_sig: np.ndarray
    task_valid: np.ndarray
    # sig arrays ([S_pad, N] / [S_pad, ...])
    sig_scores: np.ndarray
    sig_pred: np.ndarray
    sig_nz: np.ndarray
    sig_req: np.ndarray
    # job arrays ([J_pad])
    min_available: np.ndarray
    order_min_available: np.ndarray
    init_allocated: np.ndarray
    job_queue: np.ndarray
    job_priority: np.ndarray
    job_create_rank: np.ndarray
    job_valid: np.ndarray
    # queue arrays ([Q_pad])
    q_weight: np.ndarray
    q_entries: np.ndarray
    q_create_rank: np.ndarray
    q_deserved: np.ndarray
    q_alloc0: np.ndarray
    # drf
    j_alloc0: np.ndarray
    cluster_total: np.ndarray
    # dynamic nodeorder terms
    dyn_weights: np.ndarray
    dyn_enabled: bool
    # order/flag specs
    job_keys: Tuple[str, ...]
    queue_keys: Tuple[str, ...]
    gang_enabled: bool
    prop_overused: bool

    @property
    def n_tasks_real(self) -> int:
        return len(self.tasks)


def build_cycle_inputs(ssn: Session) -> Optional[CycleInputs]:
    """Tensorize the session for a whole-cycle solve, or None when some
    registered callback / snapshot feature can't run on device (callers
    then fall back without having paid the device upload)."""
    # ---- queues ----------------------------------------------------------
    queue_ids = sorted(ssn.queues)          # uid order = order fallback
    q_index = {q: i for i, q in enumerate(queue_ids)}
    q_pad = pad_to_bucket(len(queue_ids), 4)

    # ---- jobs ------------------------------------------------------------
    jobs: List[JobInfo] = [j for j in ssn.jobs.values()
                           if j.queue in q_index]
    # creation-rank tie-break (creation_timestamp, uid)
    jobs_sorted = sorted(jobs, key=lambda j: (j.creation_timestamp, j.uid))
    j_rank = {j.uid: r for r, j in enumerate(jobs_sorted)}
    j_pad = pad_to_bucket(len(jobs), 4)
    j_index = {j.uid: i for i, j in enumerate(jobs)}

    # ---- tasks (pending, non-BestEffort, in task-order per job) ----------
    tasks: List[TaskInfo] = []
    task_job_idx: List[int] = []
    task_ranks: List[int] = []
    for j in jobs:
        pend = [t for t in j.task_status_index.get(TaskStatus.PENDING,
                                                   {}).values()
                if not t.resreq.is_empty()]
        pend.sort(key=functools.cmp_to_key(
            lambda a, b: -1 if ssn.task_order_fn(a, b) else 1))
        for rank, t in enumerate(pend):
            tasks.append(t)
            task_job_idx.append(j_index[j.uid])
            task_ranks.append(rank)
    if not tasks:
        return EMPTY_CYCLE
    # cheap feature gate BEFORE tensorizing/uploading the cluster — a
    # fallback cycle must not pay the device transfer
    if not device_supported(ssn, tasks):
        return None
    if ssn.device_snapshot is None:
        ssn.device_snapshot = DeviceSession(ssn.nodes)
    device: DeviceSession = ssn.device_snapshot
    terms = solver_terms(ssn, device, tasks)
    if terms is None:
        return None
    batch = TaskBatch.from_tasks(tasks)
    t_pad = batch.t_padded

    # ---- job arrays ------------------------------------------------------
    gang = gang_enabled(ssn)
    min_av = np.zeros(j_pad, np.int32)
    order_min_av = np.zeros(j_pad, np.int32)
    init_alloc = np.zeros(j_pad, np.int32)
    job_queue = np.zeros(j_pad, np.int32)
    job_priority = np.zeros(j_pad, np.float32)
    job_create_rank = np.zeros(j_pad, np.int32)
    job_valid = np.zeros(j_pad, bool)
    for i, j in enumerate(jobs):
        min_av[i] = j.min_available if gang else 0
        order_min_av[i] = j.min_available
        init_alloc[i] = j.count(*ready_statuses())
        job_queue[i] = q_index[j.queue]
        job_priority[i] = j.priority
        job_create_rank[i] = j_rank[j.uid]
        job_valid[i] = True

    # ---- task arrays -----------------------------------------------------
    task_job = np.full(t_pad, -1, np.int32)
    task_rank = np.zeros(t_pad, np.int32)
    task_job[:len(tasks)] = task_job_idx
    task_rank[:len(tasks)] = task_ranks

    # ---- queue arrays ----------------------------------------------------
    q_weight = np.zeros(q_pad, np.float32)
    q_entries = np.zeros(q_pad, np.int32)
    q_create_rank = np.arange(q_pad, dtype=np.int32)
    q_deserved = np.zeros((q_pad, 3), np.float32)
    q_alloc0 = np.zeros((q_pad, 3), np.float32)
    for q, i in q_index.items():
        q_weight[i] = ssn.queues[q].weight
    for j in jobs:
        q_entries[q_index[j.queue]] += 1

    prop = ssn.plugins.get("proportion")
    queue_keys, _ = queue_order_spec(ssn)
    prop_overused = ("proportion" in ssn.overused_fns
                     and any(opt.name == "proportion"
                             for tier in ssn.tiers
                             for opt in tier.plugins))
    if prop is not None and getattr(prop, "queue_opts", None):
        for q, attr in prop.queue_opts.items():
            i = q_index.get(q)
            if i is not None:
                q_deserved[i] = attr.deserved.to_vec()
                q_alloc0[i] = attr.allocated.to_vec()

    # ---- drf arrays ------------------------------------------------------
    job_keys, _ = job_order_spec(ssn)
    j_alloc0 = np.zeros((j_pad, 3), np.float32)
    cluster_total = np.ones(3, np.float32)
    drf = ssn.plugins.get("drf")
    if K_DRF_SHARE in job_keys and drf is not None:
        cluster_total = drf.total_resource.to_vec()
        for j in jobs:
            attr = drf.job_opts.get(j.uid)
            if attr is not None:
                j_alloc0[j_index[j.uid]] = attr.allocated.to_vec()

    # ---- scores / predicates (sig-indexed static + in-kernel dynamic) ---
    task_sig = terms.task_sig(tasks, t_pad)
    s_pad = pad_to_bucket(terms.static.n_sigs, 4)
    sig_scores = np.zeros((s_pad, device.n_padded), np.float32)
    sig_pred = np.zeros((s_pad, device.n_padded), bool)
    sig_scores[:terms.static.n_sigs] = terms.static.score
    sig_pred[:terms.static.n_sigs] = terms.static.pred
    dyn_enabled = terms.dynamic.enabled
    dyn_weights = np.asarray([terms.dynamic.least_requested,
                              terms.dynamic.balanced_resource], np.float32)

    # per-sig mean request / nonzero-request (waterfall capacity estimates
    # in the batched kernel; exactness is not required — acceptance checks
    # real per-task requests)
    n_real = len(tasks)
    sig_real = task_sig[:n_real]
    counts = np.bincount(sig_real, minlength=s_pad).astype(np.float32)
    denom = np.maximum(counts, 1.0)[:, None]
    sig_req = np.zeros((s_pad, batch.resreq.shape[1]), np.float32)
    sig_nz = np.zeros((s_pad, 2), np.float32)
    for c in range(batch.resreq.shape[1]):
        sig_req[:, c] = np.bincount(sig_real, weights=batch.resreq[:n_real, c],
                                    minlength=s_pad)
    for c in range(2):
        sig_nz[:, c] = np.bincount(sig_real, weights=batch.nz_req[:n_real, c],
                                   minlength=s_pad)
    sig_req /= denom
    sig_nz /= denom

    return CycleInputs(
        queue_ids=queue_ids, jobs=jobs, tasks=tasks, device=device,
        resreq=batch.resreq, init_resreq=batch.init_resreq,
        task_nz=batch.nz_req, task_job=task_job, task_rank=task_rank,
        task_sig=task_sig, task_valid=batch.valid,
        sig_scores=sig_scores, sig_pred=sig_pred, sig_nz=sig_nz,
        sig_req=sig_req,
        min_available=min_av, order_min_available=order_min_av,
        init_allocated=init_alloc, job_queue=job_queue,
        job_priority=job_priority, job_create_rank=job_create_rank,
        job_valid=job_valid,
        q_weight=q_weight, q_entries=q_entries, q_create_rank=q_create_rank,
        q_deserved=q_deserved, q_alloc0=q_alloc0,
        j_alloc0=j_alloc0, cluster_total=cluster_total,
        dyn_weights=dyn_weights, dyn_enabled=dyn_enabled,
        job_keys=job_keys, queue_keys=queue_keys, gang_enabled=gang,
        prop_overused=prop_overused)


def replay_decisions(ssn: Session, inputs: CycleInputs,
                     task_state: np.ndarray, task_node: np.ndarray,
                     task_seq: np.ndarray) -> None:
    """Apply a whole-cycle kernel's decisions through the Session in the
    kernel's assignment order, so host plugin state, event handlers, and
    the gang dispatch barrier observe identical events."""
    from ..kernels.fused import ALLOC, ALLOC_OB, FAIL, PIPELINE, SKIP

    device = inputs.device
    tasks = inputs.tasks
    order = [i for i in range(len(tasks)) if task_state[i] != SKIP]
    order.sort(key=lambda i: task_seq[i])
    try:
        for i in order:
            task = tasks[i]
            kind = int(task_state[i])
            if kind in (ALLOC, ALLOC_OB, PIPELINE):
                node_name = device.node_name(int(task_node[i]))
                if kind == PIPELINE:
                    ssn.pipeline(task, node_name)
                else:
                    ssn.allocate(task, node_name, kind == ALLOC_OB)
            elif kind == FAIL:
                # fit-delta diagnostics for the task that broke its job,
                # against node state at failure time (host nodes mirror the
                # kernel here)
                job = ssn.jobs.get(task.job)
                if job is not None:
                    job.nodes_fit_delta = {}
                    for node in ssn.nodes.values():
                        delta = node.idle.clone()
                        delta.fit_delta(task.resreq)
                        job.nodes_fit_delta[node.name] = delta
    except Exception:
        # host replay stopped mid-way (e.g. volume allocation failure):
        # device state holds phantom allocations — rebuild from host truth
        device.resync(ssn.nodes)
        raise
