"""Host wrapper for the batched (round-based) allocate solver.

Same tensorization and replay as the fused path (actions/cycle_inputs.py)
— only the device algorithm differs: kernels/batched.py places many tasks
per round instead of one per while-iteration, trading placement-by-
placement ordering exactness for two orders of magnitude less sequential
device work (see the faithfulness contract in kernels/batched.py).

``sharded=True`` (KUBEBATCH_SOLVER=sharded) runs the same round loop with
the node axis partitioned over every visible device
(kernels/batched_sharded.py); it falls back to the single-chip engine
when only one device exists.
"""
from __future__ import annotations

from ..faults import check as _fault_check
from ..framework import Session
from ..kernels.batched import solve_batched
from .cycle_inputs import (EMPTY_CYCLE, build_cycle_inputs, cycle_supported,
                           replay_decisions)

batched_supported = cycle_supported


def execute_batched(ssn: Session, sharded: bool = False,
                    hier: bool = False, activeset: bool = False,
                    inputs=None):
    """Run the whole allocate action as a handful of round dispatches.
    Returns the engine that actually ran ("activeset" / "hier" /
    "batched" / "sharded" — truthy), or False — without consuming any
    state — when the snapshot has features the kernels can't express
    (the caller falls back). Affinity/port cycles run first-class on
    the batched and sharded engines: the sharded twin partitions the
    affinity matmuls over the mesh with a replicated carry
    (kernels/batched_sharded.py). The two-level engine cannot express
    the cluster-global affinity carries, so an affinity cycle demotes
    hier -> batched/sharded — counted
    (metrics.engine_demotions_total), never silent.

    ``activeset=True`` lets the steady active-set engine
    (kernels/activeset.py) claim the cycle first: it solves the packed
    churn-grain sub-problem (or the combined full-width audit on its
    cadence) and declines — falling through to the full solve below —
    when the cycle is cold-sized, carries inexact pairs, or the engine
    demoted itself.

    ``inputs`` lets a caller that already tensorized this session hand
    the result in (the pipelined executor builds inputs to decide
    whether to dispatch async and falls back here on decline) —
    build_cycle_inputs consumes one-shot cache state
    (EventFold.take_active_rows), so building twice per session would
    hand the second build an empty active set."""
    if inputs is None:
        inputs = build_cycle_inputs(ssn, allow_affinity=True)
    if inputs is EMPTY_CYCLE:
        return "hier" if hier else ("sharded" if sharded else "batched")
    if inputs is None:
        return False
    # injection seam: after the support gates (no state consumed yet),
    # before the device dispatch and the replay
    _fault_check("device.dispatch")
    if hier:
        if getattr(inputs, "affinity", None) is None:
            if activeset:
                from ..kernels import activeset as _activeset
                res = _activeset.solve_cycle(inputs.device, inputs)
                if res is not None:
                    task_state, task_node, task_seq, _ = res
                    replay_decisions(ssn, inputs, task_state, task_node,
                                     task_seq)
                    return "activeset"
            from ..kernels.hier import solve_hier
            task_state, task_node, task_seq, _ = solve_hier(
                inputs.device, inputs)
            replay_decisions(ssn, inputs, task_state, task_node, task_seq)
            return "hier"
        # affinity vocabulary: the flat engines own it — demote, and
        # keep the sharded upgrade when a mesh is visible
        from ..metrics import count_engine_demotion
        import jax as _jax
        sharded = len(_jax.devices()) > 1
        count_engine_demotion("hier", "sharded" if sharded else "batched")
    if sharded:
        import jax

        if len(jax.devices()) > 1:
            from ..kernels.batched_sharded import (node_mesh,
                                                   solve_batched_sharded)
            task_state, task_node, task_seq, _ = solve_batched_sharded(
                node_mesh(), inputs.device, inputs)
            replay_decisions(ssn, inputs, task_state, task_node, task_seq)
            return "sharded"
        # single device: the mesh adds nothing — plain engine below
        from ..metrics import count_engine_demotion
        count_engine_demotion("sharded", "batched")
    task_state, task_node, task_seq, _ = solve_batched(inputs.device, inputs)
    replay_decisions(ssn, inputs, task_state, task_node, task_seq)
    return "batched"
