"""Host wrapper for the fused allocate kernel: session -> tensors ->
ONE dispatch -> replay decisions through the Session.

The replay (ssn.allocate / ssn.pipeline in the kernel's assignment order)
keeps host-side plugin state, event handlers, and the gang dispatch
barrier byte-identical to what the per-visit paths produce — the kernel
only *decides*, the Session still *applies*.
"""
from __future__ import annotations

import functools
import time
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from ..api import JobInfo, TaskInfo, TaskStatus, ready_statuses
from ..framework import Session
from ..kernels.fused import (ALLOC, ALLOC_OB, FAIL, PIPELINE, SKIP,
                             K_DRF_SHARE, K_GANG_READY, K_PRIORITY,
                             K_PROP_SHARE, fused_allocate, unpack_host_block)
from ..kernels.solver import DeviceSession
from ..kernels.tensorize import TaskBatch, pad_to_bucket
from ..kernels.terms import device_supported, solver_terms
from ..metrics import update_solver_kernel_duration

#: job-order plugins the kernel can express, in any tier order
_JOB_KEYS = {"priority": K_PRIORITY, "gang": K_GANG_READY,
             "drf": K_DRF_SHARE}
_QUEUE_KEYS = {"proportion": K_PROP_SHARE}


def _job_order_spec(ssn: Session) -> Tuple[Tuple[str, ...], bool]:
    keys: List[str] = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.job_order_disabled or opt.name not in ssn.job_order_fns:
                continue
            key = _JOB_KEYS.get(opt.name)
            if key is None:
                return (), False
            keys.append(key)
    return tuple(keys), True


def _queue_order_spec(ssn: Session) -> Tuple[Tuple[str, ...], bool]:
    keys: List[str] = []
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if opt.queue_order_disabled or opt.name not in ssn.queue_order_fns:
                continue
            key = _QUEUE_KEYS.get(opt.name)
            if key is None:
                return (), False
            keys.append(key)
    return tuple(keys), True


def fused_supported(ssn: Session) -> bool:
    """The fused kernel expresses the built-in order/fairness plugins; any
    custom job/queue order, overused, or ready fn falls back to the
    per-visit path. Predicate / node-order callbacks are supported through
    kernels/terms.solver_terms — static terms as sig-indexed matrices,
    least-requested / balanced-resource in-kernel; snapshots with
    allocation-dependent features the kernels can't model (inter-pod
    affinity, pending host ports — terms.py) are rejected inside
    execute_fused, which then returns False."""
    _, ok_j = _job_order_spec(ssn)
    _, ok_q = _queue_order_spec(ssn)
    custom_overused = any(name != "proportion" for name in ssn.overused_fns)
    custom_ready = any(name != "gang" for name in ssn.job_ready_fns)
    return ok_j and ok_q and not custom_overused and not custom_ready


def _gang_enabled(ssn: Session) -> bool:
    for tier in ssn.tiers:
        for opt in tier.plugins:
            if not opt.job_ready_disabled and opt.name in ssn.job_ready_fns:
                return True
    return False


def execute_fused(ssn: Session) -> bool:
    """Run the whole allocate action as one dispatch. Returns False —
    without consuming any state — when the snapshot has features the
    kernel can't express (the caller falls back to the host path)."""
    # ---- queues ----------------------------------------------------------
    queue_ids = sorted(ssn.queues)          # uid order = order fallback
    q_index = {q: i for i, q in enumerate(queue_ids)}
    q_pad = pad_to_bucket(len(queue_ids), 4)

    # ---- jobs ------------------------------------------------------------
    jobs: List[JobInfo] = [j for j in ssn.jobs.values()
                           if j.queue in q_index]
    # creation-rank tie-break (creation_timestamp, uid)
    jobs_sorted = sorted(jobs, key=lambda j: (j.creation_timestamp, j.uid))
    j_rank = {j.uid: r for r, j in enumerate(jobs_sorted)}
    j_pad = pad_to_bucket(len(jobs), 4)
    j_index = {j.uid: i for i, j in enumerate(jobs)}

    # ---- tasks (pending, non-BestEffort, in task-order per job) ----------
    tasks: List[TaskInfo] = []
    task_job_idx: List[int] = []
    task_ranks: List[int] = []
    for j in jobs:
        pend = [t for t in j.task_status_index.get(TaskStatus.PENDING,
                                                   {}).values()
                if not t.resreq.is_empty()]
        pend.sort(key=functools.cmp_to_key(
            lambda a, b: -1 if ssn.task_order_fn(a, b) else 1))
        for rank, t in enumerate(pend):
            tasks.append(t)
            task_job_idx.append(j_index[j.uid])
            task_ranks.append(rank)
    if not tasks:
        return True
    # cheap feature gate BEFORE tensorizing/uploading the cluster — a
    # fallback cycle must not pay the device transfer
    if not device_supported(ssn, tasks):
        return False
    if ssn.device_snapshot is None:
        ssn.device_snapshot = DeviceSession(ssn.nodes)
    device: DeviceSession = ssn.device_snapshot
    terms = solver_terms(ssn, device, tasks)
    if terms is None:
        return False
    batch = TaskBatch.from_tasks(tasks)
    t_pad = batch.t_padded

    # ---- job arrays ------------------------------------------------------
    gang = _gang_enabled(ssn)
    min_av = np.zeros(j_pad, np.int32)
    order_min_av = np.zeros(j_pad, np.int32)
    init_alloc = np.zeros(j_pad, np.int32)
    job_queue = np.zeros(j_pad, np.int32)
    job_priority = np.zeros(j_pad, np.float32)
    job_create_rank = np.zeros(j_pad, np.int32)
    job_valid = np.zeros(j_pad, bool)
    for i, j in enumerate(jobs):
        min_av[i] = j.min_available if gang else 0
        order_min_av[i] = j.min_available
        init_alloc[i] = j.count(*ready_statuses())
        job_queue[i] = q_index[j.queue]
        job_priority[i] = j.priority
        job_create_rank[i] = j_rank[j.uid]
        job_valid[i] = True

    # ---- task arrays -----------------------------------------------------
    task_job = np.full(t_pad, -1, np.int32)
    task_rank = np.zeros(t_pad, np.int32)
    task_job[:len(tasks)] = task_job_idx
    task_rank[:len(tasks)] = task_ranks

    # ---- queue arrays ----------------------------------------------------
    q_weight = np.zeros(q_pad, np.float32)
    q_entries = np.zeros(q_pad, np.int32)
    q_create_rank = np.arange(q_pad, dtype=np.int32)
    q_deserved = np.zeros((q_pad, 3), np.float32)
    q_alloc0 = np.zeros((q_pad, 3), np.float32)
    for q, i in q_index.items():
        q_weight[i] = ssn.queues[q].weight
    for j in jobs:
        q_entries[q_index[j.queue]] += 1

    prop = ssn.plugins.get("proportion")
    queue_keys, _ = _queue_order_spec(ssn)
    prop_overused = ("proportion" in ssn.overused_fns
                     and any(opt.name == "proportion"
                             for tier in ssn.tiers
                             for opt in tier.plugins))
    if prop is not None and getattr(prop, "queue_opts", None):
        for q, attr in prop.queue_opts.items():
            i = q_index.get(q)
            if i is not None:
                q_deserved[i] = attr.deserved.to_vec()
                q_alloc0[i] = attr.allocated.to_vec()

    # ---- drf arrays ------------------------------------------------------
    job_keys, _ = _job_order_spec(ssn)
    j_alloc0 = np.zeros((j_pad, 3), np.float32)
    cluster_total = np.ones(3, np.float32)
    drf = ssn.plugins.get("drf")
    if K_DRF_SHARE in job_keys and drf is not None:
        cluster_total = drf.total_resource.to_vec()
        for j in jobs:
            attr = drf.job_opts.get(j.uid)
            if attr is not None:
                j_alloc0[j_index[j.uid]] = attr.allocated.to_vec()

    # ---- scores / predicates (sig-indexed static + in-kernel dynamic) ---
    task_sig = terms.task_sig(tasks, t_pad)
    s_pad = pad_to_bucket(terms.static.n_sigs, 4)
    sig_scores = np.zeros((s_pad, device.n_padded), np.float32)
    sig_pred = np.zeros((s_pad, device.n_padded), bool)
    sig_scores[:terms.static.n_sigs] = terms.static.score
    sig_pred[:terms.static.n_sigs] = terms.static.pred
    dyn_enabled = terms.dynamic.enabled
    dyn_weights = np.asarray([terms.dynamic.least_requested,
                              terms.dynamic.balanced_resource], np.float32)

    max_iters = int(t_pad + 3 * j_pad + q_pad + 8)

    start = time.perf_counter()
    (host_block, idle_f, rel_f, ntasks_f, nz_f) = fused_allocate(
        device.idle, device.releasing, device.backfilled,
        device.allocatable_cm, device.nz_req,
        device.max_task_num, device.n_tasks, device.node_ok,
        jnp.asarray(batch.resreq), jnp.asarray(batch.init_resreq),
        jnp.asarray(batch.nz_req), jnp.asarray(task_job),
        jnp.asarray(task_rank), jnp.asarray(task_sig),
        jnp.asarray(batch.valid), jnp.asarray(sig_scores),
        jnp.asarray(sig_pred),
        jnp.asarray(min_av), jnp.asarray(order_min_av),
        jnp.asarray(init_alloc), jnp.asarray(job_queue),
        jnp.asarray(job_priority), jnp.asarray(job_create_rank),
        jnp.asarray(job_valid),
        jnp.asarray(q_weight), jnp.asarray(q_entries),
        jnp.asarray(q_create_rank), jnp.asarray(q_deserved),
        jnp.asarray(q_alloc0),
        jnp.asarray(j_alloc0), jnp.asarray(cluster_total),
        jnp.asarray(dyn_weights),
        job_keys=job_keys, queue_keys=queue_keys,
        gang_enabled=gang, prop_overused=prop_overused,
        dyn_enabled=dyn_enabled, max_iters=max_iters)
    host_block = np.asarray(host_block)   # the cycle's ONE blocking read
    task_state, task_node, task_seq, _ = unpack_host_block(host_block)
    device.idle, device.releasing, device.n_tasks = idle_f, rel_f, ntasks_f
    device.nz_req = nz_f
    update_solver_kernel_duration("fused_allocate",
                                  time.perf_counter() - start)

    # ---- replay decisions through the Session, in kernel order ----------
    order = [i for i in range(len(tasks))
             if task_state[i] != SKIP]
    order.sort(key=lambda i: task_seq[i])
    try:
        for i in order:
            task = tasks[i]
            kind = int(task_state[i])
            if kind in (ALLOC, ALLOC_OB, PIPELINE):
                node_name = device.node_name(int(task_node[i]))
                if kind == PIPELINE:
                    ssn.pipeline(task, node_name)
                else:
                    ssn.allocate(task, node_name, kind == ALLOC_OB)
            elif kind == FAIL:
                # fit-delta diagnostics for the task that broke its job,
                # against node state at failure time (host nodes mirror the
                # kernel here)
                job = ssn.jobs.get(task.job)
                if job is not None:
                    job.nodes_fit_delta = {}
                    for node in ssn.nodes.values():
                        delta = node.idle.clone()
                        delta.fit_delta(task.resreq)
                        job.nodes_fit_delta[node.name] = delta
    except Exception:
        # host replay stopped mid-way (e.g. volume allocation failure):
        # device state holds phantom allocations — rebuild from host truth
        device.resync(ssn.nodes)
        raise
    return True
