"""Host wrapper for the fused allocate kernel: session -> tensors ->
ONE dispatch -> replay decisions through the Session.

The replay (ssn.allocate / ssn.pipeline in the kernel's assignment order)
keeps host-side plugin state, event handlers, and the gang dispatch
barrier byte-identical to what the per-visit paths produce — the kernel
only *decides*, the Session still *applies*.
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np

from ..compilesvc import instrument as _instrument
from ..compilesvc import register_provider as _register_provider
from ..faults import check as _fault_check
from ..framework import Session
from ..kernels.fused import fused_allocate, unpack_host_block
from ..kernels.narrow import narrow_enabled
from ..kernels.pack import pack_inputs, unpack
from ..metrics import count_blocking_readback
from ..obs import span as _span
from .cycle_inputs import (EMPTY_CYCLE, build_cycle_inputs, cycle_supported,
                           replay_decisions)

# compatibility re-exports (tests and older callers import these from here)
fused_supported = cycle_supported

#: per-cycle inputs shipped as packed buffers (see kernels/pack.py);
#: node-axis arrays live on the DeviceSession already
_F32 = ("resreq", "init_resreq", "task_nz", "sig_scores", "job_priority",
        "q_weight", "q_deserved", "q_alloc0", "j_alloc0", "cluster_total",
        "dyn_weights")
_I32 = ("task_job", "task_rank", "task_sig", "min_available",
        "order_min_available", "init_allocated", "job_queue",
        "job_create_rank", "q_entries", "q_create_rank")
_BOOL = ("task_valid", "job_valid", "sig_pred")


@partial(jax.jit, static_argnames=("lay_f", "lay_i", "lay_b", "job_keys",
                                   "queue_keys", "gang_enabled",
                                   "prop_overused", "dyn_enabled",
                                   "max_iters", "narrow", "narrow_gate"))
def _fused_packed(buf_f, buf_i, buf_b, idle, releasing, backfilled,
                  allocatable_cm, nz_req0, max_task_num, n_tasks, node_ok,
                  lay_f, lay_i, lay_b, job_keys, queue_keys, gang_enabled,
                  prop_overused, dyn_enabled, max_iters, narrow=False,
                  narrow_gate=False):
    f = unpack(buf_f, lay_f)
    i = unpack(buf_i, lay_i)
    b = unpack(buf_b, lay_b)
    return fused_allocate(
        idle, releasing, backfilled, allocatable_cm, nz_req0, max_task_num,
        n_tasks, node_ok,
        f["resreq"], f["init_resreq"], f["task_nz"], i["task_job"],
        i["task_rank"], i["task_sig"], b["task_valid"], f["sig_scores"],
        b["sig_pred"],
        i["min_available"], i["order_min_available"], i["init_allocated"],
        i["job_queue"], f["job_priority"], i["job_create_rank"],
        b["job_valid"],
        f["q_weight"], i["q_entries"], i["q_create_rank"], f["q_deserved"],
        f["q_alloc0"],
        f["j_alloc0"], f["cluster_total"], f["dyn_weights"],
        job_keys=job_keys, queue_keys=queue_keys, gang_enabled=gang_enabled,
        prop_overused=prop_overused, dyn_enabled=dyn_enabled,
        max_iters=max_iters, narrow=narrow, narrow_gate=narrow_gate)


# accounted trace boundary (compilesvc): the small-cycle fused entry
_fused_packed = _instrument("fused", "_fused_packed", _fused_packed)


def prepare_fused(inputs):
    """The exact (args, statics) the fused packed entry dispatches for
    these CycleInputs — shared by the live dispatch and the compilesvc
    signature provider (a registered signature can never drift from the
    live arg-building code)."""
    device = inputs.device
    t_pad = inputs.task_valid.shape[0]
    j_pad = inputs.job_valid.shape[0]
    q_pad = inputs.q_weight.shape[0]
    max_iters = int(t_pad + 3 * j_pad + q_pad + 8)
    buf_f, lay_f, buf_i, lay_i, buf_b, lay_b = pack_inputs(
        lambda n: getattr(inputs, n), _F32, _I32, _BOOL)
    args = (buf_f, buf_i, buf_b,
            device.idle, device.releasing, device.backfilled,
            device.allocatable_cm, device.nz_req,
            device.max_task_num, device.n_tasks, device.node_ok)
    # shape-derived (the rpc wire's device lacks n_padded); AUTO narrow
    # requires bf16-exact score scale (kernels/narrow.py)
    narrow = narrow_enabled(
        int(device.node_ok.shape[0]), t_pad,
        static_scores=inputs.sig_scores,
        dyn_weights=(inputs.dyn_weights if inputs.dyn_enabled
                     else None))
    statics = dict(
        lay_f=lay_f, lay_i=lay_i, lay_b=lay_b,
        job_keys=inputs.job_keys, queue_keys=inputs.queue_keys,
        gang_enabled=inputs.gang_enabled,
        prop_overused=inputs.prop_overused,
        dyn_enabled=inputs.dyn_enabled, max_iters=max_iters,
        narrow=narrow,
        # telemetry: the exactness-gate hit — the shape thresholds alone
        # wanted the narrow diet but the score/weight scale refused it
        narrow_gate=(not narrow and narrow_enabled(
            int(device.node_ok.shape[0]), t_pad)))
    return args, statics


def execute_fused(ssn: Session) -> bool:
    """Run the whole allocate action as one dispatch. Returns False —
    without consuming any state — when the snapshot has features the
    kernel can't express (the caller falls back to the host path)."""
    inputs = build_cycle_inputs(ssn)
    if inputs is EMPTY_CYCLE:
        return True
    if inputs is None:
        return False
    # injection seam: after the support gates, before the dispatch
    _fault_check("device.dispatch")
    device = inputs.device
    args, statics = prepare_fused(inputs)

    # the kernel span replaces the perf_counter pair AND the explicit
    # solver_trace annotation (cat="kernel" enters both derived views);
    # its extent matches the old accounting: dispatch through carry commit
    with _span("fused_allocate", cat="kernel") as sp:
        (host_block, idle_f, rel_f, ntasks_f, nz_f) = _fused_packed(
            *args, **statics)
        count_blocking_readback()
        with _span("readback", cat="readback"):
            host_block = np.asarray(host_block)  # the cycle's ONE blocking read
        task_state, task_node, task_seq, _, telem = \
            unpack_host_block(host_block)
        from ..obs import telemetry as _obs_telemetry
        _obs_telemetry.record(telem, span=sp)
        device.idle, device.releasing, device.n_tasks = \
            idle_f, rel_f, ntasks_f
        device.nz_req = nz_f

    replay_decisions(ssn, inputs, task_state, task_node, task_seq)
    return True


# ---------------------------------------------------------------------
# compilesvc signature provider — fused engages below the auto-batched
# threshold: tiny cold configs and the steady churn regime
# ---------------------------------------------------------------------

@_register_provider("actions.allocate_fused")
def compile_signatures(materials):
    from ..compilesvc.registry import Signature, signature_key
    from .allocate import AUTO_BATCHED_MIN, AUTO_HIER_MIN_NODES

    out = []
    for regime, inputs in (("cold", materials.cold_inputs),
                           ("steady", materials.steady_inputs)):
        if inputs is None or isinstance(inputs, str):
            continue
        if len(inputs.tasks) >= AUTO_BATCHED_MIN:
            continue    # this regime dispatches the batched engine
        if len(inputs.device.state.names) >= AUTO_HIER_MIN_NODES:
            continue    # auto keys on the node axis first (ISSUE 15):
            # hier/activeset own cluster-scale configs at every churn
            # level, so a fused graph here would never be dispatched
        if getattr(inputs, "affinity", None) is not None:
            continue    # fused never consumes the affinity vocabulary
        args, statics = prepare_fused(inputs)
        out.append(Signature(
            engine="fused", entry="_fused_packed",
            key=signature_key("_fused_packed", args, statics),
            lower=lambda a=args, s=statics: _fused_packed.lower(*a, **s),
            run=lambda a=args, s=statics: _fused_packed(*a, **s),
            note=(f"{regime} T={inputs.task_valid.shape[0]} "
                  f"N={inputs.device.n_padded}")))
    return out
