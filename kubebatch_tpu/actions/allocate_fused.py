"""Host wrapper for the fused allocate kernel: session -> tensors ->
ONE dispatch -> replay decisions through the Session.

The replay (ssn.allocate / ssn.pipeline in the kernel's assignment order)
keeps host-side plugin state, event handlers, and the gang dispatch
barrier byte-identical to what the per-visit paths produce — the kernel
only *decides*, the Session still *applies*.
"""
from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from ..framework import Session
from ..kernels.fused import fused_allocate, unpack_host_block
from ..metrics import update_solver_kernel_duration
from .cycle_inputs import (EMPTY_CYCLE, build_cycle_inputs, cycle_supported,
                           replay_decisions)

# compatibility re-exports (tests and older callers import these from here)
fused_supported = cycle_supported


def execute_fused(ssn: Session) -> bool:
    """Run the whole allocate action as one dispatch. Returns False —
    without consuming any state — when the snapshot has features the
    kernel can't express (the caller falls back to the host path)."""
    inputs = build_cycle_inputs(ssn)
    if inputs is EMPTY_CYCLE:
        return True
    if inputs is None:
        return False
    device = inputs.device
    t_pad = inputs.task_valid.shape[0]
    j_pad = inputs.job_valid.shape[0]
    q_pad = inputs.q_weight.shape[0]
    max_iters = int(t_pad + 3 * j_pad + q_pad + 8)

    start = time.perf_counter()
    (host_block, idle_f, rel_f, ntasks_f, nz_f) = fused_allocate(
        device.idle, device.releasing, device.backfilled,
        device.allocatable_cm, device.nz_req,
        device.max_task_num, device.n_tasks, device.node_ok,
        jnp.asarray(inputs.resreq), jnp.asarray(inputs.init_resreq),
        jnp.asarray(inputs.task_nz), jnp.asarray(inputs.task_job),
        jnp.asarray(inputs.task_rank), jnp.asarray(inputs.task_sig),
        jnp.asarray(inputs.task_valid), jnp.asarray(inputs.sig_scores),
        jnp.asarray(inputs.sig_pred),
        jnp.asarray(inputs.min_available),
        jnp.asarray(inputs.order_min_available),
        jnp.asarray(inputs.init_allocated), jnp.asarray(inputs.job_queue),
        jnp.asarray(inputs.job_priority),
        jnp.asarray(inputs.job_create_rank),
        jnp.asarray(inputs.job_valid),
        jnp.asarray(inputs.q_weight), jnp.asarray(inputs.q_entries),
        jnp.asarray(inputs.q_create_rank), jnp.asarray(inputs.q_deserved),
        jnp.asarray(inputs.q_alloc0),
        jnp.asarray(inputs.j_alloc0), jnp.asarray(inputs.cluster_total),
        jnp.asarray(inputs.dyn_weights),
        job_keys=inputs.job_keys, queue_keys=inputs.queue_keys,
        gang_enabled=inputs.gang_enabled,
        prop_overused=inputs.prop_overused,
        dyn_enabled=inputs.dyn_enabled, max_iters=max_iters)
    host_block = np.asarray(host_block)   # the cycle's ONE blocking read
    task_state, task_node, task_seq, _ = unpack_host_block(host_block)
    device.idle, device.releasing, device.n_tasks = idle_f, rel_f, ntasks_f
    device.nz_req = nz_f
    update_solver_kernel_duration("fused_allocate",
                                  time.perf_counter() - start)

    replay_decisions(ssn, inputs, task_state, task_node, task_seq)
    return True
