"""gRPC solver sidecar — dense snapshots in, assignment decisions out.

Serves the allocate kernels behind the Solver service defined in
solver.proto, selecting the engine by snapshot size exactly like the
in-process auto mode (actions/allocate.py): snapshots at or above
AUTO_BATCHED_MIN pending tasks run the round-based batched engine,
smaller ones the bind-for-bind fused engine. The service wiring is
hand-written over grpc generic handlers (grpcio-tools is not available
in this image; message classes are protoc-generated into solver_pb2.py).

Multi-tenant (ISSUE 8): every request is attributed to a tenant via the
``kb-tenant`` gRPC metadata key (absent = the "default" tenant — a
tenant-unaware client behaves exactly as before). Solve routes through
the tenantsvc service (admission + priority lanes + cross-tenant mega
coalescing, tenantsvc/service.py); the victim endpoints resolve their
registry through the tenant's session, so state ids are namespaced per
tenant and cross-tenant bleed is structurally impossible. The wire
schema (solver.proto) is untouched — tenancy is metadata, like the
kb-trace-* keys.

The request decode is split out (``decode_snapshot``) so the single
solve path and the mega dispatcher consume the same arrays, and the
fused branch exposes its exact (args, statics) via ``fused_lane_args``
— the coalescing key and the registered mega compile signatures both
derive from it, so they cannot drift from a live dispatch.
"""
from __future__ import annotations

import json
import os
from concurrent import futures
from dataclasses import dataclass, field
from typing import Optional

import grpc
import jax.numpy as jnp
import numpy as np

from .. import obs

from ..kernels.fused import (ALLOC, ALLOC_OB, PIPELINE, SKIP,
                             K_DRF_SHARE, K_GANG_READY, K_PRIORITY,
                             K_PROP_SHARE, fused_allocate, unpack_host_block)
from ..kernels.tensorize import pad_to_bucket
from . import solver_pb2

SERVICE = "kubebatch_tpu.Solver"


def _mat(values, n, r=3) -> np.ndarray:
    out = np.zeros((n, r), np.float32)
    flat = np.asarray(values, np.float32)
    out.flat[:flat.size] = flat
    return out


@dataclass
class WireSolve:
    """One decoded SnapshotRequest: every padded array the engines read,
    plus the derived static flags. Built once per request (the tenant
    dispatcher decodes before grouping; the solve paths reuse it)."""

    n: int
    t: int
    j: int
    q: int
    n_pad: int
    t_pad: int
    j_pad: int
    q_pad: int
    idle: np.ndarray
    releasing: np.ndarray
    backfilled: np.ndarray
    mtn: np.ndarray
    ntasks: np.ndarray
    node_ok: np.ndarray
    resreq: np.ndarray
    init_resreq: np.ndarray
    task_job: np.ndarray
    task_rank: np.ndarray
    task_valid: np.ndarray
    min_av: np.ndarray
    order_min_av: np.ndarray
    init_ready: np.ndarray
    job_queue: np.ndarray
    job_priority: np.ndarray
    job_create_rank: np.ndarray
    job_valid: np.ndarray
    q_weight: np.ndarray
    q_entries: np.ndarray
    q_create_rank: np.ndarray
    q_deserved: np.ndarray
    q_alloc0: np.ndarray
    cluster_total: np.ndarray
    sig_scores: np.ndarray
    sig_pred: np.ndarray
    task_sig: np.ndarray
    dyn_weights: np.ndarray
    dyn_enabled: bool
    task_nz: np.ndarray
    allocatable_cm: np.ndarray
    nz_req0: np.ndarray
    j_alloc0: np.ndarray
    job_keys: tuple
    queue_keys: tuple
    affinity: object = None
    use_batched: bool = False
    max_iters: int = 0
    pipe_enabled: bool = False
    _extra: dict = field(default_factory=dict)


def decode_snapshot(req: solver_pb2.SnapshotRequest) -> WireSolve:
    nodes, tasks, jobs, queues = req.nodes, req.tasks, req.jobs, req.queues
    n = len(nodes.names)
    t = len(tasks.uids)
    j = len(jobs.uids)
    q = max(1, len(queues.names))
    n_pad = pad_to_bucket(n)
    t_pad = pad_to_bucket(t)
    j_pad = pad_to_bucket(j, 4)
    q_pad = pad_to_bucket(q, 4)

    idle = np.zeros((n_pad, 3), np.float32)
    idle[:n] = _mat(nodes.idle, n)
    releasing = np.zeros((n_pad, 3), np.float32)
    releasing[:n] = _mat(nodes.releasing, n)
    backfilled = np.zeros((n_pad, 3), np.float32)
    backfilled[:n] = _mat(nodes.backfilled, n)
    mtn = np.zeros(n_pad, np.int32)
    mtn[:n] = nodes.max_task_num
    ntasks = np.zeros(n_pad, np.int32)
    ntasks[:n] = nodes.n_tasks
    node_ok = np.zeros(n_pad, bool)
    node_ok[:n] = nodes.schedulable

    resreq = np.zeros((t_pad, 3), np.float32)
    resreq[:t] = _mat(tasks.resreq, t)
    init_resreq = np.zeros((t_pad, 3), np.float32)
    init_resreq[:t] = _mat(tasks.init_resreq, t)
    task_job = np.full(t_pad, -1, np.int32)
    task_job[:t] = tasks.job_index
    task_rank = np.zeros(t_pad, np.int32)
    task_rank[:t] = tasks.rank
    task_valid = np.zeros(t_pad, bool)
    task_valid[:t] = True

    min_av = np.zeros(j_pad, np.int32)
    min_av[:j] = jobs.min_available if req.gang_enabled else [0] * j
    order_min_av = np.zeros(j_pad, np.int32)
    order_min_av[:j] = jobs.min_available
    init_ready = np.zeros(j_pad, np.int32)
    init_ready[:j] = jobs.init_ready
    job_queue = np.zeros(j_pad, np.int32)
    job_queue[:j] = jobs.queue_index
    job_priority = np.zeros(j_pad, np.float32)
    job_priority[:j] = jobs.priority
    job_create_rank = np.zeros(j_pad, np.int32)
    job_create_rank[:j] = jobs.create_rank
    job_valid = np.zeros(j_pad, bool)
    job_valid[:j] = True

    q_weight = np.zeros(q_pad, np.float32)
    q_weight[:len(queues.weight)] = queues.weight
    q_entries = np.zeros(q_pad, np.int32)
    for ji_ in range(j):
        q_entries[jobs.queue_index[ji_]] += 1
    q_create_rank = np.arange(q_pad, dtype=np.int32)
    q_deserved = np.zeros((q_pad, 3), np.float32)
    if len(queues.deserved):
        q_deserved[:len(queues.names)] = _mat(queues.deserved,
                                              len(queues.names))
    q_alloc0 = np.zeros((q_pad, 3), np.float32)
    if len(queues.allocated):
        q_alloc0[:len(queues.names)] = _mat(queues.allocated,
                                            len(queues.names))

    cluster_total = np.ones(3, np.float32)
    if len(req.cluster_total):
        cluster_total = np.asarray(req.cluster_total, np.float32)

    if req.job_order_keys:
        job_keys = [k for k in req.job_order_keys
                    if k in (K_PRIORITY, K_GANG_READY, K_DRF_SHARE)]
    else:
        job_keys = []
        if req.priority_enabled:
            job_keys.append(K_PRIORITY)
        if req.gang_enabled:
            job_keys.append(K_GANG_READY)
        if req.drf_enabled:
            job_keys.append(K_DRF_SHARE)
    queue_keys = (K_PROP_SHARE,) if req.proportion_enabled else ()

    # policy terms from the wire: sig-indexed predicate/score matrices +
    # dynamic nodeorder config (PolicyTerms); absent fields fall back to
    # the trivial space (all nodes allowed, zero scores, dynamics off)
    terms = req.terms
    n_sigs = max(1, terms.n_sigs)
    s_pad = pad_to_bucket(n_sigs, 4)
    sig_scores = np.zeros((s_pad, n_pad), np.float32)
    sig_pred = np.zeros((s_pad, n_pad), bool)
    if terms.n_sigs and len(terms.sig_pred):
        sig_pred[:n_sigs, :n] = np.asarray(
            terms.sig_pred, bool).reshape(n_sigs, n)
        sig_scores[:n_sigs, :n] = np.asarray(
            terms.sig_scores, np.float32).reshape(n_sigs, n)
    else:
        sig_pred[:1, :n] = True
    task_sig = np.zeros(t_pad, np.int32)
    if len(terms.task_sig):
        task_sig[:t] = terms.task_sig

    dyn_weights = np.asarray([terms.least_requested_weight,
                              terms.balanced_resource_weight], np.float32)
    dyn_enabled = bool(dyn_weights.any())
    # task_nz travels regardless of the dynamic flags: the batched
    # engine's waterfall cohorts are (sig, nonzero-request) pairs even
    # when dynamic scoring is off
    task_nz = np.zeros((t_pad, 2), np.float32)
    allocatable_cm = np.zeros((n_pad, 2), np.float32)
    nz_req0 = np.zeros((n_pad, 2), np.float32)
    if len(terms.task_nz):
        task_nz[:t] = np.asarray(terms.task_nz, np.float32).reshape(t, 2)
    if len(terms.node_nz):
        nz_req0[:n] = np.asarray(terms.node_nz, np.float32).reshape(n, 2)
    if len(terms.allocatable_cm):
        allocatable_cm[:n] = np.asarray(
            terms.allocatable_cm, np.float32).reshape(n, 2)

    j_alloc0 = np.zeros((j_pad, 3), np.float32)
    if len(jobs.allocated):
        j_alloc0[:j] = _mat(jobs.allocated, j)

    # ---- affinity payload (batched engine only) ------------------------
    affinity = None
    if len(req.affinity):
        affinity = _affinity_from_wire(req, n_pad, t_pad)

    # ---- engine selection by snapshot size (in-process auto parity);
    # affinity snapshots always take the round engine — it alone carries
    # the vocabulary (the client refuses small affinity snapshots) ------
    from ..actions.allocate import AUTO_BATCHED_MIN
    use_batched = t >= AUTO_BATCHED_MIN or affinity is not None
    # strictly-positive like the in-process derivation
    # (cycle_inputs.py pipe_enabled) — negative releasing rows
    # (pipelined reuse) must not enable the pipeline path
    pipe_enabled = bool((releasing[:n] > 0).any())

    return WireSolve(
        n=n, t=t, j=j, q=q, n_pad=n_pad, t_pad=t_pad, j_pad=j_pad,
        q_pad=q_pad, idle=idle, releasing=releasing, backfilled=backfilled,
        mtn=mtn, ntasks=ntasks, node_ok=node_ok, resreq=resreq,
        init_resreq=init_resreq, task_job=task_job, task_rank=task_rank,
        task_valid=task_valid, min_av=min_av, order_min_av=order_min_av,
        init_ready=init_ready, job_queue=job_queue,
        job_priority=job_priority, job_create_rank=job_create_rank,
        job_valid=job_valid, q_weight=q_weight, q_entries=q_entries,
        q_create_rank=q_create_rank, q_deserved=q_deserved,
        q_alloc0=q_alloc0, cluster_total=cluster_total,
        sig_scores=sig_scores, sig_pred=sig_pred, task_sig=task_sig,
        dyn_weights=dyn_weights, dyn_enabled=dyn_enabled, task_nz=task_nz,
        allocatable_cm=allocatable_cm, nz_req0=nz_req0, j_alloc0=j_alloc0,
        job_keys=tuple(job_keys), queue_keys=queue_keys,
        affinity=affinity, use_batched=use_batched,
        max_iters=int(t_pad + 3 * j_pad + q_pad + 8),
        pipe_enabled=pipe_enabled)


def fused_lane_args(req: solver_pb2.SnapshotRequest,
                    w: Optional[WireSolve] = None):
    """The fused branch's exact (positional args, statics) in
    kernels/fused.fused_allocate order — or None when the snapshot
    takes the batched engine (mega never coalesces those). The mega
    coalescing key and the registered mega compile signatures both
    derive from this, so they share the live decode path."""
    if w is None:
        w = decode_snapshot(req)
    if w.use_batched:
        return None
    args = (w.idle, w.releasing, w.backfilled, w.allocatable_cm,
            w.nz_req0, w.mtn, w.ntasks, w.node_ok,
            w.resreq, w.init_resreq, w.task_nz, w.task_job, w.task_rank,
            w.task_sig, w.task_valid, w.sig_scores, w.sig_pred,
            w.min_av, w.order_min_av, w.init_ready, w.job_queue,
            w.job_priority, w.job_create_rank, w.job_valid,
            w.q_weight, w.q_entries, w.q_create_rank, w.q_deserved,
            w.q_alloc0, w.j_alloc0, w.cluster_total, w.dyn_weights)
    statics = dict(job_keys=w.job_keys, queue_keys=w.queue_keys,
                   gang_enabled=bool(req.gang_enabled),
                   prop_overused=bool(req.proportion_enabled),
                   dyn_enabled=w.dyn_enabled, max_iters=w.max_iters)
    return args, statics


def fused_response(req, w: WireSolve, host_block: np.ndarray,
                   solve_ms: float, tenant: Optional[str] = None
                   ) -> solver_pb2.DecisionsResponse:
    """Decode one fused/mega host block into the wire response."""
    task_state, task_node, task_seq, iters, telem = \
        unpack_host_block(host_block)
    # device telemetry: attaches to the innermost open span — under an
    # rpc handler that is the per-request server root, so the frame
    # ships to the client inside the EXISTING kb-trace-bin trailing
    # metadata; a tenant id lands it in metrics' per-tenant store too
    obs.telemetry.record(telem, tenant=tenant)
    return _decisions(req, w, task_state, task_node, task_seq,
                      int(iters), solve_ms)


def _decisions(req, w: WireSolve, task_state, task_node, task_seq,
               iterations: int, solve_ms: float
               ) -> solver_pb2.DecisionsResponse:
    resp = solver_pb2.DecisionsResponse(solve_ms=solve_ms,
                                        iterations=iterations)
    nodes, tasks = req.nodes, req.tasks
    for i in range(w.t):
        kind = int(task_state[i])
        resp.decisions.append(solver_pb2.Decision(
            task_uid=tasks.uids[i], kind=kind,
            node_name=(nodes.names[int(task_node[i])]
                       if kind in (ALLOC, ALLOC_OB, PIPELINE) else ""),
            order=int(task_seq[i]) if kind != SKIP else -1))
    return resp


def solve_snapshot(req: solver_pb2.SnapshotRequest,
                   w: Optional[WireSolve] = None,
                   tenant: Optional[str] = None
                   ) -> solver_pb2.DecisionsResponse:
    if w is None:
        w = decode_snapshot(req)
    if w.use_batched:
        return _solve_batched_wire(req, w)

    lane = fused_lane_args(req, w)
    args, statics = lane
    # cat="host": the server-side solve wall; the update_solver_kernel
    # histogram belongs to the CLIENT's engine accounting, not the
    # sidecar's (solve_ms travels back on the wire as before)
    with obs.span("solve_fused", cat="host", engine="fused") as sp:
        (host_block, *_device_state) = fused_allocate(*args, **statics)
    solve_ms = sp.dur * 1e3        # same extent the perf_counter pair had
    with obs.span("readback", cat="readback"):
        host_block = np.asarray(host_block)   # one device->host transfer
    return fused_response(req, w, host_block, solve_ms, tenant=tenant)


def _affinity_from_wire(req, n_pad: int, t_pad: int):
    """Rebuild kernels/affinity.AffinityInputs from the wire tensors,
    padding the node/task axes to the server's buckets. Field order is
    the shared kernels/affinity.WIRE_FIELDS constant — the client
    encodes with the same one."""
    from ..kernels.affinity import WIRE_FIELDS, AffinityInputs
    from .victims_wire import from_tensor

    if len(req.affinity) != len(WIRE_FIELDS):
        raise ValueError(
            f"affinity payload carries {len(req.affinity)} tensors, "
            f"expected {len(WIRE_FIELDS)}")
    by_name = dict(zip(WIRE_FIELDS, (from_tensor(x)
                                     for x in req.affinity)))
    (node_dom, task_grp, task_req_aff, task_req_anti, task_self_ok,
     task_carry_w, task_pref_w, task_ports, port_base,
     grp_cnt0, anti_cnt0, pref_w0, grp_total0) = (
        by_name[f] for f in WIRE_FIELDS)

    def pad_rows(a, rows, fill=0):
        if a.shape[0] == rows:
            return a
        out = np.full((rows,) + a.shape[1:], fill, a.dtype)
        out[:a.shape[0]] = a
        return out

    def pad_cols(a, cols, fill=0):
        if a.shape[1] == cols:
            return a
        out = np.full((a.shape[0], cols), fill, a.dtype)
        out[:, :a.shape[1]] = a
        return out

    # D axis (domain counts) must match the padded node axis the kernels
    # use (build_affinity_inputs sets D = n_pad)
    return AffinityInputs(
        node_dom=pad_cols(node_dom, n_pad, fill=-1),
        task_grp=pad_rows(task_grp, t_pad),
        task_req_aff=pad_rows(task_req_aff, t_pad),
        task_req_anti=pad_rows(task_req_anti, t_pad),
        task_self_ok=pad_rows(task_self_ok, t_pad),
        task_carry_w=pad_rows(task_carry_w, t_pad),
        task_pref_w=pad_rows(task_pref_w, t_pad),
        task_ports=pad_rows(task_ports, t_pad),
        port_base=pad_rows(port_base, n_pad),
        grp_cnt0=pad_cols(grp_cnt0, n_pad),
        anti_cnt0=pad_cols(anti_cnt0, n_pad),
        pref_w0=pad_cols(pref_w0, n_pad),
        grp_total0=grp_total0.astype(np.float32),
        ip_weight=float(req.affinity_ip_weight),
        ip_enabled=bool(req.affinity_ip_enabled))


class _WireDevice:
    """DeviceSession stand-in for the sidecar: just the capacity arrays
    solve_batched reads and commits (no cross-cycle reuse server-side —
    every request carries its own snapshot)."""

    def __init__(self, idle, releasing, backfilled, allocatable_cm, nz_req,
                 n_tasks, max_task_num, node_ok):
        self.idle = jnp.asarray(idle)
        self.releasing = jnp.asarray(releasing)
        self.backfilled = jnp.asarray(backfilled)
        self.allocatable_cm = jnp.asarray(allocatable_cm)
        self.nz_req = jnp.asarray(nz_req)
        self.n_tasks = jnp.asarray(n_tasks)
        self.max_task_num = jnp.asarray(max_task_num)
        self.node_ok = jnp.asarray(node_ok)


def _solve_batched_wire(req, w: WireSolve) -> solver_pb2.DecisionsResponse:
    """Round-engine path: rebuild CycleInputs from the wire arrays and
    run the same solve_batched the in-process batched mode uses."""
    from ..actions.cycle_inputs import CycleInputs
    from ..kernels.batched import solve_batched

    inputs = CycleInputs(
        queue_ids=list(req.queues.names), jobs=[], tasks=[None] * w.t,
        device=None,
        resreq=w.resreq, init_resreq=w.init_resreq, resreq_raw=None,
        task_nz=w.task_nz, task_job=w.task_job, task_rank=w.task_rank,
        task_sig=w.task_sig, task_valid=w.task_valid,
        sig_scores=w.sig_scores, sig_pred=w.sig_pred,
        min_available=w.min_av, order_min_available=w.order_min_av,
        init_allocated=w.init_ready, job_queue=w.job_queue,
        job_priority=w.job_priority, job_create_rank=w.job_create_rank,
        job_valid=w.job_valid,
        q_weight=w.q_weight, q_entries=w.q_entries,
        q_create_rank=w.q_create_rank, q_deserved=w.q_deserved,
        q_alloc0=w.q_alloc0, j_alloc0=w.j_alloc0,
        cluster_total=w.cluster_total,
        dyn_weights=w.dyn_weights, dyn_enabled=w.dyn_enabled,
        job_keys=w.job_keys, queue_keys=w.queue_keys,
        gang_enabled=req.gang_enabled,
        prop_overused=req.proportion_enabled,
        affinity=w.affinity,
        pipe_enabled=w.pipe_enabled)
    device = _WireDevice(w.idle, w.releasing, w.backfilled,
                         w.allocatable_cm, w.nz_req0, w.ntasks, w.mtn,
                         w.node_ok)
    # cat="host": solve_batched's own kernel span (inside) carries the
    # update_solver_kernel view; this wrapper is the wire solve_ms extent
    with obs.span("solve_batched", cat="host", engine="batched") as sp:
        task_state, task_node, task_seq, rounds = solve_batched(device,
                                                                inputs)
    return _decisions(req, w, task_state, task_node, task_seq,
                      int(rounds), sp.dur * 1e3)


def _tenant_of(context) -> tuple:
    """(tenant, lane) from the request metadata; absent keys mean the
    single-tenant default."""
    md = {k: v for k, v in (context.invocation_metadata() or ())}
    return (md.get("kb-tenant") or "default",
            md.get("kb-lane") or "normal", md)


def _make_solve_handler(svc):
    """Unary Solve handler bound to the server's tenant service. Trace
    stitching: incoming gRPC metadata carries the client's cycle id +
    parent span name (and now the tenant id); the handler runs under a
    per-request server root span TAGGED with the tenant and ships the
    finished tree back in TRAILING metadata (kb-trace-bin) for the
    client to graft — the wire request/response schema is untouched."""
    from ..tenantsvc.admission import AdmissionError

    def _solve_handler(request: bytes, context) -> bytes:
        req = solver_pb2.SnapshotRequest.FromString(request)
        tenant, lane, md = _tenant_of(context)
        wt = md.get("kb-weight")
        if wt:
            # per-request WFQ weight update, last writer wins; a full
            # registry is ignored here — admit() below raises the same
            # AdmissionError with the proper wire code
            try:
                svc.registry.get(tenant).weight = max(1e-6, float(wt))
            except (ValueError, AdmissionError):
                pass
        root = obs.begin_server_root(
            "sidecar_solve", cycle=md.get("kb-trace-cycle"),
            parent=md.get("kb-trace-span"), tenant=tenant, lane=lane)
        resp = None
        stale = False
        reject: Optional[AdmissionError] = None
        try:
            try:
                resp, stale = svc.solve(tenant, lane, req)
            except AdmissionError as e:
                reject = e
        finally:
            obs.end_server_root(root)
            try:
                trailing = [("kb-trace-bin",
                             json.dumps(root.to_dict()).encode())]
                if stale:
                    trailing.append(("kb-stale", "1"))
                context.set_trailing_metadata(tuple(trailing))
            except Exception:   # trailing trace is best-effort evidence
                pass
        if reject is not None:
            # admission rejection -> RESOURCE_EXHAUSTED; the client
            # falls back in-process WITHOUT tripping its breaker
            context.set_code(grpc.StatusCode.RESOURCE_EXHAUSTED)
            context.set_details(f"{reject.reason}: {reject}")
            return b""
        return resp.SerializeToString()

    return _solve_handler


def make_server(address: str = "127.0.0.1:0",
                max_workers: int = 4,
                tenant_service=None) -> tuple:
    """Returns (grpc.Server, bound_port).

    ``tenant_service``: a pre-built tenantsvc TenantSolveService (tests
    pass one to tune queue depth / batching window); None builds the
    default. The built service is installed as tenantsvc.service.active()
    so the dryrun and /debug surfaces can reach it.

    Handler threads get a 64 MB stack: XLA/LLVM compilation of the big
    round-engine graphs recurses deeply, and on the default 8 MB pool
    thread stack a first-compile inside a handler segfaulted (observed
    on the affinity-variant graph mid-suite, r5). threading.stack_size
    is process-global for threads started AFTERWARDS, so the pool's
    workers are pre-spawned deterministically under the raised value
    and the previous setting is restored before returning — threads the
    embedding process creates later are unaffected."""
    import threading

    from ..tenantsvc import service as tenantsvc_service
    from ..tenantsvc.service import TenantSolveService

    executor = futures.ThreadPoolExecutor(max_workers=max_workers)
    try:
        prev_stack = threading.stack_size(64 * 1024 * 1024)
    except (ValueError, RuntimeError):   # platform minimum/denied: keep
        prev_stack = None
    try:
        # force the executor to create every worker NOW (it spawns
        # lazily per submit): park them all on a barrier
        barrier = threading.Barrier(max_workers + 1)
        waiters = [executor.submit(barrier.wait)
                   for _ in range(max_workers)]
        barrier.wait(timeout=30)
        for w in waiters:
            w.result(timeout=30)
    finally:
        if prev_stack is not None:
            try:
                threading.stack_size(prev_stack)
            except (ValueError, RuntimeError):  # pragma: no cover
                pass

    svc = tenant_service or TenantSolveService()
    tenantsvc_service.install(svc)

    def _victim_session(context):
        tenant, _, _ = _tenant_of(context)
        session = svc.registry.get(tenant)
        if session.quarantined():
            # same refusal the Solve leg gets at admission — the client
            # falls back to its local kernels (pure analysis, safe)
            raise PermissionError(
                f"tenant {tenant!r} is quarantined; retry after the "
                "cooldown")
        return session, tenant

    def _victim_upload(request: bytes, context) -> bytes:
        req = solver_pb2.VictimUploadRequest.FromString(request)
        session, _ = _victim_session(context)
        return solver_pb2.VictimUploadResponse(
            state_id=session.victims.upload(req)).SerializeToString()

    def _victim_visit(request: bytes, context) -> bytes:
        req = solver_pb2.VictimVisitRequest.FromString(request)
        session, tenant = _victim_session(context)
        if req.mutable:
            # the tenant's mutable mirrors route through the versioned
            # MirrorStore BEFORE the registry applies them: a rollback
            # (version not strictly advancing for this state id — a
            # split-brain tenant replaying old uploads) is REJECTED
            # here and strikes toward the tenant's quarantine; the
            # legit client only re-ships mirrors when its version moved
            session.upload_mirror(f"victim-mut:{req.state_id}",
                                  req.mut_version, None)
        return session.victims.visit(req,
                                     tenant=tenant).SerializeToString()

    server = grpc.server(executor)
    handler = grpc.method_handlers_generic_handler(SERVICE, {
        "Solve": grpc.unary_unary_rpc_method_handler(
            _make_solve_handler(svc),
            request_deserializer=None,   # raw bytes in
            response_serializer=None),   # raw bytes out
        "VictimUpload": grpc.unary_unary_rpc_method_handler(
            _victim_upload, request_deserializer=None,
            response_serializer=None),
        "VictimVisit": grpc.unary_unary_rpc_method_handler(
            _victim_visit, request_deserializer=None,
            response_serializer=None),
    })
    server.add_generic_rpc_handlers((handler,))
    port = server.add_insecure_port(address)
    return server, port


def serve(address: str = "127.0.0.1:50061") -> None:  # pragma: no cover
    server, port = make_server(address)
    server.start()
    print(f"kubebatch-tpu solver sidecar listening on port {port}")
    lease_port = os.environ.get("KUBEBATCH_LEASE_PORT")
    if lease_port:
        # the sidecar doubles as the cross-host leader-election medium
        # (runtime/leaderelection.HttpLease points replicas here — the
        # analogue of the reference's ConfigMap lock on the API server,
        # cmd/kube-batch/app/server.go:170-193)
        from ..runtime.leaderelection import HttpLeaseServer

        bound = HttpLeaseServer(port=int(lease_port)).start()
        print(f"lease service on port {bound}")
    server.wait_for_termination()


if __name__ == "__main__":  # pragma: no cover
    serve()
