"""Victim-analysis over the gRPC boundary (VERDICT r4 directive 7).

The reference executes all four actions against its backend each cycle
(ref: pkg/scheduler/scheduler.go:88-105); round 4's sidecar carried only
allocate. This module routes preempt/reclaim's KERNEL DISPATCHES through
the sidecar while the host keeps everything stateful — VictimState's row
spaces, the event-log replay, the wave cache and node choice. The split:

- ``VictimUpload``: the action's immutable arrays (victim rows, perms,
  fairness seeds, sig matrices) ship once and get a server-side state id;
- ``VictimVisit``: each wave/visit ships its lanes (+ the six mutable
  mirrors only when the host's state version moved) and returns the SAME
  packed buffer the local kernels produce — the host-side consumers
  cannot tell the difference.

Failure contract: any RPC error returns None to the dispatch site, which
runs the local kernel for that dispatch — the analysis is pure, so the
fallback can never double-apply state (same safe-fallback spirit as the
allocate path, actions/allocate.py _execute_rpc).
"""
from __future__ import annotations

import uuid
from typing import Dict, Optional, Tuple

import numpy as np

from .. import obs
from . import solver_pb2

_DTYPES = {0: np.float32, 1: np.int32, 2: np.bool_}
_DTYPE_IDS = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
              np.dtype(np.bool_): 2}


def to_tensor(arr: np.ndarray) -> solver_pb2.Tensor:
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _DTYPE_IDS:
        # extended float dtypes (ml_dtypes bfloat16 — the narrowed sig
        # store, kernels/narrow.py — registers as kind 'V', NOT a
        # np.floating subdtype) must upcast to f32, never fall into the
        # int32 arm: truncating scores remote-side would silently
        # diverge remote decisions from local ones
        import ml_dtypes
        floatish = (np.issubdtype(arr.dtype, np.floating)
                    or arr.dtype == np.dtype(ml_dtypes.bfloat16))
        arr = arr.astype(np.float32 if floatish else np.int32)
    return solver_pb2.Tensor(shape=list(arr.shape),
                             dtype=_DTYPE_IDS[arr.dtype],
                             data=arr.tobytes())


def from_tensor(t: solver_pb2.Tensor) -> np.ndarray:
    arr = np.frombuffer(t.data, dtype=_DTYPES[t.dtype])
    return arr.reshape(tuple(t.shape))


# ---------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------

class VictimRegistry:
    """Server-side store of uploaded victim states, keyed by state id.
    Bounded LRU (a visit refreshes its entry's recency): entries are per
    ACTION EXECUTION, so a small cap covers the live set; a stale id
    errors and the client re-uploads (the backend retries once with a
    fresh upload before going local). Registry AND entry mutations are
    lock-guarded — the gRPC server runs a thread pool."""

    MAX_STATES = 16

    def __init__(self):
        import collections
        import threading
        self._states = collections.OrderedDict()
        self._lock = threading.Lock()

    def upload(self, req: solver_pb2.VictimUploadRequest) -> str:
        import jax

        static = req.static
        arrays = [from_tensor(t) for t in static.arrays]
        if len(arrays) != 20:
            raise ValueError(f"expected 20 arrays, got {len(arrays)}")
        state_id = uuid.uuid4().hex[:12]
        entry = {
            "static": jax.device_put(tuple(arrays[:18])),
            "sig": jax.device_put((arrays[18], arrays[19])),
            "tiers": tuple(tuple(t.split(",")) for t in static.tiers),
            "veto_critical": static.veto_critical,
            "score_nodes": static.score_nodes,
            "room_check": static.room_check,
            "dyn_enabled": static.dyn_enabled,
            "mut": None,
            "mut_version": -1,
        }
        with self._lock:
            while len(self._states) >= self.MAX_STATES:
                self._states.popitem(last=False)
            self._states[state_id] = entry
        return state_id

    def visit(self, req: solver_pb2.VictimVisitRequest,
              tenant: str = "default"
              ) -> solver_pb2.VictimVisitResponse:
        import jax

        from ..kernels.victims import run_visit_kernel, run_wave_kernel

        mut_in = (jax.device_put(tuple(from_tensor(t)
                                       for t in req.mutable))
                  if req.mutable else None)
        with self._lock:
            entry = self._states.get(req.state_id)
            if entry is None:
                raise KeyError(f"unknown victim state {req.state_id!r}")
            self._states.move_to_end(req.state_id)    # LRU touch
            if mut_in is not None:
                entry["mut"] = mut_in
                entry["mut_version"] = req.mut_version
            elif entry["mut"] is None \
                    or entry["mut_version"] != req.mut_version:
                raise ValueError("mutable state out of sync; resend mirrors")
            mut = entry["mut"]
        lanes = [from_tensor(t) for t in req.lanes]
        p_res, p_resreq, p_nz, p_sig, p_job, p_queue = lanes
        kw = dict(tiers=entry["tiers"],
                  veto_critical=entry["veto_critical"],
                  filter_kind=req.filter_kind,
                  dyn_enabled=entry["dyn_enabled"],
                  score_nodes=entry["score_nodes"],
                  room_check=entry["room_check"])
        # server-side victim solve wall (cat="host": the client's
        # victim_wave/visit kernel span owns the histogram accounting;
        # tenant-tagged so shared-sidecar dumps stay attributable)
        with obs.span("victim_solve", cat="host",
                      wave=bool(req.wave), tenant=tenant) as sp:
            if req.wave:
                out = run_wave_kernel(entry["static"], mut,
                                      entry["sig"], p_res, p_resreq, p_nz,
                                      p_sig, p_job, p_queue, **kw)
            else:
                out = run_visit_kernel(entry["static"], mut,
                                       entry["sig"], p_res, p_resreq,
                                       p_nz,
                                       p_sig.reshape(()),
                                       p_job.reshape(()),
                                       p_queue.reshape(()),
                                       from_tensor(req.visited), **kw)
            packed = np.asarray(out)
        return solver_pb2.VictimVisitResponse(
            packed=to_tensor(packed), solve_ms=sp.dur * 1e3)


# ---------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------

# process-wide circuit breaker: rpc-mode callers (the victim attach AND
# allocate's Solve leg, actions/allocate.py) skip a sidecar inside its
# failure cooldown — a wedged sidecar must not stall EVERY cycle for its
# timeouts; one failed action trips the breaker, later cycles go
# straight in-process and re-probe after the cooldown. The mechanism and
# its timing constants live in faults.py (SIDECAR_QUARANTINE +
# BackoffPolicy) so quarantine timing is configured in ONE place,
# shared with the cache retry queues and the degradation ladder.
from ..faults import SIDECAR_QUARANTINE


def breaker_target(address: str, tenant: str = "default") -> str:
    """Quarantine key for one (sidecar, tenant) pair. In production each
    tenant is its own scheduler process, so the process-wide breaker is
    naturally per-tenant; a multi-tenant test/sim process gets the same
    isolation by keying non-default tenants separately — one tenant's
    sidecar failures must not quarantine the sidecar for its neighbors
    in the same process. The default tenant keeps the bare address so
    single-tenant behavior (and every existing caller) is unchanged."""
    if not tenant or tenant == "default":
        return address
    return f"{address}#{tenant}"


def breaker_open(address: str) -> bool:
    """True while the address is inside its failure cooldown; when the
    cooldown elapses exactly one caller gets a recovery probe."""
    return SIDECAR_QUARANTINE.blocked(address)


def trip_breaker(address: str) -> None:
    SIDECAR_QUARANTINE.trip(address)


def clear_breaker(address: str) -> None:
    """A successful call answered the recovery probe — reset strikes."""
    SIDECAR_QUARANTINE.clear(address)

#: rpc deadlines: the sidecar is co-located — seconds mean it is wedged
_UPLOAD_TIMEOUT_S = 10.0
_VISIT_TIMEOUT_S = 30.0


class RemoteVictimBackend:
    """Attached to a VictimSolver (solver.remote) by build_action_solver
    under KUBEBATCH_SOLVER=rpc: routes wave/visit dispatches through the
    sidecar. Returns None on ANY failure — the dispatch site then runs
    the local kernel (pure analysis; retrying locally is always safe).
    A stale server state id is retried ONCE with a fresh upload (the
    registry's LRU can evict between visits on a shared sidecar); any
    other failure disables the backend for the rest of the action and
    trips the process-wide breaker for the address."""

    def __init__(self, channel, address: str = "",
                 tenant: str = "default"):
        self.address = address
        self.tenant = tenant or "default"
        #: tenancy rides gRPC metadata next to the kb-trace-* keys — the
        #: sidecar scopes the victim registry per tenant with it
        self._md = (("kb-tenant", self.tenant),)
        from .server import SERVICE

        self._upload_rpc = channel.unary_unary(
            f"/{SERVICE}/VictimUpload",
            request_serializer=solver_pb2.VictimUploadRequest
            .SerializeToString,
            response_deserializer=solver_pb2.VictimUploadResponse
            .FromString)
        self._visit_rpc = channel.unary_unary(
            f"/{SERVICE}/VictimVisit",
            request_serializer=solver_pb2.VictimVisitRequest
            .SerializeToString,
            response_deserializer=solver_pb2.VictimVisitResponse
            .FromString)
        self._state_id: Optional[str] = None
        self._sent_version = -1
        self._dead = False
        #: observability (tests assert the remote path actually ran)
        self.calls = 0

    def _ensure_uploaded(self, solver) -> Optional[str]:
        if self._state_id is not None:
            return self._state_id
        static = solver.host_static_arrays()
        score, pred = solver.host_sig_arrays()
        req = solver_pb2.VictimUploadRequest()
        req.static.tiers.extend(",".join(t) for t in solver.tiers)
        req.static.veto_critical = solver.veto_critical
        req.static.score_nodes = solver.score_nodes
        req.static.room_check = solver.room_check
        req.static.dyn_enabled = bool(solver.dyn is not None
                                      and solver.dyn.enabled)
        for arr in (*static, score, pred):
            req.static.arrays.append(to_tensor(np.asarray(arr)))
        self._state_id = self._upload_rpc(
            req, timeout=_UPLOAD_TIMEOUT_S, metadata=self._md).state_id
        self._sent_version = -1        # fresh server state has no mirrors
        return self._state_id

    def _call_once(self, solver, lanes, wave: bool, filter_kind: str,
                   visited) -> np.ndarray:
        from ..faults import check as _fault_check

        # injection seam: sidecar failure on the victim leg — the
        # dispatch site answers None and runs the local kernels
        _fault_check("rpc.victim")
        state_id = self._ensure_uploaded(solver)
        req = solver_pb2.VictimVisitRequest(
            state_id=state_id, wave=wave, filter_kind=filter_kind,
            mut_version=solver.state.version)
        if self._sent_version != solver.state.version:
            for arr in solver.host_mutable_arrays():
                req.mutable.append(to_tensor(np.asarray(arr)))
        for arr in lanes:
            req.lanes.append(to_tensor(np.asarray(arr)))
        if visited is not None:
            req.visited.CopyFrom(to_tensor(np.asarray(visited)))
        resp = self._visit_rpc(req, timeout=_VISIT_TIMEOUT_S,
                               metadata=self._md)
        # commit the version only after the server accepted it
        self._sent_version = solver.state.version
        self.calls += 1
        return from_tensor(resp.packed)

    def _call(self, solver, lanes: Tuple[np.ndarray, ...], wave: bool,
              filter_kind: str,
              visited: Optional[np.ndarray]) -> Optional[np.ndarray]:
        if self._dead:
            return None
        target = breaker_target(self.address, self.tenant)
        for attempt in (0, 1):
            try:
                out = self._call_once(solver, lanes, wave, filter_kind,
                                      visited)
                clear_breaker(target)
                return out
            except Exception as e:  # noqa: BLE001 — any failure -> local
                # a shared sidecar's LRU may have evicted our state id
                # between visits: retry ONCE with a fresh upload
                if attempt == 0 and self._state_id is not None \
                        and "unknown victim state" in str(e):
                    self._state_id = None
                    continue
                import logging
                logging.getLogger("kubebatch").warning(
                    "victim sidecar call failed (%s); using local kernels",
                    e)
                self._dead = True
                trip_breaker(target)
                return None
        return None   # pragma: no cover — loop always returns

    def wave(self, solver, p_res, p_resreq, p_nz, p_sig, p_job, p_queue,
             *, filter_kind: str, dyn_enabled: bool = False):
        # dyn_enabled rides the one-time upload (constant per solver);
        # accepted here only so the dispatch-site signature stays uniform
        return self._call(
            solver, (p_res, p_resreq, p_nz, p_sig, p_job, p_queue),
            wave=True, filter_kind=filter_kind, visited=None)

    def visit(self, solver, p_res, p_resreq, p_nz, sig: int, p_job: int,
              p_queue: int, visited, *, filter_kind: str,
              dyn_enabled: bool = False):
        return self._call(
            solver,
            (p_res, p_resreq, p_nz, np.asarray(sig, np.int32),
             np.asarray(p_job, np.int32), np.asarray(p_queue, np.int32)),
            wave=False, filter_kind=filter_kind, visited=visited)


def attach_remote(solver, address: str) -> bool:
    """Wire a RemoteVictimBackend onto the solver; False if the channel
    can't be created or the address recently failed (process-wide
    breaker, keyed per (address, tenant) — a wedged sidecar must not
    stall every cycle on rpc timeouts, and one tenant's quarantine must
    not block its in-process neighbors; the breaker re-probes after the
    cooldown)."""
    from .client import current_tenant

    tenant = current_tenant()
    target = breaker_target(address, tenant)
    if breaker_open(target):
        return False
    try:
        from .client import get_solver_client

        client = get_solver_client(address, tenant=tenant)
        solver.remote = RemoteVictimBackend(client._channel,
                                            address=address,
                                            tenant=tenant)
        return True
    except Exception:
        trip_breaker(target)
        return False
