"""Client for the solver sidecar: builds a SnapshotRequest from a Session
and applies the returned decisions — the front-end half of the gRPC
boundary (SURVEY.md sect. 2.9). The wire carries the FULL policy-term
payload the in-process engines consume: sig-indexed predicate/score
matrices, dynamic nodeorder weights with their per-task / per-node
nonzero-request inputs, and the drf/proportion fairness seeds.

Multi-tenant (ISSUE 8): every call carries a ``kb-tenant`` (and
``kb-lane``) metadata key. The tenant id resolves per THREAD
(``set_tenant``) before the ``KUBEBATCH_TENANT`` env, so a sim driving
N tenants from one process gets per-tenant clients, per-tenant breaker
targets, and per-tenant span attribution without env juggling; an
unconfigured client is the "default" tenant and behaves exactly as
before. A sidecar shedding load answers RESOURCE_EXHAUSTED
(``AdmissionRejected`` here) or a stale mirror (``StaleDecisions``) —
both are fallback signals, NOT sidecar death: callers go in-process
for the cycle without tripping the quarantine breaker."""
from __future__ import annotations

import functools
import json
import os
import threading
from typing import Dict, List, Optional

import grpc
import numpy as np

from .. import obs

from ..actions.cycle_inputs import (cycle_supported, gang_enabled,
                                    job_order_spec)
from ..api import TaskStatus, ready_statuses
from ..framework import Session
from ..kernels.fused import (ALLOC, ALLOC_OB, K_DRF_SHARE, K_PRIORITY,
                             PIPELINE)
from ..kernels.tensorize import NodeState, nz_request_vec
from ..kernels.terms import solver_terms
from . import solver_pb2
from .server import SERVICE


class _StateShim:
    """Adapter: solver_terms reads only ``.state`` off its device arg, so
    the client can encode terms from a host-side NodeState without a
    device upload."""

    def __init__(self, state: NodeState):
        self.state = state


class AdmissionRejected(RuntimeError):
    """The sidecar's tenant service refused the request (queue full,
    shed mode, quarantined tenant). An overload signal — fall back
    in-process for the cycle, do NOT trip the sidecar breaker."""


class StaleDecisions(AdmissionRejected):
    """The sidecar answered from the tenant's stale decision mirror
    (serve-stale shed mode). Stale decisions reference a previous
    snapshot's tasks, so a scheduler client must not replay them —
    treated as a fallback signal unless the caller opted in
    (``accept_stale=True``, for saturation benches that only measure
    service behavior)."""

    def __init__(self, msg: str, resp=None):
        super().__init__(msg)
        self.resp = resp


# -- per-thread tenant identity ---------------------------------------
_TENANT_TLS = threading.local()


def set_tenant(tenant: Optional[str],
               weight: Optional[float] = None) -> None:
    """Pin this thread's tenant id (None clears back to the env/default
    resolution) — the multi-tenant sim drives one tenant per thread.
    ``weight`` pins the tenant's weighted-fair share alongside; it rides
    every Solve as ``kb-weight`` metadata (server-side last writer
    wins)."""
    _TENANT_TLS.value = tenant
    _TENANT_TLS.weight = weight


def current_tenant() -> str:
    """Thread-local tenant, else KUBEBATCH_TENANT, else "default"."""
    return (getattr(_TENANT_TLS, "value", None)
            or os.environ.get("KUBEBATCH_TENANT", "")
            or "default")


def current_weight() -> Optional[float]:
    """Thread-local WFQ weight, else KUBEBATCH_TENANT_WEIGHT, else None
    (meaning: don't send kb-weight; the server keeps its last value)."""
    wt = getattr(_TENANT_TLS, "weight", None)
    if wt is not None:
        return float(wt)
    env = os.environ.get("KUBEBATCH_TENANT_WEIGHT", "")
    try:
        return float(env) if env else None
    except ValueError:
        return None


#: process-wide client per (sidecar address, tenant) —
#: KUBEBATCH_SOLVER=rpc mode keeps one channel per daemon per tenant,
#: not one per cycle
_CLIENTS: Dict[tuple, "SolverClient"] = {}

#: (client-observed rtt seconds, server solve_ms) per Solve dispatch —
#: bench.py --mode rpc diffs this to report the per-dispatch HOP cost
#: (rtt - solve = serialization + wire + queueing, the deployment-mode
#: overhead the sidecar charges on top of the kernel). A FIXED RING
#: (deque maxlen), never an unbounded process-lifetime list: a
#: long-running daemon with nobody reading it keeps the most RECENT
#: window (first-N retention would freeze diagnostics on warmup-era
#: samples), while bench runs clear it at start and never hit the cap.
#: Consumers should prefer metrics.rpc_dispatch_percentiles() (p50/p99
#: of rtt/solve/hop, also on /debug/vars) over the raw tuples.
import collections

DISPATCH_STATS_CAPACITY = 4096

DISPATCH_STATS = collections.deque(maxlen=DISPATCH_STATS_CAPACITY)


def get_solver_client(target: str,
                      tenant: Optional[str] = None) -> "SolverClient":
    tenant = tenant or current_tenant()
    key = (target, tenant)
    client = _CLIENTS.get(key)
    if client is None:
        client = _CLIENTS[key] = SolverClient(target, tenant=tenant)
    return client


class SolverClient:
    def __init__(self, target: str, tenant: str = "default",
                 lane: str = "normal", accept_stale: bool = False):
        self.tenant = tenant or "default"
        self.lane = lane
        self.accept_stale = accept_stale
        self._channel = grpc.insecure_channel(target)
        self._solve = self._channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=solver_pb2.SnapshotRequest.SerializeToString,
            response_deserializer=solver_pb2.DecisionsResponse.FromString)

    def close(self):
        self._channel.close()

    # ------------------------------------------------------------------
    def snapshot_from_session(self, ssn: Session):
        """Returns (SnapshotRequest, {task_uid: TaskInfo}) — delegates to
        the module-level :func:`build_snapshot` (shared with the mega
        signature provider, which derives registered compile keys
        through the live wire encode)."""
        return build_snapshot(ssn)

    @staticmethod
    def _build_snapshot(ssn: Session):
        """Raises ValueError for configurations the sidecar kernel
        cannot express (custom order fns, predicate/node-order plugins)
        — silent divergence from the in-process path is worse than an
        error."""
        if not cycle_supported(ssn):
            raise ValueError(
                "session plugins exceed the sidecar solver's vocabulary; "
                "run allocate in-process for this configuration")
        req = solver_pb2.SnapshotRequest()
        node_names = sorted(ssn.nodes)
        for name in node_names:
            ni = ssn.nodes[name]
            req.nodes.names.append(name)
            req.nodes.idle.extend(ni.idle.to_vec().tolist())
            req.nodes.releasing.extend(ni.releasing.to_vec().tolist())
            req.nodes.backfilled.extend(ni.backfilled.to_vec().tolist())
            req.nodes.max_task_num.append(ni.allocatable.max_task_num)
            req.nodes.n_tasks.append(len(ni.tasks))
            req.nodes.schedulable.append(
                ni.node is not None and not ni.node.unschedulable)

        queue_names = sorted(ssn.queues)
        q_index = {q: i for i, q in enumerate(queue_names)}
        prop = ssn.plugins.get("proportion")
        for qn in queue_names:
            req.queues.names.append(qn)
            req.queues.weight.append(ssn.queues[qn].weight)
            attr = getattr(prop, "queue_opts", {}).get(qn) if prop else None
            if attr is not None:
                req.queues.deserved.extend(attr.deserved.to_vec().tolist())
                req.queues.allocated.extend(attr.allocated.to_vec().tolist())
            else:
                req.queues.deserved.extend([0.0, 0.0, 0.0])
                req.queues.allocated.extend([0.0, 0.0, 0.0])

        jobs = [jb for jb in ssn.jobs.values() if jb.queue in q_index]
        rank = {jb.uid: r for r, jb in enumerate(
            sorted(jobs, key=lambda x: (x.creation_timestamp, x.uid)))}
        tasks_by_uid: Dict[str, object] = {}
        for ji, jb in enumerate(jobs):
            req.jobs.uids.append(jb.uid)
            req.jobs.min_available.append(jb.min_available)
            req.jobs.init_ready.append(jb.count(*ready_statuses()))
            req.jobs.queue_index.append(q_index[jb.queue])
            req.jobs.priority.append(jb.priority)
            req.jobs.create_rank.append(rank[jb.uid])
            pend = [t for t in jb.task_status_index.get(TaskStatus.PENDING,
                                                        {}).values()
                    if not t.resreq.is_empty()]
            pend.sort(key=functools.cmp_to_key(
                lambda a, b: -1 if ssn.task_order_fn(a, b) else 1))
            for r, t in enumerate(pend):
                req.tasks.uids.append(t.uid)
                req.tasks.resreq.extend(t.resreq.to_vec().tolist())
                req.tasks.init_resreq.extend(t.init_resreq.to_vec().tolist())
                req.tasks.job_index.append(ji)
                req.tasks.rank.append(r)
                tasks_by_uid[t.uid] = t

        # derive flags the same way the in-process fused path does, so
        # per-tier disable flags are honored identically
        job_keys, _ = job_order_spec(ssn)
        req.gang_enabled = gang_enabled(ssn)
        req.proportion_enabled = (
            "proportion" in ssn.overused_fns
            and any(opt.name == "proportion" for tier in ssn.tiers
                    for opt in tier.plugins))
        req.drf_enabled = K_DRF_SHARE in job_keys
        req.priority_enabled = K_PRIORITY in job_keys
        req.job_order_keys.extend(job_keys)  # exact tier-dispatch order
        drf = ssn.plugins.get("drf")
        if drf is not None:
            req.cluster_total.extend(
                drf.total_resource.to_vec().tolist())
            for jb in jobs:
                attr = drf.job_opts.get(jb.uid)
                vec = (attr.allocated.to_vec() if attr is not None
                       else np.zeros(3, np.float32))
                req.jobs.allocated.extend(vec.tolist())

        SolverClient._attach_terms(ssn, req, node_names, tasks_by_uid)
        return req, tasks_by_uid

    @staticmethod
    def _attach_terms(ssn: Session, req, node_names: List[str],
                      tasks_by_uid: Dict[str, object]) -> None:
        """Encode the predicate/score terms (kernels/terms) into the wire
        payload. Raises ValueError for snapshots whose callbacks the
        kernels cannot express (custom plugins, a real volume binder,
        over-cap affinity vocabularies, small affinity snapshots the
        in-process host path should keep) — silent divergence is worse
        than an error."""
        from ..kernels.affinity import (affinity_features_present,
                                        affinity_within_vocabulary,
                                        build_affinity_inputs)
        from ..kernels.terms import device_supported

        pending = list(tasks_by_uid.values())
        state = NodeState.from_nodes(ssn.nodes)
        if not device_supported(ssn, pending, allow_affinity=True):
            raise ValueError(
                "session predicates/score callbacks exceed the sidecar "
                "solver's vocabulary; run allocate in-process")
        if affinity_features_present(ssn, pending):
            # only the batched engine carries the affinity vocabulary;
            # below the batched threshold the in-process path (fused ->
            # host fallback, bind-exact) should keep the cycle
            from ..actions.allocate import AUTO_BATCHED_MIN
            if len(pending) < AUTO_BATCHED_MIN:
                raise ValueError(
                    "affinity snapshot below the batched threshold; "
                    "run allocate in-process")
            if not affinity_within_vocabulary(ssn, pending):
                raise ValueError(
                    "affinity vocabulary exceeds the caps; run allocate "
                    "in-process")
            aff = build_affinity_inputs(ssn, pending, _StateShim(state),
                                        t_pad=len(pending))
            if aff is None:
                # inside the raw window but over MAX_PAIRS/MAX_PORTS
                # even after compaction — the in-process path owns the
                # host fallback for this shape
                raise ValueError(
                    "affinity vocabulary exceeds the caps after "
                    "compaction; run allocate in-process")
            from ..kernels.affinity import WIRE_FIELDS
            from .victims_wire import to_tensor
            for name in WIRE_FIELDS:
                req.affinity.append(to_tensor(getattr(aff, name)))
            req.affinity_ip_weight = aff.ip_weight
            req.affinity_ip_enabled = aff.ip_enabled
        terms = solver_terms(ssn, _StateShim(state), pending,
                             assume_supported=True)
        if terms is None:   # pragma: no cover — gated above
            raise ValueError(
                "session predicates/score callbacks exceed the sidecar "
                "solver's vocabulary; run allocate in-process")
        n = len(node_names)
        t = req.terms
        static = terms.static
        t.n_sigs = static.n_sigs
        t.sig_pred.extend(
            np.asarray(static.pred[:, :n], bool).reshape(-1).tolist())
        t.sig_scores.extend(
            np.asarray(static.score[:, :n], np.float32).reshape(-1).tolist())
        t.task_sig.extend(static.sig_of[uid] for uid in tasks_by_uid)
        # task_nz always travels: the batched engine's waterfall cohorts
        # are (sig, nonzero-request) pairs even with dynamic scoring off
        for task in pending:
            t.task_nz.extend(
                nz_request_vec(task.resreq.to_vec()).tolist())
        if terms.dynamic.enabled:
            t.least_requested_weight = terms.dynamic.least_requested
            t.balanced_resource_weight = terms.dynamic.balanced_resource
            t.node_nz.extend(
                state.nz_requested[:n].reshape(-1).tolist())
            t.allocatable_cm.extend(
                state.allocatable[:n, :2].reshape(-1).tolist())

    def solve(self, req, timeout: float = 60.0
              ) -> solver_pb2.DecisionsResponse:
        """The remote call alone — no session mutation. Callers that want
        a fallback path must fall back BEFORE apply_decisions runs;
        after the replay starts the session is committed to the remote
        decisions.

        Trace context travels as gRPC METADATA (cycle id + parent span
        name, plus the tenant id and lane) — wire *metadata*, so
        solver.proto and the affinity WIRE_FIELDS contract are
        untouched — and the server ships its own span tree back in
        trailing metadata; it is grafted under this call's rpc span so
        sidecar solve spans stitch into the client's cycle tree,
        attributable per tenant on both sides.

        Raises AdmissionRejected when the sidecar's tenant service
        refused the request (RESOURCE_EXHAUSTED — overload, not death)
        and StaleDecisions when it answered from the tenant's stale
        mirror and this client did not opt in."""
        from ..faults import check as _fault_check

        # injection seam: sidecar unavailability, exercised before the
        # wire call — callers treat it exactly like a dead channel
        _fault_check("rpc.solve")
        md = [("kb-trace-span", "rpc_solve"),
              ("kb-tenant", self.tenant), ("kb-lane", self.lane)]
        wt = current_weight()
        if wt is not None:
            md.append(("kb-weight", f"{wt:g}"))
        root = obs.current_cycle()
        cyc = (root.args or {}).get("cycle") if root is not None else None
        if cyc is not None:
            md.append(("kb-trace-cycle", str(cyc)))
        try:
            with obs.span("rpc_solve", cat="rpc",
                          tenant=self.tenant) as sp:
                resp, call = self._solve.with_call(req, timeout=timeout,
                                                   metadata=md)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
                raise AdmissionRejected(e.details() or "admission "
                                        "rejected") from e
            raise
        # the span's dur is the client-observed rtt (the graft below is
        # deliberately outside it — deserializing the remote tree is not
        # wire time); DISPATCH_STATS keeps its (rtt s, server solve ms)
        # ring contract for bench.py / metrics.rpc_dispatch_percentiles
        DISPATCH_STATS.append((sp.dur, float(resp.solve_ms)))
        stale = False
        try:
            for key, value in (call.trailing_metadata() or ()):
                if key == "kb-trace-bin":
                    obs.graft(sp, obs.Span.from_dict(json.loads(value)))
                elif key == "kb-stale":
                    stale = value in ("1", b"1")
        except Exception:       # a malformed trace must never fail a solve
            pass
        if stale and not self.accept_stale:
            raise StaleDecisions(
                "sidecar shed load by serving the stale decision mirror; "
                "this client did not opt in — solve in-process", resp)
        return resp

    @staticmethod
    def apply_decisions(ssn: Session, resp, tasks_by_uid) -> None:
        """Replay the remote decisions through the Session. A pre-mutation
        volume-allocation failure skips that task (it stays Pending and
        reschedules next cycle — the remote solver cannot offer the
        ordered path's try-next-node, ref allocate.go:157-161); any other
        error propagates, it must NOT be treated as sidecar
        unavailability."""
        from ..framework import VolumeAllocationError

        decisions = [d for d in resp.decisions if d.order >= 0]
        decisions.sort(key=lambda d: d.order)
        for d in decisions:
            task = tasks_by_uid.get(d.task_uid)
            if task is None:
                continue
            try:
                if d.kind in (ALLOC, ALLOC_OB):
                    ssn.allocate(task, d.node_name, d.kind == ALLOC_OB)
                elif d.kind == PIPELINE:
                    ssn.pipeline(task, d.node_name)
            except VolumeAllocationError:
                continue

    def solve_and_apply(self, ssn: Session,
                        timeout: float = 60.0) -> solver_pb2.DecisionsResponse:
        """One remote solve; decisions replayed through the Session."""
        req, tasks_by_uid = self.snapshot_from_session(ssn)
        resp = self.solve(req, timeout=timeout)
        self.apply_decisions(ssn, resp, tasks_by_uid)
        return resp


def build_snapshot(ssn: Session):
    """Module-level wire encode: (SnapshotRequest, {task_uid: TaskInfo})
    from a Session — no channel needed. The mega compile-signature
    provider (tenantsvc/megasolve.py) derives registered keys through
    THIS function so they share the live encode code with every real
    tenant request."""
    return SolverClient._build_snapshot(ssn)


# -- fleet: router-aware target resolution + the client pool ------------

def resolve_solver_target(tenant: Optional[str] = None) -> str:
    """The dial target for one tenant: the fleet router's answer when
    one is installed (tenantsvc.router.install), else the single-
    sidecar env/default — so every existing single-address caller is
    unchanged until a fleet is actually armed."""
    from ..tenantsvc import router as _router

    rt = _router.active()
    if rt is not None:
        return rt.route(tenant or current_tenant())
    return os.environ.get("KUBEBATCH_SOLVER_ADDR", "127.0.0.1:50061")


#: injected delay for the fleet.slowpeer seam (seconds) — long enough
#: to read as "slow" against DEFAULT_SLOW_MS, short enough to keep soak
#: runs fast
SLOWPEER_DELAY_S = 0.05


class SolverClientPool:
    """Multi-address Solve frontend for a sidecar fleet.

    Each call resolves its target through the router (health-drained
    placement + failover overrides), reuses one SolverClient per
    address, and feeds the router back: rtt on success, failure on a
    wire error. Two fault seams live here — they are the fleet plane's
    front door:

    - ``rpc.partition``: the route to the resolved target is severed.
      Fires like a dead channel: the (address, tenant) breaker target
      strikes, the router's health drains, the optional failover_cb
      fires, and the call retries ONCE on a re-resolved target (the
      ring walk now avoids the sick address).
    - ``fleet.slowpeer``: the target answers, late — an injected
      pre-wire delay whose rtt is reported to the router, so health-
      weighted routing drains the slow sidecar before its breaker
      ever trips.
    """

    def __init__(self, addresses: List[str], tenant: str = "default",
                 lane: str = "normal", accept_stale: bool = False,
                 router=None, failover_cb=None):
        self.addresses = list(addresses)
        self.tenant = tenant or "default"
        self.lane = lane
        self.accept_stale = accept_stale
        self._router = router
        #: called (tenant, dead_address) after a partition/wire failure
        #: — bench/sim hook this to run the replication handshake
        self.failover_cb = failover_cb
        self._clients: Dict[str, SolverClient] = {}
        self._lock = threading.Lock()

    def router(self):
        if self._router is not None:
            return self._router
        from ..tenantsvc import router as _router

        return _router.active()

    def target(self) -> str:
        rt = self.router()
        if rt is not None:
            return rt.route(self.tenant)
        return (self.addresses[0] if self.addresses
                else resolve_solver_target(self.tenant))

    def client_for(self, address: str) -> SolverClient:
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = self._clients[address] = SolverClient(
                    address, tenant=self.tenant, lane=self.lane,
                    accept_stale=self.accept_stale)
        return client

    def close(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()

    def _partition(self, address: str, rt) -> None:
        """A severed route's bookkeeping — identical to what a dead
        channel earns in actions/allocate._execute_rpc."""
        from ..faults import SIDECAR_QUARANTINE
        from .victims_wire import breaker_target

        SIDECAR_QUARANTINE.trip(breaker_target(address, self.tenant))
        if rt is not None:
            rt.report_failure(address)
        cb = self.failover_cb or _FAILOVER_CB
        if cb is not None:
            try:
                cb(self.tenant, address)
            except Exception:   # the cb is advisory, never call-fatal
                pass

    def solve(self, req, timeout: float = 60.0):
        import time as _time

        from ..faults import check as _fault_check, should_fail

        rt = self.router()
        last_exc: Optional[BaseException] = None
        tried: List[str] = []
        for attempt in range(2):
            addr = self.target()
            if tried and addr == tried[-1]:
                break              # nowhere else to go — re-raise below
            tried.append(addr)
            delay = 0.0
            if should_fail("fleet.slowpeer"):
                delay = SLOWPEER_DELAY_S
                _time.sleep(delay)
            try:
                _fault_check("rpc.partition")
            except Exception as e:
                last_exc = e
                self._partition(addr, rt)
                continue
            t0 = _time.monotonic()
            try:
                resp = self.client_for(addr).solve(req, timeout=timeout)
            except AdmissionRejected:
                raise              # overload, not death — never re-route
            except grpc.RpcError as e:
                last_exc = e
                self._partition(addr, rt)
                continue
            if rt is not None:
                rt.observe(addr, _time.monotonic() - t0 + delay)
            return resp
        raise last_exc if last_exc is not None else RuntimeError(
            "solver pool exhausted its targets")


#: process-wide pools per (router addresses, tenant) — the fleet analog
#: of _CLIENTS; one pool (and its channels) per tenant per fleet shape
_POOLS: Dict[tuple, SolverClientPool] = {}
_POOLS_LOCK = threading.Lock()

#: default failover callback for ambient pools — fleet harnesses
#: (bench --fleet, sim fleet chaos) install the replication plane's
#: handshake-then-reroute here so a partitioned target fails its
#: tenants over mid-call
_FAILOVER_CB = None


def set_failover_callback(cb) -> None:
    global _FAILOVER_CB
    _FAILOVER_CB = cb


def get_solver_pool(tenant: Optional[str] = None) -> SolverClientPool:
    """The ambient fleet pool for one tenant (requires an installed
    tenantsvc router). Cached per (fleet addresses, tenant) so a
    re-armed fleet with different membership gets fresh pools."""
    from ..tenantsvc import router as _router

    rt = _router.active()
    if rt is None:
        raise RuntimeError("get_solver_pool needs an installed "
                           "tenantsvc router (tenantsvc.router.install)")
    tenant = tenant or current_tenant()
    # keyed by router IDENTITY (the pool keeps rt alive, so the id is
    # stable): a re-armed fleet gets fresh pools even at the same addrs
    key = (id(rt), tenant)
    with _POOLS_LOCK:
        pool = _POOLS.get(key)
        if pool is None:
            pool = _POOLS[key] = SolverClientPool(
                list(rt.addresses), tenant=tenant, router=rt)
    return pool


def reset_solver_pools() -> None:
    """Close and drop every cached fleet pool (fleet harness teardown)."""
    with _POOLS_LOCK:
        pools = list(_POOLS.values())
        _POOLS.clear()
    for p in pools:
        try:
            p.close()
        except Exception:
            pass
