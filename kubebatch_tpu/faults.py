"""Process-wide fault injection, backoff policy, and the degradation ladder.

Borg's central lesson is that the scheduler must stay up and making
progress through component failure (Verma et al., EuroSys'15); injected
faults are the only way to TEST that rather than assert it (Basiri et
al., IEEE Software 2016). This module is both halves for the whole
process:

- **Injection seams** (``check``/``should_fail``): named crossing points
  wired into every failure-prone layer (the ``SEAMS`` catalog below).
  Disarmed cost is one module-global read and a ``None`` compare per
  crossing — no env lookup, no lock, no branch into plan logic. Armed
  via ``KUBEBATCH_FAULTS`` / the CLI ``--faults`` flag / ``arm()``.
- **BackoffPolicy**: the ONE object holding every retry/quarantine
  timing constant. The cache's ``RetryQueue`` (write-back retries), the
  rpc sidecar circuit breaker (``rpc/victims_wire.py``), and the
  ladder's recovery probes all read it, so quarantine timing is
  configured in a single place (``set_backoff_policy`` or
  ``KUBEBATCH_QUARANTINE_S``).
- **Quarantine**: per-target failure state with backoff-gated recovery
  probes — the generalization of the startup watchdog and the private
  rpc breaker into one mid-run mechanism. ``blocked(t)`` is True inside
  the cooldown; when it elapses, exactly the next caller gets one probe
  attempt, and a re-trip escalates the cooldown.
- **DegradationLadder**: cycle-level engine degradation driven by the
  scheduler loop (runtime/scheduler.py). Repeated cycle failures (raise
  or deadline overrun) demote the allocate engine one tier at a time —
  sharded -> batched -> fused -> host — through ``cap_engine``; every
  demotion lands in the existing ``engine_demotions_total`` taxonomy.
  Sustained healthy cycles plus an optional health probe re-promote one
  level per cooldown, back to the full device engine.

The chaos soak (sim/chaos.py, ``bench.py --chaos``, tests/test_chaos.py)
drives hundreds of cycles with a seeded plan over every seam family and
asserts the invariants: loop alive, no task lost or double-bound,
fairness conserved, full recovery with bit-identical decisions.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from .metrics import (count_engine_demotion, count_fault_injected,
                      set_degradation_level)

log = logging.getLogger("kubebatch.faults")

#: the seam catalog: every named injection point, grouped into families
#: (device / rpc / cache / source / lease / fleet / solve / pipeline).
#: Rates in a
#: plan may address an exact seam, a family wildcard ("cache.*"), or "*".
SEAMS: Dict[str, str] = {
    "device.dispatch": "device solver dispatch (allocate visit, fused, "
                       "batched and sharded kernels)",
    "rpc.solve": "sidecar Solve call (rpc/client.py)",
    "rpc.victim": "sidecar victim wave/visit call (rpc/victims_wire.py)",
    "rpc.admission": "tenantsvc admission gate (tenantsvc/service.py — "
                     "an injected fault rejects the request; the client "
                     "falls back in-process without tripping the "
                     "breaker)",
    "rpc.partition": "client->sidecar route severed (rpc/client.py pool "
                     "dispatch — fires like a dead channel: the breaker "
                     "strikes the (address, tenant) target and the fleet "
                     "router drains the address's health)",
    "cache.bind": "binder write-back (cache/cache.py _bind_one)",
    "cache.evict": "evictor write-back (cache/cache.py evict)",
    "cache.resync": "resync ground-truth replay (cache/cache.py "
                    "sync_task)",
    "cache.fold": "event-fold layer (cache/eventfold.py — a fired seam "
                  "DEMOTES the cache to snapshot-primary full clones "
                  "for the rest of the process instead of raising; the "
                  "degradation rung, not a crash)",
    "solve.activeset": "active-set solve dispatch (kernels/activeset.py "
                       "— a fired seam DEMOTES the solve to the "
                       "full-width engine for the rest of the process "
                       "instead of raising, exactly like cache.fold: "
                       "the rung trades the O(churn) steady cycle for "
                       "the always-sound full solve)",
    "pipeline.conflict": "pipelined consume conflict check "
                         "(runtime/pipeline.py — a fired seam forces the "
                         "in-flight solve result stale at consume time: "
                         "the decisions are discarded, the device carry "
                         "restored from its shadow, and the cycle "
                         "re-solves sequentially against the fresh "
                         "active set; the invalidate rung, not a crash)",
    "source.deliver": "sim event-stream delivery (sim/source.py pump)",
    "source.disconnect": "watch stream drop (cache/k8s_source.py watch "
                         "loop)",
    "source.gone": "HTTP 410 Gone on the watch (cache/k8s_source.py)",
    "lease.renew": "leader lease renew CAS (runtime/leaderelection.py)",
    "fleet.kill": "fleet sidecar death (sim/chaos.py fleet supervisor / "
                  "bench.py --fleet): one in-process sidecar is stopped "
                  "abruptly mid-run — kill -9 semantics, no grace; its "
                  "tenants must fail over to their warm standby",
    "fleet.slowpeer": "fleet slow peer (rpc/client.py pool dispatch): the "
                      "target answers, late — an injected pre-wire delay; "
                      "health-weighted routing must drain the slow "
                      "sidecar BEFORE its breaker ever trips",
    "obs.slo": "SLO plane evaluation tick (obs/slo.py — a fired seam "
               "forces a synthetic breach through the REAL fire path: "
               "slo_breaches_total increments and the flight recorder "
               "dumps, without any objective burning; demote-not-raise "
               "like cache.fold — the breach machinery must never "
               "corrupt a scheduling cycle)",
    "workload.elastic": "elastic gang resize delivered mid-flight "
                        "(workloads/elastic.py / sim/chaos.py — a fired "
                        "seam forces a grow/shrink event onto a live "
                        "gang BETWEEN solve launch and consume, so the "
                        "pipelined executor's flight-window fingerprint "
                        "must invalidate the in-flight result rather "
                        "than double-bind against the resized gang; the "
                        "adversarial-timing rung, not a crash)",
}

FAMILIES = ("device", "rpc", "cache", "source", "lease", "fleet",
            "solve", "pipeline", "obs", "workload")


class FaultInjected(RuntimeError):
    """Raised at an armed seam. Deliberately a plain RuntimeError
    subclass: every seam sits inside a layer whose real failures are
    generic exceptions, so the injected fault exercises the exact
    handler the real one would."""


class FaultPlan:
    """A seeded, thread-safe fault schedule.

    ``rates`` maps seam (or "family.*" / "*") to a per-crossing failure
    probability; ``counts`` maps an exact seam to "fail the first N
    crossings, then pass" (deterministic — the test-seam form). A seam
    with a count entry is governed by the count alone. The same seed
    yields the same schedule for the same crossing sequence, which is
    what makes a chaos soak replayable."""

    def __init__(self, rates: Optional[Dict[str, float]] = None,
                 counts: Optional[Dict[str, int]] = None, seed: int = 0):
        self.rates = dict(rates or {})
        self.counts = dict(counts or {})
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        #: injected crossings per seam, for assertions and evidence lines
        self.injected: Dict[str, int] = {}

    def _rate_for(self, seam: str) -> float:
        rate = self.rates.get(seam)
        if rate is not None:
            return rate
        fam = seam.split(".", 1)[0] + ".*"
        rate = self.rates.get(fam)
        if rate is not None:
            return rate
        return self.rates.get("*", 0.0)

    def should_fail(self, seam: str) -> bool:
        with self._lock:
            n = self.counts.get(seam)
            if n is not None:
                if n <= 0:
                    return False
                self.counts[seam] = n - 1
            else:
                rate = self._rate_for(seam)
                if rate <= 0.0 or self._rng.random() >= rate:
                    return False
            self.injected[seam] = self.injected.get(seam, 0) + 1
            return True


#: the armed plan; None = disarmed (the zero-cost fast path)
_PLAN: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide and return it."""
    global _PLAN
    _PLAN = plan
    log.warning("fault injection ARMED (seed=%d rates=%s counts=%s)",
                plan.seed, plan.rates, plan.counts)
    return plan


def disarm() -> None:
    global _PLAN
    if _PLAN is not None:
        log.warning("fault injection disarmed (injected=%s)",
                    _PLAN.injected)
    _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def armed() -> bool:
    return _PLAN is not None


def should_fail(seam: str) -> bool:
    """True when the armed plan fires at ``seam`` (counted). The form
    for seams whose failure is a refused operation rather than an
    exception (lease renew)."""
    plan = _PLAN
    if plan is None:
        return False
    if plan.should_fail(seam):
        count_fault_injected(seam)
        return True
    return False


def check(seam: str) -> None:
    """Raise FaultInjected when the armed plan fires at ``seam``."""
    if _PLAN is not None and should_fail(seam):
        raise FaultInjected(f"injected fault at seam <{seam}>")


def check_raise(seam: str, exc_factory: Callable[[str], BaseException]
                ) -> None:
    """Typed variant for seams whose handlers dispatch on the exception
    class (e.g. a watch 410 must be a ResourceExpired)."""
    if _PLAN is not None and should_fail(seam):
        raise exc_factory(f"injected fault at seam <{seam}>")


def parse_fault_spec(spec: str, seed: int = 0) -> FaultPlan:
    """Parse "seam:rate,seam:nN,..." — ``rate`` a probability, ``nN`` a
    deterministic fail-first-N count; a bare seam means rate 1.0."""
    rates: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        seam, _, val = part.partition(":")
        seam = seam.strip()
        val = val.strip() or "1"
        if val.startswith("n"):
            counts[seam] = int(val[1:])
        else:
            rates[seam] = float(val)
    return FaultPlan(rates=rates, counts=counts, seed=seed)


def arm_from_env(env: str = "KUBEBATCH_FAULTS",
                 seed_env: str = "KUBEBATCH_FAULTS_SEED"
                 ) -> Optional[FaultPlan]:
    """Arm from the environment (the daemon/CLI path); None when the
    variable is unset — the default, and the zero-cost state."""
    spec = os.environ.get(env, "")
    if not spec:
        return None
    seed = int(os.environ.get(seed_env, "0") or "0")
    return arm(parse_fault_spec(spec, seed=seed))


# ---------------------------------------------------------------------
# the one backoff/quarantine policy (ISSUE 5 satellite: the rpc breaker
# cooldown and the cache RetryQueue constants lived in two modules)
# ---------------------------------------------------------------------

@dataclass(frozen=True)
class BackoffPolicy:
    """Every retry/quarantine timing constant, in one object.

    ``base_delay``/``max_delay`` drive the cache's rate-limited retry
    queues (5ms * 2^retries, capped — the workqueue.RateLimiting
    equivalent); ``cooldown`` is the quarantine before the first
    recovery probe (the old private rpc-breaker constant), escalated by
    ``probe_backoff`` per repeated trip up to ``max_cooldown``.

    ``jitter`` > 0 decorrelates the escalation: a FLEET of breakers
    quarantining the same sick sidecar would otherwise re-probe it in
    lockstep (every cooldown is the same fixed step), and the
    simultaneous probe volley is its own thundering herd against a
    recovering process. The jittered schedule is SEEDED per
    (``jitter_seed``, breaker target), so a chaos run with a fixed seed
    replays the exact same probe times — reproducibility is the whole
    reason the schedule is derived, not drawn from global randomness.
    ``jitter == 0`` (the default) reproduces ``quarantine_for``
    bit-for-bit, so every existing consumer is unchanged."""

    base_delay: float = 0.005
    max_delay: float = 10.0
    cooldown: float = 60.0
    probe_backoff: float = 2.0
    max_cooldown: float = 480.0
    jitter: float = 0.0
    jitter_seed: int = 0

    def retry_delay(self, retries: int) -> float:
        return min(self.base_delay * (2 ** retries), self.max_delay)

    def quarantine_for(self, strikes: int) -> float:
        return min(self.cooldown * (self.probe_backoff
                                    ** max(0, strikes - 1)),
                   self.max_cooldown)

    def jittered_quarantine_for(self, strikes: int,
                                token: str = "") -> float:
        """Decorrelated-jitter cooldown (the AWS "decorrelated jitter"
        shape): strike 1 is the exact base ``cooldown``; every further
        strike draws uniformly between the base and ``probe_backoff *
        (1 + jitter)`` times the PREVIOUS draw, capped at
        ``max_cooldown``. The walk is replayed from strike 1 on each
        call with an RNG seeded by (jitter_seed, token) — stateless,
        thread-safe, and two breakers for different targets land on
        different schedules while the same (seed, target, strike)
        always yields the same cooldown."""
        if self.jitter <= 0.0:
            return self.quarantine_for(strikes)
        rng = random.Random(f"{self.jitter_seed}:{token}")
        d = self.cooldown
        for _ in range(max(0, strikes - 1)):
            hi = max(self.cooldown,
                     d * self.probe_backoff * (1.0 + self.jitter))
            d = min(self.max_cooldown,
                    rng.uniform(self.cooldown, hi))
        return d


DEFAULT_BACKOFF = BackoffPolicy()

_policy: BackoffPolicy = DEFAULT_BACKOFF
_env_cooldown = os.environ.get("KUBEBATCH_QUARANTINE_S", "")
if _env_cooldown:
    _policy = BackoffPolicy(cooldown=float(_env_cooldown))


def backoff_policy() -> BackoffPolicy:
    """The process-wide policy (consumers that cache it at construction
    time, like RetryQueue, read it once — set the policy before building
    the cache/scheduler)."""
    return _policy


def set_backoff_policy(policy: BackoffPolicy) -> BackoffPolicy:
    global _policy
    _policy = policy
    return policy


class Quarantine:
    """Per-target failure quarantine with backoff-gated recovery probes.

    ``trip(t)`` starts (or escalates) the cooldown; ``blocked(t)`` is
    True inside it. When the cooldown elapses the NEXT ``blocked`` call
    returns False exactly once — the probe window — and a failure
    re-trips with an escalated cooldown while a success (``clear``)
    resets the strike count. This is the rpc circuit breaker and the
    mid-run engine watchdog expressed as one mechanism."""

    def __init__(self, policy: Optional[BackoffPolicy] = None):
        #: None = follow the process-wide policy dynamically
        self.policy = policy
        self._until: Dict[str, float] = {}
        self._strikes: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _pol(self) -> BackoffPolicy:
        return self.policy or _policy

    def trip(self, target: str) -> None:
        if not target:
            return
        with self._lock:
            strikes = self._strikes.get(target, 0) + 1
            self._strikes[target] = strikes
            self._until[target] = (
                time.monotonic()
                + self._pol().jittered_quarantine_for(strikes,
                                                      token=target))

    def blocked(self, target: str) -> bool:
        with self._lock:
            until = self._until.get(target)
            if until is None:
                return False
            now = time.monotonic()
            if now >= until:
                # probe window: let exactly THIS caller through and
                # re-arm the cooldown immediately, so concurrent callers
                # (and later cycles while the probe is still timing out
                # against a wedged target) stay blocked. A successful
                # probe calls clear(); a failed one trips and escalates.
                strikes = self._strikes.get(target, 1)
                self._until[target] = (
                    now + self._pol().jittered_quarantine_for(
                        strikes, token=target))
                return False
            return True

    def clear(self, target: str) -> None:
        """The target answered a probe — full reset."""
        with self._lock:
            self._until.pop(target, None)
            self._strikes.pop(target, None)

    def strikes(self, target: str) -> int:
        with self._lock:
            return self._strikes.get(target, 0)

    def strike_snapshot(self) -> Dict[str, int]:
        """A locked copy of {target: strikes} — the fleet router reads
        this to aggregate per-address health across the per-(address,
        tenant) breaker targets without consuming probe windows."""
        with self._lock:
            return dict(self._strikes)

    def reset(self) -> None:
        with self._lock:
            self._until.clear()
            self._strikes.clear()


#: the sidecar circuit breaker (rpc/victims_wire.py breaker_open /
#: trip_breaker delegate here) — one quarantine for BOTH rpc legs
SIDECAR_QUARANTINE = Quarantine()


# ---------------------------------------------------------------------
# the cycle degradation ladder
# ---------------------------------------------------------------------

#: ladder levels in demotion order; level 0 imposes no cap
LADDER_LEVELS = ("full", "batched", "fused", "host")

#: observers notified (with the new level) after a ladder demotion — the
#: obs flight recorder registers here when armed; hooks run OUTSIDE the
#: ladder lock and must never raise into the scheduling loop
_DEMOTION_HOOKS: list = []


def on_ladder_demotion(cb: Callable[[int], None]) -> None:
    if cb not in _DEMOTION_HOOKS:
        _DEMOTION_HOOKS.append(cb)


def remove_ladder_demotion_hook(cb: Callable[[int], None]) -> None:
    try:
        _DEMOTION_HOOKS.remove(cb)
    except ValueError:
        pass


def _notify_demotion(level: int) -> None:
    for cb in list(_DEMOTION_HOOKS):
        try:
            cb(level)
        except Exception:                  # pragma: no cover — observer bug
            log.exception("ladder demotion hook failed")

#: engine tier ranks: an engine at rank >= the ladder level is already
#: at or below the cap and passes through unchanged. rpc counts as a
#: full-tier engine (its own breaker handles sidecar failure; the
#: ladder demotes it with everything else once CYCLES start failing).
_ENGINE_RANK = {"rpc": 0, "sharded": 0, "hier": 0, "activeset": 0,
                "batched": 1, "native": 1, "fused": 2, "jax": 2, "host": 3}


class DegradationLadder:
    """Engine degradation driven by guarded scheduler cycles.

    ``record_failure`` after ``demote_after`` consecutive failed cycles
    demotes one level (counted in engine_demotions_total at the
    cap_engine site); ``record_success`` after ``promote_after``
    consecutive healthy cycles — and once the policy cooldown since the
    demotion has elapsed, and the optional health ``probe`` answers —
    re-promotes one level. The scheduler loop owns the transitions;
    AllocateAction consults ``cap_engine`` once per cycle."""

    def __init__(self, policy: Optional[BackoffPolicy] = None,
                 demote_after: int = 2, promote_after: int = 3,
                 probe: Optional[Callable[[], bool]] = None):
        self.demote_after = demote_after
        self.promote_after = promote_after
        self.policy = policy
        self.probe = probe
        self._lock = threading.Lock()
        self.level = 0
        self._fail_streak = 0
        self._ok_streak = 0
        self._next_probe_at = 0.0
        #: async probe state: the probe (a subprocess device query, up
        #: to 20 s against a wedged accelerator) must never block the
        #: scheduling thread — record_success consults the LAST result
        #: and kicks off a fresh probe on a daemon thread when due
        self._probe_running = False
        self._probe_result: Optional[bool] = None

    def _pol(self) -> BackoffPolicy:
        return self.policy or _policy

    def record_failure(self) -> None:
        with self._lock:
            self._fail_streak += 1
            self._ok_streak = 0
            if (self._fail_streak < self.demote_after
                    or self.level >= len(LADDER_LEVELS) - 1):
                return
            self.level += 1
            level = self.level
            self._fail_streak = 0
            self._next_probe_at = (time.monotonic()
                                   + self._pol().quarantine_for(self.level))
            set_degradation_level(self.level)
            log.warning("degradation ladder DEMOTED to level %d (%s)",
                        self.level, LADDER_LEVELS[self.level])
        _notify_demotion(level)

    def _run_probe_async(self, probe: Callable[[], bool]) -> None:
        def _worker():
            try:
                ok = bool(probe())
            except Exception:
                ok = False
            with self._lock:
                self._probe_running = False
                self._probe_result = ok
                if not ok:
                    self._next_probe_at = (
                        time.monotonic()
                        + self._pol().quarantine_for(self.level))
            if not ok:
                log.warning("degradation ladder: recovery probe failed "
                            "at level %d; staying", self.level)

        threading.Thread(target=_worker, daemon=True,
                         name="kb-ladder-probe").start()

    def record_success(self) -> None:
        with self._lock:
            self._ok_streak += 1
            self._fail_streak = 0
            if self.level == 0 or self._ok_streak < self.promote_after:
                return
            if time.monotonic() < self._next_probe_at:
                return
            probe = self.probe
            if probe is not None:
                if self._probe_running:
                    return                     # answer pending; stay put
                if self._probe_result is None:
                    # kick off a probe on its own thread — a wedged
                    # accelerator costs that thread the probe timeout,
                    # never the scheduling loop
                    self._probe_running = True
                    self._probe_result = None
                    do_probe = True
                else:
                    do_probe = False
                    if not self._probe_result:   # consumed: failed
                        self._probe_result = None
                        return
                    self._probe_result = None    # consumed: passed
            else:
                do_probe = False
            if not do_probe:
                if self.level > 0:
                    self.level -= 1
                    self._ok_streak = 0
                    set_degradation_level(self.level)
                    log.warning("degradation ladder promoted to level "
                                "%d (%s)", self.level,
                                LADDER_LEVELS[self.level])
                return
        self._run_probe_async(probe)

    def cap_engine(self, mode: str) -> str:
        """The engine the current level allows: modes already at or
        below the cap pass through; higher tiers demote to the level's
        engine (counted in engine_demotions_total)."""
        level = self.level
        if level == 0:
            return mode
        if _ENGINE_RANK.get(mode, len(LADDER_LEVELS)) >= level:
            return mode
        capped = LADDER_LEVELS[level]
        count_engine_demotion(mode, capped)
        return capped

    def reset(self) -> None:
        with self._lock:
            self.level = 0
            self._fail_streak = 0
            self._ok_streak = 0
            self._next_probe_at = 0.0
            self._probe_running = False
            self._probe_result = None
        set_degradation_level(0)


#: the process-wide ladder — the scheduler loop drives it, the allocate
#: action consults it (one scheduler per process is the deployment
#: shape; interleaved test schedulers share it and reset() between runs)
LADDER = DegradationLadder()


# ---------------------------------------------------------------------
# the shed ladder (ISSUE 8): the degradation ladder's overload twin
# ---------------------------------------------------------------------

#: shed modes in escalation order. The engine ladder answers "the device
#: path is failing" by demoting the ENGINE; this one answers "demand
#: exceeds capacity" by degrading the lowest service tier first:
#: level 1 serves the lowest lane from the tenant's stale decision
#: mirror (a cached answer beats a queue timeout), level 2 rejects the
#: lowest lane outright and stale-serves the middle one. The "latency"
#: lane is never shed — only bounded by its per-tenant queue.
SHED_LEVELS = ("none", "serve-stale", "reject-lowest")


class ShedLadder:
    """Overload-driven shedding for the tenant solve service.

    ``record_pressure(overloaded)`` is called at every admission with
    the queue-depth verdict: ``shed_after`` consecutive overloaded
    admissions escalate one level; ``recover_after`` consecutive calm
    ones — once the BackoffPolicy cooldown since the escalation has
    elapsed — step back down. Same streak+cooldown shape as the
    DegradationLadder, same one policy object for the timing."""

    def __init__(self, policy: Optional[BackoffPolicy] = None,
                 shed_after: int = 3, recover_after: int = 8):
        self.policy = policy
        self.shed_after = shed_after
        self.recover_after = recover_after
        self._lock = threading.Lock()
        self.level = 0
        self._over_streak = 0
        self._ok_streak = 0
        self._cooldown_until = 0.0

    def _pol(self) -> BackoffPolicy:
        return self.policy or _policy

    def record_pressure(self, overloaded: bool) -> None:
        from .metrics import set_shed_level
        with self._lock:
            if overloaded:
                self._over_streak += 1
                self._ok_streak = 0
                if (self._over_streak < self.shed_after
                        or self.level >= len(SHED_LEVELS) - 1):
                    return
                self.level += 1
                self._over_streak = 0
                self._cooldown_until = (time.monotonic()
                                        + self._pol().quarantine_for(1))
                set_shed_level(self.level)
                log.warning("shed ladder ESCALATED to level %d (%s)",
                            self.level, SHED_LEVELS[self.level])
            else:
                self._ok_streak += 1
                self._over_streak = 0
                if (self.level == 0
                        or self._ok_streak < self.recover_after
                        or time.monotonic() < self._cooldown_until):
                    return
                self.level -= 1
                self._ok_streak = 0
                set_shed_level(self.level)
                log.warning("shed ladder recovered to level %d (%s)",
                            self.level, SHED_LEVELS[self.level])

    def mode(self) -> str:
        return SHED_LEVELS[self.level]

    def reset(self) -> None:
        from .metrics import set_shed_level
        with self._lock:
            self.level = 0
            self._over_streak = 0
            self._ok_streak = 0
            self._cooldown_until = 0.0
        set_shed_level(0)


#: the process-wide shed ladder — tenantsvc admission drives and
#: consults it
SHED = ShedLadder()


def reset() -> None:
    """Test/soak helper: disarm and clear every piece of process-wide
    robustness state."""
    global _PLAN
    _PLAN = None
    LADDER.reset()
    SIDECAR_QUARANTINE.reset()
    SHED.reset()


# daemon path: arm directly from the environment at import so every
# entry point (CLI, bench, sidecar) honors KUBEBATCH_FAULTS without
# plumbing
arm_from_env()
