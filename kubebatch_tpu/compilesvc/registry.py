"""Shape-bucket registry — the compile surface, explicit and enumerable.

Every jitted entry point registers a *signature provider*: a function
that, given a config's :class:`~kubebatch_tpu.compilesvc.profile.
ConfigMaterials`, yields the canonical (shape-bucket x static-arg)
signatures that engine dispatches for the config. The padding
granularity itself already lives in ``kernels/tensorize.py``
(``pad_to_bucket`` / ``sticky_bucket``) and in each engine's static jit
args; this module only makes the resulting bucket set a first-class,
listable object so the full compile surface of a config can be listed,
counted, and diffed — and so the warm-up pass (compilesvc/warmup.py)
can compile it ahead of the first scheduling cycle.

A signature's ``key`` is a canonical string derived from the entry name,
the avals (dtype x shape, weak-typedness included) of every positional
argument, and the static kwargs — the SAME derivation the monitor's
instrumented trace boundaries apply to live calls (monitor.py), so
registry membership of a live dispatch is a set lookup. Keys carry no
process-local state (no ids, no addresses); for a fixed config and
environment they are bit-stable across fresh processes, which
tests/test_compilesvc.py pins.

This module is import-light on purpose: the kernel modules import it at
module load to register their providers, so it must not import jax, the
kernels, or the sim at module level.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Signature", "register_provider", "providers",
           "enumerate_signatures", "diff_signatures", "signature_key"]


# ---------------------------------------------------------------------
# canonical signature keys
# ---------------------------------------------------------------------

def _aval(x) -> str:
    """Canonical token for one argument: dtype[shape] for array-likes
    (jnp / np arrays and scalars), repr for python statics, recursion
    for tuples (pack layouts, order-key specs) and NamedTuple pytrees
    (RoundState / CycleArrays)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        weak = "~" if getattr(x, "weak_type", False) else ""
        name = getattr(dtype, "name", str(dtype))
        return f"{weak}{name}[{'x'.join(str(int(d)) for d in shape)}]"
    if x is None or isinstance(x, (bool, int, float, str)):
        return repr(x)
    if hasattr(x, "_fields"):          # NamedTuple pytree
        inner = ",".join(f"{f}={_aval(getattr(x, f))}" for f in x._fields)
        return f"{type(x).__name__}({inner})"
    if isinstance(x, (tuple, list)):
        return "(" + ",".join(_aval(v) for v in x) + ")"
    return type(x).__name__


def signature_key(entry: str, args: tuple, statics: dict) -> str:
    """The canonical key for one (entry, avals, statics) combination —
    shared by registry enumeration and the monitor's live boundaries."""
    kw = ";".join(f"{k}={_aval(v)}" for k, v in sorted(statics.items()))
    return f"{entry}|{','.join(_aval(a) for a in args)}|{kw}"


# ---------------------------------------------------------------------
# signatures + providers
# ---------------------------------------------------------------------

@dataclass
class Signature:
    """One registered (shape-bucket x static-arg) compile signature.

    ``lower``: zero-arg callable returning a ``jax.stages.Lowered`` for
    the AOT ``.lower().compile()`` pass. ``run``: zero-arg callable that
    EXECUTES the entry on canonical inputs through its instrumented
    wrapper — unlike AOT compilation this also populates the in-process
    jit dispatch cache, which is what pins same-process recompiles to
    zero (jax's AOT executables do not feed the live-call cache; see
    docs/COMPILE.md "Warm-up modes").
    """
    engine: str
    entry: str
    key: str
    lower: Optional[Callable] = None
    run: Optional[Callable] = None
    note: str = ""

    def __repr__(self) -> str:  # keys are long; keep repr scannable
        return f"Signature({self.engine}/{self.entry}, {self.note or self.key[:60]})"


#: provider registry: insertion-ordered {name: provider}; providers are
#: registered by the engine modules at import (see PROVIDER_MODULES)
_PROVIDERS: Dict[str, Callable] = {}

#: modules whose import registers every provider — the one list that
#: defines "the full compile surface" (new engines add themselves here)
PROVIDER_MODULES: Tuple[str, ...] = (
    "kubebatch_tpu.kernels.solver",
    "kubebatch_tpu.kernels.batched",
    "kubebatch_tpu.kernels.batched_sharded",
    "kubebatch_tpu.kernels.hier",
    "kubebatch_tpu.kernels.activeset",
    "kubebatch_tpu.kernels.sharded",
    "kubebatch_tpu.kernels.victims",
    "kubebatch_tpu.actions.allocate_fused",
    "kubebatch_tpu.tenantsvc.megasolve",
)


def register_provider(name: str):
    """Decorator: register ``fn(materials) -> List[Signature]`` under
    ``name`` (the engine module's identity in listings)."""
    def deco(fn):
        _PROVIDERS[name] = fn
        return fn
    return deco


def providers() -> Dict[str, Callable]:
    """The registered providers (imports PROVIDER_MODULES first so the
    listing is complete regardless of what the process touched)."""
    import importlib

    for mod in PROVIDER_MODULES:
        importlib.import_module(mod)
    return dict(_PROVIDERS)


def enumerate_signatures(config, steady: bool = True,
                         materials=None) -> List[Signature]:
    """The full registered compile surface for ``config`` (cfg1..cfg5p),
    deduped by key and sorted — the listed/counted/diffed object.

    ``steady=False`` restricts to the cold-cycle surface (cheap: no
    engine executes); ``steady=True`` also advances the profile cluster
    to the steady/churn regime, which is where the victim kernels and
    the small-cycle fused shapes live — reaching that state executes one
    scheduling round (see profile.ConfigMaterials.advance_to_steady).
    """
    from .profile import build_materials

    if materials is None:
        materials = build_materials(config, steady=steady)
    elif steady and not materials.is_steady:
        materials.advance_to_steady()
    out: Dict[str, Signature] = {}
    for name, provider in providers().items():
        for sig in provider(materials):
            out.setdefault(sig.key, sig)
    return sorted(out.values(), key=lambda s: (s.engine, s.entry, s.key))


def diff_signatures(a: List[Signature], b: List[Signature]):
    """(only_in_a, only_in_b) by key — the config-to-config compile
    surface diff (e.g. what cfg5p adds over cfg5)."""
    ka = {s.key: s for s in a}
    kb = {s.key: s for s in b}
    only_a = [s for k, s in sorted(ka.items()) if k not in kb]
    only_b = [s for k, s in sorted(kb.items()) if k not in ka]
    return only_a, only_b
