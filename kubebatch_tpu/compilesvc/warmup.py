"""AOT warm-up — compile the registered bucket set before the first
scheduling cycle, then pin recompiles to zero.

Two warm-up modes per signature (docs/COMPILE.md "Warm-up modes"):

- ``execute`` (the default for a live process): run the entry on its
  canonical inputs through the instrumented wrapper. This both compiles
  the program (persisted by the managed cache) AND populates jax's
  in-process dispatch cache, so the daemon's first real cycle is a pure
  cache hit — the property the steady benches pin
  (``recompiles_total == 0``). Executing a scheduler kernel on
  synthetic inputs is safe: every entry is a pure function of its
  arguments.

- ``aot`` (``execute=False`` — the offline ``tools/precompile.py``
  shape): ``jax.jit(...).lower().compile()`` only. No device execution;
  the product is the persistent-cache entries, which a later process
  retrieves in milliseconds instead of recompiling. jax's AOT
  executables do NOT feed the live dispatch cache, so an aot-warmed
  process still pays a (cheap, disk-served) retrace per signature —
  retrievals are warm by definition and never count as recompiles.

Sequencing matters: the cold surface is compiled FIRST, so that
advancing the profile cluster to the steady regime (which executes one
scheduling round) rides the just-warmed cold signatures instead of
compiling them as an untracked side effect.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Tuple

from . import monitor
from .registry import Signature, enumerate_signatures

__all__ = ["warmup", "WarmupReport"]


@dataclass
class WarmupReport:
    config: object
    mode: str
    signatures: int = 0
    compiled: int = 0
    skipped: int = 0
    failed: List[Tuple[str, str]] = field(default_factory=list)
    compile_ms: float = 0.0
    wall_ms: float = 0.0
    cache_dir: str = ""
    keys: List[str] = field(default_factory=list)

    def summary(self) -> str:
        out = (f"cfg{self.config}: {self.compiled}/{self.signatures} "
               f"signatures compiled ({self.mode}), "
               f"{self.compile_ms:.0f} ms compile wall, "
               f"{self.wall_ms:.0f} ms total")
        if self.skipped:
            out += f", {self.skipped} already warm"
        if self.failed:
            out += f", {len(self.failed)} FAILED"
        if self.cache_dir:
            out += f", cache {self.cache_dir}"
        return out


def _one(sig: Signature, execute: bool, seen: set, report: WarmupReport):
    if sig.key in seen:      # cold keys re-listed by the steady pass
        return
    seen.add(sig.key)
    try:
        if execute and sig.run is not None:
            import jax

            jax.block_until_ready(sig.run())
        elif sig.lower is not None:
            sig.lower().compile()
        else:                      # pragma: no cover — providers set one
            report.skipped += 1
            return
        report.compiled += 1
    except Exception as e:         # a failed signature must not sink the
        report.failed.append((sig.key, f"{type(e).__name__}: {e}"))


def warmup(config, execute: bool = True, steady: bool = True,
           persistent_cache: bool = True) -> WarmupReport:
    """Warm the registered bucket set for ``config`` and mark the
    process warm (``monitor.mark_warm``): from the moment this returns,
    any real compile at a trace boundary increments
    ``recompiles_total{engine, reason}``.

    ``steady=False`` restricts to the cold surface (no execution of a
    scheduling round); ``persistent_cache=False`` leaves the cache
    config untouched (tests)."""
    from .. import metrics
    from .profile import build_materials

    monitor.install()
    report = WarmupReport(config=config,
                          mode="execute" if execute else "aot")
    if persistent_cache:
        from .cache import enable_persistent_compile_cache

        report.cache_dir = enable_persistent_compile_cache()
    t0 = time.perf_counter()
    c0 = metrics.compile_ms_total()
    seen: set = set()

    materials = build_materials(config, steady=False)
    cold = enumerate_signatures(config, steady=False, materials=materials)
    for sig in cold:
        _one(sig, execute, seen, report)
    sigs = cold
    if steady:
        # the steady advance executes one scheduling round — its cold
        # dispatches are cache hits now, its steady shapes get compiled
        # by the round itself (execute) or lowered below (aot)
        materials.advance_to_steady()
        sigs = enumerate_signatures(config, steady=True,
                                    materials=materials)
        for sig in sigs:
            _one(sig, execute, seen, report)
    materials.close()
    report.signatures = len(sigs)
    report.keys = [s.key for s in sigs]
    report.compile_ms = metrics.compile_ms_total() - c0
    report.wall_ms = (time.perf_counter() - t0) * 1e3
    monitor.mark_warm(report.keys)
    return report
