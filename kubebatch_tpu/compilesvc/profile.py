"""Per-config bucket profiles — the canonical cycle materials the
signature providers derive shapes from.

A config's compile surface is a function of the shapes its scheduling
cycles produce, and those shapes are already deterministic: the sim
generators are seeded, the pad buckets are pow2 with documented minimums
(kernels/tensorize.py), and the static jit args derive from the shipped
plugin stack. So instead of hand-maintaining a shape table that would
drift from the code, a profile IS a deterministically-built cycle:
:func:`build_materials` populates the config's simulated cluster, opens
a session, and tensorizes it exactly the way a live cycle would —
without dispatching anything. Providers then read real
``CycleInputs`` / ``DeviceSession`` / ``VictimSolver`` objects, so a
registered signature can never disagree with the live path's
arg-building code (they share it).

Two regimes per config:

- **cold** (always built): the full-backlog first cycle — the big
  batched/fused shapes, the per-visit scan, the scatter buckets. Pure
  host work; building it compiles nothing.
- **steady** (``advance_to_steady``): one full scheduling round is
  EXECUTED, bound pods flip Running, a canonical churn tick arrives,
  and a fresh session is tensorized — the regime the 1 s schedule loop
  lives in, where the victim kernels (running tasks exist now) and the
  small-cycle fused shapes appear. Reaching this state necessarily
  executes the cold engines once; warmup orders its passes so that
  execution is itself the cold warm-up, not a redundant compile.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

__all__ = ["ConfigMaterials", "build_materials", "STEADY_CHURN"]

#: canonical steady-regime churn (pods per tick) — matches the committed
#: steady bench lines (bench.py --steady 256); tiny configs clamp to
#: their population
STEADY_CHURN = 256


class _Seam:
    """Binder/evictor seam for the profile cluster (sim pods never touch
    a real apiserver)."""

    def __init__(self):
        self.bound: List = []

    def bind(self, pod, hostname):
        pod.node_name = hostname
        self.bound.append(pod)

    def evict(self, pod):
        pod.deletion_timestamp = 1.0


@dataclass
class ConfigMaterials:
    """Everything the signature providers need for one config."""
    config: object
    actions: Tuple[str, ...]
    tiers: list
    sim: object
    cache: object
    seam: _Seam
    #: cold-regime cycle inputs (CycleInputs | EMPTY_CYCLE | None) and
    #: the pending-task gang sizes feeding the per-visit scan buckets
    cold_inputs: object = None
    gang_buckets: Tuple[int, ...] = ()
    #: steady-regime products (None until advance_to_steady)
    steady_inputs: object = None
    reclaim_solver: object = None
    preempt_solver: object = None
    is_steady: bool = False
    #: sessions kept referenced so device snapshots stay attached
    _sessions: list = field(default_factory=list)

    # -- construction ---------------------------------------------------

    def _open(self):
        from ..framework import OpenSession

        return OpenSession(self.cache, self.tiers)

    def _build_cold(self) -> None:
        from ..actions.cycle_inputs import build_cycle_inputs
        from ..api import TaskStatus
        from ..framework import CloseSession
        from ..kernels.tensorize import pad_to_bucket

        ssn = self._open()
        try:
            self.cold_inputs = build_cycle_inputs(ssn, allow_affinity=True)
            buckets = sorted({
                pad_to_bucket(len(j.task_status_index.get(
                    TaskStatus.PENDING, {})), 8)
                for j in ssn.jobs.values()
                if TaskStatus.PENDING in j.task_status_index})
            self.gang_buckets = tuple(buckets)
        finally:
            CloseSession(ssn)

    def advance_to_steady(self) -> None:
        """Execute one full scheduling round, flip bound pods Running,
        churn-tick, and tensorize the resulting steady session. The
        execution is deliberate: it is the only honest way to reach the
        shapes the steady loop dispatches (and it warms the cold
        signatures as a side effect — warmup sequences around that)."""
        if self.is_steady:
            return
        from ..actions.allocate import AllocateAction
        from ..actions.backfill import BackfillAction
        from ..actions.cycle_inputs import build_cycle_inputs
        from ..actions.preempt import PreemptAction
        from ..actions.reclaim import ReclaimAction
        from ..framework import CloseSession
        from ..objects import PodPhase

        mk = {"allocate": lambda: AllocateAction(mode="auto"),
              "backfill": BackfillAction,
              "preempt": PreemptAction,
              "reclaim": ReclaimAction}
        acts = [mk[name]() for name in self.actions]
        ssn = self._open()
        try:
            for act in acts:
                act.execute(ssn)
        finally:
            CloseSession(ssn)
        # kubelet tick: bound pods start Running outside the cycle, so
        # the steady session carries victim rows (running tasks)
        for pod in self.seam.bound:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                self.cache.update_pod(pod, pod)
        self.seam.bound.clear()
        spec = self.sim.spec
        churn = max(1, min(STEADY_CHURN,
                           spec.pods_per_group * spec.n_groups))
        # churn_tick degrades gracefully on its own (a cluster with no
        # fully-bound gang recycles 0 pods); a real raise here must
        # propagate — a silently churn-less profile would register the
        # wrong steady shapes, i.e. exactly the mid-run recompiles this
        # subsystem exists to prevent
        self.sim.churn_tick(self.cache, churn)

        ssn = self._open()
        self._sessions.append(ssn)   # stays open: victim solvers read it
        self.steady_inputs = build_cycle_inputs(ssn, allow_affinity=True)
        if "reclaim" in self.actions or "preempt" in self.actions:
            from ..kernels.victims import SKIP_ACTION, build_action_solver

            if "reclaim" in self.actions:
                s = build_action_solver(ssn, "reclaimable_fns",
                                        "reclaimable_disabled",
                                        score_nodes=False)
                self.reclaim_solver = None if s is SKIP_ACTION else s
            if "preempt" in self.actions:
                s = build_action_solver(ssn, "preemptable_fns",
                                        "preemptable_disabled",
                                        score_nodes=True)
                self.preempt_solver = None if s is SKIP_ACTION else s
        self.is_steady = True

    def close(self) -> None:
        from ..framework import CloseSession

        while self._sessions:
            CloseSession(self._sessions.pop())


def build_materials(config, steady: bool = False) -> ConfigMaterials:
    """Deterministic materials for ``config`` (a BASELINE key: 1..5,
    "2p"/"3p"/"5p"). Cold regime always; ``steady=True`` also advances
    to the churn regime (executes one scheduling round — see class
    docstring)."""
    from ..cache import SchedulerCache
    from ..conf import CONFIG_ACTIONS, shipped_tiers
    from ..sim import baseline_cluster

    seam = _Seam()
    cache = SchedulerCache(binder=seam, evictor=seam,
                           async_writeback=False)
    sim = baseline_cluster(config)
    sim.populate(cache)
    m = ConfigMaterials(config=config, actions=CONFIG_ACTIONS[config],
                        tiers=shipped_tiers(), sim=sim, cache=cache,
                        seam=seam)
    m._build_cold()
    if steady:
        m.advance_to_steady()
    return m
