"""Persistent compile-cache discipline.

XLA's persistent compilation cache is what lets warmed executables
survive process restarts (an AOT ``lower().compile()`` at daemon start
writes the disk entries; the next start retrieves them in milliseconds).
Left unmanaged it has two sharp edges this module owns:

- **Key salting.** Entries written by a different kubebatch/jax build
  must never be retrieved (deserializing foreign entries has segfaulted
  full-suite runs — see tests/conftest.py). The managed cache directory
  is therefore salted per (package version, jax version, backend):
  ``<root>/<salt>/``; a version bump rolls to a fresh directory instead
  of mixing entries.

- **Explicit off-switch.** Tests force ``KUBEBATCH_COMPILE_CACHE=0`` —
  hermeticity requires in-process caches only. Everything else (CLI,
  bench, precompile tool) opts in at entry.

``enable_persistent_compile_cache`` is re-exported at package root
(``kubebatch_tpu.enable_persistent_compile_cache``) for embedders.
"""
from __future__ import annotations

import os

__all__ = ["cache_salt", "cache_root", "enable_persistent_compile_cache"]

#: min compile seconds below which entries are not persisted — small
#: programs retrace faster than they deserialize
MIN_PERSIST_SECS = 1.0


def cache_salt() -> str:
    """The versioned key salt: entries only ever shared between
    identically-versioned processes on the same backend.

    The backend component must be resolved WITHOUT initializing a
    backend: the entry points enable the cache before the accelerator
    watchdog probes (a wedged transport can hang init forever, which is
    the watchdog's whole reason to exist), so ``jax.default_backend()``
    is off the table here. ``jax.config.jax_platforms`` covers every
    deliberate pin — the test env, an explicit JAX_PLATFORMS, and the
    watchdog's cpu-fallback flip (the entry points re-call
    enable_persistent_compile_cache after the probe so a flipped
    process re-salts onto the cpu directory instead of mixing cpu
    executables into the accelerator's) — leaving "default" only for a
    process genuinely running the platform-default accelerator."""
    from .. import __version__
    import jax

    backend = (getattr(jax.config, "jax_platforms", "")
               or os.environ.get("JAX_PLATFORMS", "") or "default")
    return f"kb{__version__}-jax{jax.__version__}-{backend}"


def cache_root(path=None) -> str:
    env = os.environ.get("KUBEBATCH_COMPILE_CACHE", "")
    if path is None:
        path = env or os.path.expanduser("~/.cache/kubebatch-tpu/xla")
    return path


def enable_persistent_compile_cache(path=None) -> str:
    """Point XLA's persistent compilation cache at the managed, salted
    directory (default ``$KUBEBATCH_COMPILE_CACHE`` or
    ``~/.cache/kubebatch-tpu/xla``, plus the version salt) so a
    restarted scheduler retrieves compiled solver programs instead of
    re-compiling them — measured on the v5e tunnel, the first cfg5 solve
    of a fresh process drops 67 s -> 11 s, and after a
    ``tools/precompile.py`` pass the whole registered bucket set is a
    retrieval. Process entry points (CLI, bench, precompile) call this;
    embedders opt in explicitly. ``KUBEBATCH_COMPILE_CACHE=0`` disables
    (tests force this — they must never share entries across
    differently-shaped processes). Returns the directory ("" when
    disabled)."""
    env = os.environ.get("KUBEBATCH_COMPILE_CACHE", "")
    if env in ("0", "false", "off"):
        return ""
    import jax

    path = os.path.join(cache_root(path), cache_salt())
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      MIN_PERSIST_SECS)
    return path
