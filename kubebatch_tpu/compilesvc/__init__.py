"""compilesvc — the compile manager (ISSUE 6 / ROADMAP item 4).

XLA compilation is a first-class production concern for this scheduler:
the one recorded cfg5p device-shaped run spent 536 s dominated by
compile, and a daemon serving the <15 ms p50 target cannot eat a
compile wall mid-cycle. This subsystem makes the compile surface
explicit and keeps it off the latency path, in three parts:

- **Shape-bucket registry** (registry.py + providers in every engine
  module): the canonical (shape-bucket x static-arg) signatures each
  jitted entry point dispatches per config — listable, countable,
  diffable.
- **AOT warm-up** (warmup.py, profile.py, cache.py): compile the
  registered set at daemon start (CLI ``--warmup``) or offline
  (``tools/precompile.py``), with managed persistent-cache discipline
  (salted directory) so warmed executables survive restarts.
- **Enforcement** (monitor.py + metrics): ``compile_ms_total`` and
  ``recompiles_total{engine, reason}`` at every trace boundary, wired
  into bench emission and the scheduler's degradation ladder; steady
  benches fail when ``recompiles_total > 0`` after warm-up, and a
  mid-run shape outside the registry surfaces as
  ``reason="unregistered"`` instead of a silent stall.

Import discipline: this package root and registry/monitor are light
(kernel modules import them at load); profile/warmup pull in the sim
and actions lazily.
"""
from __future__ import annotations

from .cache import (cache_salt, enable_persistent_compile_cache)  # noqa: F401
from .monitor import (install, instrument, is_warm, known_keys,  # noqa: F401
                      mark_warm, reset)
from .registry import (Signature, diff_signatures,  # noqa: F401
                       enumerate_signatures, register_provider,
                       signature_key)

__all__ = [
    "Signature", "register_provider", "enumerate_signatures",
    "diff_signatures", "signature_key", "instrument", "install",
    "mark_warm", "is_warm", "known_keys", "reset",
    "enable_persistent_compile_cache", "cache_salt", "warmup",
]


def warmup(config, execute: bool = True, steady: bool = True,
           persistent_cache: bool = True):
    """Warm the registered bucket set (see compilesvc.warmup.warmup) —
    lazy wrapper so importing the package stays light."""
    from .warmup import warmup as _warmup

    return _warmup(config, execute=execute, steady=steady,
                   persistent_cache=persistent_cache)
