"""Compile monitoring — every trace boundary observed, every compile
accounted.

Two mechanisms compose:

- A process-wide ``jax.monitoring`` listener accumulates every XLA
  backend compile's wall (persistent-cache retrieval wall included)
  into ``metrics.compile_ms_total``, whatever thread compiles.

- :func:`instrument` wraps each jitted entry point. The wrapper is the
  TRACE BOUNDARY: it detects a dispatch-cache miss (``_cache_size``
  growth on the underlying pjit function), derives the live call's
  canonical signature key (registry.signature_key — identical to the
  registry's derivation), and classifies any post-warm-up REAL compile
  (a backend compile not served by the persistent cache) into
  ``metrics.recompiles_total{engine, reason}``:

  * ``reason="unregistered"`` — the signature is outside the known set
    (registered bucket set + everything traced before warm-up): a
    mid-run shape the registry does not cover, surfaced instead of
    silently absorbed.
  * ``reason="warm-miss"`` — a known signature compiled anyway (the
    persistent cache is off, was evicted, or its key salt changed).

  Boundaries nest (``fused_allocate`` inside ``_fused_packed``'s trace,
  ``batched_allocate`` inside ``_sharded_entry``): only the outermost
  wrapper on a thread accounts, so one logical dispatch is one boundary.

The hot path cost is two C++ ``_cache_size`` calls and a list
push/pop; the key is only derived on a cache miss.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional, Set

from .. import metrics
from .registry import signature_key

__all__ = ["install", "instrument", "mark_warm", "is_warm", "known_keys",
           "add_known_keys", "reset"]

_tls = threading.local()
_lock = threading.Lock()
_installed = False
_warm = False
#: signatures the process may legitimately trace without it counting as
#: a recompile source classification of "unregistered": the registered /
#: warmed bucket set plus everything traced BEFORE mark_warm()
_known: Set[str] = set()


class _Boundary:
    __slots__ = ("engine", "entry", "compiles", "disk_hits")

    def __init__(self, engine: str, entry: str):
        self.engine = engine
        self.entry = entry
        self.compiles = 0
        self.disk_hits = 0


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _on_duration(name: str, duration: float, **kw) -> None:
    # backend_compile only: the trace/lowering phase events nest (one
    # fires per inner jaxpr) and would double-count against wall time;
    # backend compiles are disjoint per program, so their sum is a true
    # "XLA compile wall" (persistent-cache retrieval wall included)
    if not name.endswith("backend_compile_duration"):
        return
    metrics.add_compile_ms(duration * 1e3)
    # land the compile as an event in the obs span tree (the listener
    # fires on the compiling thread, so it attaches inside the dispatch
    # span that paid the wall) — exported traces then SHOW the compile
    # instead of an unexplained gap
    try:
        from ..obs import add_event
        add_event("xla_compile", duration, cat="compile")
    except Exception:       # pragma: no cover — obs must never break jax
        pass
    st = getattr(_tls, "stack", None)
    if st:
        st[-1].compiles += 1


def _on_event(name: str, **kw) -> None:
    # a persistent-cache retrieval still fires backend_compile_duration
    # (the retrieval wall); the paired cache_hits event marks it warm
    if name == "/jax/compilation_cache/cache_hits":
        st = getattr(_tls, "stack", None)
        if st:
            st[-1].disk_hits += 1


def install() -> None:
    """Register the jax.monitoring listeners once per process."""
    global _installed
    if _installed:
        return
    with _lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        jax.monitoring.register_event_listener(_on_event)
        _installed = True


def mark_warm(keys=()) -> None:
    """Declare warm-up complete: from here on, a real compile at an
    instrumented boundary is a counted recompile. ``keys``: extra
    signature keys to fold into the known set (warmup passes the
    registered bucket set)."""
    global _warm
    with _lock:
        _known.update(keys)
        _warm = True


def is_warm() -> bool:
    return _warm


def add_known_keys(keys) -> None:
    with _lock:
        _known.update(keys)


def known_keys() -> Set[str]:
    """A copy of the known signature set (registered + pre-warm-traced)."""
    with _lock:
        return set(_known)


def reset() -> None:
    """Drop compile-manager state AND jax's in-process executable caches.

    The scoped reset the test fixture uses (tests/conftest.py): clears
    jax's native compiler caches (the accumulated-state segfault
    mitigation), the warm mark + known-signature set (so one module's
    warm-up cannot classify another module's compiles), and the sticky
    shape-bucket holds (so a big module's pow2 hold never leaks onto a
    small module's shapes). Process-lifetime metrics counters are NOT
    zeroed — consumers diff across a window, like every other counter.
    """
    global _warm
    with _lock:
        _warm = False
        _known.clear()
    from ..kernels import tensorize

    tensorize._STICKY.clear()
    import jax

    jax.clear_caches()


def _note_miss(engine: str, entry: str, args, statics, b: _Boundary) -> None:
    key = signature_key(entry, args, statics)
    with _lock:
        known = key in _known
        _known.add(key)
        warm = _warm
    if warm and b.compiles > b.disk_hits:
        metrics.count_recompile(engine,
                                "warm-miss" if known else "unregistered")


def instrument(engine: str, entry: str, fn) -> Callable:
    """Wrap a jitted entry point as an accounted trace boundary.

    The wrapper forwards ``lower`` / ``_cache_size`` (AOT warm-up and
    tests use them) and exposes the underlying pjit function as
    ``jit_fn``. Nested boundaries pass straight through to the pjit
    function — the outermost boundary owns the accounting.
    """
    def wrapper(*args, **kwargs):
        st = _stack()
        if st:                      # nested under an outer boundary
            return fn(*args, **kwargs)
        install()
        try:
            size0 = fn._cache_size()
        except Exception:           # pragma: no cover — older jax
            size0 = None
        b = _Boundary(engine, entry)
        st.append(b)
        try:
            out = fn(*args, **kwargs)
        finally:
            st.pop()
        grew = b.compiles > 0 if size0 is None else False
        if size0 is not None:
            try:
                grew = fn._cache_size() > size0
            except Exception:       # pragma: no cover
                grew = b.compiles > 0
        if grew and b.compiles:
            _note_miss(engine, entry, args, kwargs, b)
        return out

    wrapper.__name__ = entry
    wrapper.__qualname__ = entry
    wrapper.__wrapped__ = fn
    wrapper.jit_fn = fn
    wrapper.engine = engine
    wrapper.lower = fn.lower
    try:
        wrapper._cache_size = fn._cache_size
    except Exception:               # pragma: no cover — older jax
        pass
    return wrapper
