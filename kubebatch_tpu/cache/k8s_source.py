"""Kubernetes API-server EventSource — the concrete informer adapter.

This is the real-cluster implementation of the ``EventSource`` boundary
(cache/source.py): one LIST+WATCH loop per ``INFORMER_MAP`` row against
an API server, feeding deltas through the same cache handler surface the
sim source uses (ref: pkg/scheduler/cache/cache.go:217-295 — the nine
client-go informers — and pkg/client/clientset/versioned/clientset.go:62
for the CRD clientset this module's podgroups/queues rows replace).

Two layers, deliberately separable:

1. **Manifest conversion** (`pod_from_manifest` & friends) — pure
   functions from Kubernetes JSON manifests (what LIST/WATCH bodies
   carry) to the scheduler's dataclass vocabulary (objects.py). These
   have no dependency on the ``kubernetes`` package, so fixture-replay
   tests drive the full adapter path with recorded JSON and no API
   server (SURVEY §4 tier-2 strategy).
2. **`K8sEventSource`** — the live adapter: LIST each kind (capturing
   ``resourceVersion``), replay as ADDED events, then WATCH from that
   version in a daemon thread; on HTTP 410 Gone the loop re-LISTs and
   resumes from the fresh version (client-go Reflector semantics).
   Construction requires the ``kubernetes`` client only when no
   transport is injected; everything is seam-injectable for tests.

Pod filtering (pending pods for our scheduler name only, non-pending
always — cache.go:246-264) is NOT re-implemented here: it lives in
``SchedulerCache._pod_relevant`` so every source shares one filter. The
adapter's server-side field selector merely narrows the wire traffic.
"""
from __future__ import annotations

import calendar
import logging
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..faults import check as _fault_check
from ..faults import check_raise as _fault_check_raise
from ..objects import (Affinity, Container, MatchExpression, Node,
                       NodeAffinity, NodeSelectorTerm, Pod, PodAffinityTerm,
                       PodDisruptionBudget, PodGroup, PodGroupCondition,
                       PodGroupPhase, PodGroupStatus, PodPhase, PriorityClass,
                       Queue, Taint, TaintEffect, Toleration, parse_quantity)
from .source import EventType, InformerAdapter, WatchEvent

log = logging.getLogger("kubebatch.k8s")

# CRD coordinates (ref: pkg/apis/scheduling/v1alpha1/register.go:255-258)
CRD_GROUP = "scheduling.incubator.k8s.io"
CRD_VERSION = "v1alpha1"


# ---------------------------------------------------------------------
# manifest conversion (pure; no kubernetes-client dependency)
# ---------------------------------------------------------------------

def _ts(v) -> float:
    """RFC3339 creationTimestamp -> epoch seconds (0.0 when absent)."""
    if not v:
        return 0.0
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).rstrip("Z")
    try:
        return float(calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%S")))
    except ValueError:
        return 0.0


def _meta(m: dict) -> dict:
    return m.get("metadata") or {}


def _controller_uid(meta: dict) -> str:
    """Owner UID of the controlling reference (ref:
    pkg/apis/utils/utils.go:305-317 — the shadow-PodGroup job key)."""
    for ref in meta.get("ownerReferences") or []:
        if ref.get("controller"):
            return str(ref.get("uid", ""))
    return ""


def _requests(container: dict) -> Dict[str, float]:
    reqs = ((container.get("resources") or {}).get("requests")) or {}
    out: Dict[str, float] = {}
    for key, raw in reqs.items():
        val = parse_quantity(raw)
        # internal convention: cpu/gpu in millis (resource_info.go:58-73)
        if key in ("cpu", "nvidia.com/gpu"):
            val *= 1000.0
        out[key] = val
    return out


def _container(c: dict) -> Container:
    ports = [p["hostPort"] for p in (c.get("ports") or [])
             if p.get("hostPort")]
    return Container(requests=_requests(c), ports=ports)


def _match_expressions(terms: Iterable[dict]) -> List[MatchExpression]:
    return [MatchExpression(key=e.get("key", ""),
                            operator=e.get("operator", "In"),
                            values=[str(v) for v in e.get("values") or []])
            for e in terms]


def _node_selector_term(t: dict) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=_match_expressions(t.get("matchExpressions") or []))


def _pod_affinity_term(t: dict) -> PodAffinityTerm:
    sel = (t.get("labelSelector") or {}).get("matchLabels") or {}
    return PodAffinityTerm(
        match_labels=dict(sel),
        topology_key=t.get("topologyKey", "kubernetes.io/hostname"),
        namespaces=list(t.get("namespaces") or []))


def _affinity(spec: dict) -> Optional[Affinity]:
    a = spec.get("affinity")
    if not a:
        return None
    node_aff = None
    na = a.get("nodeAffinity") or {}
    req = (na.get("requiredDuringSchedulingIgnoredDuringExecution")
           or {}).get("nodeSelectorTerms") or []
    pref = na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    if req or pref:
        node_aff = NodeAffinity(
            required=[_node_selector_term(t) for t in req],
            preferred=[(p.get("weight", 1),
                        _node_selector_term(p.get("preference") or {}))
                       for p in pref])

    def _req_terms(kind: str) -> List[PodAffinityTerm]:
        terms = (a.get(kind) or {}).get(
            "requiredDuringSchedulingIgnoredDuringExecution") or []
        return [_pod_affinity_term(t) for t in terms]

    def _pref_terms(kind: str) -> List[Tuple[int, PodAffinityTerm]]:
        terms = (a.get(kind) or {}).get(
            "preferredDuringSchedulingIgnoredDuringExecution") or []
        return [(t.get("weight", 1),
                 _pod_affinity_term(t.get("podAffinityTerm") or {}))
                for t in terms]

    aff = Affinity(node_affinity=node_aff,
                   pod_affinity_required=_req_terms("podAffinity"),
                   pod_anti_affinity_required=_req_terms("podAntiAffinity"),
                   pod_affinity_preferred=_pref_terms("podAffinity"),
                   pod_anti_affinity_preferred=_pref_terms("podAntiAffinity"))
    if (node_aff is None and not aff.pod_affinity_required
            and not aff.pod_anti_affinity_required
            and not aff.pod_affinity_preferred
            and not aff.pod_anti_affinity_preferred):
        return None
    return aff


def pod_from_manifest(m: dict) -> Pod:
    """v1.Pod manifest -> Pod (the field subset the scheduler reads;
    ref: pkg/scheduler/api/job_info.go:36-131, pod_info.go:262-282)."""
    meta, spec = _meta(m), m.get("spec") or {}
    status = m.get("status") or {}
    pvc_names = [v["persistentVolumeClaim"]["claimName"]
                 for v in spec.get("volumes") or []
                 if v.get("persistentVolumeClaim", {}).get("claimName")]
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=str(meta.get("uid") or f"{meta.get('namespace', 'default')}"
                                   f"/{meta.get('name', '')}"),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        node_name=spec.get("nodeName", ""),
        phase=PodPhase(status.get("phase", "Pending")),
        priority=spec.get("priority"),
        priority_class_name=spec.get("priorityClassName", ""),
        containers=[_container(c) for c in spec.get("containers") or []],
        init_containers=[_container(c)
                         for c in spec.get("initContainers") or []],
        node_selector=dict(spec.get("nodeSelector") or {}),
        affinity=_affinity(spec),
        tolerations=[Toleration(key=t.get("key", ""),
                                operator=t.get("operator", "Equal"),
                                value=t.get("value", ""),
                                effect=t.get("effect", ""))
                     for t in spec.get("tolerations") or []],
        scheduler_name=spec.get("schedulerName", "default-scheduler"),
        deletion_timestamp=(_ts(meta["deletionTimestamp"])
                            if meta.get("deletionTimestamp") else None),
        creation_timestamp=_ts(meta.get("creationTimestamp")),
        owner_uid=_controller_uid(meta),
        status_conditions=[dict(c) for c in status.get("conditions") or []],
        pvc_names=pvc_names)


def node_from_manifest(m: dict) -> Node:
    """v1.Node manifest -> Node (ref: api/node_info.go:95-111 reads
    status.allocatable/capacity; spec taints/unschedulable)."""
    meta, spec = _meta(m), m.get("spec") or {}
    status = m.get("status") or {}

    def _rl(d: dict) -> Dict[str, float]:
        out = {}
        for key, raw in (d or {}).items():
            val = parse_quantity(raw)
            if key in ("cpu", "nvidia.com/gpu"):
                val *= 1000.0
            out[key] = val
        return out

    return Node(
        name=meta.get("name", ""),
        uid=str(meta.get("uid") or meta.get("name", "")),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        allocatable=_rl(status.get("allocatable")),
        capacity=_rl(status.get("capacity")),
        taints=[Taint(key=t.get("key", ""), value=t.get("value", ""),
                      effect=TaintEffect(t.get("effect", "NoSchedule")))
                for t in spec.get("taints") or []],
        unschedulable=bool(spec.get("unschedulable", False)))


def podgroup_from_manifest(m: dict) -> PodGroup:
    """PodGroup CRD manifest -> PodGroup (ref: v1alpha1/types.go:90-149)."""
    meta, spec = _meta(m), m.get("spec") or {}
    status = m.get("status") or {}
    return PodGroup(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=str(meta.get("uid") or f"{meta.get('namespace', 'default')}"
                                   f"/{meta.get('name', '')}"),
        min_member=int(spec.get("minMember", 0)),
        queue=spec.get("queue", ""),
        priority_class_name=spec.get("priorityClassName", ""),
        creation_timestamp=_ts(meta.get("creationTimestamp")),
        annotations=dict(meta.get("annotations") or {}),
        status=PodGroupStatus(
            phase=PodGroupPhase(status.get("phase", "Pending")),
            conditions=[PodGroupCondition(
                type=c.get("type", ""), status=c.get("status", "True"),
                transition_id=c.get("transitionID", ""),
                reason=c.get("reason", ""), message=c.get("message", ""))
                for c in status.get("conditions") or []],
            running=int(status.get("running", 0)),
            succeeded=int(status.get("succeeded", 0)),
            failed=int(status.get("failed", 0))))


def queue_from_manifest(m: dict) -> Queue:
    """Queue CRD manifest -> Queue (ref: v1alpha1/types.go:170-186)."""
    meta, spec = _meta(m), m.get("spec") or {}
    return Queue(name=meta.get("name", ""),
                 weight=int(spec.get("weight", 1)),
                 uid=str(meta.get("uid") or meta.get("name", "")))


def pdb_from_manifest(m: dict) -> PodDisruptionBudget:
    """policy/v1beta1 PDB manifest (legacy gang grouping path;
    ref: cache/event_handlers.go:477-515)."""
    meta, spec = _meta(m), m.get("spec") or {}
    min_avail = spec.get("minAvailable", 0)
    if isinstance(min_avail, str):          # percentage form unsupported
        min_avail = int(min_avail.rstrip("%") or 0)
    return PodDisruptionBudget(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=str(meta.get("uid") or f"{meta.get('namespace', 'default')}"
                                   f"/{meta.get('name', '')}"),
        min_available=int(min_avail),
        match_labels=dict((spec.get("selector") or {})
                          .get("matchLabels") or {}),
        creation_timestamp=_ts(meta.get("creationTimestamp")),
        owner_uid=_controller_uid(meta))


def priorityclass_from_manifest(m: dict) -> PriorityClass:
    """scheduling.k8s.io/v1beta1 PriorityClass manifest."""
    meta = _meta(m)
    return PriorityClass(name=meta.get("name", ""),
                         value=int(m.get("value", 0)),
                         global_default=bool(m.get("globalDefault", False)))


#: kind -> manifest converter; kinds whose INFORMER_MAP handlers are None
#: (PV/PVC/StorageClass) pass their manifests through to the volume sink
CONVERTERS: Dict[str, Callable[[dict], object]] = {
    "pods": pod_from_manifest,
    "nodes": node_from_manifest,
    "podgroups": podgroup_from_manifest,
    "queues": queue_from_manifest,
    "pdbs": pdb_from_manifest,
    "priorityclasses": priorityclass_from_manifest,
    "persistentvolumes": lambda m: m,
    "persistentvolumeclaims": lambda m: m,
    "storageclasses": lambda m: m,
}


def convert_manifest_event(kind: str, event_type: str, manifest: dict,
                           old_manifest: Optional[dict] = None) -> WatchEvent:
    """One recorded/live watch body -> a typed WatchEvent for dispatch."""
    conv = CONVERTERS[kind]
    return WatchEvent(kind=kind, type=EventType(event_type),
                      obj=conv(manifest),
                      old=conv(old_manifest) if old_manifest else None)


# ---------------------------------------------------------------------
# the live adapter
# ---------------------------------------------------------------------

class ResourceExpired(Exception):
    """HTTP 410 Gone — the watch resourceVersion fell out of etcd's
    window; the loop must re-LIST (client-go Reflector's relist path)."""


#: transport contract: list_fn(kind) -> (items: List[dict], resource_version),
#: watch_fn(kind, resource_version) -> iterable of
#: (event_type: str, manifest: dict); watch_fn raises ResourceExpired on 410
ListFn = Callable[[str], Tuple[List[dict], str]]
WatchFn = Callable[[str, str], Iterable[Tuple[str, dict]]]


def kubernetes_available() -> bool:
    try:
        import kubernetes  # noqa: F401
        return True
    except ImportError:
        return False


class K8sEventSource:
    """EventSource over a Kubernetes API server.

    ``kinds`` defaults to every INFORMER_MAP row with a cache handler
    (the PV/PVC/SC rows are included only when a ``volume_sink`` is
    given, mirroring how the reference wires those informers into the
    volume binder rather than the cache — cache.go:222-230).

    A custom ``transport`` (ListFn, WatchFn) replaces the kubernetes
    client entirely — this is the test seam; without one the
    ``kubernetes`` package is required at start().
    """

    RELIST_BACKOFF = 1.0

    def __init__(self, scheduler_name: str = "kube-batch",
                 kubeconfig: Optional[str] = None,
                 kinds: Optional[List[str]] = None,
                 transport: Optional[Tuple[ListFn, WatchFn]] = None,
                 volume_sink: Optional[Callable[[WatchEvent], None]] = None):
        from .source import INFORMER_MAP
        if kinds is None:
            kinds = [k for k, names in INFORMER_MAP.items()
                     if names[0] is not None]
            if volume_sink is not None:
                kinds += [k for k, names in INFORMER_MAP.items()
                          if names[0] is None]
        self.scheduler_name = scheduler_name
        self.kubeconfig = kubeconfig
        self.kinds = list(kinds)
        self._transport = transport
        self._adapter = InformerAdapter(volume_sink=volume_sink)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._listed = threading.Event()
        self._lock = threading.Lock()       # serialize cache dispatch

    # --- EventSource ---------------------------------------------------
    def start(self, cache) -> None:
        self._adapter.start(cache)
        if self._transport is None:
            self._transport = self._build_client_transport()
        list_fn, watch_fn = self._transport
        versions: Dict[str, str] = {}
        for kind in self.kinds:             # LIST: replay world as adds
            items, rv = list_fn(kind)
            versions[kind] = rv
            for manifest in items:
                self._dispatch(kind, "ADDED", manifest)
        self._listed.set()
        for kind in self.kinds:             # WATCH: one loop per kind
            t = threading.Thread(target=self._watch_loop,
                                 args=(kind, versions[kind]),
                                 name=f"kb-watch-{kind}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def sync(self, timeout: float = 5.0) -> bool:
        """True once the initial LIST of every kind has been applied
        (WaitForCacheSync, cache.go:318-331)."""
        return self._listed.wait(timeout)

    # --- internals -----------------------------------------------------
    def _dispatch(self, kind: str, event_type: str, manifest: dict,
                  old_manifest: Optional[dict] = None) -> None:
        ev = convert_manifest_event(kind, event_type, manifest, old_manifest)
        with self._lock:
            self._adapter.dispatch(ev)

    def _watch_loop(self, kind: str, resource_version: str) -> None:
        list_fn, watch_fn = self._transport
        rv = resource_version
        # MODIFIED needs the previous object (client-go hands OnUpdate
        # both); keep the last manifest seen per object key
        last: Dict[str, dict] = {}
        while not self._stop.is_set():
            try:
                # injection seams: a 410 Gone must flow through the
                # relist path (typed), a dropped stream through the
                # generic backoff+rewatch path — both BEFORE the watch
                # call, like failures the transport itself would raise
                _fault_check_raise("source.gone", ResourceExpired)
                _fault_check("source.disconnect")
                for event_type, manifest in watch_fn(kind, rv):
                    if self._stop.is_set():
                        return
                    rv = (_meta(manifest).get("resourceVersion") or rv)
                    key = (f"{_meta(manifest).get('namespace', '')}"
                           f"/{_meta(manifest).get('name', '')}")
                    old = last.get(key)
                    if event_type == "DELETED":
                        last.pop(key, None)
                    else:
                        last[key] = manifest
                    self._dispatch(kind, event_type, manifest,
                                   old if event_type == "MODIFIED" else None)
            except ResourceExpired:
                # 410 Gone: resourceVersion too old — re-LIST and resume
                # from the fresh version (Reflector relist). The re-LIST
                # replays adds; cache handlers are idempotent updates.
                log.warning("watch %s expired at rv=%s; relisting", kind, rv)
                try:
                    items, rv = list_fn(kind)
                    for manifest in items:
                        key = (f"{_meta(manifest).get('namespace', '')}"
                               f"/{_meta(manifest).get('name', '')}")
                        if key in last:
                            self._dispatch(kind, "MODIFIED", manifest,
                                           last[key])
                        else:
                            self._dispatch(kind, "ADDED", manifest)
                        last[key] = manifest
                except Exception:
                    log.exception("relist %s failed; backing off", kind)
                    self._stop.wait(self.RELIST_BACKOFF)
            except Exception:
                if self._stop.is_set():
                    return
                log.exception("watch %s failed; backing off", kind)
                self._stop.wait(self.RELIST_BACKOFF)

    def _build_client_transport(self) -> Tuple[ListFn, WatchFn]:
        """Transport over the real ``kubernetes`` client (import-guarded:
        only reached when no transport seam was injected)."""
        try:
            from kubernetes import client, config, watch
        except ImportError as e:            # pragma: no cover
            raise RuntimeError(
                "K8sEventSource needs the 'kubernetes' package (or an "
                "injected transport)") from e
        if self.kubeconfig:
            config.load_kube_config(config_file=self.kubeconfig)
        else:                               # pragma: no cover
            try:
                config.load_incluster_config()
            except Exception:
                config.load_kube_config()
        core = client.CoreV1Api()
        policy = client.PolicyV1beta1Api()
        sched = client.SchedulingV1beta1Api()
        crd = client.CustomObjectsApi()

        # pods: narrow the wire to (pending for our scheduler) ∪ (assigned)
        # server-side where possible; the authoritative filter remains
        # SchedulerCache._pod_relevant (cache.go:246-264)
        calls = {
            "pods": lambda **kw: core.list_pod_for_all_namespaces(**kw),
            "nodes": lambda **kw: core.list_node(**kw),
            "pdbs": lambda **kw:
                policy.list_pod_disruption_budget_for_all_namespaces(**kw),
            "priorityclasses": lambda **kw: sched.list_priority_class(**kw),
            "persistentvolumes": lambda **kw:
                core.list_persistent_volume(**kw),
            "persistentvolumeclaims": lambda **kw:
                core.list_persistent_volume_claim_for_all_namespaces(**kw),
            "storageclasses": lambda **kw:
                client.StorageV1Api().list_storage_class(**kw),
            "podgroups": lambda **kw: crd.list_cluster_custom_object(
                CRD_GROUP, CRD_VERSION, "podgroups", **kw),
            "queues": lambda **kw: crd.list_cluster_custom_object(
                CRD_GROUP, CRD_VERSION, "queues", **kw),
        }

        def _to_dict(obj):
            if isinstance(obj, dict):
                return obj
            return client.ApiClient().sanitize_for_serialization(obj)

        def list_fn(kind: str):
            resp = calls[kind]()
            body = _to_dict(resp)
            items = body.get("items") or []
            rv = (body.get("metadata") or {}).get("resourceVersion", "")
            return [_to_dict(i) for i in items], rv

        def watch_fn(kind: str, rv: str):
            w = watch.Watch()
            try:
                for ev in w.stream(calls[kind], resource_version=rv,
                                   timeout_seconds=300):
                    yield ev["type"], _to_dict(ev["object"])
            except client.ApiException as e:
                if e.status == 410:
                    raise ResourceExpired(str(e)) from e
                raise

        return list_fn, watch_fn
