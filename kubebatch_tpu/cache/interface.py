"""Cache seam interfaces — the boundaries tests fake and the runtime wires
to a real cluster API.

ref: pkg/scheduler/cache/interface.go. The Binder/Evictor/StatusUpdater/
VolumeBinder seams are exactly where the reference's unit tests inject
fakes (SURVEY.md sect. 4 tier 2); we keep that architecture so the same
test strategy applies.
"""
from __future__ import annotations

from typing import Optional, Protocol, runtime_checkable

from ..api import ClusterInfo, JobInfo, TaskInfo
from ..objects import Pod, PodGroup


@runtime_checkable
class Binder(Protocol):
    def bind(self, pod: Pod, hostname: str) -> None:
        """Bind pod to host; raise on failure (ref: interface.go:63-65).

        A binder MAY additionally expose ``bind_many(pairs)`` taking a
        list of ``(pod, hostname)`` tuples; the cache then ships whole
        decision batches through one call per chunk instead of one seam
        crossing per task (cache.py _submit_binds). All-or-nothing per
        chunk: a raise resyncs every task of the chunk."""
        ...


@runtime_checkable
class Evictor(Protocol):
    def evict(self, pod: Pod) -> None:
        """Delete the pod (3s grace in the reference, cache.go:125-142)."""
        ...


@runtime_checkable
class StatusUpdater(Protocol):
    def update_pod_condition(self, pod: Pod, condition: dict) -> None:
        ...

    def update_pod_group(self, pg: PodGroup) -> PodGroup:
        ...


@runtime_checkable
class VolumeBinder(Protocol):
    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        ...

    def bind_volumes(self, task: TaskInfo) -> None:
        ...


@runtime_checkable
class EventRecorder(Protocol):
    def eventf(self, obj, event_type: str, reason: str, message: str) -> None:
        ...


class Cache(Protocol):
    """ref: cache/interface.go:28-57."""

    def run(self) -> None: ...
    def snapshot(self) -> ClusterInfo: ...
    def wait_for_cache_sync(self) -> bool: ...
    def bind(self, task: TaskInfo, hostname: str) -> None: ...
    def evict(self, task: TaskInfo, reason: str) -> None: ...
    def record_job_status_event(self, job: JobInfo) -> None: ...
    def update_job_status(self, job: JobInfo) -> Optional[JobInfo]: ...
    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None: ...
    def bind_volumes(self, task: TaskInfo) -> None: ...


class NullBinder:
    """In-process binder for simulation: flips the pod's node_name."""

    def bind(self, pod: Pod, hostname: str) -> None:
        pod.node_name = hostname

    def bind_many(self, pairs) -> None:
        """Batched form (see Binder protocol): one call per decision
        chunk instead of one per task."""
        for pod, hostname in pairs:
            pod.node_name = hostname


class NullEvictor:
    def evict(self, pod: Pod) -> None:
        pod.deletion_timestamp = 0.0


class NullStatusUpdater:
    def update_pod_condition(self, pod: Pod, condition: dict) -> None:
        pod.status_conditions.append(condition)

    def update_pod_group(self, pg: PodGroup) -> PodGroup:
        return pg


class NullVolumeBinder:
    """Volume handling is a no-op in simulation (the reference delegates to
    the upstream k8s volumebinder with a 30s timeout, cache.go:164-184)."""

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        task.volume_ready = True

    def bind_volumes(self, task: TaskInfo) -> None:
        return None


class SimVolumeBinder:
    """Functional volume binder for simulation: tracks per-host volume
    capacity (volumes pending + bound per hostname) and fails allocation
    when a host is out of slots — the sim stand-in for the upstream
    volumebinder's AssumePodVolumes/BindPodVolumes pair
    (ref: cache/cache.go:164-184, k8s.io/kubernetes volumebinder).

    A non-default volume binder also forces the decision replay onto the
    exact per-event path (actions/cycle_inputs.py bulk-replay gate), so
    this class doubles as the seam tests use to exercise that fallback
    and mid-replay failure recovery.
    """

    def __init__(self, slots_per_host: int = 0):
        #: 0 = unlimited
        self.slots_per_host = slots_per_host
        self.allocated: dict = {}      # hostname -> set of task uids
        self.bound: set = set()        # task uids with bound volumes

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        holders = self.allocated.setdefault(hostname, set())
        if (self.slots_per_host
                and len(holders) >= self.slots_per_host
                and task.uid not in holders):
            raise RuntimeError(
                f"host {hostname} has no volume slots left for "
                f"{task.namespace}/{task.name}")
        holders.add(task.uid)
        task.volume_ready = True

    def bind_volumes(self, task: TaskInfo) -> None:
        if not task.volume_ready:
            raise RuntimeError(
                f"volumes for {task.namespace}/{task.name} were never "
                f"allocated")
        self.bound.add(task.uid)


class ListRecorder:
    """Collects (event_type, reason, message) tuples; the sim equivalent of
    the k8s event stream."""

    def __init__(self):
        self.events = []

    def eventf(self, obj, event_type: str, reason: str, message: str) -> None:
        self.events.append((obj, event_type, reason, message))
