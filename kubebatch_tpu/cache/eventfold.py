"""EventFold — the event-driven side of the incremental cycle (ISSUE 9).

The cache's event handlers used to scatter eight dirty/refresh sets plus
the adopted snapshot base across SchedulerCache; every cycle then
re-derived the parts of that state it needed. This module makes the
event the primary object: each cache event (add/update/delete of a
pod/node/podgroup, a bind, an evict, a decision lease) is **folded**
once, at event time, into

- the per-entity dirty marks that drive the O(churn) snapshot patch
  (``dirty_jobs`` / ``dirty_nodes``) — the folded host base (``base``,
  the previous session's clones adopted at close) is patched only at
  these keys;
- the persistent device-array dirty rows (``dev_dirty`` -> migrated to
  ``dev_refresh`` at snapshot time, consumed by the jitted dirty-row
  scatter in kernels/solver.py ``update_rows``);
- the persistent victim-segment marks (``vic_* `` / ``vicjob_*``,
  consumed by kernels/victims.py SegmentStore);

and counted per kind in ``metrics.events_folded_total`` — the evidence
that the steady cycle's open phase is O(events), not O(cluster).

The host snapshot is thereby demoted to a **lazy audit view**: the
steady cycle consumes the folded base directly (``cache.snapshot()``
patches it at dirty keys), while a from-scratch ``snapshot_full()``
clone is built only on demand — debug endpoints, host-oracle pins, and
the audit cadence (``cache.audited_snapshot``) that asserts
``debug.snapshot_diff == 0`` between the two.

Degradation rung: the ``cache.fold`` injection seam fires here, and an
audit divergence lands here too — both call :meth:`EventFold.demote`,
which flips the cache back to **snapshot-primary** (reference-faithful
full clones every cycle) for the rest of the process instead of
raising into an event handler. A slower-but-sound cycle beats a
corrupted fold. Counted in ``metrics.fold_demotions_total``.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from ..faults import armed as _faults_armed
from ..faults import should_fail as _should_fail
from ..metrics import count_event_folded, count_fold_demotion

log = logging.getLogger("kubebatch.fold")

#: every event kind the fold layer translates; the fold-vs-replay
#: equivalence test (tests/test_incremental_snapshot.py) covers each
EVENT_KINDS = (
    "pod.add", "pod.update", "pod.delete",
    "node.add", "node.update", "node.delete",
    "podgroup.add", "podgroup.update", "podgroup.delete",
    "bind", "evict", "resync", "invalidate",
)


class EventFold:
    """Per-cache event-fold state (owned by SchedulerCache).

    ``enabled`` is the fold/snapshot-primary switch: True = events fold
    into the persistent base + device marks and ``snapshot()`` is an
    O(churn) patch; False = the reference's full deep clone every cycle
    (the rung :meth:`demote` falls back to)."""

    def __init__(self, cache, enabled: bool):
        self.cache = cache
        self.enabled = bool(enabled)
        #: previous session's entity clones (jobs-by-uid, nodes-by-name),
        #: adopted at session close; None = next snapshot is a full clone
        self.base: Optional[Tuple[Dict, Dict]] = None
        #: entities whose cache truth changed since their base clone
        self.dirty_jobs: set = set()
        self.dirty_nodes: set = set()
        #: device-array row marks: ``dev_dirty`` holds marks made since
        #: the LAST snapshot; at snapshot time they migrate to
        #: ``dev_refresh``, the set the DeviceSession may safely repack
        #: from the session's clones (a mark made AFTER the snapshot
        #: refers to truth the session cannot see)
        self.dev_dirty: set = set()
        self.dev_refresh: set = set()
        #: persistent per-node victim segments — same discipline
        self.vic_dirty: set = set()
        self.vic_refresh: set = set()
        #: job-level marks for the SegmentStore's persistent job rows
        self.vicjob_dirty: set = set()
        self.vicjob_refresh: set = set()
        #: uids cache truth holds that snapshots exclude (no PodGroup/
        #: PDB, or missing queue) — rebuilt by the full snapshot paths,
        #: patched at dirty jobs by the incremental path
        self.excluded_uids: set = set()
        #: in-flight tagging (ISSUE 16): while a pipelined solve is in
        #: flight, every mark is ALSO tagged into these sets so the
        #: consume-time conflict check can ask "did any event since
        #: dispatch touch an entity the in-flight decisions bind
        #: against?". Tagging is unconditional on ``enabled`` — the
        #: conflict check needs the marks even after a fold demotion.
        self._flight_open = False
        self.flight_jobs: set = set()
        self.flight_nodes: set = set()
        #: node marks from node.* capacity events specifically — a
        #: capacity change invalidates decisions onto that node even
        #: when the general node-mark echo (our own bind write-backs)
        #: is being subtracted out
        self.flight_caps: set = set()

    # ------------------------------------------------------------------
    # the fold entry point (called by every cache handler, under the
    # cache lock)
    # ------------------------------------------------------------------
    def record(self, kind: str, n: int = 1) -> None:
        """Count one folded event and cross the ``cache.fold`` injection
        seam. A fired seam does NOT raise into the event handler (the
        event itself was applied to truth before this call): it demotes
        the fold layer to snapshot-primary — the failure mode this
        subsystem is allowed, and the one the chaos soak exercises.

        No-op when the fold is disabled/demoted: events_folded_total is
        the evidence the fold layer is ENGAGED — a snapshot-primary
        process must not report folds that never happen."""
        if not self.enabled:
            return
        count_event_folded(kind, n)
        if _faults_armed() and _should_fail("cache.fold"):
            self.demote("fault")

    def mark_job(self, uid: str) -> None:
        if self._flight_open:
            self.flight_jobs.add(uid)
        if self.enabled:
            self.dirty_jobs.add(uid)
            self.vicjob_dirty.add(uid)

    def mark_node(self, name: str, cap: bool = False) -> None:
        if self._flight_open:
            self.flight_nodes.add(name)
            if cap:
                self.flight_caps.add(name)
        if self.enabled:
            self.dirty_nodes.add(name)
            self.dev_dirty.add(name)
            self.vic_dirty.add(name)

    # ------------------------------------------------------------------
    # in-flight window (ISSUE 16; runtime/pipeline.py)
    # ------------------------------------------------------------------
    def begin_flight(self) -> None:
        """Open the in-flight mark window: called right after a
        pipelined solve dispatches, under the cache lock's caller (the
        scheduler thread). Any mark folded until ``end_flight`` is
        evidence the dispatched inputs may be stale."""
        self.flight_jobs = set()
        self.flight_nodes = set()
        self.flight_caps = set()
        self._flight_open = True

    def end_flight(self) -> Tuple[set, set, set]:
        """Close the window and hand back (jobs, nodes, capacity-nodes)
        marked while the solve was in flight."""
        self._flight_open = False
        marks = (self.flight_jobs, self.flight_nodes, self.flight_caps)
        self.flight_jobs = set()
        self.flight_nodes = set()
        self.flight_caps = set()
        return marks

    # ------------------------------------------------------------------
    # snapshot-side protocol
    # ------------------------------------------------------------------
    def migrate_marks(self, has_victim_store: bool) -> None:
        """Snapshot time: dirty marks become refresh marks (the session
        about to open can see the truth they refer to)."""
        self.dev_refresh |= self.dev_dirty
        self.dev_dirty = set()
        self.vic_refresh |= self.vic_dirty
        self.vic_dirty = set()
        self.vicjob_refresh |= self.vicjob_dirty
        self.vicjob_dirty = set()
        if not has_victim_store:
            # no store to refresh against (host victim mode, store
            # dropped, or never built): the next build is a full one
            # anyway — without this, a scheduler that never runs the
            # device victim path accumulates job uids forever
            self.vic_refresh.clear()
            self.vicjob_refresh.clear()

    def take_active_rows(self) -> set:
        """CONSUME the device-row active set for the session being
        built: the rows whose device-array state changed since the last
        consumer (folded events migrated at snapshot time, plus rows a
        dead session handed back). Exactly one consumer per cycle — the
        DeviceSession refresh and the active-set solve share the one
        returned set instead of each draining ``dev_refresh``, so a row
        can neither be double-counted nor dropped. Marks that land
        MID-CYCLE (after ``migrate_marks``) stay in ``dev_dirty`` — they
        refer to truth the open session cannot see and migrate at the
        NEXT snapshot (the regression in tests/test_activeset.py pins
        this). Call under the cache lock."""
        rows, self.dev_refresh = self.dev_refresh, set()
        return rows

    def take_base(self):
        """Consume the adopted base for this snapshot (the objects are
        handed to the new session, which will mutate them; if the
        session dies before adoption, the next snapshot is full)."""
        base, self.base = self.base, None
        dirty_jobs, self.dirty_jobs = self.dirty_jobs, set()
        dirty_nodes, self.dirty_nodes = self.dirty_nodes, set()
        return base, dirty_jobs, dirty_nodes

    def adopt(self, ssn) -> None:
        """Session close hands its entity clones back as the next
        cycle's base; session-touched entities fold into the dirty sets
        (their clones may diverge from cache truth)."""
        self.dirty_jobs |= ssn.touched_jobs
        self.dirty_nodes |= ssn.touched_nodes
        self.dev_dirty |= ssn.touched_nodes
        self.vic_dirty |= ssn.touched_nodes
        self.vicjob_dirty |= ssn.touched_jobs
        self.base = (ssn.jobs, ssn.nodes)

    def invalidate(self) -> None:
        """Cluster-wide inputs changed: the per-entity fold can't scope
        the effect — full clone next cycle."""
        self.base = None

    def demote(self, reason: str) -> None:
        """The ladder rung back to snapshot-primary: disable the fold
        for the rest of the process (full reference-faithful clones
        every cycle), keeping the scheduler correct at the cost of the
        open-phase O(cluster) walk. Idempotent."""
        if not self.enabled:
            return
        self.enabled = False
        self.base = None
        count_fold_demotion(reason)
        log.error("event-fold layer DEMOTED to snapshot-primary "
                  "(reason=%s): cycles fall back to full per-cycle "
                  "clones; restart to re-enable", reason)
