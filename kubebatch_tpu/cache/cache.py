"""SchedulerCache — the cluster-state mirror between sessions.

ref: pkg/scheduler/cache/cache.go + event_handlers.go + util.go.

Architecture notes (TPU-first redesign, not a Go translation):

- Event ingestion is a plain method surface (``add_pod``/``update_node``/...)
  fed by any event source — the synthetic ``sim`` cluster, the gRPC
  front-end, or (out of scope here) a real k8s informer adapter. The
  reference binds these same handlers to client-go informers
  (cache.go:217-295).
- Decision write-back (bind/evict/status) updates local state under the
  lock, then fires the seam call on a thread pool — the reference uses
  goroutines (cache.go:377-382, 423-429). Failures enqueue the task on a
  rate-limited retry queue whose worker re-fetches ground truth and
  replays the cache update (``sync_task``, ref event_handlers.go:88-106).
  ``drain()`` gives tests/benchmarks a deterministic barrier.
- ``snapshot()`` deep-clones into an immutable-by-convention ClusterInfo
  (ref cache.go:515-583). At 10k x 5k this clone is the second bottleneck
  after the solve; the tensorization in kernels/ reads from the snapshot,
  and a native C++ packer can replace this path (see kernels/tensorize).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Callable, Dict, List, Optional, Tuple

from ..api import (ClusterInfo, JobInfo, NodeInfo, QueueInfo, Resource,
                   TaskInfo, TaskStatus, allocated_status, job_terminated)
from ..faults import BackoffPolicy, backoff_policy
from ..faults import check as _fault_check
from ..objects import (Node, Pod, PodDisruptionBudget, PodGroup,
                       PodGroupPhase, PodPhase, PriorityClass, Queue,
                       UNSCHEDULABLE_CONDITION, is_backfill_pod)
from ..obs import ledger as _ledger
from ..obs import span as _span
from ..util import env_on
from .eventfold import EventFold
from .interface import (Binder, EventRecorder, Evictor, ListRecorder,
                        NullBinder, NullEvictor, NullStatusUpdater,
                        NullVolumeBinder, StatusUpdater, VolumeBinder)

log = logging.getLogger("kubebatch.cache")

SHADOW_POD_GROUP_KEY = "kube-batch/shadow-pod-group"


def shadow_pod_group(pg: Optional[PodGroup]) -> bool:
    """ref: cache/util.go:104-111 (nil PodGroup counts as shadow)."""
    return pg is None or SHADOW_POD_GROUP_KEY in pg.annotations


def create_shadow_pod_group(pod: Pod) -> PodGroup:
    """Implicit single-member gang for ownerless/ungrouped pods
    (ref: cache/util.go:113-136)."""
    job_id = pod.owner_uid or pod.uid
    return PodGroup(name=str(job_id), namespace=pod.namespace, min_member=1,
                    annotations={SHADOW_POD_GROUP_KEY: str(job_id)})


def _is_terminated(status: TaskStatus) -> bool:
    return status in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)


class RetryQueue:
    """Rate-limited retry queue (the workqueue.RateLimiting equivalent).

    Items become due after an exponential backoff (base * 2^retries,
    capped). The constants come from the process-wide BackoffPolicy
    (faults.py) — one object configures these retries, the rpc circuit
    breaker, and the ladder's recovery probes. ``pop_due`` is pumped by
    the cache's worker loop or ``drain()``.
    """

    def __init__(self, base_delay: Optional[float] = None,
                 max_delay: Optional[float] = None,
                 policy: Optional[BackoffPolicy] = None):
        pol = policy or backoff_policy()
        self._items: deque = deque()
        self._retries: Dict[int, int] = {}
        self._base = base_delay if base_delay is not None else pol.base_delay
        self._max = max_delay if max_delay is not None else pol.max_delay
        self._lock = threading.Lock()

    def add_rate_limited(self, item) -> None:
        with self._lock:
            n = self._retries.get(id(item), 0)
            self._retries[id(item)] = n + 1
            delay = min(self._base * (2 ** n), self._max)
            self._items.append((time.monotonic() + delay, item))

    def forget(self, item) -> None:
        with self._lock:
            self._retries.pop(id(item), None)

    def pop_due(self) -> List:
        now = time.monotonic()
        due, later = [], deque()
        with self._lock:
            for ready_at, item in self._items:
                (due if ready_at <= now else later).append((ready_at, item))
            self._items = deque(later)
        return [item for _, item in due]

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def next_due_in(self) -> Optional[float]:
        with self._lock:
            if not self._items:
                return None
            return max(0.0, min(t for t, _ in self._items) - time.monotonic())


class SchedulerCache:
    """ref: cache/cache.go:70-105."""

    def __init__(self,
                 scheduler_name: str = "kube-batch",
                 default_queue: str = "default",
                 binder: Optional[Binder] = None,
                 evictor: Optional[Evictor] = None,
                 status_updater: Optional[StatusUpdater] = None,
                 volume_binder: Optional[VolumeBinder] = None,
                 recorder: Optional[EventRecorder] = None,
                 pod_lister: Optional[Callable[[str, str], Optional[Pod]]] = None,
                 async_writeback: bool = True,
                 incremental_snapshot: Optional[bool] = None):
        self._lock = threading.RLock()
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue

        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.default_priority_class: Optional[PriorityClass] = None
        self.default_priority: int = 0

        self.binder = binder if binder is not None else NullBinder()
        self.evictor = evictor if evictor is not None else NullEvictor()
        self.status_updater = (status_updater if status_updater is not None
                               else NullStatusUpdater())
        self.volume_binder = (volume_binder if volume_binder is not None
                              else NullVolumeBinder())
        self.recorder = recorder if recorder is not None else ListRecorder()

        #: ground-truth pod lookup for the resync repair loop; None means
        #: "replay from the task's own pod" (no external source of truth)
        self.pod_lister = pod_lister

        self.err_tasks = RetryQueue()
        self.deleted_jobs = RetryQueue()

        # ------------------------------------------------------------
        # event-fold state (no reference counterpart — the reference
        # deep-copies the whole cluster every cycle, cache.go:515-583,
        # which is exactly the steady-state bottleneck this removes).
        # Every event handler folds its event into the EventFold layer
        # (cache/eventfold.py): per-entity dirty marks for the O(churn)
        # snapshot patch, dirty rows for the persistent device arrays,
        # and victim-segment marks — counted per kind. Invariant:
        # snapshot() output is always deep-equal to a from-scratch clone
        # of cache truth (audited on demand via audited_snapshot()).
        # ------------------------------------------------------------
        if incremental_snapshot is None:
            incremental_snapshot = env_on("KUBEBATCH_INCREMENTAL")
        self.fold = EventFold(self, incremental_snapshot)
        #: bumped by cluster-wide invalidations; a session snapshot handed
        #: out under an older epoch is refused at adoption
        self._snap_epoch = 0
        self._handout_epoch = 0
        #: bumped on node shape changes; a TermsCache built by a session
        #: whose snapshot predates the change is refused persistence
        self._shape_epoch = 0
        self._handout_shape_epoch = 0
        #: persistent device-side node arrays (kernels/solver.DeviceSession)
        self._dev_state = None
        #: persistent per-node victim segments (kernels/victims.py
        #: SegmentStore) — same dirty/refresh discipline, in the fold
        self.victim_segments = None
        #: observers fired (outside the lock) when a PENDING pod lands —
        #: the schedule-on-arrival sub-cycle registers here
        #: (runtime/subcycle.py); hooks must never raise
        self.arrival_hooks: List[Callable[[Pod], None]] = []
        #: persistent static-term encoder state (kernels/terms.TermsCache);
        #: invalidated whenever node labels/taints/shape change
        self.terms_cache = None
        #: cross-cycle plugin state (SCALING.md latency item 2). Contract:
        #: entries keyed by job uid are valid only while the owning job's
        #: clone is reused by the incremental snapshot — plugins rebuild
        #: entries for ssn.refreshed_jobs at open and rebuild everything
        #: when refreshed_jobs is None (full snapshot). Mutations a session
        #: makes to scratch entries stay consistent because every session
        #: mutator marks its job touched, and touched jobs are refreshed
        #: next cycle (adopt_snapshot folds touched into dirty).
        self.plugin_scratch: Dict[str, object] = {}
        #: per-cache sticky jit-shape holds (kernels/tensorize.py
        #: sticky_bucket): interleaved schedulers in one process must not
        #: fight over a shared shape hold
        self.pad_sticky: Dict[str, list] = {}
        #: the device-row active set consumed for the CURRENT cycle
        #: (EventFold.take_active_rows via device_session) — read by the
        #: active-set solve's telemetry/dispatch policy; never drained a
        #: second time
        self.last_active_rows: set = set()
        #: maintained sum of node allocatable over the cluster (drf and
        #: proportion consume it each open, drf.go:59-60); recomputed
        #: lazily after any node-shape change instead of walked per open
        self._alloc_total: Optional[Resource] = None
        #: bumped whenever the NODE ITERATION ORDER can change (new node
        #: appended, node deleted — a delete+re-add reorders the dict
        #: without changing the set); consumers caching order-derived
        #: state (victims.py host_rank) key on it
        self._node_order_epoch = 0

        self._async = async_writeback
        self._pool: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=8,
                               thread_name_prefix="kb-writeback")
            if async_writeback else None)
        self._inflight: set = set()
        self._inflight_lock = threading.Lock()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle (ref: cache.go:300-331)
    # ------------------------------------------------------------------
    def run(self) -> None:
        """Start the resync/cleanup repair worker."""
        if self._worker is None:
            self._worker = threading.Thread(target=self._repair_loop,
                                            name="kb-cache-repair",
                                            daemon=True)
            self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def wait_for_cache_sync(self) -> bool:
        """Event sources here are synchronous pushes; always synced."""
        return True

    def _repair_loop(self) -> None:
        while not self._stop.is_set():
            self.process_resync_tasks()
            self.process_cleanup_jobs()
            self._stop.wait(0.005)

    # ------------------------------------------------------------------
    # write-back plumbing
    # ------------------------------------------------------------------
    def _submit(self, fn: Callable[[], None]) -> None:
        if self._pool is not None:
            fut: Future = self._pool.submit(fn)
            with self._inflight_lock:
                self._inflight.add(fut)
            fut.add_done_callback(self._discard_inflight)
        else:
            fn()

    def _discard_inflight(self, fut: Future) -> None:
        with self._inflight_lock:
            self._inflight.discard(fut)

    def drain(self, timeout: float = 5.0) -> bool:
        """Barrier: wait for in-flight write-backs and due retries. Returns
        False on timeout. Test/benchmark helper; the reference relies on
        channel waits in tests instead."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._inflight_lock:
                pending = list(self._inflight)
            if pending:
                try:
                    for fut in pending:
                        fut.result(
                            timeout=max(0.0, deadline - time.monotonic()))
                except FuturesTimeoutError:
                    return False
                continue
            self.process_resync_tasks()
            self.process_cleanup_jobs()
            if not self.err_tasks and not self.deleted_jobs:
                with self._inflight_lock:
                    if not self._inflight:
                        return True
                continue
            nxt = self.err_tasks.next_due_in()
            nxt2 = self.deleted_jobs.next_due_in()
            waits = [w for w in (nxt, nxt2) if w is not None]
            time.sleep(min(min(waits, default=0.001), 0.01))
        return False

    # ------------------------------------------------------------------
    # event-fold bookkeeping (cache/eventfold.py owns the state; these
    # properties keep the old read surface for external consumers —
    # kernels/victims.py and tests)
    # ------------------------------------------------------------------
    @property
    def _incremental(self) -> bool:
        return self.fold.enabled

    @property
    def _vic_refresh(self) -> set:
        return self.fold.vic_refresh

    @property
    def _vicjob_refresh(self) -> set:
        return self.fold.vicjob_refresh

    def _mark_job(self, uid: str) -> None:
        self.fold.mark_job(uid)

    def _mark_node(self, name: str) -> None:
        self.fold.mark_node(name)

    def _mark_node_shape(self, name: str) -> None:
        """A node's static profile (labels/taints/unschedulable/allocatable)
        or the node set changed — static-term encodings are stale too.
        ``cap=True`` keeps the mark visible to the pipelined conflict
        check even through its own-bind echo subtraction (a capacity
        change is never our echo)."""
        self.fold.mark_node(name, cap=True)
        self.terms_cache = None
        self._shape_epoch += 1
        self._alloc_total = None

    def offer_terms_cache(self, tc) -> None:
        """Persist a session-built TermsCache for later cycles — refused
        when a node shape change landed after the building session's
        snapshot (its profiles encode pre-change labels; the session may
        still use it locally for its own consistent snapshot)."""
        with self._lock:
            if self._shape_epoch == self._handout_shape_epoch \
                    and self.terms_cache is None:
                self.terms_cache = tc

    def _invalidate_snapshot(self) -> None:
        """Cluster-wide inputs changed (queue set, priority classes):
        per-entity dirty tracking can't scope the effect — fall back to a
        full clone next cycle. The epoch bump also voids adoption of any
        session snapshot handed out BEFORE the change (its clones carry
        pre-change priorities/inclusion)."""
        self.fold.invalidate()
        self.fold.record("invalidate")
        self._dev_state = None
        self.terms_cache = None
        self.victim_segments = None
        self._snap_epoch += 1

    # ------------------------------------------------------------------
    # pod/task ingestion (ref: event_handlers.go:37-247)
    # ------------------------------------------------------------------
    def _pod_relevant(self, pod: Pod) -> bool:
        """Informer filter (ref: cache.go:246-258): pending pods only for
        our scheduler; non-pending pods always (they occupy nodes)."""
        if pod.phase == PodPhase.PENDING:
            return pod.scheduler_name == self.scheduler_name
        return True

    def _get_or_create_job(self, ti: TaskInfo) -> JobInfo:
        """ref: event_handlers.go:41-61 (shadow PodGroup for ungrouped)."""
        if not ti.job:
            pg = create_shadow_pod_group(ti.pod)
            ti.job = pg.name
            if ti.job not in self.jobs:
                job = JobInfo(ti.job)
                job.set_pod_group(pg)
                job.queue = self.default_queue
                self.jobs[ti.job] = job
        elif ti.job not in self.jobs:
            self.jobs[ti.job] = JobInfo(ti.job)
        return self.jobs[ti.job]

    def _add_task(self, ti: TaskInfo) -> None:
        job = self._get_or_create_job(ti)
        job.add_task_info(ti)
        self._mark_job(job.uid)
        if ti.node_name:
            if ti.node_name not in self.nodes:
                # placeholder until the node event arrives
                self.nodes[ti.node_name] = NodeInfo(None)
                self._node_order_epoch += 1
            if not _is_terminated(ti.status):
                self.nodes[ti.node_name].add_task(ti)
            self._mark_node(ti.node_name)

    def _delete_task(self, ti: TaskInfo) -> None:
        errs = []
        if ti.job:
            self._mark_job(ti.job)
        if ti.node_name:
            self._mark_node(ti.node_name)
        if ti.job:
            job = self.jobs.get(ti.job)
            if job is not None:
                try:
                    job.delete_task_info(ti)
                except KeyError as e:
                    errs.append(e)
            else:
                errs.append(KeyError(f"failed to find Job <{ti.job}> for "
                                     f"Task {ti.namespace}/{ti.name}"))
        if ti.node_name:
            node = self.nodes.get(ti.node_name)
            if node is not None:
                try:
                    node.remove_task(ti)
                except KeyError as e:
                    errs.append(e)
        if errs:
            raise KeyError("; ".join(str(e) for e in errs))

    def add_pod(self, pod: Pod) -> None:
        if not self._pod_relevant(pod):
            return
        with self._lock:
            self._add_task(TaskInfo(pod))
            self.fold.record("pod.add")
        self._fire_arrival_hooks(pod)

    def _fire_arrival_hooks(self, pod: Pod) -> None:
        """Notify arrival observers (the schedule-on-arrival sub-cycle)
        about a freshly-added PENDING pod — OUTSIDE the cache lock: the
        hook opens a session, which re-enters the cache. The ledger
        arrival stamp fires here too, hook list or not: every PENDING
        pod's decision clock starts at ingestion."""
        if pod.phase != PodPhase.PENDING:
            return
        _ledger.stamp_arrival(pod)
        if not self.arrival_hooks:
            return
        for hook in list(self.arrival_hooks):
            try:
                hook(pod)
            except Exception:   # an observer must never wedge ingestion
                log.exception("pod arrival hook failed")

    def update_pod(self, old: Pod, new: Pod) -> None:
        """Delete + re-add (ref: event_handlers.go:108-122). Relevance is
        per-side: a pod that was filtered at add time (old irrelevant) is
        treated as a fresh add, like client-go's filtering handler does —
        including the arrival hooks, so a latency-lane pod that becomes
        relevant via an update still gets its sub-cycle."""
        with self._lock:
            was_relevant = self._pod_relevant(old)
            if was_relevant:
                self._delete_pod_locked(old)
            now_relevant = self._pod_relevant(new)
            if now_relevant:
                self._add_task(TaskInfo(new))
            self.fold.record("pod.update")
        if now_relevant and not was_relevant:
            self._fire_arrival_hooks(new)

    def delete_pod(self, pod: Pod) -> None:
        with self._lock:
            self._delete_pod_locked(pod)
            self.fold.record("pod.delete")
        # a pod deleted while pending will never bind: drop its open
        # ledger record instead of leaving it to the MAX_OPEN evictor
        _ledger.discard(pod.uid)

    def _delete_pod_locked(self, pod: Pod) -> None:
        """ref: event_handlers.go:151-171 — prefer the cache's own task (it
        may be in Binding state with a node the stale event lacks)."""
        ti = TaskInfo(pod)
        job = self.jobs.get(ti.job)
        task = ti
        if job is not None:
            task = job.tasks.get(ti.uid, ti)
        self._delete_task(task)
        if job is not None and job_terminated(job):
            self.deleted_jobs.add_rate_limited(job)

    # ------------------------------------------------------------------
    # node ingestion (ref: event_handlers.go:249-356)
    # ------------------------------------------------------------------
    def add_node(self, node: Node) -> None:
        with self._lock:
            if node.name in self.nodes:
                self.nodes[node.name].set_node(node)
            else:
                self.nodes[node.name] = NodeInfo(node)
                self._node_order_epoch += 1
            self._mark_node_shape(node.name)
            self.fold.record("node.add")

    def update_node(self, old: Node, new: Node) -> None:
        with self._lock:
            ni = self.nodes.get(new.name)
            if ni is None:
                raise KeyError(f"node <{new.name}> does not exist")
            if (old.allocatable != new.allocatable or old.taints != new.taints
                    or old.labels != new.labels
                    or old.unschedulable != new.unschedulable):
                ni.set_node(new)
                self._mark_node_shape(new.name)
            self.fold.record("node.update")

    def delete_node(self, node: Node) -> None:
        with self._lock:
            if node.name not in self.nodes:
                raise KeyError(f"node <{node.name}> does not exist")
            del self.nodes[node.name]
            self._node_order_epoch += 1
            self._mark_node_shape(node.name)
            self.fold.record("node.delete")

    # ------------------------------------------------------------------
    # PodGroup / PDB / Queue / PriorityClass (ref: event_handlers.go:358-769)
    # ------------------------------------------------------------------
    def add_pod_group(self, pg: PodGroup) -> None:
        with self._lock:
            self._set_pod_group(pg)
            self.fold.record("podgroup.add")

    def update_pod_group(self, old: PodGroup, new: PodGroup) -> None:
        with self._lock:
            self._set_pod_group(new)
            self.fold.record("podgroup.update")

    def delete_pod_group(self, pg: PodGroup) -> None:
        with self._lock:
            job_id = f"{pg.namespace}/{pg.name}"
            job = self.jobs.get(job_id)
            if job is None:
                raise KeyError(f"can not find job {job_id}")
            job.unset_pod_group()
            self._mark_job(job_id)
            self.fold.record("podgroup.delete")
            self.deleted_jobs.add_rate_limited(job)

    def _set_pod_group(self, pg: PodGroup) -> None:
        job_id = f"{pg.namespace}/{pg.name}"
        if job_id not in self.jobs:
            self.jobs[job_id] = JobInfo(job_id)
        self.jobs[job_id].set_pod_group(pg)
        self._mark_job(job_id)
        if not pg.queue:
            self.jobs[job_id].queue = self.default_queue

    def add_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._lock:
            self._set_pdb(pdb)

    def update_pdb(self, old: PodDisruptionBudget,
                   new: PodDisruptionBudget) -> None:
        with self._lock:
            self._set_pdb(new)

    def delete_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._lock:
            job_id = pdb.owner_uid
            job = self.jobs.get(job_id)
            if job is None:
                raise KeyError(f"can not find job {job_id}")
            job.unset_pdb()
            self._mark_job(job_id)
            self.deleted_jobs.add_rate_limited(job)

    def _set_pdb(self, pdb: PodDisruptionBudget) -> None:
        """PDBs are grouped by their controller owner
        (ref: event_handlers.go:477-493)."""
        job_id = pdb.owner_uid
        if not job_id:
            raise ValueError("the controller of PodDisruptionBudget is empty")
        if job_id not in self.jobs:
            self.jobs[job_id] = JobInfo(job_id)
        self.jobs[job_id].set_pdb(pdb)
        self._mark_job(job_id)
        self.jobs[job_id].queue = self.default_queue

    def add_queue(self, queue: Queue) -> None:
        with self._lock:
            qi = QueueInfo(queue)
            self.queues[qi.uid] = qi
            # queue membership gates which jobs a snapshot includes
            # (snapshot() skip rule) — per-entity tracking can't scope it
            self._invalidate_snapshot()

    def update_queue(self, old: Queue, new: Queue) -> None:
        with self._lock:
            self.queues.pop(old.name, None)
            qi = QueueInfo(new)
            self.queues[qi.uid] = qi
            self._invalidate_snapshot()

    def delete_queue(self, queue: Queue) -> None:
        with self._lock:
            self.queues.pop(queue.name, None)
            self._invalidate_snapshot()

    def add_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self._add_priority_class(pc)

    def update_priority_class(self, old: PriorityClass,
                              new: PriorityClass) -> None:
        with self._lock:
            self._delete_priority_class(old)
            self._add_priority_class(new)

    def delete_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self._delete_priority_class(pc)

    def _add_priority_class(self, pc: PriorityClass) -> None:
        if pc.global_default:
            self.default_priority_class = pc
            self.default_priority = pc.value
        self.priority_classes[pc.name] = pc
        # job.priority is stamped from priority classes at snapshot time
        # for EVERY job (cache.go:561-576) — scope is cluster-wide
        self._invalidate_snapshot()

    def _delete_priority_class(self, pc: PriorityClass) -> None:
        if pc.global_default:
            self.default_priority_class = None
            self.default_priority = 0
        self.priority_classes.pop(pc.name, None)
        self._invalidate_snapshot()

    # ------------------------------------------------------------------
    # decisions out (ref: cache.go:349-442)
    # ------------------------------------------------------------------
    def _find_job_and_task(self, ti: TaskInfo) -> Tuple[JobInfo, TaskInfo]:
        job = self.jobs.get(ti.job)
        if job is None:
            raise KeyError(f"failed to find Job {ti.job} for Task {ti.uid}")
        # CoW: the cache twin must be privately owned before the caller
        # mutates it in place — the shared object may still back a live
        # session's snapshot (JobInfo.clone is copy-on-write)
        job._own_tasks()
        task = job.tasks.get(ti.uid)
        if task is None:
            raise KeyError(f"failed to find task in status {ti.status} "
                           f"by id {ti.uid}")
        return job, task

    def bind(self, ti: TaskInfo, hostname: str) -> None:
        """Local state flips to Binding under the lock; the API call runs
        async with resync-on-failure (ref: cache.go:392-432)."""
        _ledger.stage_mark("apply")
        with self._lock:
            job, task = self._find_job_and_task(ti)
            node = self.nodes.get(hostname)
            if node is None:
                raise KeyError(f"failed to bind Task {task.uid} to host "
                               f"{hostname}, host does not exist")
            # the backfill mark travels on the pod annotation (stamped by
            # actions/backfill.py on the SHARED pod); refresh before node
            # accounting so lent capacity lands in NodeInfo.backfilled
            if not task.is_backfill and is_backfill_pod(task.pod):
                task.is_backfill = True
            job.update_task_status(task, TaskStatus.BINDING)
            task.node_name = hostname
            node.add_task(task)
            self._mark_job(job.uid)
            self._mark_node(hostname)
            self.fold.record("bind")
            pod = task.pod

        # the decision is durably applied at the state flip above — the
        # ledger closes HERE, not at the async API write-back
        _ledger.close(pod)
        self._submit(lambda: self._bind_one(task, pod, hostname))

    def _bind_one(self, task: TaskInfo, pod, hostname: str) -> None:
        """The API-side half of a bind: POST through the binder seam, resync
        the task on failure, emit the Scheduled event on success. Shared by
        bind() and both bind_many() submission paths."""
        try:
            # injection seam: a transient API-server write failure —
            # heals through the rate-limited resync loop, like the real
            # one would
            _fault_check("cache.bind")
            self.binder.bind(pod, hostname)
        except Exception:
            self.resync_task(task)
        else:
            self.recorder.eventf(
                pod, "Normal", "Scheduled",
                f"Successfully assigned {pod.namespace}/{pod.name} "
                f"to {hostname}")

    def bind_many(self, bindings: List[Tuple[TaskInfo, str]]) -> None:
        """Batched bind: identical state flips to per-task bind(), but one
        lock acquisition for the whole decision batch, with the per-task
        interpreter work collapsed into grouped/native column ops. The
        reference has no counterpart (it fires one goroutine per bind,
        cache.go:423-429); whole-cycle device solvers hand back thousands
        of decisions at once and per-bind Python dominates replay without
        this. Arithmetic lands as per-job / per-node float64 sums — same
        values in a different addition order, far below the fit epsilons
        (the discipline the bulk session replay already established)."""
        from ..kernels.tensorize import (batch_clone_tasks, batch_set_attr,
                                         extract_resreq)

        submits = []
        binding = TaskStatus.BINDING
        # ledger: "apply" is stamped at ENTRY (per-pod closes happen
        # inside the span below, before its exit could stamp anything)
        _ledger.stage_mark("apply")
        # the "apply" phase: grouped column updates under ONE lock hold —
        # the decision-apply share of the steady host split
        # (bench host_share split; ISSUE 9 tentpole part 3)
        with _span("apply", cat="phase", decisions=len(bindings)), \
                self._lock:
            # resolve every lookup BEFORE mutating: a vanished pod or a
            # duplicate key must reject the batch while the cache is still
            # consistent (the deferred arithmetic below never half-applies).
            # _find_job_and_task is inlined for the batch (10k+ calls);
            # the miss path delegates to it for the exact error
            resolved = []
            jobs_d = self.jobs
            nodes_d = self.nodes
            for ti, hostname in bindings:
                job = jobs_d.get(ti.job)
                if job is not None:
                    # CoW: own before resolving — the twins get mutated
                    # in place below (batch_set_attr), and a shared map
                    # would leak the flips into a live session snapshot
                    job._own_tasks()
                task = job.tasks.get(ti.uid) if job is not None else None
                if task is None:
                    job, task = self._find_job_and_task(ti)
                node = nodes_d.get(hostname)
                if node is None:
                    raise KeyError(f"failed to bind Task {task.uid} to host "
                                   f"{hostname}, host does not exist")
                resolved.append((job, task, node, hostname))
            # a batch naming one task twice is malformed (the per-host
            # key check below only sees SAME-host duplicates): reject it
            # whole while the cache is untouched — the deferred status
            # flip would otherwise double-count job.allocated where the
            # per-task loop's inline flip netted the repeat to zero
            if len({t.uid for _, t, _, _ in resolved}) != len(resolved):
                seen_uids: set = set()
                for _, task, _, _ in resolved:
                    if task.uid in seen_uids:
                        raise KeyError(
                            f"task {task.uid} appears twice in one "
                            f"bind_many batch")
                    seen_uids.add(task.uid)
            #: hostname -> indices into resolved, in bindings order
            by_host: Dict[str, list] = {}
            for k, (_, task, _, hostname) in enumerate(resolved):
                by_host.setdefault(hostname, []).append(k)
            for hostname, idxs in by_host.items():
                node = self.nodes[hostname]
                key_set = {resolved[k][1].key for k in idxs}
                if len(key_set) != len(idxs) or key_set & node.tasks.keys():
                    seen: set = set()
                    for k in idxs:      # error path: first conflict wins
                        task = resolved[k][1]
                        if task.key in node.tasks or task.key in seen:
                            raise KeyError(
                                f"task <{task.namespace}/{task.name}> "
                                f"already on node <{node.name}>")
                        seen.add(task.key)

            twins = [r[1] for r in resolved]
            hostnames = [r[3] for r in resolved]
            # one native pass pulls every request the batched arithmetic
            # needs (host units; falls back to a per-item loop without
            # the packer)
            raw = extract_resreq(twins)

            # --- job side: index moves off the OLD status, allocated as
            #     per-job net sums, priority restamp (last explicit wins,
            #     matching the per-task order) -------------------------
            by_job: Dict[str, list] = {}
            for k, (job, _, _, _) in enumerate(resolved):
                by_job.setdefault(job.uid, []).append(k)
            cpu_l = raw[:, 0].tolist()
            mem_l = raw[:, 1].tolist()
            gpu_l = raw[:, 2].tolist()
            for idxs in by_job.values():
                job = resolved[idxs[0]][0]
                index = job.task_status_index
                c = m = g = 0.0
                # whole-bucket fast path: when this batch drains the
                # job's entire old-status bucket (a full gang binding out
                # of PENDING — the cold-cycle common case), drop the
                # bucket once instead of popping per task
                first = resolved[idxs[0]][1]
                bucket0 = index.get(first.status)
                if (bucket0 is not None and len(bucket0) == len(idxs)
                        and not allocated_status(first.status)
                        and all(resolved[k][1].status is first.status
                                and resolved[k][1].uid in bucket0
                                for k in idxs)):
                    del index[first.status]
                    for k in idxs:
                        c += cpu_l[k]
                        m += mem_l[k]
                        g += gpu_l[k]
                else:
                    for k in idxs:
                        task = resolved[k][1]
                        bucket = index.get(task.status)
                        if bucket is not None:
                            bucket.pop(task.uid, None)
                            if not bucket:
                                del index[task.status]
                        # update_task_status(task, BINDING), inlined: the
                        # stored task IS ti's cache twin, so the net-zero
                        # total_request ops drop out; Pending isn't an
                        # allocated status, Binding is — and a twin
                        # already in an allocated status contributes
                        # sub+add = nothing
                        if not allocated_status(task.status):
                            c += cpu_l[k]
                            m += mem_l[k]
                            g += gpu_l[k]
                job.allocated.add_vec((c, m, g))
                bucket = index.get(binding)
                if bucket is None:
                    bucket = index[binding] = {}
                bucket.update((resolved[k][1].uid, resolved[k][1])
                              for k in idxs)
                for k in reversed(idxs):
                    if resolved[k][1].pod.priority is not None:
                        job.priority = resolved[k][1].priority
                        break
                self._mark_job(job.uid)

            # annotation-borne backfill marks, refreshed before the node
            # accounting and the clone (see bind())
            for t in twins:
                if not t.is_backfill and is_backfill_pod(t.pod):
                    t.is_backfill = True
            batch_set_attr(twins, "status", binding)
            batch_set_attr(twins, "node_name", hostnames)
            clones = batch_clone_tasks(twins, binding, hostnames)

            # --- node side: NodeInfo.add_task with the per-task
            #     arithmetic batched per node; Binding consumes idle ----
            backfill_l = [t.is_backfill for t in twins]
            has_backfill = True in backfill_l
            for hostname, idxs in by_host.items():
                node = self.nodes[hostname]
                if node.node is not None:
                    if has_backfill:
                        for k in idxs:
                            if backfill_l[k]:
                                node.backfilled.add(twins[k].resreq)
                    take = raw[idxs].sum(axis=0)
                    node.idle.sub_vec(take)
                    node.used.add_vec(take)
                # the maintained job counter screens the per-pod affinity
                # walk: a job with zero affinity tasks can't contribute
                if any(resolved[k][0].affinity_tasks for k in idxs):
                    node.affinity_tasks += sum(
                        1 for k in idxs if twins[k].pod.has_pod_affinity())
                node._own_tasks()
                node.tasks.update((twins[k].key, clones[k]) for k in idxs)
                self._mark_node(hostname)

            submits.extend((t, t.pod, h) for t, h in zip(twins, hostnames))
            self.fold.record("bind", n=len(submits))

        # per-pod ledger closes at the state flip (outside the lock —
        # the records are already durably applied above)
        if _ledger.enabled():
            for t in twins:
                _ledger.close(t.pod)
        self._submit_binds(submits)

    def _submit_binds(self, submits: List[tuple]) -> None:
        """Ship a decision batch through the binder seam. A binder that
        exposes ``bind_many`` gets the whole batch in a few chunked
        calls (one seam crossing + one API round-trip per chunk instead
        of one per task — the last per-decision Python in the apply
        path); per-task ``bind`` stays the fallback, byte-for-byte the
        old behavior."""
        if not submits:
            return
        binder_many = getattr(self.binder, "bind_many", None)
        if binder_many is None:
            if self._pool is None:
                # sync mode: run inline without the per-task closure
                # allocation (10k+ binds per cycle at the stress configs)
                bind_one = self._bind_one
                for task, pod, hostname in submits:
                    bind_one(task, pod, hostname)
                return
            for task, pod, hostname in submits:
                self._submit(
                    lambda t=task, p=pod, h=hostname: self._bind_one(t, p, h))
            return
        # batched seam: chunk so the async pool still parallelizes the
        # write-back where it used to fan out per task
        n_chunks = 8 if self._pool is not None else 1
        size = max(1, -(-len(submits) // n_chunks))
        for i in range(0, len(submits), size):
            chunk = submits[i:i + size]
            if self._pool is None:
                self._bind_batch(chunk)
            else:
                self._submit(lambda c=chunk: self._bind_batch(c))

    def _bind_batch(self, chunk: List[tuple]) -> None:
        """The API-side half of a bind batch: ONE seam crossing + one
        ``binder.bind_many`` POST for the chunk; on failure every task
        of the chunk resyncs (the rate-limited repair loop re-derives
        per-task truth, so the conservative blast radius heals exactly
        like per-task failures do)."""
        try:
            _fault_check("cache.bind")    # injection seam, once per chunk
            self.binder.bind_many([(pod, hostname)
                                   for _, pod, hostname in chunk])
        except Exception:
            for task, _, _ in chunk:
                self.resync_task(task)
            return
        for _, pod, hostname in chunk:
            self.recorder.eventf(
                pod, "Normal", "Scheduled",
                f"Successfully assigned {pod.namespace}/{pod.name} "
                f"to {hostname}")

    def evict(self, ti: TaskInfo, reason: str) -> None:
        """ref: cache.go:349-389."""
        with self._lock:
            job, task = self._find_job_and_task(ti)
            node = self.nodes.get(task.node_name)
            if node is None:
                raise KeyError(f"failed to evict Task {task.uid} on host "
                               f"{task.node_name}, host does not exist")
            job.update_task_status(task, TaskStatus.RELEASING)
            node.update_task(task)
            self._mark_job(job.uid)
            self._mark_node(task.node_name)
            self.fold.record("evict")
            pod = task.pod
            pg = job.pod_group

        def do_evict(task=task, pod=pod):
            try:
                _fault_check("cache.evict")    # injection seam
                self.evictor.evict(pod)
            except Exception:
                self.resync_task(task)

        self._submit(do_evict)
        if not shadow_pod_group(pg):
            self.recorder.eventf(pg, "Normal", "Evict", reason)

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def bind_volumes(self, task: TaskInfo) -> None:
        self.volume_binder.bind_volumes(task)

    # ------------------------------------------------------------------
    # repair loops (ref: cache.go:464-513, event_handlers.go:88-106)
    # ------------------------------------------------------------------
    def resync_task(self, task: TaskInfo) -> None:
        self.err_tasks.add_rate_limited(task)

    def process_resync_tasks(self) -> None:
        for task in self.err_tasks.pop_due():
            try:
                self.sync_task(task)
                self.err_tasks.forget(task)
            except Exception:
                self.err_tasks.add_rate_limited(task)

    def sync_task(self, old_task: TaskInfo) -> None:
        """Re-fetch ground truth and replay (ref: event_handlers.go:88-106)."""
        # injection seam: a failed resync re-enqueues rate-limited
        # (process_resync_tasks catches), like a failed GET would
        _fault_check("cache.resync")
        with self._lock:
            if self.pod_lister is None:
                # no external truth: replay the task's own pod state
                new_pod: Optional[Pod] = old_task.pod
            else:
                new_pod = self.pod_lister(old_task.namespace, old_task.name)
            self.fold.record("resync")
            if new_pod is None:
                self._delete_task(old_task)
                return
            self._delete_task(old_task)
            self._add_task(TaskInfo(new_pod))

    def process_cleanup_jobs(self) -> None:
        for job in self.deleted_jobs.pop_due():
            with self._lock:
                if job_terminated(job):
                    self.jobs.pop(job.uid, None)
                    # the incremental snapshot patches deletions only at
                    # dirty keys — an unmarked pop would leave a ghost
                    # job in every later snapshot's bulk-copied base
                    self._mark_job(job.uid)
                    self.deleted_jobs.forget(job)
                else:
                    self.deleted_jobs.add_rate_limited(job)

    # ------------------------------------------------------------------
    # snapshot (ref: cache.go:515-583)
    # ------------------------------------------------------------------
    def snapshot(self) -> ClusterInfo:
        """The session's cluster view, assembled from the FOLDED state:
        entity clones from the previous session are reused when neither
        the cache (event-fold dirty marks) nor that session (touched
        sets, folded in at adopt_snapshot) invalidated them — output is
        deep-equal to snapshot_full() by construction, and the lazy
        audit (audited_snapshot / KUBEBATCH_AUDIT_EVERY) asserts it."""
        with self._lock:
            self._handout_epoch = self._snap_epoch
            self._handout_shape_epoch = self._shape_epoch
            fold = self.fold
            fold.migrate_marks(self.victim_segments is not None)
            alloc_total = self._allocatable_total_locked()
            if not fold.enabled or fold.base is None:
                snap = self.snapshot_full()
                if fold.enabled:
                    # the full clone IS current truth for every entity
                    fold.dirty_jobs.clear()
                    fold.dirty_nodes.clear()
                return snap
            with _span("fold", cat="phase"):
                return self._snapshot_folded_locked(alloc_total)

    def _snapshot_folded_locked(self, alloc_total) -> ClusterInfo:
        """O(events) assembly: bulk dict copies of the adopted base
        (C-speed) patched only at event-dirtied keys — the per-entity
        Python walk over 5k nodes + 1k jobs was the steady open phase's
        floor. Soundness: every way an entity can appear, vanish, or
        change folds a dirty mark (cache handlers via EventFold, session
        touched sets folded at adoption, validate-dropped jobs), and
        cluster-wide inputs (queues, priority classes) bump the snapshot
        epoch, which forces the full path instead."""
        base, dirty_jobs, dirty_nodes = self.fold.take_base()
        base_jobs, base_nodes = base
        snap = ClusterInfo()
        snap.allocatable_total = alloc_total
        snap.node_order_epoch = self._node_order_epoch
        snap.refreshed_jobs = set()
        nodes_map = dict(base_nodes)
        for name in dirty_nodes:
            ni = self.nodes.get(name)
            if ni is None:
                nodes_map.pop(name, None)
            else:
                nodes_map[name] = ni.clone()
        snap.nodes = nodes_map
        for uid, q in self.queues.items():
            snap.queues[uid] = q.clone()
        jobs_map = dict(base_jobs)
        excluded = self.fold.excluded_uids
        for uid in dirty_jobs:
            job = self.jobs.get(uid)
            if job is None:
                jobs_map.pop(uid, None)
                excluded.discard(uid)
                continue
            if self._job_excluded(job, snap.queues):
                jobs_map.pop(uid, None)
                excluded.add(uid)
                continue
            excluded.discard(uid)
            self._stamp_priority(job)
            jobs_map[uid] = job.clone()
            snap.refreshed_jobs.add(uid)
        snap.jobs = jobs_map
        snap.jobs_excluded = len(excluded)
        return snap

    def snapshot_full(self) -> ClusterInfo:
        """From-scratch deep clone (the reference's snapshot semantics,
        cache.go:515-583) — demoted from the per-cycle input to the LAZY
        AUDIT VIEW: built on demand (debug endpoints, host-oracle pins,
        audited_snapshot) and by the snapshot-primary fallback, never on
        the folded steady cycle's critical path. Also the oracle the
        fold path is equality-tested against."""
        with self._lock:
            snap = ClusterInfo()
            snap.allocatable_total = self._allocatable_total_locked()
            snap.node_order_epoch = self._node_order_epoch
            excluded = self.fold.excluded_uids = set()
            for name, node in self.nodes.items():
                snap.nodes[node.name] = node.clone()
            for uid, q in self.queues.items():
                snap.queues[uid] = q.clone()
            for uid, job in self.jobs.items():
                if self._job_excluded(job, snap.queues):
                    excluded.add(uid)
                    continue
                self._stamp_priority(job)
                snap.jobs[uid] = job.clone()
            snap.jobs_excluded = len(excluded)
            return snap

    def audited_snapshot(self) -> Tuple[ClusterInfo, List[str]]:
        """The lazy audit: build the from-scratch oracle AND the folded
        snapshot under ONE lock hold (no events can land between them)
        and deep-compare. Returns ``(snapshot, diffs)`` — on divergence
        the fold layer DEMOTES itself to snapshot-primary (the ladder
        rung; counted in fold_demotions_total) and the returned snapshot
        is the trustworthy full clone, so the calling cycle proceeds on
        sound state. Scheduler cadence: KUBEBATCH_AUDIT_EVERY /
        ``Scheduler(audit_every=N)``; the chaos soak runs it too."""
        from ..debug import snapshot_diff

        with self._lock:
            full = self.snapshot_full()
            snap = self.snapshot()
            diffs = snapshot_diff(snap, full)
            if diffs:
                self.fold.demote("audit")
                snap = full
        return snap, diffs

    @staticmethod
    def _job_excluded(job: JobInfo, queues: Dict[str, QueueInfo]) -> bool:
        """The snapshot's job-exclusion rule (ref: cache.go:528-551 —
        jobs without a PodGroup/PDB or with a missing queue are skipped).
        ONE predicate for both snapshot paths: the incremental path's
        _excluded_uids bookkeeping relies on it matching snapshot_full."""
        return (job.pod_group is None and job.pdb is None) \
            or job.queue not in queues

    def _allocatable_total_locked(self) -> Resource:
        """Cluster-wide allocatable sum, recomputed only after node-shape
        changes (SCALING.md item 2: drf/proportion walked all nodes per
        open, ref drf.go:59-60, proportion.go:52-53)."""
        if self._alloc_total is None:
            total = Resource.empty()
            for ni in self.nodes.values():
                total.add(ni.allocatable)
            self._alloc_total = total
        return self._alloc_total.clone()

    def _stamp_priority(self, job: JobInfo) -> None:
        """ref: cache.go:561-576 (PriorityClass -> job priority)."""
        if job.pod_group is not None:
            job.priority = self.default_priority
            pc = self.priority_classes.get(
                job.pod_group.priority_class_name)
            if pc is not None:
                job.priority = pc.value

    def adopt_snapshot(self, ssn) -> None:
        """Session close hands its entity clones back as the next cycle's
        snapshot base. Entities the session mutated (touched sets) may
        diverge from cache truth — fold them into the dirty sets so the
        next snapshot re-clones them; everything else is verbatim the
        state a fresh clone would produce (clones share pod/pod_group/pdb
        objects with cache truth, so status write-back at close is visible
        on both sides)."""
        if not self.fold.enabled:
            return
        with self._lock:
            if self._snap_epoch != self._handout_epoch:
                # a cluster-wide invalidation landed mid-session: the
                # session's clones predate it — full clone next cycle
                return
            self.fold.adopt(ssn)
            if ssn.device_snapshot is not None:
                self._dev_state = ssn.device_snapshot
            vs = getattr(ssn, "_victim_store", None)
            if vs is not None:
                self.victim_segments = vs

    def device_session(self, ssn):
        """A DeviceSession for this cycle: the previous cycle's device
        arrays with dirty/touched node rows re-packed from the session's
        host truth, or a fresh build when the node set changed (or nothing
        is adoptable). The refresh set includes nodes the CURRENT session
        already touched (e.g. reclaim evictions run before allocate).

        The refresh rows come from ``EventFold.take_active_rows`` — the
        ONE consuming read of the cycle's device-row active set, shared
        with the active-set solve's dispatch policy via
        ``last_active_rows`` (kernels/activeset.py reads the count; a
        second drain of ``dev_refresh`` could double-count a row or drop
        a mark that lands mid-cycle)."""
        from ..kernels.solver import DeviceSession

        with self._lock:
            ds = self._dev_state
            self._dev_state = None   # consumed; re-adopted at close
            active = self.fold.take_active_rows()
            self.last_active_rows = active
            if not self.fold.enabled or ds is None:
                # the fresh build reflects the session snapshot — marks up
                # to THAT point are satisfied (the consuming read above
                # already drained them); later marks (dev_dirty) must
                # survive to the next snapshot
                return DeviceSession(ssn.nodes)
        refresh = active | ssn.touched_nodes
        if not ds.update_rows(ssn.nodes, refresh):
            return DeviceSession(ssn.nodes)
        return ds

    # ------------------------------------------------------------------
    # status write-back (ref: cache.go:615-658)
    # ------------------------------------------------------------------
    def task_unschedulable(self, task: TaskInfo, message: str) -> None:
        """ref: cache.go:445-462."""
        pod = task.pod
        self.recorder.eventf(pod, "Warning", "Unschedulable", message)
        self.status_updater.update_pod_condition(pod, {
            "type": "PodScheduled",
            "status": "False",
            "reason": "Unschedulable",
            "message": message,
        })

    def record_job_status_event(self, job: JobInfo) -> None:
        """ref: cache.go:616-643."""
        job_err = job.fit_error()
        if not shadow_pod_group(job.pod_group):
            pg_unschedulable = job.pod_group is not None and (
                job.pod_group.status.phase in (PodGroupPhase.PENDING,
                                               PodGroupPhase.UNKNOWN))
            pdb_unschedulable = (job.pdb is not None
                                 and job.count(TaskStatus.PENDING) != 0)
            if pg_unschedulable or pdb_unschedulable:
                msg = (f"{job.count(TaskStatus.PENDING)}/{len(job.tasks)} "
                       f"tasks in gang unschedulable: {job_err}")
                self.recorder.eventf(job.pod_group, "Warning",
                                     UNSCHEDULABLE_CONDITION, msg)
        for status in (TaskStatus.ALLOCATED, TaskStatus.PENDING):
            for task in list(job.task_status_index.get(status, {}).values()):
                self.task_unschedulable(task, job_err)

    def update_job_status(self, job: JobInfo) -> JobInfo:
        """ref: cache.go:646-658."""
        if not shadow_pod_group(job.pod_group):
            pg = self.status_updater.update_pod_group(job.pod_group)
            job.pod_group = pg
        self.record_job_status_event(job)
        return job
