"""Consistency audit — invariant checks over cache/session state.

The reference leans on Go's race detector plus design discipline (one
mutex, snapshot isolation — SURVEY §5 "race detection"); the equivalent
operational tool here is an explicit auditor: walk the live maps and
verify the arithmetic invariants that every mutation path (event
handlers, decision replays, resync repairs) is supposed to preserve.
Tests call it between cycles; operators can call it from a REPL against
a wedged scheduler to localize drift.

Checked invariants:
- node: allocatable - idle == used - pipelined_sum (+/- eps; Pipelined
  tasks consume releasing, not idle); used equals the resreq sum of the
  node's task map; releasing equals the sum over RELEASING tasks minus
  PIPELINED reuse; task_map keys are unique by construction.
- job: allocated equals the resreq sum over allocated-status tasks;
  total_request equals the sum over all tasks; the status double-index
  is consistent (every task bucketed exactly once, under its own status).
- cross: every node-map task has a cache twin in some job with a
  compatible status, and bound tasks' node_name matches the node.
"""
from __future__ import annotations

from typing import List

from .api import allocated_status
from .api.types import TaskStatus

#: float slack for audit comparisons — far below the scheduling epsilons
#: (10 milli-cpu / 10 MiB), far above f64 noise from vectorized sums
_EPS_CPU = 1e-3
_EPS_MEM = 64.0


def _close(a: float, b: float, eps: float) -> bool:
    return abs(a - b) <= eps


def audit_cache(cache) -> List[str]:
    """Returns a list of human-readable violations (empty = consistent)."""
    problems: List[str] = []

    for name, node in cache.nodes.items():
        if node.node is None:
            continue            # placeholder node: no accounting contract
        used_cpu = used_mem = 0.0
        rel_cpu = 0.0
        pipe_cpu = 0.0
        for t in node.tasks.values():
            used_cpu += t.resreq.milli_cpu
            used_mem += t.resreq.memory
            if t.status == TaskStatus.RELEASING:
                rel_cpu += t.resreq.milli_cpu
            elif t.status == TaskStatus.PIPELINED:
                rel_cpu -= t.resreq.milli_cpu
                pipe_cpu += t.resreq.milli_cpu
        if not _close(node.used.milli_cpu, used_cpu, _EPS_CPU):
            problems.append(
                f"node {name}: used.cpu {node.used.milli_cpu:.3f} != "
                f"task sum {used_cpu:.3f}")
        if not _close(node.used.memory, used_mem, _EPS_MEM):
            problems.append(
                f"node {name}: used.mem {node.used.memory:.0f} != "
                f"task sum {used_mem:.0f}")
        if not _close(node.releasing.milli_cpu, rel_cpu, _EPS_CPU):
            problems.append(
                f"node {name}: releasing.cpu {node.releasing.milli_cpu:.3f}"
                f" != releasing-pipelined sum {rel_cpu:.3f}")
        # the exact identity add_task/remove_task maintain: every task
        # consumes idle EXCEPT a Pipelined one, which consumes releasing —
        # so allocatable - idle == used - pipelined_sum
        lhs = node.allocatable.milli_cpu - node.idle.milli_cpu
        rhs = node.used.milli_cpu - pipe_cpu
        if not _close(lhs, rhs, _EPS_CPU):
            problems.append(
                f"node {name}: allocatable-idle {lhs:.3f} != "
                f"used-pipelined {rhs:.3f}")
        aff = sum(1 for t in node.tasks.values()
                  if t.pod.has_pod_affinity())
        if node.affinity_tasks != aff:
            problems.append(
                f"node {name}: affinity_tasks {node.affinity_tasks} != "
                f"recount {aff}")

    for uid, job in cache.jobs.items():
        alloc_cpu = total_cpu = 0.0
        for t in job.tasks.values():
            total_cpu += t.resreq.milli_cpu
            if allocated_status(t.status):
                alloc_cpu += t.resreq.milli_cpu
        if not _close(job.allocated.milli_cpu, alloc_cpu, _EPS_CPU):
            problems.append(
                f"job {uid}: allocated.cpu {job.allocated.milli_cpu:.3f} "
                f"!= task sum {alloc_cpu:.3f}")
        if not _close(job.total_request.milli_cpu, total_cpu, _EPS_CPU):
            problems.append(
                f"job {uid}: total_request.cpu "
                f"{job.total_request.milli_cpu:.3f} != {total_cpu:.3f}")
        aff = sum(1 for t in job.tasks.values()
                  if t.pod.has_pod_affinity())
        if job.affinity_tasks != aff:
            problems.append(
                f"job {uid}: affinity_tasks {job.affinity_tasks} != "
                f"recount {aff}")
        indexed = 0
        for status, bucket in job.task_status_index.items():
            for t_uid, t in bucket.items():
                indexed += 1
                if t.status != status:
                    problems.append(
                        f"job {uid}: task {t_uid} bucketed {status} but "
                        f"carries {t.status}")
                if job.tasks.get(t_uid) is not t:
                    problems.append(
                        f"job {uid}: task {t_uid} index entry is not the "
                        f"stored task")
        if indexed != len(job.tasks):
            problems.append(
                f"job {uid}: status index holds {indexed} tasks, map "
                f"holds {len(job.tasks)}")

    for name, node in cache.nodes.items():
        for key, t in node.tasks.items():
            job = cache.jobs.get(t.job)
            if job is None:
                continue        # job GC'd while node copy lingers is legal
            twin = job.tasks.get(t.uid)
            if twin is None:
                # the job exists but lost the task while the node kept its
                # copy — the leak class this cross-check exists to catch
                problems.append(
                    f"task {key}: on node {name} but missing from live "
                    f"job {t.job}")
            elif twin.node_name and twin.node_name != name:
                problems.append(
                    f"task {key}: on node {name} but twin says "
                    f"{twin.node_name}")
    return problems


# ---------------------------------------------------------------------
# snapshot equivalence (the incremental-snapshot soundness oracle)
# ---------------------------------------------------------------------

def _res_diff(where: str, a, b, problems: List[str]) -> None:
    """Exact float comparison: an untouched reused clone must be
    bit-identical to a fresh clone of the same cache truth; touched
    entities are re-cloned, so they are too."""
    if (a.milli_cpu != b.milli_cpu or a.memory != b.memory
            or a.milli_gpu != b.milli_gpu
            or a.max_task_num != b.max_task_num):
        problems.append(f"{where}: {a} != {b}")


def _task_diff(where: str, a, b, problems: List[str]) -> None:
    if a.uid != b.uid or a.status != b.status \
            or a.node_name != b.node_name \
            or a.is_backfill != b.is_backfill \
            or a.pod is not b.pod:
        problems.append(
            f"{where}: ({a.uid},{a.status},{a.node_name},{a.is_backfill}) "
            f"!= ({b.uid},{b.status},{b.node_name},{b.is_backfill})")
        return
    _res_diff(f"{where}.resreq", a.resreq, b.resreq, problems)
    _res_diff(f"{where}.init_resreq", a.init_resreq, b.init_resreq,
              problems)


def snapshot_diff(a, b) -> List[str]:
    """Deep-compare two ClusterInfo snapshots; returns human-readable
    differences (empty = deep-equal). Shared-by-design references
    (pod, pod_group, pdb, node spec) are compared by identity — both
    cloning paths share them with cache truth."""
    problems: List[str] = []
    if set(a.queues) != set(b.queues):
        problems.append(f"queue sets differ: {set(a.queues) ^ set(b.queues)}")
    for uid in set(a.queues) & set(b.queues):
        qa, qb = a.queues[uid], b.queues[uid]
        if qa.name != qb.name or qa.weight != qb.weight:
            problems.append(f"queue {uid}: ({qa.name},{qa.weight}) != "
                            f"({qb.name},{qb.weight})")

    if set(a.nodes) != set(b.nodes):
        problems.append(f"node sets differ: {set(a.nodes) ^ set(b.nodes)}")
    for name in set(a.nodes) & set(b.nodes):
        na, nb = a.nodes[name], b.nodes[name]
        if na.node is not nb.node:
            problems.append(f"node {name}: spec object differs")
        if na.affinity_tasks != nb.affinity_tasks:
            problems.append(f"node {name}: affinity_tasks "
                            f"{na.affinity_tasks} != {nb.affinity_tasks}")
        for fld in ("idle", "used", "releasing", "backfilled",
                    "allocatable", "capability"):
            _res_diff(f"node {name}.{fld}", getattr(na, fld),
                      getattr(nb, fld), problems)
        if set(na.tasks) != set(nb.tasks):
            problems.append(f"node {name}: task sets differ: "
                            f"{set(na.tasks) ^ set(nb.tasks)}")
            continue
        for key in na.tasks:
            _task_diff(f"node {name} task {key}", na.tasks[key],
                       nb.tasks[key], problems)

    if set(a.jobs) != set(b.jobs):
        problems.append(f"job sets differ: {set(a.jobs) ^ set(b.jobs)}")
    for uid in set(a.jobs) & set(b.jobs):
        ja, jb = a.jobs[uid], b.jobs[uid]
        if (ja.queue != jb.queue or ja.priority != jb.priority
                or ja.min_available != jb.min_available
                or ja.max_available != jb.max_available
                or ja.creation_timestamp != jb.creation_timestamp
                or ja.pod_group is not jb.pod_group
                or ja.pdb is not jb.pdb
                or ja.affinity_tasks != jb.affinity_tasks):
            problems.append(f"job {uid}: header fields differ")
        _res_diff(f"job {uid}.allocated", ja.allocated, jb.allocated,
                  problems)
        _res_diff(f"job {uid}.total_request", ja.total_request,
                  jb.total_request, problems)
        if set(ja.tasks) != set(jb.tasks):
            problems.append(f"job {uid}: task sets differ: "
                            f"{set(ja.tasks) ^ set(jb.tasks)}")
            continue
        for tuid in ja.tasks:
            _task_diff(f"job {uid} task {tuid}", ja.tasks[tuid],
                       jb.tasks[tuid], problems)
        idx_a = {st: set(bucket) for st, bucket in
                 ja.task_status_index.items() if bucket}
        idx_b = {st: set(bucket) for st, bucket in
                 jb.task_status_index.items() if bucket}
        if idx_a != idx_b:
            problems.append(f"job {uid}: status index differs")
        fd_a = set(ja.nodes_fit_delta)
        fd_b = set(jb.nodes_fit_delta)
        if fd_a != fd_b:
            problems.append(f"job {uid}: nodes_fit_delta keys differ: "
                            f"{fd_a ^ fd_b}")
    return problems
