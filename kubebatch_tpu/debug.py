"""Consistency audit — invariant checks over cache/session state.

The reference leans on Go's race detector plus design discipline (one
mutex, snapshot isolation — SURVEY §5 "race detection"); the equivalent
operational tool here is an explicit auditor: walk the live maps and
verify the arithmetic invariants that every mutation path (event
handlers, decision replays, resync repairs) is supposed to preserve.
Tests call it between cycles; operators can call it from a REPL against
a wedged scheduler to localize drift.

Checked invariants:
- node: allocatable - idle == used - pipelined_sum (+/- eps; Pipelined
  tasks consume releasing, not idle); used equals the resreq sum of the
  node's task map; releasing equals the sum over RELEASING tasks minus
  PIPELINED reuse; task_map keys are unique by construction.
- job: allocated equals the resreq sum over allocated-status tasks;
  total_request equals the sum over all tasks; the status double-index
  is consistent (every task bucketed exactly once, under its own status).
- cross: every node-map task has a cache twin in some job with a
  compatible status, and bound tasks' node_name matches the node.
"""
from __future__ import annotations

from typing import List

from .api import allocated_status
from .api.types import TaskStatus

#: float slack for audit comparisons — far below the scheduling epsilons
#: (10 milli-cpu / 10 MiB), far above f64 noise from vectorized sums
_EPS_CPU = 1e-3
_EPS_MEM = 64.0


def _close(a: float, b: float, eps: float) -> bool:
    return abs(a - b) <= eps


def audit_cache(cache) -> List[str]:
    """Returns a list of human-readable violations (empty = consistent)."""
    problems: List[str] = []

    for name, node in cache.nodes.items():
        if node.node is None:
            continue            # placeholder node: no accounting contract
        used_cpu = used_mem = 0.0
        rel_cpu = 0.0
        pipe_cpu = 0.0
        for t in node.tasks.values():
            used_cpu += t.resreq.milli_cpu
            used_mem += t.resreq.memory
            if t.status == TaskStatus.RELEASING:
                rel_cpu += t.resreq.milli_cpu
            elif t.status == TaskStatus.PIPELINED:
                rel_cpu -= t.resreq.milli_cpu
                pipe_cpu += t.resreq.milli_cpu
        if not _close(node.used.milli_cpu, used_cpu, _EPS_CPU):
            problems.append(
                f"node {name}: used.cpu {node.used.milli_cpu:.3f} != "
                f"task sum {used_cpu:.3f}")
        if not _close(node.used.memory, used_mem, _EPS_MEM):
            problems.append(
                f"node {name}: used.mem {node.used.memory:.0f} != "
                f"task sum {used_mem:.0f}")
        if not _close(node.releasing.milli_cpu, rel_cpu, _EPS_CPU):
            problems.append(
                f"node {name}: releasing.cpu {node.releasing.milli_cpu:.3f}"
                f" != releasing-pipelined sum {rel_cpu:.3f}")
        # the exact identity add_task/remove_task maintain: every task
        # consumes idle EXCEPT a Pipelined one, which consumes releasing —
        # so allocatable - idle == used - pipelined_sum
        lhs = node.allocatable.milli_cpu - node.idle.milli_cpu
        rhs = node.used.milli_cpu - pipe_cpu
        if not _close(lhs, rhs, _EPS_CPU):
            problems.append(
                f"node {name}: allocatable-idle {lhs:.3f} != "
                f"used-pipelined {rhs:.3f}")

    for uid, job in cache.jobs.items():
        alloc_cpu = total_cpu = 0.0
        for t in job.tasks.values():
            total_cpu += t.resreq.milli_cpu
            if allocated_status(t.status):
                alloc_cpu += t.resreq.milli_cpu
        if not _close(job.allocated.milli_cpu, alloc_cpu, _EPS_CPU):
            problems.append(
                f"job {uid}: allocated.cpu {job.allocated.milli_cpu:.3f} "
                f"!= task sum {alloc_cpu:.3f}")
        if not _close(job.total_request.milli_cpu, total_cpu, _EPS_CPU):
            problems.append(
                f"job {uid}: total_request.cpu "
                f"{job.total_request.milli_cpu:.3f} != {total_cpu:.3f}")
        indexed = 0
        for status, bucket in job.task_status_index.items():
            for t_uid, t in bucket.items():
                indexed += 1
                if t.status != status:
                    problems.append(
                        f"job {uid}: task {t_uid} bucketed {status} but "
                        f"carries {t.status}")
                if job.tasks.get(t_uid) is not t:
                    problems.append(
                        f"job {uid}: task {t_uid} index entry is not the "
                        f"stored task")
        if indexed != len(job.tasks):
            problems.append(
                f"job {uid}: status index holds {indexed} tasks, map "
                f"holds {len(job.tasks)}")

    for name, node in cache.nodes.items():
        for key, t in node.tasks.items():
            job = cache.jobs.get(t.job)
            if job is None:
                continue        # job GC'd while node copy lingers is legal
            twin = job.tasks.get(t.uid)
            if twin is None:
                # the job exists but lost the task while the node kept its
                # copy — the leak class this cross-check exists to catch
                problems.append(
                    f"task {key}: on node {name} but missing from live "
                    f"job {t.job}")
            elif twin.node_name and twin.node_name != name:
                problems.append(
                    f"task {key}: on node {name} but twin says "
                    f"{twin.node_name}")
    return problems
