"""Session — the per-cycle scheduling transaction.

ref: pkg/scheduler/framework/session.go + session_plugins.go. A Session
owns an immutable snapshot of the cluster, lets plugins install policy
callbacks, and lets actions mutate session state while deferring all real
cluster effects (bind/evict) to the cache seams. Tier-dispatch semantics
are preserved exactly: per-tier victim-list INTERSECTION for
preemptable/reclaimable, AND for predicates, SUM for node scores,
first-non-zero for order fns, any-true for overused/backfill-eligible.

TPU note: the session also carries a lazily-built ``DeviceSnapshot``
(kernels/tensorize.py) so actions can hand the whole pods x nodes problem
to the jitted solver instead of looping these per-pair callbacks. The
callbacks stay as ground truth for tests and for host-side odds and ends.
"""
from __future__ import annotations

import time as _time
import uuid as _uuid
from typing import Callable, Dict, List, Optional

from ..api import (ClusterInfo, JobInfo, JobReadiness, NodeInfo, QueueInfo,
                   TaskInfo, TaskStatus, ValidateResult)
from ..conf import Tier
from ..metrics import (count_backfill_over_placement,
                       update_pod_schedule_status,
                       update_task_schedule_duration)
from ..objects import (PodGroupCondition, PodGroupPhase, PodGroupStatus,
                       UNSCHEDULABLE_CONDITION)
from .event import Event, EventHandler

# Callback signatures (ref: api/types.go:118-147)
CompareFn = Callable[[object, object], int]
PredicateFn = Callable[[TaskInfo, NodeInfo], None]   # raises to reject
NodeOrderFn = Callable[[TaskInfo, NodeInfo], float]
EvictableFn = Callable[[TaskInfo, List[TaskInfo]], Optional[List[TaskInfo]]]


class PredicateError(Exception):
    """A predicate rejection with a user-facing reason."""


class VolumeAllocationError(Exception):
    """allocate_volumes failed BEFORE any session mutation — the one
    ssn.allocate failure callers may safely answer with try-the-next-node
    (ref: allocate.go:157-161). Later failures (dispatch/bind) leave
    mutated session state behind and must propagate."""


class Session:
    def __init__(self, cache, snapshot: ClusterInfo,
                 enable_preemption: bool = False):
        self.uid: str = str(_uuid.uuid4())
        self.cache = cache
        self.jobs: Dict[str, JobInfo] = snapshot.jobs
        self.nodes: Dict[str, NodeInfo] = snapshot.nodes
        self.queues: Dict[str, QueueInfo] = snapshot.queues
        #: job uids freshly re-cloned from cache truth (None = all)
        self.refreshed_jobs = getattr(snapshot, "refreshed_jobs", None)
        #: cache-maintained cluster allocatable sum (None on hand-built
        #: snapshots; total_allocatable then falls back to a node walk)
        self._snapshot_allocatable_total = getattr(
            snapshot, "allocatable_total", None)
        #: jobs cache truth holds that this snapshot dropped (no
        #: PodGroup/PDB, or missing queue) — their pods can still occupy
        #: nodes; None on hand-built snapshots (unknown)
        self.jobs_excluded = getattr(snapshot, "jobs_excluded", None)
        #: node-iteration-order version (cache._node_order_epoch); None on
        #: hand-built snapshots — order-derived caches then rebuild
        self.node_order_epoch = getattr(snapshot, "node_order_epoch", None)
        self.backlog: List[JobInfo] = []
        self.tiers: List[Tier] = []
        self.enable_preemption = enable_preemption

        self.plugins: Dict[str, object] = {}
        self.event_handlers: List[EventHandler] = []
        self.job_order_fns: Dict[str, CompareFn] = {}
        self.queue_order_fns: Dict[str, CompareFn] = {}
        self.task_order_fns: Dict[str, CompareFn] = {}
        self.predicate_fns: Dict[str, PredicateFn] = {}
        self.node_order_fns: Dict[str, NodeOrderFn] = {}
        self.preemptable_fns: Dict[str, EvictableFn] = {}
        self.reclaimable_fns: Dict[str, EvictableFn] = {}
        self.overused_fns: Dict[str, Callable[[QueueInfo], bool]] = {}
        self.job_ready_fns: Dict[str, Callable[[JobInfo], JobReadiness]] = {}
        self.job_valid_fns: Dict[str, Callable[[JobInfo],
                                               Optional[ValidateResult]]] = {}
        self.backfill_eligible_fns: Dict[str, Callable[[JobInfo], bool]] = {}
        #: final AND-filters over victim lists, applied AFTER tier dispatch.
        #: Divergence from the reference: its per-tier intersection lets an
        #: EMPTY tier-1 intersection fall through to tier 2, where drf can
        #: select victims conformance vetoed — critical pods become
        #: evictable through the gap (session_plugins.go:99-102 nil
        #: fall-through). Safety vetoes registered here always hold.
        self.victim_veto_fns: Dict[str, EvictableFn] = {}

        #: device-side snapshot, built on first use by kernels.tensorize
        self.device_snapshot = None

        #: statements opened via statement() and not yet committed or
        #: discarded — CloseSession discards leftovers, so a mid-action
        #: fault can never leak half-applied evictions into write-back
        self.open_statements: List = []

        #: entities this session mutated in ways a fresh cache clone would
        #: not reproduce — folded into the cache's dirty sets when the
        #: snapshot is adopted as the next cycle's base (cache.py
        #: adopt_snapshot). Every session mutator records here; missing a
        #: site breaks the incremental==full snapshot invariant (pinned by
        #: tests/test_incremental_snapshot.py).
        self.touched_jobs: set = set()
        self.touched_nodes: set = set()

    # ------------------------------------------------------------------
    # plugin registration (ref: session_plugins.go:23-65)
    # ------------------------------------------------------------------
    def add_job_order_fn(self, name: str, fn: CompareFn) -> None:
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name: str, fn: CompareFn) -> None:
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name: str, fn: CompareFn) -> None:
        self.task_order_fns[name] = fn

    def add_predicate_fn(self, name: str, fn: PredicateFn) -> None:
        self.predicate_fns[name] = fn

    def add_node_order_fn(self, name: str, fn: NodeOrderFn) -> None:
        self.node_order_fns[name] = fn

    def add_preemptable_fn(self, name: str, fn: EvictableFn) -> None:
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name: str, fn: EvictableFn) -> None:
        self.reclaimable_fns[name] = fn

    def add_overused_fn(self, name: str, fn) -> None:
        self.overused_fns[name] = fn

    def add_job_ready_fn(self, name: str, fn) -> None:
        self.job_ready_fns[name] = fn

    def add_job_valid_fn(self, name: str, fn) -> None:
        self.job_valid_fns[name] = fn

    def add_backfill_eligible_fn(self, name: str, fn) -> None:
        self.backfill_eligible_fns[name] = fn

    def add_victim_veto_fn(self, name: str, fn: EvictableFn) -> None:
        self.victim_veto_fns[name] = fn

    def add_event_handler(self, eh: EventHandler) -> None:
        self.event_handlers.append(eh)

    # ------------------------------------------------------------------
    # tiered dispatch (ref: session_plugins.go:67-370)
    # ------------------------------------------------------------------
    def _evictable(self, fns: Dict[str, EvictableFn], disabled_attr: str,
                   evictor: TaskInfo,
                   evictees: List[TaskInfo]) -> List[TaskInfo]:
        """Per-tier intersection of plugin victim lists; the first tier with
        a NON-EMPTY intersection decides (session_plugins.go:67-148 — in Go
        an empty intersection is a nil slice, so it falls through to the
        next tier exactly like no plugin answering)."""
        for tier in self.tiers:
            victims: Optional[List[TaskInfo]] = None
            for plugin in tier.plugins:
                if getattr(plugin, disabled_attr):
                    continue
                fn = fns.get(plugin.name)
                if fn is None:
                    continue
                candidates = fn(evictor, evictees) or []
                if victims is None:
                    victims = list(candidates)
                else:
                    cand_ids = {c.uid for c in candidates}
                    victims = [v for v in victims if v.uid in cand_ids]
            if victims:
                return self._apply_vetoes(evictor, victims)
        return []

    def _apply_vetoes(self, evictor: TaskInfo,
                      victims: List[TaskInfo]) -> List[TaskInfo]:
        for fn in self.victim_veto_fns.values():
            allowed = {t.uid for t in (fn(evictor, victims) or [])}
            victims = [v for v in victims if v.uid in allowed]
        return victims

    def reclaimable(self, reclaimer: TaskInfo,
                    reclaimees: List[TaskInfo]) -> List[TaskInfo]:
        return self._evictable(self.reclaimable_fns, "reclaimable_disabled",
                               reclaimer, reclaimees)

    def preemptable(self, preemptor: TaskInfo,
                    preemptees: List[TaskInfo]) -> List[TaskInfo]:
        return self._evictable(self.preemptable_fns, "preemptable_disabled",
                               preemptor, preemptees)

    def overused(self, queue: QueueInfo) -> bool:
        """Any plugin true (session_plugins.go:150-164; no disable flag)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.overused_fns.get(plugin.name)
                if fn is not None and fn(queue):
                    return True
        return False

    def _job_readiness(self, job) -> JobReadiness:
        """First registered job-ready fn wins (session_plugins.go:167-207).
        The tier walk is memoized — job_ready runs once per allocation, and
        plugins only register fns during OnSessionOpen."""
        fn = getattr(self, "_ready_fn_memo", False)
        if fn is False:
            fn = None
            for tier in self.tiers:
                for plugin in tier.plugins:
                    if plugin.job_ready_disabled:
                        continue
                    f = self.job_ready_fns.get(plugin.name)
                    if f is not None:
                        fn = f
                        break
                if fn is not None:
                    break
            self._ready_fn_memo = fn
        if fn is not None:
            return fn(job)
        return JobReadiness.READY

    def job_ready(self, job) -> bool:
        return self._job_readiness(job) == JobReadiness.READY

    def job_almost_ready(self, job) -> bool:
        # NB: reference defaults to AlmostReady when no fn is registered
        # (session_plugins.go:189) — with no fn, both job_ready and
        # job_almost_ready report True-ish defaults; we mirror that.
        for tier in self.tiers:
            for plugin in tier.plugins:
                if plugin.job_ready_disabled:
                    continue
                fn = self.job_ready_fns.get(plugin.name)
                if fn is not None:
                    return fn(job) == JobReadiness.ALMOST_READY
        return True

    def backfill_eligible(self, job) -> bool:
        """Any plugin true (session_plugins.go:209-224)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.backfill_eligible_fns.get(plugin.name)
                if fn is not None and fn(job):
                    return True
        return False

    def job_valid(self, job) -> Optional[ValidateResult]:
        """First failure wins (session_plugins.go:226-242)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.job_valid_fns.get(plugin.name)
                if fn is None:
                    continue
                vr = fn(job)
                if vr is not None and not vr.passed:
                    return vr
        return None

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """True iff l should come before r (session_plugins.go:244-268)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if plugin.job_order_disabled:
                    continue
                fn = self.job_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        for tier in self.tiers:
            for plugin in tier.plugins:
                if plugin.queue_order_disabled:
                    continue
                fn = self.queue_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        return l.uid < r.uid

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        for tier in self.tiers:
            for plugin in tier.plugins:
                if plugin.task_order_disabled:
                    continue
                fn = self.task_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j
        return 0

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        if l.pod.creation_timestamp == r.pod.creation_timestamp:
            return l.uid < r.uid
        return l.pod.creation_timestamp < r.pod.creation_timestamp

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """AND of all enabled plugins; first error propagates
        (session_plugins.go:331-348). Raises PredicateError to reject."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if plugin.predicate_disabled:
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is not None:
                    fn(task, node)

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        """Sum of all enabled plugins' scores (session_plugins.go:350-370)."""
        score = 0.0
        for tier in self.tiers:
            for plugin in tier.plugins:
                if plugin.node_order_disabled:
                    continue
                fn = self.node_order_fns.get(plugin.name)
                if fn is not None:
                    score += fn(task, node)
        return score

    def total_allocatable(self):
        """Sum of node allocatable over the snapshot, computed once per
        session — drf and proportion each summed all nodes at open
        (drf.go:59-60, proportion.go:52-53); the value is identical, so
        they share one walk."""
        total = getattr(self, "_total_allocatable", None)
        if total is None:
            total = self._snapshot_allocatable_total
            if total is None:       # snapshot predates the maintained sum
                from ..api import Resource
                total = Resource.empty()
                for node in self.nodes.values():
                    total.add(node.allocatable)
            self._total_allocatable = total
        # clone: Resource's chaining API mutates in place — handing out
        # the cached object would let one caller corrupt every later one
        return total.clone()

    # ------------------------------------------------------------------
    # session mutators (ref: session.go:193-357)
    # ------------------------------------------------------------------
    def statement(self):
        from .statement import Statement
        st = Statement(self)
        self.open_statements.append(st)
        return st

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Session-only assignment onto releasing resources
        (ref: session.go:199-235)."""
        self.touched_jobs.add(task.job)
        self.touched_nodes.add(hostname)
        job = self.jobs.get(task.job)
        if job is not None:
            # CoW: the caller's reference may still point at the shared
            # clone twin — resolve to this job's canonical object before
            # the first attribute write (JobInfo.own_task)
            task = job.own_task(task)
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self._fire_allocate(task)

    def allocate(self, task: TaskInfo, hostname: str,
                 using_backfill_task_res: bool = False) -> None:
        """Assign task to host within the session; dispatch the whole job
        once it reaches Ready — the gang barrier (ref: session.go:237-297)."""
        # CoW resolution BEFORE any write — allocate_volumes already
        # mutates the task (volume_ready), so the job lookup moves ahead
        # of it (owning a map is not a semantic mutation; a pre-mutation
        # volume failure still leaves the session untouched)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        task = job.own_task(task)
        try:
            self.cache.allocate_volumes(task, hostname)
        except Exception as e:
            raise VolumeAllocationError(str(e)) from e
        self.touched_jobs.add(task.job)
        self.touched_nodes.add(hostname)
        new_status = (TaskStatus.ALLOCATED_OVER_BACKFILL
                      if using_backfill_task_res else TaskStatus.ALLOCATED)
        if using_backfill_task_res:
            # session-only reservation over lent capacity; counted here
            # so every entry path (allocate visit, device kernels,
            # backfill over-reserve) lands in the same ledger
            count_backfill_over_placement()
        job.update_task_status(task, new_status)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self._fire_allocate(task)
        if self.job_ready(job):
            for t in list(job.task_status_index.get(TaskStatus.ALLOCATED,
                                                    {}).values()):
                self.dispatch(t)

    def dispatch(self, task: TaskInfo) -> None:
        """Bind an allocated task for real (ref: session.go:299-321)."""
        self.touched_jobs.add(task.job)
        job = self.jobs.get(task.job)
        if job is not None:
            task = job.own_task(task)   # CoW (see pipeline)
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        if job is not None:
            job.update_task_status(task, TaskStatus.BINDING)
        # creation -> bind latency (ref: session.go:319)
        update_task_schedule_duration(
            max(0.0, _time.time() - task.pod.creation_timestamp))

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Real eviction through the cache plus session bookkeeping
        (ref: session.go:323-357)."""
        self.touched_jobs.add(reclaimee.job)
        self.touched_nodes.add(reclaimee.node_name)
        job = self.jobs.get(reclaimee.job)
        if job is not None:
            reclaimee = job.own_task(reclaimee)   # CoW (see pipeline)
        self.cache.evict(reclaimee, reason)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self._fire_deallocate(reclaimee)

    def update_job_condition(self, job_info: JobInfo,
                             cond: PodGroupCondition) -> None:
        """ref: session.go:360-382."""
        # a condition stamp IS a status mutation: the close-session
        # write-skip must not bypass this job's PUT/events, and the next
        # snapshot re-clones it (the shared pod_group makes the re-clone
        # redundant but harmless)
        self.touched_jobs.add(job_info.uid)
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(f"failed to find job "
                           f"<{job_info.namespace}/{job_info.name}>")
        conds = job.pod_group.status.conditions
        for i, c in enumerate(conds):
            if c.type == cond.type:
                conds[i] = cond
                return
        conds.append(cond)

    def _fire_allocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def _fire_deallocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))


def open_session(cache, enable_preemption: bool = False,
                 snapshot: Optional[ClusterInfo] = None) -> Session:
    """Snapshot the cache and drop gang-invalid jobs
    (ref: session.go:66-122). ``snapshot`` lets tests supply a snapshot
    taken moments earlier (e.g. to compare incremental vs full cloning)."""
    ssn = Session(cache, snapshot if snapshot is not None
                  else cache.snapshot(), enable_preemption)
    return ssn


def validate_jobs(ssn: Session) -> None:
    """Apply JobValid and drop failing jobs after stamping an Unschedulable
    condition on their (session-local) PodGroup (ref: session.go:92-111).
    Called after plugins install their job_valid fns.

    Verdicts are memoized across cycles (SCALING.md item 2; contract at
    cache.plugin_scratch): validity reads only job truth, so a verdict
    holds while the job's clone is reused. Failing jobs re-stamp their
    condition each cycle (the stamp marks them touched, so they are
    refreshed — and re-validated — next cycle, like the reference's
    per-cycle pass)."""
    scratch = getattr(ssn.cache, "plugin_scratch", None)
    fingerprint = tuple(opt.name for tier in ssn.tiers
                        for opt in tier.plugins)
    state = scratch.get("job_valid") if scratch is not None else None
    refreshed = ssn.refreshed_jobs
    if (state is None or refreshed is None
            or state["fingerprint"] != fingerprint):
        memo: Dict[str, Optional[ValidateResult]] = {}
        recheck = list(ssn.jobs)
    else:
        memo = state["memo"]
        for uid in list(memo):
            if uid not in ssn.jobs:
                del memo[uid]
        recheck = [uid for uid in ssn.jobs
                   if uid in refreshed or uid not in memo]
    for uid in recheck:
        memo[uid] = ssn.job_valid(ssn.jobs[uid])
    if scratch is not None:
        scratch["job_valid"] = {"memo": memo, "fingerprint": fingerprint}
    for uid, vr in memo.items():
        if vr is None or vr.passed:
            continue
        job = ssn.jobs.get(uid)
        if job is None:
            continue
        # a dropped job leaves ssn.jobs, and adoption stores ssn.jobs as
        # the next snapshot base — mark it touched so the next cycle
        # re-clones it from truth regardless of the condition-stamp path
        ssn.touched_jobs.add(uid)
        if job.pod_group is not None:
            cond = PodGroupCondition(
                type=UNSCHEDULABLE_CONDITION, status="True",
                transition_id=ssn.uid, reason=vr.reason,
                message=vr.message)
            try:
                ssn.update_job_condition(job, cond)
            except KeyError:
                pass
        del ssn.jobs[uid]


def job_status(ssn: Session, job: JobInfo) -> PodGroupStatus:
    """Recompute PodGroup status at session close (ref: session.go:158-191)."""
    status = job.pod_group.status
    unschedulable = any(
        c.type == UNSCHEDULABLE_CONDITION and c.status == "True"
        and c.transition_id == ssn.uid
        for c in status.conditions)
    if job.count(TaskStatus.RUNNING) != 0 and unschedulable:
        status.phase = PodGroupPhase.UNKNOWN
    elif job.get_readiness() == JobReadiness.READY:
        status.phase = PodGroupPhase.RUNNING
    else:
        status.phase = PodGroupPhase.PENDING
    status.running = job.count(TaskStatus.RUNNING)
    status.failed = job.count(TaskStatus.FAILED)
    status.succeeded = job.count(TaskStatus.SUCCEEDED)
    return status


def close_session(ssn: Session) -> None:
    """Write job status back through the cache (ref: session.go:124-156).

    Jobs the session never mutated AND whose clone was reused from the
    previous cycle (truth unchanged) AND that hold no pending/allocated
    work recompute to an identical status with no events to emit — the
    write is skipped (a changed-nothing PUT any production updater would
    coalesce anyway). Full snapshots (refreshed = None) write every job,
    matching the reference cycle for cycle. Integrations that treat the
    per-cycle PodGroup PUT as a liveness heartbeat (session.go:124-156
    writes every job every cycle) can set KUBEBATCH_FAITHFUL_CLOSE=1 to
    restore the reference-faithful every-cycle writes."""
    import os as _os
    scheduled = 0
    unschedulable = 0
    refreshed = ssn.refreshed_jobs
    if _os.environ.get("KUBEBATCH_FAITHFUL_CLOSE", "") not in ("", "0",
                                                               "false"):
        refreshed = None
    touched = ssn.touched_jobs
    for uid, job in ssn.jobs.items():
        pending = job.count(TaskStatus.PENDING)
        scheduled += job.count(TaskStatus.BINDING)
        unschedulable += pending
        if job.pod_group is None:
            ssn.cache.record_job_status_event(job)
            continue
        if (refreshed is not None and uid not in refreshed
                and uid not in touched and pending == 0
                and TaskStatus.ALLOCATED not in job.task_status_index
                and TaskStatus.ALLOCATED_OVER_BACKFILL
                not in job.task_status_index):
            continue
        job.pod_group.status = job_status(ssn, job)
        ssn.cache.update_job_status(job)
    # per-cycle attempt results (ref: metrics.go schedule_attempts_total;
    # results follow the upstream scheduler's vocabulary)
    update_pod_schedule_status("scheduled", scheduled)
    update_pod_schedule_status("unschedulable", unschedulable)
    # hand the session's clones back as the next snapshot's base (the
    # incremental-snapshot protocol; no-op for caches without it)
    adopt = getattr(ssn.cache, "adopt_snapshot", None)
    if adopt is not None:
        adopt(ssn)
    ssn.jobs = {}
    ssn.nodes = {}
    ssn.queues = {}
    ssn.backlog = []
    ssn.plugins = {}
    ssn.event_handlers = []
    ssn.device_snapshot = None
    ssn.open_statements = []
