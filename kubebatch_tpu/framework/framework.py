"""Session lifecycle orchestration (ref: pkg/scheduler/framework/framework.go).

Divergence note: the reference runs its JobValid drop inside openSession
BEFORE tiers/plugins are installed (framework.go:33-40 + session.go:92-111),
which makes the filter a no-op — jobValidFns is always empty at that point.
We run validation after OnSessionOpen, which is the evidently intended
behavior (gang's JobValidFn actually fires); end-state parity holds because
invalid jobs could never dispatch anyway.
"""
from __future__ import annotations

from typing import List

from ..conf import Tier
from ..metrics import ON_SESSION_CLOSE, ON_SESSION_OPEN
from ..obs import span as _span
from .registry import get_plugin_builder
from .session import Session, close_session, open_session, validate_jobs


def open_session_with_tiers(cache, tiers: List[Tier],
                            enable_preemption: bool = False,
                            snapshot=None) -> Session:
    """ref: framework.go:29-50 (OpenSession). Timing routes through obs
    spans; update_host_phase("open") / update_plugin_duration are the
    derived views fired at span exit."""
    with _span("open", cat="phase"):
        ssn = open_session(cache, enable_preemption, snapshot=snapshot)
        ssn.tiers = tiers
        for tier in tiers:
            for opt in tier.plugins:
                builder = get_plugin_builder(opt.name)
                if builder is None:
                    continue
                plugin = builder(opt.arguments)
                ssn.plugins[plugin.name] = plugin
        for plugin in ssn.plugins.values():
            with _span(plugin.name, cat="plugin", phase=ON_SESSION_OPEN):
                plugin.on_session_open(ssn)
        validate_jobs(ssn)
    return ssn


# keep the reference's exported names as aliases
OpenSession = open_session_with_tiers


def CloseSession(ssn: Session) -> None:
    """ref: framework.go:53-61. Before anything else, roll back any
    statement a mid-action fault left open — plugin close hooks and the
    status write-back must observe the pre-transaction state, never a
    half-applied eviction batch."""
    with _span("close", cat="phase"):
        for st in list(getattr(ssn, "open_statements", ()) or ()):
            st.discard()
        for plugin in ssn.plugins.values():
            with _span(plugin.name, cat="plugin", phase=ON_SESSION_CLOSE):
                plugin.on_session_close(ssn)
        close_session(ssn)


close_session_with_plugins = CloseSession
