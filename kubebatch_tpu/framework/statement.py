"""Statement — deferred-operation transaction for preemption.

ref: pkg/scheduler/framework/statement.go. Evict/Pipeline apply session
state immediately and log an op; Commit replays real cache evictions;
Discard rolls back in reverse order. Pipeline's commit is a session-only
no-op — binding happens in a later cycle once resources free up
(statement.go:153-154).
"""
from __future__ import annotations

from typing import List, Tuple

from ..api import TaskInfo, TaskStatus


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[Tuple[str, tuple]] = []

    # --- session-visible ops ---------------------------------------------
    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """ref: statement.go:35-67."""
        self.ssn.touched_jobs.add(reclaimee.job)
        self.ssn.touched_nodes.add(reclaimee.node_name)
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            # CoW: resolve to the job's canonical task before any write;
            # the op log records the resolved object so rollback mutates
            # the same one (Session.pipeline has the same contract)
            reclaimee = job.own_task(reclaimee)
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append(("evict", (reclaimee, reason)))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """ref: statement.go:110-151."""
        self.ssn.touched_jobs.add(task.job)
        self.ssn.touched_nodes.add(hostname)
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            task = job.own_task(task)   # CoW (see evict)
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(("pipeline", (task, hostname)))

    # --- rollback helpers --------------------------------------------------
    def _unevict(self, reclaimee: TaskInfo) -> None:
        """ref: statement.go:81-108. Rollback is a divergence source too:
        the sub-then-add Resource round trip need not restore the exact
        float bits a fresh clone carries."""
        self.ssn.touched_jobs.add(reclaimee.job)
        self.ssn.touched_nodes.add(reclaimee.node_name)
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RUNNING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_allocate(reclaimee)

    def _unpipeline(self, task: TaskInfo) -> None:
        """ref: statement.go:156-192."""
        self.ssn.touched_jobs.add(task.job)
        self.ssn.touched_nodes.add(task.node_name)
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    # --- transaction close -------------------------------------------------
    def _retire(self) -> None:
        """Leave the session's open-statement registry (session.py
        tracks statements so CloseSession can discard any a mid-action
        fault left open)."""
        open_list = getattr(self.ssn, "open_statements", None)
        if open_list is not None:
            try:
                open_list.remove(self)
            except ValueError:
                pass

    def commit(self) -> None:
        """Replay real evictions through the cache (ref: statement.go:207-217).
        Pipelines stay session-only."""
        for name, args in self.operations:
            if name == "evict":
                reclaimee, reason = args
                try:
                    self.ssn.cache.evict(reclaimee, reason)
                except Exception:
                    self._unevict(reclaimee)
        self.operations = []
        self._retire()

    def discard(self) -> None:
        """Roll back in reverse order (ref: statement.go:194-205)."""
        for name, args in reversed(self.operations):
            if name == "evict":
                self._unevict(args[0])
            elif name == "pipeline":
                self._unpipeline(args[0])
        self.operations = []
        self._retire()
