"""Leader election — HA for multiple scheduler replicas.

The reference elects through a ConfigMap resource lock with a
15s lease / 10s renew / 5s retry (cmd/kube-batch/app/server.go:103-106,
170-193) and kills the process on lost leadership. That design separates
cleanly into:

- a **lock backend** (the shared compare-and-swap medium — the reference
  uses the API server's resourcelock), here the ``LeaseLock`` seam:
  `try_acquire_or_renew()` must atomically grant the lease iff it is
  free, expired, or already ours;
- the **elector loop** (acquire, renew on a deadline, fatal on loss),
  here ``LeaderElector`` — backend-independent, semantics preserved.

Two backends ship:

- ``FileLease`` — a lock file on a shared filesystem (single-host /
  shared-volume replicas), CAS via an flock guard;
- ``HttpLease`` — a lease endpoint over HTTP for replicas on DIFFERENT
  hosts; ``HttpLeaseServer`` is the matching stdlib server (embed it in
  the rpc sidecar or run it standalone — the analogue of the reference
  pointing every replica at the API server). A documented k8s Lease
  implementation would slot behind the same seam via the adapter's
  `CustomObjectsApi` (cache/k8s_source.py) — not shipped, no API server
  in scope.

Both backends pass the same acquire/renew/loss/fatal contract tests
(tests/test_runtime.py).
"""
from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time
import uuid
from typing import Callable, Optional, Protocol, runtime_checkable

from ..faults import should_fail as _fault_should_fail


def _default_identity() -> str:
    return f"{socket.gethostname()}_{uuid.uuid4()}"


#: FileLease._read sentinel: a lease file exists but does not parse
_UNREADABLE = object()


@runtime_checkable
class LeaseLock(Protocol):
    """The shared-medium seam (ref: client-go resourcelock.Interface as
    used at server.go:170-181)."""

    identity: str

    def try_acquire_or_renew(self) -> bool:
        """Atomically: grant the lease to ``identity`` iff it is unheld,
        expired, or already held by ``identity``; refresh the renew time
        on success."""
        ...


class LeaderElector:
    """Backend-independent elector (ref: leaderelection.RunOrDie at
    server.go:182-193): block until acquired, renew within the deadline,
    signal the workload and call ``on_stopped_leading`` on loss —
    callers treat loss as fatal, like the reference's glog.Fatalf."""

    def __init__(self, lock: LeaseLock, lease_duration: float = 15.0,
                 renew_deadline: float = 10.0, retry_period: float = 5.0):
        self.lock = lock
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        #: wall seconds each recent acquire/renew attempt actually took —
        #: the observed cadence on THIS box, feeding loss_wait_budget()
        self.attempt_seconds: collections.deque = collections.deque(
            maxlen=32)
        #: total attempts ever made (the deque above is a bounded
        #: window; evidence consumers want the real count)
        self.renew_attempts = 0
        #: worst observed oversleep of the renew loop's waits — GIL/
        #: scheduler starvation BETWEEN attempts (jit compiles on other
        #: threads), which CAS wall time alone cannot see
        self.observed_lateness = 0.0

    def _wait(self, stop, seconds: float) -> bool:
        """Event.wait that also folds its own oversleep into
        observed_lateness; returns the event's state like wait()."""
        t0 = time.monotonic()
        signalled = stop.wait(seconds)
        late = time.monotonic() - t0 - seconds
        if late > self.observed_lateness:
            self.observed_lateness = late
        return signalled

    def loss_wait_budget(self) -> float:
        """How long a caller should wait for loss-of-leadership to be
        declared after the lease is gone, derived from the OBSERVED
        renew cadence instead of a fixed wall constant (the
        test_lease_run_and_loss flake: a fixed 30 s budget is both too
        short for a badly starved box and meaninglessly long for a
        healthy one). Loss needs the elapsed-since-last-renew to cross
        renew_deadline, discovered by the first attempt after it — each
        attempt costing up to its own wall time plus the failure wait
        plus the worst wake-up lateness this process has measured
        (scheduler starvation between attempts)."""
        worst = max(self.attempt_seconds, default=self.retry_period)
        per_attempt = (worst + min(1.0, self.retry_period)
                       + self.observed_lateness)
        return max(5.0, self.renew_deadline + 25.0 * per_attempt)

    def wait_for_loss(self, workload_stop, poll: float = 0.25) -> bool:
        """Wait until leadership loss is signalled, with a deadline
        RE-DERIVED while waiting: starvation that begins only after the
        wait starts (the original flake — jit compiles delaying the
        renew thread past any budget computed up front) shows up as
        oversleep of this poller's own waits and of the renew loop's,
        both folded into observed_lateness, which grows the budget it
        has to absorb. Returns True when loss was signalled inside the
        (final) budget."""
        start = time.monotonic()
        while True:
            remaining = start + self.loss_wait_budget() - time.monotonic()
            if remaining <= 0:
                return workload_stop.is_set()
            if self._wait(workload_stop, min(poll, remaining)):
                return True

    def run(self, on_started_leading: Callable[[threading.Event], None],
            on_stopped_leading: Callable[[], None],
            stop: Optional[threading.Event] = None) -> None:
        stop = stop or threading.Event()
        while not stop.is_set():
            t0 = time.monotonic()
            ok = self.lock.try_acquire_or_renew()
            self.attempt_seconds.append(time.monotonic() - t0)
            self.renew_attempts += 1
            if ok:
                break
            self._wait(stop, self.retry_period)
        if stop.is_set():
            return

        lost = threading.Event()

        def renew_loop():
            # Loss is declared from ACTUAL elapsed time since the last
            # successful renew, measured on the monotonic clock AFTER each
            # attempt. The old shape (a wall-clock deadline armed before
            # the attempt window) mis-times under CPU starvation: a
            # starved thread could wake past its own deadline having made
            # zero real attempts, or keep re-arming windows and never
            # accumulate the failures into a loss. Here every iteration
            # performs one attempt, and a failed attempt counts against
            # the renew deadline no matter how late the scheduler ran it.
            last_renew = time.monotonic()
            while not stop.is_set() and not lost.is_set():
                t0 = time.monotonic()
                ok = self.lock.try_acquire_or_renew()
                self.attempt_seconds.append(time.monotonic() - t0)
                self.renew_attempts += 1
                if ok:
                    last_renew = time.monotonic()
                    self._wait(stop, self.retry_period)
                    continue
                if time.monotonic() - last_renew >= self.renew_deadline:
                    lost.set()
                    return
                self._wait(stop, min(1.0, self.retry_period))

        renewer = threading.Thread(target=renew_loop, daemon=True,
                                   name="kb-lease-renew")
        renewer.start()

        workload_stop = threading.Event()

        def watchdog():
            while not stop.is_set() and not lost.is_set():
                lost.wait(0.2)
            workload_stop.set()

        threading.Thread(target=watchdog, daemon=True,
                         name="kb-lease-watchdog").start()
        try:
            on_started_leading(workload_stop)
        finally:
            if lost.is_set():
                on_stopped_leading()


class FileLease:
    """Lock-file backend: the shared medium is a file on a common
    filesystem carrying the holder's identity and lease expiry; the
    read-check-write runs under an flock guard so two replicas racing an
    empty/expired lease cannot both win (the reference gets this
    atomicity from the API server's compare-and-swap)."""

    def __init__(self, path: str, lease_duration: float = 15.0,
                 renew_deadline: float = 10.0, retry_period: float = 5.0,
                 identity: Optional[str] = None):
        self.path = path
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.identity = identity or _default_identity()

    def _read(self):
        """The lease record, None when no lease file exists, or
        ``_UNREADABLE`` when a file exists but does not parse. The
        distinction is load-bearing: our own writes are atomic
        (os.replace), so an unparseable file is another writer mid-write
        — treating it as "free" would let a reader racing a non-atomic
        writer steal the lease back (the lease-loss flake: a renew racing
        the takeover's truncate+write window re-acquired over the new
        holder, and loss was never detected)."""
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            return _UNREADABLE

    def _write(self) -> bool:
        record = {"holder": self.identity,
                  "renew_time": time.time(),
                  "lease_duration": self.lease_duration}
        tmp = f"{self.path}.{self.identity}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.path)
            return True
        except OSError:
            return False

    def try_acquire_or_renew(self) -> bool:
        import fcntl

        # injection seam: a failed renew (a CAS the medium refused) —
        # the elector's elapsed-based deadline turns persistence into
        # loss, a transient blip heals on the next retry
        if _fault_should_fail("lease.renew"):
            return False
        guard_path = f"{self.path}.guard"
        try:
            guard = open(guard_path, "a+")
        except OSError:
            return False
        try:
            fcntl.flock(guard, fcntl.LOCK_EX)
            rec = self._read()
            if rec is _UNREADABLE:
                # cannot prove the lease is free or ours — not renewed;
                # the elector's retry loop settles it once readable
                return False
            now = time.time()
            if rec is not None and rec.get("holder") != self.identity:
                expires = rec.get("renew_time", 0) + rec.get(
                    "lease_duration", self.lease_duration)
                if now < expires:
                    return False  # someone else holds a live lease
            return self._write()
        finally:
            fcntl.flock(guard, fcntl.LOCK_UN)
            guard.close()

    def run(self, on_started_leading: Callable[[threading.Event], None],
            on_stopped_leading: Callable[[], None],
            stop: Optional[threading.Event] = None) -> None:
        """Back-compat wrapper: elect with this file as the lock."""
        LeaderElector(self, self.lease_duration, self.renew_deadline,
                      self.retry_period).run(on_started_leading,
                                             on_stopped_leading, stop)


# ---------------------------------------------------------------------
# cross-host backend: lease over HTTP
# ---------------------------------------------------------------------

class HttpLease:
    """Cross-host lock backend: the CAS lives in one ``HttpLeaseServer``
    (e.g. embedded in the rpc solver sidecar) that every replica points
    at — the structural analogue of the reference's replicas all talking
    to the API server's ConfigMap lock."""

    def __init__(self, url: str, lease_duration: float = 15.0,
                 renew_deadline: float = 10.0, retry_period: float = 5.0,
                 identity: Optional[str] = None, timeout: float = 3.0):
        base = url.rstrip("/")
        self.url = base if base.endswith("/lease") else base + "/lease"
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.identity = identity or _default_identity()
        self.timeout = timeout
        self._err_logged = False

    def try_acquire_or_renew(self) -> bool:
        import urllib.request

        if _fault_should_fail("lease.renew"):    # injection seam
            return False
        body = json.dumps({"holder": self.identity,
                           "lease_duration": self.lease_duration}).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read().decode())
        except Exception as e:
            # unreachable server = cannot prove the lease — treat as not
            # renewed (the elector's deadline turns persistent failures
            # into loss-of-leadership, exactly like API-server outages).
            # Log the transition once so a misconfigured URL/port is
            # distinguishable from legitimate contention.
            if not self._err_logged:
                self._err_logged = True
                import logging
                logging.getLogger("kubebatch").warning(
                    "lease service %s unreachable (%s: %s); reading as "
                    "not-acquired", self.url, type(e).__name__, e)
            return False
        self._err_logged = False
        return bool(out.get("acquired"))

    def run(self, on_started_leading: Callable[[threading.Event], None],
            on_stopped_leading: Callable[[], None],
            stop: Optional[threading.Event] = None) -> None:
        LeaderElector(self, self.lease_duration, self.renew_deadline,
                      self.retry_period).run(on_started_leading,
                                             on_stopped_leading, stop)


class HttpLeaseServer:
    """The lease CAS as a tiny stdlib HTTP service.

    POST /lease {holder, lease_duration} -> {acquired, holder}
    GET  /lease -> current record (introspection)

    State is in-memory under one mutex; expiry semantics identical to
    FileLease, plus a **boot grace**: for ``boot_grace`` seconds after a
    (re)start with no state, every acquisition by a NEW holder is
    refused — a restart of the lock medium must not hand the lease to a
    second replica while the incumbent is still inside its renew
    deadline (the file/ConfigMap media get this from persistence).

    Binds loopback by default. The endpoint trusts the peer network and
    the holder string exactly as far as the reference trusts anything
    that can write its ConfigMap — expose it beyond localhost only on a
    network where every peer may legitimately contend for (or break)
    leadership, or behind an authenticating proxy.

    ``start()`` binds and serves on a daemon thread and returns the
    bound port (0 = ephemeral, for tests)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 boot_grace: float = 15.0):
        self.host = host
        self.port = port
        self.boot_grace = boot_grace
        self._boot = time.time()
        self._state: Optional[dict] = None
        self._lock = threading.Lock()
        self._httpd = None
        self._thread = None

    def _try_acquire(self, holder: str, lease_duration: float) -> dict:
        with self._lock:
            now = time.time()
            rec = self._state
            if rec is None and now < self._boot + self.boot_grace:
                # restart window: an incumbent may still believe it
                # leads; make claimants wait out one lease duration
                return {"acquired": False, "holder": ""}
            if rec is not None and rec["holder"] != holder:
                if now < rec["renew_time"] + rec["lease_duration"]:
                    return {"acquired": False, "holder": rec["holder"]}
            self._state = {"holder": holder, "renew_time": now,
                           "lease_duration": lease_duration}
            return {"acquired": True, "holder": holder}

    def start(self) -> int:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        owner = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # quiet
                pass

            def _reply(self, code: int, obj: dict):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/lease":
                    return self._reply(404, {"error": "not found"})
                with owner._lock:
                    rec = dict(owner._state) if owner._state else {}
                self._reply(200, rec)

            def do_POST(self):
                if self.path != "/lease":
                    return self._reply(404, {"error": "not found"})
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n).decode())
                    holder = str(req["holder"])
                    dur = float(req.get("lease_duration", 15.0))
                except (ValueError, KeyError):
                    return self._reply(400, {"error": "bad request"})
                self._reply(200, owner._try_acquire(holder, dur))

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="kb-lease-http")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
