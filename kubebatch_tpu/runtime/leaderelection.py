"""File-lease leader election — HA for multiple scheduler replicas.

The reference elects through a ConfigMap resource lock with a
15s lease / 10s renew / 5s retry (cmd/kube-batch/app/server.go:103-106,
170-193) and kills the process on lost leadership. Without an API server,
the shared medium here is a lock file on a shared filesystem carrying the
holder's identity and lease expiry; semantics (acquire, renew, fatal on
loss) are preserved.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from typing import Callable, Optional


class FileLease:
    def __init__(self, path: str, lease_duration: float = 15.0,
                 renew_deadline: float = 10.0, retry_period: float = 5.0,
                 identity: Optional[str] = None):
        self.path = path
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.identity = identity or f"{socket.gethostname()}_{uuid.uuid4()}"

    def _read(self):
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self) -> bool:
        record = {"holder": self.identity,
                  "renew_time": time.time(),
                  "lease_duration": self.lease_duration}
        tmp = f"{self.path}.{self.identity}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.path)
            return True
        except OSError:
            return False

    def try_acquire_or_renew(self) -> bool:
        """Read-check-write under an flock guard so two replicas racing an
        empty/expired lease cannot both win (the reference gets this
        atomicity from the API server's compare-and-swap)."""
        import fcntl

        guard_path = f"{self.path}.guard"
        try:
            guard = open(guard_path, "a+")
        except OSError:
            return False
        try:
            fcntl.flock(guard, fcntl.LOCK_EX)
            rec = self._read()
            now = time.time()
            if rec is not None and rec.get("holder") != self.identity:
                expires = rec.get("renew_time", 0) + rec.get(
                    "lease_duration", self.lease_duration)
                if now < expires:
                    return False  # someone else holds a live lease
            return self._write()
        finally:
            fcntl.flock(guard, fcntl.LOCK_UN)
            guard.close()

    def run(self, on_started_leading: Callable[[threading.Event], None],
            on_stopped_leading: Callable[[], None],
            stop: Optional[threading.Event] = None) -> None:
        """Block until leadership is acquired, run the workload, and call
        on_stopped_leading if the lease is ever lost (the reference
        glog.Fatalf's there — callers should treat it as fatal)."""
        stop = stop or threading.Event()
        while not stop.is_set():
            if self.try_acquire_or_renew():
                break
            stop.wait(self.retry_period)
        if stop.is_set():
            return

        lost = threading.Event()

        def renew_loop():
            while not stop.is_set() and not lost.is_set():
                deadline = time.time() + self.renew_deadline
                ok = False
                while time.time() < deadline:
                    if self.try_acquire_or_renew():
                        ok = True
                        break
                    stop.wait(min(1.0, self.retry_period))
                if not ok:
                    lost.set()
                    return
                stop.wait(self.retry_period)

        renewer = threading.Thread(target=renew_loop, daemon=True,
                                   name="kb-lease-renew")
        renewer.start()

        workload_stop = threading.Event()

        def watchdog():
            while not stop.is_set() and not lost.is_set():
                lost.wait(0.2)
            workload_stop.set()

        threading.Thread(target=watchdog, daemon=True,
                         name="kb-lease-watchdog").start()
        try:
            on_started_leading(workload_stop)
        finally:
            if lost.is_set():
                on_stopped_leading()
