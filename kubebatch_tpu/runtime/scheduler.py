"""Scheduler loop (ref: pkg/scheduler/scheduler.go + pkg/scheduler/util.go).

Every ``schedule_period`` the loop opens a Session against the cache,
executes the configured actions in order with per-action latency metrics,
and closes the session (status write-back). Malformed policy config falls
back to the compiled-in default; an unknown action name is an error
(util.go:148-169).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import List, Optional, Tuple

log = logging.getLogger("kubebatch")

from .. import actions as _actions  # noqa: F401  (self-registration)
from .. import faults as _faults
from .. import obs as _obs
from .. import plugins as _plugins  # noqa: F401  (self-registration)
from ..conf import SchedulerConfiguration, Tier, parse_scheduler_conf
from ..framework import (Action, CloseSession, OpenSession, get_action)
from ..metrics import count_cycle_failure

DEFAULT_SCHEDULER_CONF = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def load_scheduler_conf(conf_str: str) -> Tuple[List[Action], List[Tier]]:
    """ref: util.go:148-169 — unknown action name is an error."""
    conf: SchedulerConfiguration = parse_scheduler_conf(conf_str)
    actions: List[Action] = []
    for name in conf.actions.split(","):
        name = name.strip()
        if not name:
            continue
        action = get_action(name)
        if action is None:
            raise ValueError(f"failed to find Action {name}, ignore it")
        actions.append(action)
    return actions, conf.tiers


class Scheduler:
    """ref: scheduler.go:33-105."""

    def __init__(self, cache, scheduler_conf: str = "",
                 schedule_period: float = 1.0,
                 enable_preemption: bool = False,
                 cycle_deadline: Optional[float] = None,
                 explain_unschedulable: bool = False,
                 audit_every: Optional[int] = None,
                 solve_audit_every: Optional[int] = None,
                 subcycle: Optional[bool] = None,
                 pipeline: Optional[bool] = None,
                 slo: Optional[bool] = None):
        self.cache = cache
        self.schedule_period = schedule_period
        self.enable_preemption = enable_preemption
        self.actions, self.tiers = self._load_conf(scheduler_conf)
        self._stop = threading.Event()
        if cycle_deadline is None:
            env = os.environ.get("KUBEBATCH_CYCLE_DEADLINE", "")
            cycle_deadline = float(env) if env else None
        #: per-cycle wall budget (seconds); an overrun counts as a cycle
        #: failure for the degradation ladder. None = no budget.
        self.cycle_deadline = cycle_deadline
        #: lazy-audit cadence (ISSUE 9): every Nth cycle opens from
        #: cache.audited_snapshot() — the folded state deep-compared
        #: against a fresh full clone (snapshot_diff == 0 asserted; a
        #: divergence demotes the fold layer to snapshot-primary and the
        #: cycle proceeds on the trustworthy full clone). 0/None = off.
        if audit_every is None:
            env = os.environ.get("KUBEBATCH_AUDIT_EVERY", "")
            audit_every = int(env) if env else 0
        self.audit_every = int(audit_every or 0)
        #: active-set solve audit cadence (ISSUE 15): same machinery one
        #: layer down — every Nth ENGAGED steady cycle the solve runs
        #: the combined full-width comparison entry; a decision
        #: divergence demotes the active-set engine to full-width for
        #: the rest of the process (kernels/activeset.py owns the
        #: counter and the rung; the scheduler only sets the cadence,
        #: which the env default already covers when the flag is None)
        if solve_audit_every is not None:
            from ..kernels import activeset as _activeset
            _activeset.set_audit_every(solve_audit_every)
        #: schedule-on-arrival sub-cycle (ISSUE 9): latency-lane pod
        #: arrivals get a narrow allocate against the live device arrays
        #: instead of waiting for the period (runtime/subcycle.py)
        if subcycle is None:
            from ..util import env_on
            subcycle = env_on("KUBEBATCH_SUBCYCLE", default="0")
        self.subcycle_enabled = bool(subcycle)
        #: full cycles and sub-cycles never overlap: both run under this
        #: lock (arrival hooks block on it for at most one cycle)
        self._cycle_lock = threading.Lock()
        self._arrival_lock = threading.Lock()
        self._pending_arrivals: list = []
        self._subcycle_seq = -1
        if self.subcycle_enabled \
                and hasattr(cache, "arrival_hooks"):
            cache.arrival_hooks.append(self._on_pod_arrival)
        #: the process-wide degradation ladder (faults.py): run_cycle
        #: feeds it failures/successes, AllocateAction consults its cap
        self.ladder = _faults.LADDER
        if self.ladder.probe is None:
            self.ladder.probe = self._recovery_probe
        #: why the last run_cycle returned False (None / "exception" /
        #: "deadline") — a deadline overrun is a SLOW cycle, not a
        #: broken one
        self.last_cycle_failure: Optional[str] = None
        #: opt-in unschedulability explainer (obs/explain.py): one extra
        #: readback per cycle when on, /debug/explain serves the snapshot
        self.explain_unschedulable = explain_unschedulable
        #: monotonically increasing cycle id stamped on each cycle root
        #: span (and propagated over the rpc hop as trace context)
        self._cycle_seq = -1
        #: pipelined cycles (ISSUE 16; runtime/pipeline.py): overlap the
        #: device solve's readback with the next cycle's host work. The
        #: executor replaces run_once's session block while it is
        #: active; a conflict-storm demotion flips cycles back to the
        #: sequential block below, permanently for the process.
        if pipeline is None:
            from ..util import env_on
            pipeline = env_on("KUBEBATCH_PIPELINE", default="0")
        self.pipeline_enabled = bool(pipeline)
        self._pipeline = None
        if self.pipeline_enabled:
            from .pipeline import PipelinedExecutor
            self._pipeline = PipelinedExecutor(self)
        #: SLO burn-rate plane (ISSUE 17; obs/slo.py): armed explicitly
        #: per scheduler — the cycle hook evaluates the shipped
        #: objectives over the decision ledger; disarmed it costs
        #: nothing. KUBEBATCH_TIMELINE_DIR also arms the long-horizon
        #: timeline spill (obs/timeline.py) for soak runs.
        if slo is None:
            from ..util import env_on
            slo = env_on("KUBEBATCH_SLO", default="0")
        self.slo_enabled = bool(slo)
        if self.slo_enabled:
            from ..obs import slo as _slo
            _slo.arm()
        tdir = os.environ.get("KUBEBATCH_TIMELINE_DIR", "")
        if tdir:
            from ..obs import timeline as _timeline
            _timeline.arm(tdir)

    @staticmethod
    def _load_conf(conf_str: str):
        """Only file-READ errors fall back to the default (handled by the
        CLI); a conf that parses wrong or names an unknown action is fatal,
        like the reference's panic (scheduler.go:80-83)."""
        if conf_str:
            return load_scheduler_conf(conf_str)
        return load_scheduler_conf(DEFAULT_SCHEDULER_CONF)

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Blocking loop: cache workers + periodic run_once
        (ref: scheduler.go:63-86).

        GC discipline: a cycle allocates tens of thousands of short-lived
        objects (snapshot clones, decision tuples); CPython's automatic
        collector fires gen2 passes mid-cycle that scan the entire
        long-lived cluster graph. The loop freezes the pre-existing heap,
        turns automatic collection off, and collects explicitly between
        cycles — off the latency path. Go gets the equivalent from its
        concurrent collector; here it is an explicit scheduling-loop
        concern."""
        import gc

        stop = stop or self._stop
        self.cache.run()
        self.cache.wait_for_cache_sync()
        gc.freeze()
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            while not stop.is_set():
                with _obs.span("loop_tick", cat="host") as tick:
                    self.run_cycle()
                    gc.collect()
                stop.wait(max(0.0, self.schedule_period - tick.dur))
        finally:
            if gc_was_enabled:
                gc.enable()
            gc.unfreeze()

    def stop(self) -> None:
        self._stop.set()

    @staticmethod
    def _recovery_probe() -> bool:
        """Mid-run health probe gating ladder re-promotion: the startup
        accelerator watchdog generalized to run between cycles. Honors
        the same skip env as startup (tests, CPU-only runs)."""
        from .watchdog import midrun_probe
        return midrun_probe()

    # ------------------------------------------------------------------
    # schedule-on-arrival (ISSUE 9; runtime/subcycle.py)
    # ------------------------------------------------------------------
    def _on_pod_arrival(self, pod) -> None:
        """Cache arrival hook (fired outside the cache lock, on the
        event-delivery thread): queue latency-lane pods and drain them
        through a sub-cycle. A non-latency pod costs one annotation
        lookup."""
        import time as _time

        from .subcycle import is_latency_pod
        if not is_latency_pod(pod):
            return
        with self._arrival_lock:
            self._pending_arrivals.append((pod, _time.perf_counter()))
        self._drain_arrivals()

    def _drain_arrivals(self) -> None:
        """Run one sub-cycle over every queued arrival. Blocks on the
        cycle lock (never overlaps a full cycle; a hook thread waiting
        here coalesces the burst that accumulated meanwhile). Guarded:
        a failing sub-cycle is counted, logged, and never propagates
        into the event pump."""
        from . import subcycle as _subcycle

        with self._cycle_lock:
            with self._arrival_lock:
                arrivals, self._pending_arrivals = \
                    self._pending_arrivals, []
            if not arrivals:
                return
            try:
                _subcycle.run_subcycle(self, arrivals)
            except Exception:
                log.exception("schedule-on-arrival sub-cycle failed; "
                              "pods wait for the next full cycle")
                count_cycle_failure("subcycle")

    def run_cycle(self) -> bool:
        """One GUARDED cycle: never raises. A raising cycle is logged
        structurally and counted (cycle_failures_total{reason=exception});
        a cycle that completes but blows the deadline budget counts as
        {reason=deadline} — or {reason=recompile} when the compile
        manager observed a post-warm-up recompile during the cycle (an
        unexpected mid-run XLA compile is an explained overrun cause,
        not a silent stall; ISSUE 6 enforcement). All feed the
        degradation ladder; a healthy cycle feeds its recovery side.
        Returns True iff healthy; ``last_cycle_failure`` then carries
        None, "exception", "deadline" or "recompile" for callers that
        must tell a broken cycle from a merely slow one (the CLI's
        finite-cycle exit code treats everything but "exception" as
        slow-but-working)."""
        from ..metrics import recompiles_total
        from ..obs import flight as _flight

        self.last_cycle_failure = None
        recompiles0 = recompiles_total()
        self._cycle_seq += 1
        root = _obs.begin_cycle(self._cycle_seq,
                                ladder=self.ladder.level)
        try:
            # full cycles and schedule-on-arrival sub-cycles serialize
            # on the cycle lock (an arrival hook mid-cycle waits here)
            with self._cycle_lock:
                self.run_once()
        except Exception:
            # a failed cycle must not kill the loop (run_once guarantees
            # CloseSession ran: statements rolled back, status written,
            # snapshot adopted — the session did not leak)
            _obs.end_cycle(root, failed="exception")
            log.exception("scheduling cycle failed; loop continues "
                          "(ladder level %d)", self.ladder.level)
            count_cycle_failure("exception")
            self.last_cycle_failure = "exception"
            self.ladder.record_failure()
            # the failing cycle's span tree is IN the ring the dump
            # writes — end_cycle above ran before the dump trigger
            _flight.maybe_dump_on_failure("exception")
            return False
        _obs.end_cycle(root)
        elapsed = root.dur
        recompiled = recompiles_total() - recompiles0
        if self.cycle_deadline is not None and elapsed > self.cycle_deadline:
            reason = "recompile" if recompiled else "deadline"
            log.warning("scheduling cycle took %.3fs, over the %.3fs "
                        "deadline budget (%s; ladder level %d)",
                        elapsed, self.cycle_deadline,
                        f"{recompiled} mid-run recompiles" if recompiled
                        else "no recompile observed", self.ladder.level)
            count_cycle_failure(reason)
            self.last_cycle_failure = reason
            self.ladder.record_failure()
            _flight.maybe_dump_on_failure(reason)
            return False
        if recompiled:
            # inside budget but still unexpected: surface it — the next
            # occurrence of this shape is warm, but the registry (or the
            # warm-up config) missed it
            log.warning("scheduling cycle performed %d post-warm-up "
                        "recompile(s) (recompiles_total; see "
                        "docs/COMPILE.md)", recompiled)
        self.ladder.record_success()
        return True

    def run_once(self) -> None:
        """One scheduling cycle (ref: scheduler.go:88-105). CloseSession is
        guaranteed even when an action throws (the reference defers it) so
        status write-back happens and the loop survives. Timing routes
        through obs spans: the session span is the e2e histogram's source,
        each action span feeds action_scheduling_latency."""
        jobs = nodes = None
        session_span = None
        snapshot = None
        if (self.audit_every
                and self._cycle_seq % self.audit_every == 0
                and hasattr(self.cache, "audited_snapshot")):
            # the lazy audit (ISSUE 9): build the full-clone oracle next
            # to the folded snapshot and deep-compare; a divergence
            # demotes the fold layer (cache side) — here it is counted,
            # logged, and flight-dumped, and the cycle proceeds on the
            # trustworthy full clone audited_snapshot returned
            from ..metrics import count_audit_cycle
            from ..obs import flight as _flight
            with _obs.span("audit", cat="phase"):
                snapshot, diffs = self.cache.audited_snapshot()
            count_audit_cycle(ok=not diffs)
            if diffs:
                log.error("fold audit FAILED (%d diffs; fold demoted to "
                          "snapshot-primary): %s", len(diffs), diffs[:4])
                _flight.maybe_dump_on_failure("fold-audit")
        if self._pipeline is not None and self._pipeline.active():
            # pipelined cycle (ISSUE 16): same session protocol, but the
            # previous cycle's in-flight solve is consumed first and the
            # allocate action dispatches without reading back
            self._pipeline.run_once(snapshot)
            return
        try:
            with _obs.span("session", cat="e2e") as session_span:
                ssn = OpenSession(self.cache, self.tiers,
                                  self.enable_preemption,
                                  snapshot=snapshot)
                jobs, nodes = len(ssn.jobs), len(ssn.nodes)
                try:
                    for action in self.actions:
                        action.initialize()
                        with _obs.span(action.name, cat="action") as asp:
                            action.execute(ssn)
                        log.debug("action %s took %.2fms", action.name,
                                  1e3 * asp.dur)
                        action.uninitialize()
                    if self.explain_unschedulable:
                        # opt-in debug pass (ISSUE 7): one extra readback,
                        # published to /debug/explain — NEVER on by
                        # default, and guarded: a diagnostic must not
                        # fail the cycle (decisions are already applied)
                        # or feed the degradation ladder
                        from ..obs import explain as _explain
                        try:
                            with _obs.span("explain", cat="host"):
                                _explain.explain_session(ssn)
                        except Exception:
                            log.exception("unschedulability explainer "
                                          "failed; cycle unaffected")
                finally:
                    CloseSession(ssn)
        finally:
            # the glog V(2)-style cycle line (ref: scheduler.go:92
            # metric; verbosity wired by the CLI --v flag) — emitted on
            # raising cycles too (the session span has closed by now,
            # so its dur is final), exactly like the old finally did
            if jobs is not None:
                log.info("scheduling cycle: %d jobs / %d nodes in %.2fms",
                         jobs, nodes, 1e3 * session_span.dur)
