"""CLI entry — flags mirror the reference's ServerOption
(ref: cmd/kube-batch/app/options/options.go:222-268,
cmd/kube-batch/app/server.go).

Without a Kubernetes API server, the cluster source is the synthetic sim
(--sim-config N picks a BASELINE config); a real informer-backed source
would plug in through the same SchedulerCache handler surface. The
/metrics endpoint serves the kube_batch Prometheus taxonomy.

Run:  python -m kubebatch_tpu --sim-config 2 --schedule-period 1
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubebatch-tpu",
        description="TPU-native batch/gang scheduler (kube-batch capability"
                    " set)")
    # reference flags (options.go:243-258)
    p.add_argument("--master", default="",
                   help="the address of the Kubernetes API server (unused "
                        "in sim mode)")
    p.add_argument("--kubeconfig", default="",
                   help="path to kubeconfig file (unused in sim mode)")
    p.add_argument("--scheduler-name", default="kube-batch",
                   help="vc-scheduler name in pod spec")
    p.add_argument("--scheduler-conf", default="",
                   help="path to the YAML policy configuration")
    p.add_argument("--schedule-period", type=float, default=1.0,
                   help="the period between each scheduling cycle (s)")
    p.add_argument("--default-queue", default="default",
                   help="the default queue name of the job")
    p.add_argument("--enable-preemption", action="store_true",
                   help="whether to enable preemption")
    p.add_argument("--leader-elect", action="store_true",
                   help="HA leader election among replicas")
    p.add_argument("--lock-object-namespace", default="",
                   help="namespace of the lock object / directory of the "
                        "lease file")
    p.add_argument("--leader-elect-url", default="",
                   help="elect through an HTTP lease service instead of "
                        "the lease file (cross-host replicas; e.g. the "
                        "rpc sidecar with KUBEBATCH_LEASE_PORT set)")
    p.add_argument("--listen-address", default=":8080",
                   help="address for the /metrics endpoint")
    p.add_argument("--version", action="store_true",
                   help="show version and quit")
    p.add_argument("--v", type=int, default=0, dest="verbosity",
                   help="log level verbosity (glog-style: 0 = warnings, "
                        "1+ = per-cycle lines, 3+ = per-action detail)")
    # sim-mode extensions
    p.add_argument("--sim-config", type=int, default=0,
                   choices=[0, 1, 2, 3, 4, 5],
                   help="populate from a BASELINE sim config (0 = empty "
                        "cluster)")
    p.add_argument("--cycles", type=int, default=0,
                   help="stop after N cycles (0 = run forever)")
    p.add_argument("--solver", default="",
                   choices=["", "auto", "host", "jax", "fused", "batched",
                            "sharded", "native"],
                   help="override the allocate solver mode")
    # robustness extensions (docs/ROBUSTNESS.md)
    p.add_argument("--faults", default="",
                   help="arm fault injection: 'seam:rate,seam:nN,...' "
                        "(rate = probability per crossing, nN = fail the "
                        "first N deterministically); see faults.SEAMS "
                        "for the catalog. Also armable via "
                        "KUBEBATCH_FAULTS.")
    p.add_argument("--faults-seed", type=int, default=0,
                   help="seed for the randomized fault schedule")
    p.add_argument("--cycle-deadline", type=float, default=None,
                   help="per-cycle wall budget in seconds; overruns count "
                        "as cycle failures and demote the engine ladder "
                        "(also via KUBEBATCH_CYCLE_DEADLINE)")
    # compile manager (docs/COMPILE.md)
    p.add_argument("--warmup", nargs="?", const="auto", default="",
                   metavar="CONFIG",
                   help="compile the registered shape-bucket set before "
                        "the first cycle (compilesvc AOT warm-up) and arm "
                        "the recompiles_total==0 invariant; CONFIG is a "
                        "BASELINE key (1-5, 2p/3p/5p; default: the "
                        "--sim-config). Warmed executables persist via "
                        "the managed compile cache and survive restarts.")
    # observability (docs/OBSERVABILITY.md)
    p.add_argument("--flight-record", nargs="?", const="flight-records",
                   default="", metavar="DIR",
                   help="arm the flight recorder: ring-buffer the last "
                        "cycles' span trees + counter snapshots + ladder "
                        "state and auto-dump to DIR on cycle failures, "
                        "ladder demotions and chaos invariant violations "
                        "(also armable via KUBEBATCH_FLIGHT_RECORD)")
    p.add_argument("--trace-dir", default="", metavar="DIR",
                   help="export every cycle's span tree as Chrome "
                        "trace-event JSON (Perfetto-loadable) into "
                        "DIR/trace.json, written at exit")
    p.add_argument("--profile-cycles", type=int, default=0, metavar="N",
                   help="with --trace-dir: additionally capture a "
                        "jax.profiler programmatic trace covering the "
                        "first N cycles into the same directory")
    p.add_argument("--explain-unschedulable", action="store_true",
                   help="run the unschedulability explainer after each "
                        "cycle's actions (one extra device readback; "
                        "off the steady path by default) and serve the "
                        "snapshot on /debug/explain")
    p.add_argument("--audit-every", type=int, default=None, metavar="N",
                   help="lazy-audit cadence: every Nth cycle deep-"
                        "compares the folded snapshot against a fresh "
                        "full clone (snapshot_diff == 0 asserted; a "
                        "divergence demotes the event-fold layer to "
                        "snapshot-primary). Default: KUBEBATCH_AUDIT_"
                        "EVERY, else off")
    p.add_argument("--solve-audit-every", type=int, default=None,
                   metavar="N",
                   help="active-set solve audit cadence: every Nth "
                        "engaged steady cycle also runs the full-width "
                        "solve in the same dispatch and compares "
                        "decisions bit-for-bit (a divergence demotes "
                        "the active-set engine to full-width). Default: "
                        "KUBEBATCH_SOLVE_AUDIT_EVERY, else 16; 0 "
                        "disables the audit")
    p.add_argument("--subcycle", action="store_true", default=None,
                   help="schedule-on-arrival: latency-lane pod arrivals "
                        "(annotation scheduling.k8s.io/kube-batch/"
                        "lane=latency) get a narrow allocate against "
                        "the live device arrays immediately instead of "
                        "waiting for the schedule period (also "
                        "KUBEBATCH_SUBCYCLE=1)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        from .. import __version__
        print(f"kubebatch-tpu {__version__}")
        return 0
    from .. import enable_persistent_compile_cache
    enable_persistent_compile_cache()

    import logging

    level = (logging.WARNING if args.verbosity <= 0
             else logging.INFO if args.verbosity < 3 else logging.DEBUG)
    logging.basicConfig(
        level=level,
        format="%(levelname).1s%(asctime)s %(name)s] %(message)s",
        datefmt="%m%d %H:%M:%S")

    import os

    if args.solver:
        os.environ["KUBEBATCH_SOLVER"] = args.solver

    if args.faults:
        from .. import faults
        faults.arm(faults.parse_fault_spec(args.faults,
                                           seed=args.faults_seed))

    # accelerator wedge watchdog: a hung transport must degrade the daemon
    # to host scheduling, not hang its first kernel dispatch forever
    from .watchdog import ensure_responsive_backend
    if ensure_responsive_backend() == "cpu-fallback":
        # platform flipped: re-salt the managed compile cache onto the
        # cpu directory (compilesvc/cache.py cache_salt) so fallback
        # executables never mix into the accelerator's entries
        enable_persistent_compile_cache()

    if args.warmup:
        # AOT warm-up over the registered bucket set BEFORE the loop: the
        # daemon's first cycle must not eat the compile wall, and from
        # here on an unexpected recompile is counted (and attributed as
        # a cycle-overrun cause by the scheduler's ladder)
        from .. import compilesvc
        from ..conf import CONFIG_ACTIONS

        cfg = args.warmup
        if cfg == "auto":
            cfg = str(args.sim_config or 2)
        cfg = int(cfg) if cfg.isdigit() else cfg
        if cfg not in CONFIG_ACTIONS:
            # an operator typo must fail loudly at startup, not start an
            # un-warmed daemon that then eats the compile wall mid-cycle
            print(f"--warmup: unknown BASELINE config {cfg!r} "
                  f"(choose from {sorted(map(str, CONFIG_ACTIONS))})",
                  file=sys.stderr)
            return 2
        try:
            report = compilesvc.warmup(cfg)
        except Exception as e:  # materials/profile failure: degrade —
            # an un-warmed daemon still schedules (recompiles are
            # counted + attributed); losing the warm start must not
            # lose the scheduler
            print(f"compilesvc warm-up failed ({type(e).__name__}: {e}); "
                  f"starting un-warmed", file=sys.stderr)
        else:
            print(f"compilesvc warm-up: {report.summary()}",
                  file=sys.stderr)
            for key, err in report.failed:
                print(f"compilesvc warm-up FAILED {key[:100]}: {err}",
                      file=sys.stderr)

    from ..cache import SchedulerCache
    from ..sim import baseline_cluster
    from .scheduler import Scheduler

    # observability arming (docs/OBSERVABILITY.md): flight recorder,
    # Chrome-trace export dir, gated jax.profiler capture
    from ..obs import export as obs_export
    from ..obs import flight as obs_flight
    if args.flight_record:
        obs_flight.arm(args.flight_record)
    else:
        obs_flight.arm_from_env()
    if args.trace_dir:
        obs_export.arm(args.trace_dir)
        if args.profile_cycles:
            from ..obs import arm_profile
            arm_profile(args.profile_cycles, args.trace_dir)

    # /metrics endpoint (ref: server.go:138-141) — served with /healthz,
    # /debug/vars and /debug/explain by the obs HTTP server; /metrics
    # delegates to prometheus_client when present and degrades to a text
    # rendering of the mirror counters when it is not
    http_server = None
    if args.listen_address:
        from ..obs.http import start as start_debug_http
        http_server = start_debug_http(args.listen_address)
        if http_server is None:
            print(f"metrics endpoint disabled: could not bind "
                  f"{args.listen_address}", file=sys.stderr)

    cache = SchedulerCache(scheduler_name=args.scheduler_name,
                           default_queue=args.default_queue)
    if args.sim_config:
        sim = baseline_cluster(args.sim_config)
        sim.populate(cache)
        cache.pod_lister = sim.pod_lister

    conf_str = ""
    if args.scheduler_conf:
        # unreadable conf falls back to the compiled-in default, like the
        # reference (scheduler.go:71-77)
        try:
            with open(args.scheduler_conf) as f:
                conf_str = f.read()
        except OSError as e:
            print(f"failed to read scheduler conf, using default: {e}",
                  file=sys.stderr)

    sched = Scheduler(cache, scheduler_conf=conf_str,
                      schedule_period=args.schedule_period,
                      enable_preemption=args.enable_preemption,
                      cycle_deadline=args.cycle_deadline,
                      explain_unschedulable=args.explain_unschedulable,
                      audit_every=args.audit_every,
                      solve_audit_every=args.solve_audit_every,
                      subcycle=args.subcycle)

    stop = threading.Event()

    def handle_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, handle_signal)
    signal.signal(signal.SIGTERM, handle_signal)

    #: finite-cycle outcome: every cycle failing must surface as a
    #: nonzero exit (run_cycle guards the loop, so a totally broken
    #: scheduler would otherwise exit 0 with nothing but log lines)
    cycle_outcome = {"ran": 0, "failed": 0}

    def run_workload(workload_stop: threading.Event) -> None:
        if args.cycles:
            cache.run()
            for _ in range(args.cycles):
                if stop.is_set() or workload_stop.is_set():
                    break
                cycle_outcome["ran"] += 1
                if not sched.run_cycle() \
                        and sched.last_cycle_failure == "exception":
                    # deadline overruns are slow-but-working cycles;
                    # only raising cycles count toward total breakage
                    cycle_outcome["failed"] += 1
        else:
            merged = threading.Event()

            def bridge():
                while not (stop.is_set() or workload_stop.is_set()):
                    stop.wait(0.2)
                merged.set()

            threading.Thread(target=bridge, daemon=True).start()
            sched.run(merged)

    if args.leader_elect:
        if args.leader_elect_url:
            from .leaderelection import HttpLease

            lease = HttpLease(args.leader_elect_url)
        else:
            from .leaderelection import FileLease

            lease_dir = args.lock_object_namespace or "/tmp"
            lease = FileLease(f"{lease_dir}/kube-batch-leader.lock")

        def fatal():
            print("leaderelection lost", file=sys.stderr)
            sys.exit(1)

        lease.run(run_workload, fatal, stop)
    else:
        run_workload(threading.Event())
    if args.trace_dir:
        written = obs_export.flush()
        if written:
            print(f"trace written to {written}", file=sys.stderr)
    if cycle_outcome["ran"] and cycle_outcome["failed"] == cycle_outcome["ran"]:
        print(f"all {cycle_outcome['ran']} scheduling cycles failed",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
