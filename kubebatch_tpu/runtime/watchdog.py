"""Accelerator wedge watchdog — shared by bench.py and the CLI daemon.

A hung accelerator transport can block the FIRST device query forever
(backend init never returns), which would wedge a scheduler daemon at
its first kernel dispatch with no error and no cycles. The probe runs
the device query in a SUBPROCESS so the parent can abandon it: a child
stuck in an uninterruptible driver call cannot be reaped, so on timeout
it is killed best-effort and left un-waited (start_new_session keeps it
out of our process group; the zombie is collected when this process
exits).

``midrun_probe`` is the same subprocess probe generalized into a
between-cycles health check: the degradation ladder (faults.py) calls
it before re-promoting back onto a device engine after device-fault
demotions, so a scheduler never climbs back onto an accelerator that is
still wedged.
"""
from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

PROBE_SRC = ("import jax; jax.numpy.zeros(()).block_until_ready(); "
             "print(jax.default_backend())")


def probe_backend(timeout: float = 60.0,
                  probe_src: str = PROBE_SRC) -> Tuple[str, str]:
    """Run the device probe in an abandonable subprocess.

    Returns (status, detail): status is "ok" | "timeout" | "error";
    detail is the backend name for "ok", or the tail of the child's
    stderr for "error" (so a broken install is reported as what it is,
    not as an unresponsive device). Child output goes to temp files, not
    pipes — a chatty failing child must not block in write() and turn an
    "error" into a 60 s "timeout". ``probe_src`` is swappable for tests.
    """
    import tempfile

    with tempfile.TemporaryFile(mode="w+") as out_f, \
            tempfile.TemporaryFile(mode="w+") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-c", probe_src],
            stdout=out_f, stderr=err_f, start_new_session=True)
        try:
            # wait(timeout) polls with WNOHANG — it cannot block on a
            # D-state child
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()   # pends if the child is in D state; do NOT reap
            return "timeout", ""
        out_f.seek(0)
        err_f.seek(0)
        if proc.returncode == 0:
            return "ok", out_f.read().strip() or "unknown"
        return "error", err_f.read().strip()[-400:]


def midrun_probe(timeout: float = 20.0,
                 skip_env: Optional[str] = "KUBEBATCH_NO_BACKEND_PROBE",
                 probe_src: str = PROBE_SRC) -> bool:
    """Between-cycles health probe: True when the accelerator answers a
    device query (or probing is skipped — tests and CPU-only runs, where
    a subprocess probe is pure latency). Unlike the startup path this
    never flips the platform: the caller (the degradation ladder) only
    wants a go/no-go for re-promotion, and mid-run the backend is
    already initialized."""
    if skip_env and os.environ.get(skip_env):
        return True
    status, _ = probe_backend(timeout, probe_src)
    return status == "ok"


def ensure_responsive_backend(timeout: float = 60.0,
                              skip_env: Optional[str] =
                              "KUBEBATCH_NO_BACKEND_PROBE",
                              probe_src: str = PROBE_SRC) -> str:
    """Probe the default backend; on timeout/failure flip THIS process to
    the host platform before any device query happens (jax may be
    imported but must be uninitialized).

    Returns the probed backend name, or "cpu-fallback" (flipped),
    "pinned" (flip impossible — running would hang), or "skipped"
    (``skip_env`` set; tests and CPU-only runs).
    """
    if skip_env and os.environ.get(skip_env):
        return "skipped"
    status, detail = probe_backend(timeout, probe_src)
    if status == "ok":
        return detail
    if status == "error" and detail:
        print(f"backend probe failed:\n{detail}", file=sys.stderr)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return "pinned"
    print("accelerator backend unresponsive; continuing on the host "
          "platform", file=sys.stderr)
    return "cpu-fallback"
