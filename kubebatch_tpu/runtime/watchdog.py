"""Accelerator wedge watchdog — shared by bench.py and the CLI daemon.

A hung accelerator transport can block the FIRST device query forever
(backend init never returns), which would wedge a scheduler daemon at
its first kernel dispatch with no error and no cycles. The probe runs
the device query in a SUBPROCESS so the parent can abandon it: a child
stuck in an uninterruptible driver call cannot be reaped, so on timeout
it is killed best-effort and left un-waited (start_new_session keeps it
out of our process group; the zombie is collected when this process
exits).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Tuple

PROBE_SRC = ("import jax; jax.numpy.zeros(()).block_until_ready(); "
             "print(jax.default_backend())")


def probe_backend(timeout: float = 60.0) -> Tuple[str, str]:
    """Run the device probe in an abandonable subprocess.

    Returns (status, detail): status is "ok" | "timeout" | "error";
    detail is the backend name for "ok", or the tail of the child's
    stderr for "error" (so a broken install is reported as what it is,
    not as an unresponsive device).
    """
    proc = subprocess.Popen(
        [sys.executable, "-c", PROBE_SRC],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    deadline = time.monotonic() + timeout
    while proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    if proc.poll() is None:
        proc.kill()   # pends if the child is in D state; do NOT reap
        return "timeout", ""
    out, err = proc.communicate()   # child exited; reaping is safe
    if proc.returncode == 0:
        return "ok", (out or "").strip() or "unknown"
    return "error", (err or "").strip()[-400:]


def ensure_responsive_backend(timeout: float = 60.0,
                              skip_env: Optional[str] =
                              "KUBEBATCH_NO_BACKEND_PROBE") -> str:
    """Probe the default backend; on timeout/failure flip THIS process to
    the host platform before any device query happens (jax may be
    imported but must be uninitialized).

    Returns the probed backend name, or "cpu-fallback" (flipped),
    "pinned" (flip impossible — running would hang), or "skipped"
    (``skip_env`` set; tests and CPU-only runs).
    """
    if skip_env and os.environ.get(skip_env):
        return "skipped"
    status, detail = probe_backend(timeout)
    if status == "ok":
        return detail
    if status == "error" and detail:
        print(f"backend probe failed:\n{detail}", file=sys.stderr)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        return "pinned"
    print("accelerator backend unresponsive; continuing on the host "
          "platform", file=sys.stderr)
    return "cpu-fallback"
